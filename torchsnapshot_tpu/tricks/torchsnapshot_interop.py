"""Migration from reference-format torchsnapshot snapshots.

A user of the reference library (pytorch/torchsnapshot) switching to this
framework has existing checkpoints on disk in the reference's on-disk
format: a ``.snapshot_metadata`` YAML manifest plus one payload file per
tensor chunk / shard / object (reference: snapshot.py:72, io_preparer.py:
792-798, manifest.py:255-321). This module reads that format black-box —
from its documented YAML schema, not from the reference's code — and
materializes the app state as plain Python/NumPy pytrees, optionally
re-writing it as a native snapshot.

Covered entry types (reference manifest.py:37-242):

- ``Tensor``: ``buffer_protocol`` payloads are raw little-endian row-major
  bytes, decoded via a torch-dtype-name -> numpy mapping (bfloat16 and the
  float8 family via ml_dtypes); ``torch_save`` payloads are decoded with
  ``torch.load`` (requires torch, imported lazily).
- ``ChunkedTensor``: chunks are reassembled into the full array by their
  N-D offsets (reference io_preparer.py:113-141).
- ``ShardedTensor``: shards from *all* ranks are merged into one dense
  array (reference manifest.py:324-382 merges shards across ranks).
- ``object``: unpickled with ``torch.load``; contained torch.Tensors are
  converted to numpy arrays when ``convert_tensors`` is set.
- primitives (``int``/``float``/``str``/``bool``/``bytes``): parsed from
  the inlined ``serialized_value`` (float/bytes are base64; float is a
  little-endian IEEE-754 double — reference manifest.py:146-242).
- containers (``dict``/``OrderedDict``/``list``): rebuilt in manifest
  order; ``%``-escaped path tokens are unescaped the way the reference's
  flatten layer escapes them (reference flatten.py:158-165).

Quantized-tensor payloads (``per_tensor_affine_qtensor`` /
``per_channel_affine_qtensor``) are rejected with a clear error: JAX has
no quantized array type (see serialization.py's documented divergence).

Like the orbax trick, imports are lazy: the core library never requires
torch or yaml beyond what it already uses.
"""

from __future__ import annotations

import base64
import os
import struct
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple
from urllib.parse import unquote

import numpy as np

SNAPSHOT_METADATA_FILENAME = ".snapshot_metadata"

# torch dtype-string -> numpy dtype. This is the interop boundary: the
# reference stamps entries with ``str(tensor.dtype)`` (e.g. "torch.float32"),
# so the names are pinned by torch's public API, not by the reference's code.
_TORCH_DTYPE_TO_NP: Dict[str, Any] = {
    "torch.float32": np.dtype(np.float32),
    "torch.float": np.dtype(np.float32),
    "torch.float64": np.dtype(np.float64),
    "torch.double": np.dtype(np.float64),
    "torch.float16": np.dtype(np.float16),
    "torch.half": np.dtype(np.float16),
    "torch.int8": np.dtype(np.int8),
    "torch.int16": np.dtype(np.int16),
    "torch.short": np.dtype(np.int16),
    "torch.int32": np.dtype(np.int32),
    "torch.int": np.dtype(np.int32),
    "torch.int64": np.dtype(np.int64),
    "torch.long": np.dtype(np.int64),
    "torch.uint8": np.dtype(np.uint8),
    "torch.bool": np.dtype(np.bool_),
    "torch.complex64": np.dtype(np.complex64),
    "torch.complex128": np.dtype(np.complex128),
}

try:  # bf16 / fp8 arrive via ml_dtypes (ships with jax)
    import ml_dtypes

    _TORCH_DTYPE_TO_NP["torch.bfloat16"] = np.dtype(ml_dtypes.bfloat16)
    _TORCH_DTYPE_TO_NP["torch.float8_e4m3fn"] = np.dtype(ml_dtypes.float8_e4m3fn)
    _TORCH_DTYPE_TO_NP["torch.float8_e5m2"] = np.dtype(ml_dtypes.float8_e5m2)
except (ImportError, AttributeError):  # pragma: no cover
    pass

_QUANTIZED_SERIALIZERS = frozenset(
    ["per_tensor_affine_qtensor", "per_channel_affine_qtensor"]
)


def _torch_dtype_to_np(dtype_str: str) -> np.dtype:
    try:
        return _TORCH_DTYPE_TO_NP[dtype_str]
    except KeyError:
        raise ValueError(
            f"Cannot map torch dtype {dtype_str!r} to a numpy dtype. "
            "Quantized dtypes have no JAX equivalent; other dtypes may "
            "need an ml_dtypes upgrade."
        ) from None


def read_metadata(path: str) -> Dict[str, Any]:
    """Parse a reference snapshot's ``.snapshot_metadata`` YAML.

    Returns ``{"version": str, "world_size": int, "manifest": {path: entry}}``
    with manifest insertion order preserved (the reference relies on YAML
    document order for list reconstruction).
    """
    import yaml

    with open(os.path.join(path, SNAPSHOT_METADATA_FILENAME), "rb") as f:
        meta = yaml.safe_load(f.read())
    if not isinstance(meta, dict) or "manifest" not in meta:
        raise ValueError(f"{path} does not look like a torchsnapshot snapshot")
    return meta


def _read_file(path: str, location: str, byte_range: Optional[List[int]]) -> bytes:
    with open(os.path.join(path, location), "rb") as f:
        if byte_range is None:
            return f.read()
        f.seek(byte_range[0])
        return f.read(byte_range[1] - byte_range[0])


def _decode_tensor(path: str, entry: Dict[str, Any]) -> np.ndarray:
    """Decode a reference ``Tensor`` entry into a writable numpy array."""
    serializer = entry["serializer"]
    if serializer == "buffer_protocol":
        dtype = _torch_dtype_to_np(entry["dtype"])
        shape = entry["shape"]
        nelem = int(np.prod(shape, dtype=np.int64)) if shape else 1
        byte_range = entry.get("byte_range")
        # np.fromfile reads straight into a fresh writable array (frombuffer
        # over read() bytes would yield a read-only view).
        with open(os.path.join(path, entry["location"]), "rb") as f:
            if byte_range is not None:
                f.seek(byte_range[0])
            arr = np.fromfile(f, dtype=dtype, count=nelem)
        if arr.size != nelem:
            raise ValueError(
                f"Payload {entry['location']!r} is truncated: expected "
                f"{nelem} elements of {dtype}, got {arr.size}"
            )
        return arr.reshape(shape)
    if serializer in _QUANTIZED_SERIALIZERS:
        raise ValueError(
            f"Entry at {entry['location']!r} is a quantized tensor "
            f"({serializer}); JAX has no quantized array type. Dequantize "
            "in torch before migrating."
        )
    if serializer != "torch_save":
        raise ValueError(f"Unknown serializer {serializer!r}")
    buf = _read_file(path, entry["location"], entry.get("byte_range"))
    import io as _io

    import torch

    # The payload is a bare tensor; weights_only keeps unpickling
    # restricted (no arbitrary-object gadgets from a hostile snapshot).
    t = torch.load(_io.BytesIO(buf), map_location="cpu", weights_only=True)
    return _torch_to_np(t)


def _torch_to_np(t: Any) -> np.ndarray:
    """torch.Tensor -> numpy, bridging dtypes numpy can't express natively.

    bf16/fp8 travel through the reference's torch_save serializer (they are
    not in its buffer-protocol dtype table), so they land here and need a
    bit-pattern reinterpret into their ml_dtypes equivalents.
    """
    import torch

    if t.dtype == torch.bfloat16:
        import ml_dtypes

        return t.view(torch.uint16).numpy().view(ml_dtypes.bfloat16)
    for torch_name, ml_name in (
        ("float8_e4m3fn", "float8_e4m3fn"),
        ("float8_e5m2", "float8_e5m2"),
        ("float8_e4m3fnuz", "float8_e4m3fnuz"),
        ("float8_e5m2fnuz", "float8_e5m2fnuz"),
    ):
        if hasattr(torch, torch_name) and t.dtype == getattr(torch, torch_name):
            import ml_dtypes

            return t.view(torch.uint8).numpy().view(getattr(ml_dtypes, ml_name))
    return t.numpy()


def _fill_region(
    out: np.ndarray, tensor: np.ndarray, offsets: List[int], sizes: List[int]
) -> None:
    idx = tuple(slice(o, o + s) for o, s in zip(offsets, sizes))
    out[idx] = tensor.reshape(sizes)


def _check_coverage(
    boxes: List[Tuple[Tuple[int, ...], Tuple[int, ...]]], shape: List[int], what: str
) -> None:
    """Require disjoint (offsets, sizes) boxes to tile ``shape`` exactly.

    Valid reference snapshots partition a tensor into disjoint chunks/
    shards; a missing box would otherwise leave uninitialized memory in
    the output (the arrays are allocated with np.empty).
    """
    covered = sum(int(np.prod(sz, dtype=np.int64)) for _, sz in boxes)
    total = int(np.prod(shape, dtype=np.int64)) if shape else 1
    if covered != total:
        raise ValueError(
            f"{what} cover {covered} of {total} elements of shape "
            f"{list(shape)}: the snapshot is missing or duplicating regions"
        )


def _decode_chunked(path: str, entry: Dict[str, Any]) -> np.ndarray:
    dtype = _torch_dtype_to_np(entry["dtype"])
    out = np.empty(entry["shape"], dtype=dtype)
    _check_coverage(
        [(tuple(c["offsets"]), tuple(c["sizes"])) for c in entry["chunks"]],
        entry["shape"],
        "chunks",
    )
    for chunk in entry["chunks"]:
        t = _decode_tensor(path, chunk["tensor"])
        _fill_region(out, t, chunk["offsets"], chunk["sizes"])
    return out


def _decode_sharded(path: str, shards: List[Dict[str, Any]]) -> np.ndarray:
    """Merge shards (gathered across all ranks) into one dense array.

    The reference's shard metadata carries no global shape, so it is
    inferred as the bounding box of the shards; the coverage check then
    rejects interior gaps. (Loss of ALL trailing shards is undetectable
    at this level — the bounding box shrinks with them — which is why
    ``_merge_for_rank`` separately verifies that every rank up to the
    manifest's world_size contributed entries.) Identical shards saved by
    multiple ranks are deduplicated by their box first.
    """
    if not shards:
        raise ValueError("ShardedTensor entry with no shards")
    dedup = {
        (tuple(s["offsets"]), tuple(s["sizes"])): s for s in shards
    }
    ndim = len(shards[0]["offsets"])
    full_shape = [
        max(off[d] + sz[d] for off, sz in dedup) for d in range(ndim)
    ]
    _check_coverage(list(dedup.keys()), full_shape, "shards")
    dtype = _torch_dtype_to_np(shards[0]["tensor"]["dtype"])
    out = np.empty(full_shape, dtype=dtype)
    for (offsets, sizes), shard in dedup.items():
        t = _decode_tensor(path, shard["tensor"])
        _fill_region(out, t, list(offsets), list(sizes))
    return out


def _decode_primitive(entry: Dict[str, Any]) -> Any:
    typ = entry["type"]
    val = entry["serialized_value"]
    if typ == "int":
        return int(val)
    if typ == "str":
        return str(val)
    if typ == "bool":
        return val == "True"
    if typ == "float":
        # Inlined as base64 little-endian IEEE-754 double for exactness
        # (reference manifest.py:146-242).
        return struct.unpack("<d", base64.b64decode(val))[0]
    if typ == "bytes":
        return base64.b64decode(val)
    raise ValueError(f"Unknown primitive type {typ!r}")


def _decode_object(path: str, entry: Dict[str, Any], convert_tensors: bool) -> Any:
    import io as _io

    import torch

    buf = _read_file(path, entry["location"], entry.get("byte_range"))
    obj = torch.load(_io.BytesIO(buf), map_location="cpu", weights_only=False)
    if convert_tensors:
        obj = _convert_tensors_to_np(obj)
    return obj


def _convert_tensors_to_np(obj: Any) -> Any:
    import torch

    if isinstance(obj, torch.Tensor):
        return _torch_to_np(obj)
    if isinstance(obj, (dict, OrderedDict)):
        return type(obj)((k, _convert_tensors_to_np(v)) for k, v in obj.items())
    if isinstance(obj, (list, tuple)):
        return type(obj)(_convert_tensors_to_np(v) for v in obj)
    return obj


def _merge_for_rank(
    manifest: Dict[str, Dict[str, Any]], rank: int, world_size: Optional[int] = None
) -> "OrderedDict[str, Dict[str, Any]]":
    """Compute the logical-path view for ``rank``, reference-style.

    Mirrors the availability rules of reference manifest.py:324-382:
    per-rank entries come from ``rank``'s prefix only; replicated entries
    from any rank (first wins — the gather step already deduplicated their
    chunk lists); ShardedTensor shards are merged across *all* ranks.
    Container entries come only from ``rank``'s own prefix — foreign
    containers would surface other ranks' private subtrees as phantom
    empty dicts (the reference drops all foreign containers too).
    """
    merged: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
    sharded: Dict[str, List[Dict[str, Any]]] = {}
    seen_ranks: set = set()
    for key, entry in manifest.items():
        owner_str, _, logical = key.partition("/")
        try:
            owner = int(owner_str)
        except ValueError:
            raise ValueError(f"Manifest key {key!r} lacks a rank prefix") from None
        seen_ranks.add(owner)
        if entry["type"] == "ShardedTensor":
            sharded.setdefault(logical, []).extend(entry["shards"])
            merged.setdefault(logical, {"type": "ShardedTensor", "shards": []})
        elif entry["type"] in ("dict", "OrderedDict", "list"):
            if owner == rank:
                merged.setdefault(logical, entry)
        elif owner == rank or entry.get("replicated", False):
            merged.setdefault(logical, entry)
    if rank not in seen_ranks:
        raise ValueError(
            f"Rank {rank} did not save this snapshot (saved ranks: "
            f"{sorted(seen_ranks)}). Pass the rank whose state you want to "
            "materialize; sharded and replicated entries are identical "
            "from every saved rank's view."
        )
    if world_size is not None and seen_ranks != set(range(world_size)):
        # Without this, a snapshot that lost every entry of a trailing rank
        # would load with sharded tensors silently truncated to the
        # bounding box of the surviving shards.
        raise ValueError(
            f"Snapshot metadata says world_size={world_size} but entries "
            f"exist only for ranks {sorted(seen_ranks)}: the snapshot is "
            "incomplete (a rank's manifest entries were lost)."
        )
    for logical, shards in sharded.items():
        merged[logical]["shards"] = shards
    return merged


def load_torchsnapshot(
    path: str, rank: int = 0, convert_tensors: bool = True
) -> Dict[str, Any]:
    """Read a reference-format snapshot into nested Python/NumPy state.

    Returns ``{app_state_key: state}`` — e.g. a snapshot taken with
    ``Snapshot.take(path, {"model": model})`` in the reference yields
    ``{"model": <state dict>}`` with torch tensors as numpy arrays
    (bf16/fp8 via ml_dtypes, directly consumable by ``jnp.asarray``).

    ``rank`` selects which rank's per-rank entries to materialize;
    replicated entries and merged sharded tensors are visible to every
    rank, matching the reference's elasticity rules.

    .. warning:: Snapshots are code. ``object`` entries are arbitrary
       pickles and are unpickled with ``torch.load(weights_only=False)``
       — exactly what the reference's own restore does — so only load
       snapshots from sources you trust. Tensor payloads, by contrast,
       are decoded with ``weights_only=True`` / raw-byte reads and are
       safe on their own.
    """
    meta = read_metadata(path)
    view = _merge_for_rank(meta["manifest"], rank, meta.get("world_size"))

    # Reference paths escape only '%' and '/' (flatten.py:158-165); the
    # native flattener escapes every URL-special byte. Re-normalize each
    # token (unquote -> native escape) so the native inflate can be reused
    # as the container-reconstruction inverse.
    from ..flatten import _escape_key, inflate
    from ..manifest import DictEntry, ListEntry, OrderedDictEntry

    def normalize(logical: str) -> str:
        return "/".join(_escape_key(unquote(t)) for t in logical.split("/"))

    leaves: Dict[str, Any] = {}
    containers: Dict[str, Any] = {}
    root_keys: List[str] = []
    for logical, entry in view.items():
        typ = entry["type"]
        norm = normalize(logical)
        if "/" not in logical:
            key = unquote(logical)
            if key not in root_keys:
                root_keys.append(key)
        if typ == "dict":
            containers[norm] = DictEntry(keys=list(entry.get("keys") or []))
        elif typ == "OrderedDict":
            containers[norm] = OrderedDictEntry(keys=list(entry.get("keys") or []))
        elif typ == "list":
            containers[norm] = ListEntry()
        elif typ == "Tensor":
            leaves[norm] = _decode_tensor(path, entry)
        elif typ == "ChunkedTensor":
            leaves[norm] = _decode_chunked(path, entry)
        elif typ == "ShardedTensor":
            leaves[norm] = _decode_sharded(path, entry["shards"])
        elif typ == "object":
            leaves[norm] = _decode_object(path, entry, convert_tensors)
        else:
            leaves[norm] = _decode_primitive(entry)

    containers[""] = DictEntry(keys=root_keys)
    return inflate(containers, leaves, prefix="")


_NP_TO_TORCH_DTYPE: Dict[Any, str] = {}
for _torch_name, _np_dtype in _TORCH_DTYPE_TO_NP.items():
    _NP_TO_TORCH_DTYPE.setdefault(_np_dtype, _torch_name)
# The reference has no fp8 support at all (its serialization dtype table
# predates fp8), so fp8 exports are written via torch_save: OUR
# load_torchsnapshot round-trips them, but the reference library rejects
# the dtype on restore either way. Migrating fp8 state to the reference
# requires casting it to a dtype the reference knows first.
_REFERENCE_BUFFER_PROTOCOL_UNSUPPORTED = frozenset(
    name for name in _NP_TO_TORCH_DTYPE.values() if "float8" in name
)


def _export_primitive(value: Any) -> Optional[Dict[str, Any]]:
    if isinstance(value, bool):  # before int: bool is an int subclass
        return {"type": "bool", "serialized_value": str(value)}
    if isinstance(value, int):
        return {"type": "int", "serialized_value": str(value)}
    if isinstance(value, float):
        return {
            "type": "float",
            "serialized_value": base64.b64encode(struct.pack("<d", value)).decode(),
        }
    if isinstance(value, str):
        return {"type": "str", "serialized_value": value}
    if isinstance(value, bytes):
        return {"type": "bytes", "serialized_value": base64.b64encode(value).decode()}
    return None


def _escape_ref_key(key: str) -> str:
    # The reference escapes only '%' then '/' (flatten.py:158-161).
    return key.replace("%", "%25").replace("/", "%2F")


def save_as_torchsnapshot(state: Dict[str, Any], path: str) -> None:
    """Write ``state`` in the REFERENCE's on-disk format (world size 1).

    The inverse of :func:`load_torchsnapshot`: the resulting directory is a
    valid pytorch/torchsnapshot snapshot the reference library restores
    directly — the exit ramp matching the orbax trick's two-way migration.

    ``state`` maps app-state keys to nested dict/OrderedDict/list
    structures of numpy arrays (bf16 via ml_dtypes export as
    buffer-protocol bytes, exactly how the reference writes them; fp8 via
    torch_save — readable by :func:`load_torchsnapshot` only, since the
    reference predates fp8 dtypes), jax arrays (fetched to host;
    single-process view), Python primitives, and arbitrary picklable
    objects (``torch.save``-serialized, so the reference can load them).

    Payloads stream to disk as the state is walked — peak memory is one
    payload, not the whole checkpoint.
    """
    import numpy as _np
    import yaml

    manifest: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
    used_locations: set = set()

    def claim_location(preferred: str) -> str:
        # Sibling entries can alias (array 'w' at '0/w_0' vs object 'w_0'
        # at '0/w_0'); a written payload must never be overwritten.
        location = preferred
        n = 0
        while location in used_locations:
            n += 1
            location = f"{preferred}~{n}"
        used_locations.add(location)
        return location

    def write_payload(location: str, blob: bytes) -> None:
        full = os.path.join(path, location)
        os.makedirs(os.path.dirname(full), exist_ok=True)
        with open(full, "wb") as f:
            f.write(blob)

    def write_torch_save(location: str, value: Any) -> None:
        import torch

        full = os.path.join(path, location)
        os.makedirs(os.path.dirname(full), exist_ok=True)
        with open(full, "wb") as f:
            torch.save(value, f)

    def visit(logical: str, value: Any) -> None:
        if isinstance(value, OrderedDict):
            manifest[logical] = {
                "type": "OrderedDict", "keys": list(value.keys())
            }
            for k, v in value.items():
                visit(f"{logical}/{_escape_ref_key(str(k))}", v)
            return
        if isinstance(value, dict):
            manifest[logical] = {"type": "dict", "keys": list(value.keys())}
            for k, v in value.items():
                visit(f"{logical}/{_escape_ref_key(str(k))}", v)
            return
        if isinstance(value, list):
            manifest[logical] = {"type": "list"}
            for i, v in enumerate(value):
                visit(f"{logical}/{i}", v)
            return
        prim = _export_primitive(value)
        if prim is not None:
            manifest[logical] = {**prim, "readable": None, "replicated": False}
            return
        if hasattr(value, "shape") and hasattr(value, "dtype") and not isinstance(
            value, (bytes, str)
        ):
            arr = _np.asarray(value)  # jax arrays fetched to host here
            torch_dtype = _NP_TO_TORCH_DTYPE.get(arr.dtype)
            if torch_dtype is not None and torch_dtype not in (
                _REFERENCE_BUFFER_PROTOCOL_UNSUPPORTED
            ):
                location = claim_location(
                    f"{logical}_" + "_".join("0" for _ in arr.shape or [0])
                )
                write_payload(location, _np.ascontiguousarray(arr).tobytes())
                tensor_entry = {
                    "type": "Tensor",
                    "location": location,
                    "serializer": "buffer_protocol",
                    "dtype": torch_dtype,
                    "shape": list(arr.shape),
                    "replicated": False,
                    "byte_range": None,
                }
                # Mirror the reference's non-sharded layout: one
                # ChunkedTensor entry holding a single whole-array chunk.
                manifest[logical] = {
                    "type": "ChunkedTensor",
                    "dtype": torch_dtype,
                    "shape": list(arr.shape),
                    "chunks": [
                        {
                            "offsets": [0] * len(arr.shape),
                            "sizes": list(arr.shape),
                            "tensor": tensor_entry,
                        }
                    ],
                    "replicated": False,
                }
                return
            # fp8 / exotic dtypes: export as a torch_save tensor payload.
            location = claim_location(logical)
            write_torch_save(location, _to_torch(arr))
            manifest[logical] = {
                "type": "Tensor",
                "location": location,
                "serializer": "torch_save",
                "dtype": torch_dtype or str(arr.dtype),
                "shape": list(arr.shape),
                "replicated": False,
                "byte_range": None,
            }
            return
        location = claim_location(logical)
        write_torch_save(location, value)
        manifest[logical] = {
            "type": "object",
            "location": location,
            "serializer": "torch_save",
            "obj_type": f"{type(value).__module__}.{type(value).__qualname__}",
            "replicated": False,
        }

    os.makedirs(path, exist_ok=True)
    for app_key in state:
        visit(f"0/{_escape_ref_key(str(app_key))}", state[app_key])

    # Metadata last: a partially exported directory is never mistaken for a
    # complete snapshot (the reference's own commit-point rule).
    meta = {"version": "0.0.3", "world_size": 1, "manifest": dict(manifest)}
    with open(os.path.join(path, SNAPSHOT_METADATA_FILENAME), "w") as f:
        yaml.safe_dump(meta, f, sort_keys=False)


def _to_torch(arr: Any):
    """numpy -> torch, bridging ml_dtypes the way _torch_to_np reverses."""
    import numpy as _np

    import torch

    arr = _np.ascontiguousarray(arr)
    name = _NP_TO_TORCH_DTYPE.get(arr.dtype)
    if name and "float8" in name:
        t = torch.from_numpy(arr.view(_np.uint8).copy())
        return t.view(getattr(torch, name.split(".", 1)[1])).reshape(arr.shape)
    if name == "torch.bfloat16":
        t = torch.from_numpy(arr.view(_np.uint16).copy())
        return t.view(torch.bfloat16).reshape(arr.shape)
    return torch.from_numpy(arr.copy())


def migrate_to_torchsnapshot(src_path: str, dst_path: str, rank: int = 0) -> None:
    """Convert a NATIVE snapshot into the reference's on-disk format.

    Reads ``src_path`` structure-free (``Snapshot.read_state_dict``) and
    writes it with :func:`save_as_torchsnapshot`, so a user leaving for
    (or round-tripping through) the reference keeps their checkpoints.
    """
    from .. import Snapshot

    state = Snapshot(src_path).read_state_dict(rank=rank)
    save_as_torchsnapshot(state, dst_path)


def migrate_from_torchsnapshot(
    src_path: str, dst_path: str, rank: int = 0
) -> Tuple[Any, Dict[str, Any]]:
    """Convert a reference-format snapshot into a native snapshot.

    Reads ``src_path`` (reference on-disk format) and takes a native
    snapshot at ``dst_path`` with the same app-state keys. Returns
    ``(Snapshot, state)`` so callers can inspect what was migrated.
    """
    from .. import Snapshot, StateDict

    state = load_torchsnapshot(src_path, rank=rank)
    app_state = {
        # StateDict(mapping), not StateDict(**mapping): loaded dicts may
        # have non-string (int) top-level keys.
        key: StateDict(val) if isinstance(val, dict) else StateDict(value=val)
        for key, val in state.items()
    }
    return Snapshot.take(dst_path, app_state), state
