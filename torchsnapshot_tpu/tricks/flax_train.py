"""Stateful adapters for flax TrainState and generic pytrees.

The reference's trick adapts a third-party engine whose state object is not
itself Stateful (DeepSpeedEngine + ZeRO-3 optimizer, tricks/deepspeed.py:
30-66): the adapter exposes state_dict/load_state_dict and reinstalls the
restored state into the engine. The flax analogue: ``TrainState`` is an
immutable pytree dataclass, so the adapter holds the current state and
*replaces* it on load — callers read ``adapter.state`` after restore.
"""

from __future__ import annotations

from typing import Any, Dict


class FlaxTrainStateAdapter:
    """Checkpoint a ``flax.training.train_state.TrainState`` (or any flax
    struct dataclass) through Snapshot.

    Uses ``flax.serialization.to_state_dict``/``from_state_dict`` so the
    on-disk layout is nested dicts of arrays — readable via ``read_object``
    and stable under flax's own serialization rules. Non-array fields
    (``apply_fn``, ``tx``) are structural and never stored.
    """

    def __init__(self, state: Any) -> None:
        self.state = state

    def state_dict(self) -> Dict[str, Any]:
        from flax import serialization

        return serialization.to_state_dict(self.state)

    def load_state_dict(self, state_dict: Dict[str, Any]) -> None:
        from flax import serialization

        self.state = serialization.from_state_dict(self.state, state_dict)


class PytreeAdapter:
    """Checkpoint an arbitrary pytree (haiku params, custom nodes, ...).

    Leaves are stored under their ``jax.tree_util.keystr`` paths, so the
    manifest stays human-readable and entries survive structural no-ops.
    The destination tree must have the same treedef at restore time; the
    restored tree replaces ``self.tree``.
    """

    def __init__(self, tree: Any) -> None:
        self.tree = tree

    def state_dict(self) -> Dict[str, Any]:
        import jax

        flat, _ = jax.tree_util.tree_flatten_with_path(self.tree)
        return {jax.tree_util.keystr(path): leaf for path, leaf in flat}

    def load_state_dict(self, state_dict: Dict[str, Any]) -> None:
        import jax

        flat, treedef = jax.tree_util.tree_flatten_with_path(self.tree)
        leaves = [state_dict[jax.tree_util.keystr(path)] for path, _ in flat]
        self.tree = jax.tree_util.tree_unflatten(treedef, leaves)
