"""Retention planning for snapshot directories.

Shared by the ``prune`` CLI and :class:`~torchsnapshot_tpu.manager.
CheckpointManager`: given the snapshots the caller wants to KEEP, compute
which others must be SPARED anyway (transitive bases of kept incremental
snapshots — deleting one would break restore) and which are safe to
delete. Base matching verifies payload-content checksums from the
manifests, not mere path/name/file existence — an unrelated snapshot of
the same model occupying a base's old path must never be spared in its
place (see cli.py's prune tests for the attack shapes).

A directory "snapshot" here is a subdirectory holding a committed
``.snapshot_metadata``; ordering is metadata mtime (name-tiebroken).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Callable, List, Sequence, Set, Tuple, Union

# The shared content-addressed payload pool's directory name (mirrored
# from tenancy.pool to avoid a package cycle; pinned by test_tenancy).
POOL_DIRNAME = ".tsnap_pool"


@dataclass
class RetentionPlan:
    """What survives and what may be deleted under a retention policy."""

    keep: List[str]                       # caller-requested survivors
    spared: List[Tuple[str, bool]]        # (name, matched_by_basename)
    doomed: List[str]                     # deletable, oldest first
    # Origins of kept snapshots that resolve to NO verified snapshot in
    # the directory: deletion cannot be proven safe while these exist.
    unresolved: Set[str] = field(default_factory=set)


KeepPolicy = Union[int, Callable[[Sequence[str]], Set[str]]]


def plan_retention(dirpath: str, keep: KeepPolicy) -> RetentionPlan:
    """Plan deletion of snapshots under ``dirpath`` not kept by ``keep``
    and not a (transitively) required base of a kept one.

    ``keep`` is either the number of NEWEST snapshots to keep, or a
    callable receiving the scanned names (mtime-ascending) and returning
    the set to keep. The policy is evaluated on the SAME directory scan
    the plan is built from — a snapshot that commits concurrently is
    either in both or in neither, never discovered-but-unprotected."""
    from .cli import _canon_snapshot_url, _scan_snapshot_dir

    names, origins_of, origin_locations_of, payloads_of = _scan_snapshot_dir(
        dirpath
    )
    if callable(keep):
        keep = set(keep(names)) & set(names)
    else:
        keep = set(names[-int(keep):]) if keep else set()
    canon_of = {
        name: _canon_snapshot_url(os.path.join(dirpath, name)) for name in names
    }
    name_of_canon = {c: n for n, c in canon_of.items()}

    # Every surviving snapshot's restore closure must survive. Origins
    # name each payload's physical writer directly, but a SPARED base's
    # own payloads can reference yet another snapshot the kept set never
    # mentions — the required set is a transitive closure via a worklist.
    required_names: Set[str] = set()
    by_name_matches: Set[str] = set()
    unresolved: Set[str] = set()
    frontier = list(keep)
    visited: Set[str] = set()
    while frontier:
        name = frontier.pop()
        if name in visited:
            continue
        visited.add(name)
        for origin in origins_of.get(name, ()):
            canon = _canon_snapshot_url(origin)
            if os.path.basename(canon.rstrip("/")) == POOL_DIRNAME:
                # Cross-tenant payload pool (tenancy/pool.py): pooled
                # payloads are protected by their own refcounts — the
                # manager releases a doomed step's refs before deletion
                # — not by sparing snapshots. The pool is not a
                # snapshot; resolving it here would flag every swept
                # chain unresolved and freeze retention.
                continue
            locations = origin_locations_of.get(name, {}).get(origin, {})

            def _holds_payloads(candidate: str) -> bool:
                # Identity, not identity of path/name or mere file
                # existence: compare the content checksums the kept
                # snapshot's deduplicated entries recorded against the
                # candidate's own manifest; checksum-less legacy
                # snapshots fall back to size + file existence.
                cand = payloads_of.get(candidate, {})
                if not locations:
                    return False
                for loc, (csum, nbytes) in locations.items():
                    have = cand.get(loc)
                    if have is None:
                        return False
                    have_csum, have_nbytes = have
                    if csum is not None and have_csum is not None:
                        if csum != have_csum:
                            return False
                    elif (
                        nbytes is not None
                        and have_nbytes is not None
                        and nbytes != have_nbytes
                    ):
                        return False
                    if not os.path.isfile(
                        os.path.join(dirpath, candidate, loc)
                    ):
                        return False
                return True

            base_name = name_of_canon.get(canon)
            if base_name is not None and not _holds_payloads(base_name):
                base_name = None
            if base_name is None:
                # Origins record absolute realpaths at take time: after a
                # tree move (or a different mount path) they resolve to
                # nothing here — a same-basename snapshot holding the
                # referenced payloads is the moved base.
                tail = os.path.basename(canon.rstrip("/"))
                if tail in origins_of and _holds_payloads(tail):
                    base_name = tail
                    by_name_matches.add(tail)
            if base_name is None:
                unresolved.add(canon)
                continue
            required_names.add(base_name)
            if base_name not in visited:
                frontier.append(base_name)

    spared: List[Tuple[str, bool]] = []
    doomed: List[str] = []
    for name in names:
        if name in keep:
            continue
        if name in required_names:
            spared.append((name, name in by_name_matches))
        else:
            doomed.append(name)
    return RetentionPlan(
        keep=sorted(keep),
        spared=spared,
        doomed=doomed,
        unresolved=unresolved,
    )


def apply_retention(dirpath: str, plan: RetentionPlan) -> int:
    """Delete the plan's doomed snapshots; returns how many. The caller
    decides policy for ``plan.unresolved`` (refuse / warn / proceed)."""
    import shutil

    for name in plan.doomed:
        shutil.rmtree(os.path.join(dirpath, name))
    return len(plan.doomed)
