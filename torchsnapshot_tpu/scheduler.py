"""Memory-budgeted async execution engine for write/read requests.

TPU-native redesign of the reference scheduler (torchsnapshot/scheduler.py):
two asyncio pipelines under a per-process host-memory budget.

Write pipeline::

    ready_for_staging -> staging -> ready_for_io -> io -> done

Staging performs the device->host boundary crossing (for jax.Arrays the
stager issues ``copy_to_host_async`` DMA and materializes a numpy view) and
serialization; it is capped by the memory budget, with a starvation escape
that admits one over-budget request when nothing is in flight (otherwise a
single huge array could deadlock the pipeline; reference: scheduler.py:255-275).
I/O concurrency is capped at 16 in-flight requests (scheduler.py:30).

``execute_write_reqs`` returns a :class:`PendingIOWork` as soon as **staging**
completes — this is the consistency point that lets ``async_take`` guarantee
that mutations after it returns do not affect the snapshot, while storage I/O
continues in the background (reference: scheduler.py:297-337).

Read pipeline:: read -> consume, with the same budget accounting
(scheduler.py:384-444).

**Streaming reads** (``TORCHSNAPSHOT_TPU_STREAM_READS``, default on):
entries whose consumer and storage plugin both opt in skip the
read-everything-then-consume two-step — the plugin yields sub-chunks as
the transport delivers them (fs: pread windows with read-ahead; s3/gcs:
a bounded window of in-flight ranged GETs yielded in order) and the
consumer verifies chained CRC32C incrementally, feeds decompression
incrementally, and issues per-sub-chunk ``jax.device_put`` — HtoD of
chunk N overlaps the read of chunk N+1, collapsing a large entry's
restore wall toward ~max(read, consume). The budget charges streamed
entries the consumer-declared retention (``stream_admission_cost`` —
the in-flight window for device-bound and direct-fill consumers), not
their full consuming cost, so large single-entry restores stop
serializing behind the budget.

The per-process budget is ``min(0.6 * available_memory / local_world_size,
32 GiB)``, overridable via ``TORCHSNAPSHOT_TPU_PER_RANK_MEMORY_BUDGET_BYTES``
(scheduler.py:27-65).

**Streaming writes** (``allow_streaming``, sync saves only): entries whose
stager and storage plugin both opt in skip the stage-then-write two-step —
one task streams 32-256 MB sub-chunks from the stager straight into the
plugin, overlapping the DtoH copy/serialization of sub-chunk N+1 with the
storage write of sub-chunk N, so a single large entry's wall is
~max(stage, write) instead of stage + write. The budget charges streamed
entries the plugin-declared retention (``stream_admission_cost`` — the
stager's 2-chunk window for fs, part buffers for s3, the retained stream
for gcs), not their full staging size.

**Cooperative restore fan-out** (fanout.py): when a multi-rank restore
engages cooperation, each read request carries a role — owners read from
storage and FORWARD every sub-chunk to subscribing peers over the peer
byte channel (one-send lookahead, so forwarding rides under the local
decode), peer-fed entries consume forwarded sub-chunks through the same
streaming consumers a storage stream feeds (full CRC re-verified on the
receiver), and any peer failure degrades that entry to a direct storage
read with the budget re-charged. Peer-fed entries are exempt from the
I/O slot cap (they issue no storage request) and dispatch first so
receiver-side buffering stays bounded by the owners' read speed.

**I/O governor** (:class:`IOGovernor`): sub-chunk size, I/O concurrency,
and the restore-side preverify gate adapt to rates this module measures on
its own traffic (per-plugin write/read bandwidth) plus the fingerprint
hash throughput recorded by warmup — static constants tuned for one host
class are wrong on the next.
"""

from __future__ import annotations

import asyncio
import logging
import os
import socket
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Set

import psutil

from . import faultinject, telemetry
from . import autotune as _autotune
from .telemetry import forensics
from .io_types import (
    ReadIO,
    ReadReq,
    ReadStream,
    StoragePlugin,
    StreamRestartRequired,
    WriteIO,
    WriteReq,
    WriteStream,
)

logger = logging.getLogger(__name__)

def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "").strip()
    if raw:
        try:
            return max(1, int(raw))
        except ValueError:
            logger.warning("ignoring non-integer %s=%r", name, raw)
    return default


try:
    # Respects cgroup cpusets/affinity masks: a pod limited to 2 cores on
    # a 64-core node must get the few-core defaults, not 64's.
    _CPU_COUNT = len(os.sched_getaffinity(0)) or 1
except (AttributeError, OSError):  # pragma: no cover - non-Linux
    _CPU_COUNT = os.cpu_count() or 1
IO_CONCURRENCY_ENV_VAR = "TORCHSNAPSHOT_TPU_IO_CONCURRENCY"
CPU_CONCURRENCY_ENV_VAR = "TORCHSNAPSHOT_TPU_CPU_CONCURRENCY"
# I/O concurrency lives in IOGovernor.io_concurrency (host-scaled
# default, adapted to measured storage bandwidth, pinned by
# IO_CONCURRENCY_ENV_VAR).
_MAX_PER_RANK_CPU_CONCURRENCY = _env_int(
    CPU_CONCURRENCY_ENV_VAR, min(4, max(2, _CPU_COUNT // 2))
)
_AVAILABLE_MEMORY_MULTIPLIER = 0.6
_MAX_PER_RANK_MEMORY_BUDGET_BYTES = 32 * 1024**3
_MEMORY_BUDGET_ENV_VAR = "TORCHSNAPSHOT_TPU_PER_RANK_MEMORY_BUDGET_BYTES"

# ------------------------------------------------------------ I/O governor

SUB_CHUNK_ENV_VAR = "TORCHSNAPSHOT_TPU_SUB_CHUNK_BYTES"
SUB_CHUNK_MIN_ENV_VAR = "TORCHSNAPSHOT_TPU_SUB_CHUNK_MIN_BYTES"
SUB_CHUNK_MAX_ENV_VAR = "TORCHSNAPSHOT_TPU_SUB_CHUNK_MAX_BYTES"
PREVERIFY_ENV_VAR = "TORCHSNAPSHOT_TPU_PREVERIFY"
STREAM_READS_ENV_VAR = "TORCHSNAPSHOT_TPU_STREAM_READS"

# Measured read bandwidth below which storage counts as latency-bound:
# streamed reads then pay off even for consumers that retain the whole
# payload (the overlap hides transport latency). At/above it, local
# page-cache reads are memcpy-speed and the buffered mmap path's fewer
# copies win for those consumers. Same 1 GB/s knee io_concurrency uses.
_STREAM_READ_LATENCY_BPS = 1e9


def stream_reads_mode() -> str:
    """THE parser for ``TORCHSNAPSHOT_TPU_STREAM_READS``: ``never``
    disables streamed reads, ``always`` streams every eligible entry,
    and the default ``auto`` streams an entry when doing so buys
    something — a smaller budget charge than the buffered consume
    (device-bound, sliced, and coalesced-slab consumers), or measured
    latency-bound storage where read/consume overlap hides transport
    latency even at full retention."""
    raw = os.environ.get(STREAM_READS_ENV_VAR, "auto").strip().lower()
    if raw in ("0", "false", "off", "no", "never"):
        return "never"
    if raw in ("always", "force"):
        return "always"
    return "auto"


def stream_reads_enabled() -> bool:
    return stream_reads_mode() != "never"

_DEFAULT_SUB_CHUNK_BYTES = 64 << 20
_DEFAULT_SUB_CHUNK_MIN_BYTES = 8 << 20
_DEFAULT_SUB_CHUNK_MAX_BYTES = 256 << 20
# Sub-chunks should take this long to write at the measured bandwidth:
# long enough to amortize per-chunk dispatch (executor hops, pwrite
# syscalls), short enough that the stage/write pipeline has several
# stages in flight per entry.
_SUB_CHUNK_TARGET_SECONDS = 0.05
# Skip the preverify hash pass only when reading is CLEARLY cheaper:
# the margin absorbs rate-measurement noise and the HtoD cost a read
# still pays after the storage fetch.
_PREVERIFY_READ_MARGIN = 1.25
# Depose an elected native engine only when its measured rate falls
# clearly below the plugin's non-native rate — hysteresis against the
# two meters' different windows (whole pipeline vs one stream).
_NATIVE_FALLBACK_MARGIN = 0.75
# Dead band around a boolean gate's knee: once a should_* election is
# made, the measured rate must cross the knee by this fraction to flip
# it back — a rate hovering at the knee (EWMA jitter) must not flip-flop
# a fast path on and off between consecutive ops.
_KNEE_MARGIN = 0.10
# Hard cap for tuned/heuristic I/O concurrency: the autotuner's climb
# must stay inside the range the pipeline was designed for (an explicit
# env pin may still exceed it).
_IO_CONCURRENCY_CAP = 32

# The closed-loop autotune mode parser lives with the controller
# (autotune.py); re-exported here because the governor is its consumer.
AUTOTUNE_ENV_VAR = _autotune.AUTOTUNE_ENV_VAR
autotune_mode = _autotune.autotune_mode

#: Every env knob consulted by an IOGovernor election site — the knobs
#: whose role shifted from "the tuning interface" to "operator override
#: above the learned profiles". The envreg tsalint pass cross-checks
#: this set against ENV_GOVERNANCE (analysis/plugins/envreg.py): each
#: knob must declare whether it overrides elections, bounds them, or
#: switches the tuner itself.
ELECTION_KNOBS = frozenset({
    "TORCHSNAPSHOT_TPU_SUB_CHUNK_BYTES",
    "TORCHSNAPSHOT_TPU_SUB_CHUNK_MIN_BYTES",
    "TORCHSNAPSHOT_TPU_SUB_CHUNK_MAX_BYTES",
    "TORCHSNAPSHOT_TPU_IO_CONCURRENCY",
    "TORCHSNAPSHOT_TPU_PREVERIFY",
    "TORCHSNAPSHOT_TPU_STREAM_READS",
    "TORCHSNAPSHOT_TPU_STREAM_WRITES",
    "TORCHSNAPSHOT_TPU_NATIVE_IO",
    "TORCHSNAPSHOT_TPU_COOP_RESTORE",
    "TORCHSNAPSHOT_TPU_RESHARD",
    "TORCHSNAPSHOT_TPU_SEED_RESTORE",
    "TORCHSNAPSHOT_TPU_AUTOTUNE",
})


class IOGovernor:
    """Process-wide adaptive tuner for the save/restore hot path.

    Static constants tuned for one host class are wrong on the next
    (1-core CI box vs 64-core pod host vs network storage): the governor
    records ACHIEVED rates — per-plugin storage write/read bandwidth
    (from the scheduler's own throughput meters) and on-device hash
    throughput (from the fingerprint warmup / a one-time probe) — and
    derives the tunables from them, within env-var bounds:

    - ``sub_chunk_bytes``: streaming sub-chunk size, sized so one
      sub-chunk takes ~``_SUB_CHUNK_TARGET_SECONDS`` to write at the
      measured bandwidth (fast local storage gets big chunks that
      amortize syscalls; slow network storage gets small chunks that
      keep the pipeline busy). Pinned by ``TORCHSNAPSHOT_TPU_SUB_CHUNK_BYTES``.
    - ``io_concurrency``: in-flight storage requests. Bandwidth-bound
      local storage saturates with few streams (extra ones thrash the
      cache hierarchy); latency-bound network storage needs many.
      Pinned by ``TORCHSNAPSHOT_TPU_IO_CONCURRENCY``.
    - ``should_preverify``: whether restore-time distributed digest
      verification is cheaper than just re-reading (VERDICT round-5
      item 6) — hashing wins on slow storage, reading wins on fast
      local disk with a slow hasher. ``TORCHSNAPSHOT_TPU_PREVERIFY``
      forces ``always``/``never``; default ``auto`` verifies unless
      reading is provably cheaper (missing measurements keep the
      status-quo verify).

    Rates are exponentially smoothed (alpha 0.5): one anomalous save
    (page-cache flush, noisy neighbor) moves a tunable halfway at most,
    and the next clean measurement pulls it back.

    **Closed loop** (ROADMAP item 4, ``TORCHSNAPSHOT_TPU_AUTOTUNE``):
    every election site resolves env override -> learned profile ->
    measured-rate heuristic, through one shared :class:`autotune.
    Election` record. The controller (autotune.AutoTuner) perturbs at
    most one tunable per operation, scores it against the critical-path
    verdict fed back by ``observe_verdict`` after commit, and persists
    converged settings per ``(storage class, world size, binding
    category)`` into the root's history journal — ``load_profiles``
    warm-starts a fresh process from them.
    """

    _EWMA_ALPHA = 0.5

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._write_bps: Dict[str, float] = {}
        self._read_bps: Dict[str, float] = {}
        self._hash_bps: Optional[float] = None
        self._tuner = _autotune.AutoTuner()
        #: Last Election per (dim, plugin): the decision-change detector
        #: that keeps ``governor.elect`` flight events to transitions
        #: (io_concurrency is consulted inside dispatch loops).
        self._elections: Dict[Any, _autotune.Election] = {}
        #: Boolean gate memory for the knee dead band (_banded).
        self._gate_state: Dict[Any, bool] = {}
        #: Roots whose profile records were already loaded (once each).
        self._profile_roots: Set[str] = set()

    # ------------------------------------------------------- recording

    def _ewma(self, table: Dict[str, float], key: str, bps: float) -> None:
        with self._lock:
            prev = table.get(key)
            table[key] = (
                bps
                if prev is None
                else prev + self._EWMA_ALPHA * (bps - prev)
            )

    def record_write(self, plugin: str, nbytes: int, seconds: float) -> None:
        if nbytes <= 0 or seconds <= 1e-6:
            return
        self._ewma(self._write_bps, plugin, nbytes / seconds)

    def record_read(self, plugin: str, nbytes: int, seconds: float) -> None:
        if nbytes <= 0 or seconds <= 1e-6:
            return
        self._ewma(self._read_bps, plugin, nbytes / seconds)

    def record_hash(self, nbytes: int, seconds: float) -> None:
        if nbytes <= 0 or seconds <= 1e-6:
            return
        bps = nbytes / seconds
        with self._lock:
            self._hash_bps = (
                bps
                if self._hash_bps is None
                else self._hash_bps + self._EWMA_ALPHA * (bps - self._hash_bps)
            )

    # ------------------------------------------------------- measured rates

    def write_bps(self, plugin: Optional[str] = None) -> Optional[float]:
        with self._lock:
            if plugin is not None:
                return self._write_bps.get(plugin)
            return max(self._write_bps.values()) if self._write_bps else None

    def read_bps(self, plugin: Optional[str] = None) -> Optional[float]:
        with self._lock:
            if plugin is not None:
                return self._read_bps.get(plugin)
            return max(self._read_bps.values()) if self._read_bps else None

    def hash_bps(self) -> Optional[float]:
        with self._lock:
            return self._hash_bps

    def measured_rates(self) -> Dict[str, object]:
        """Snapshot of every measured rate, for logs and benchmarks."""
        with self._lock:
            return {
                "write_bps": dict(self._write_bps),
                "read_bps": dict(self._read_bps),
                "hash_bps": self._hash_bps,
            }

    # -------------------------------------------------- election plumbing

    def _resolved(
        self,
        site: str,
        dim: str,
        plugin: Optional[str],
        value: Any,
        source: str,
        **inputs: Any,
    ) -> Any:
        """Every election site funnels its decision through here: one
        shared :class:`autotune.Election` record per (dim, plugin), a
        ``governor.elect`` flight event WHEN THE DECISION CHANGES (the
        hot dispatch loops re-consult io_concurrency; steady-state
        re-elections must not flood the ring), and the profile key
        attached when a learned profile or trial made the call."""
        profile = None
        if source in ("profile", "trial"):
            op = dim.rsplit(".", 1)[1] if "." in dim else "read"
            profile = self._tuner.key_for(plugin or "", op)
        election = _autotune.Election(
            site, dim, plugin, value, source, profile=profile, inputs=inputs
        )
        key = (dim, plugin or "")
        with self._lock:
            prev = self._elections.get(key)
            changed = (
                prev is None or prev.value != value or prev.source != source
            )
            self._elections[key] = election
        if changed:
            telemetry.record_election(**election.as_fields())
        return value

    def _banded(
        self, gate: str, plugin: Optional[str], rate: float, knee: float
    ) -> bool:
        """Knee comparison with a dead band: True while the rate is
        below the knee, but once a decision is made the rate must cross
        the knee by ``_KNEE_MARGIN`` to flip it — measurement jitter
        around the knee cannot flip-flop a fast path between ops."""
        key = (gate, plugin or "")
        with self._lock:
            prev = self._gate_state.get(key)
            if prev is None:
                decision = rate < knee
            elif prev:
                decision = rate < knee * (1.0 + _KNEE_MARGIN)
            else:
                decision = rate < knee * (1.0 - _KNEE_MARGIN)
            self._gate_state[key] = decision
        return decision

    def _tuned(self, dim: str, plugin: Optional[str], op: str):
        """Learned-profile / armed-trial resolution for one dimension,
        or None (cold start / autotune off). The ``never`` mode costs
        exactly this one env check."""
        if _autotune.autotune_mode() == "never":
            return None
        return self._tuner.resolve(dim, plugin or "", op)

    # ---------------------------------------------------------- tunables

    def sub_chunk_bytes(self, plugin: Optional[str] = None, op: str = "write") -> int:
        """Streaming sub-chunk size for ``op`` ("write"/"read") —
        env override > learned profile > sized from the MATCHING
        measured bandwidth (a fast local save must not size a later
        network restore's read windows, and vice versa)."""
        dim = f"sub_chunk.{op}"
        pinned = os.environ.get(SUB_CHUNK_ENV_VAR, "").strip()
        if pinned:
            try:
                # An explicit pin is honored as-is (tests pin tiny chunks
                # to exercise many-sub-chunk streams on small payloads).
                value = max(1, int(pinned))
            except ValueError:
                logger.warning(
                    "ignoring non-integer %s=%r", SUB_CHUNK_ENV_VAR, pinned
                )
            else:
                return self._resolved("sub_chunk", dim, plugin, value, "env")
        lo = _env_int(SUB_CHUNK_MIN_ENV_VAR, _DEFAULT_SUB_CHUNK_MIN_BYTES)
        hi = _env_int(SUB_CHUNK_MAX_ENV_VAR, _DEFAULT_SUB_CHUNK_MAX_BYTES)
        hi = max(lo, hi)
        tuned = self._tuned(dim, plugin, op)
        if tuned is not None:
            value, source = tuned
            try:
                value = int(value)
            except (TypeError, ValueError):
                value = _DEFAULT_SUB_CHUNK_BYTES
            # Learned values stay inside the env bounds (trials were
            # generated inside them; a profile learned under different
            # bounds is clamped into today's).
            return self._resolved(
                "sub_chunk", dim, plugin, min(max(value, lo), hi), source
            )
        bps = self.read_bps(plugin) if op == "read" else self.write_bps(plugin)
        if bps is None:
            return self._resolved(
                "sub_chunk", dim, plugin,
                min(max(_DEFAULT_SUB_CHUNK_BYTES, lo), hi), "heuristic",
            )
        target = int(bps * _SUB_CHUNK_TARGET_SECONDS)
        # Round to a 1 MB multiple: exact-size staging-pool free lists
        # recycle far better when sizes don't wander byte-by-byte.
        target = max(1 << 20, (target >> 20) << 20)
        return self._resolved(
            "sub_chunk", dim, plugin, min(max(target, lo), hi), "heuristic",
            bps=round(bps),
        )

    def io_concurrency(
        self, op: str = "write", plugin: Optional[str] = None
    ) -> int:
        """In-flight storage requests for ``op`` ("write"/"read") —
        env override > learned profile > tuned from the MATCHING
        measured rate (a fast local save must not clamp concurrency for
        a later latency-bound network restore, and vice versa), for
        ``plugin`` when it has a recorded rate."""
        dim = f"io_concurrency.{op}"
        raw = os.environ.get(IO_CONCURRENCY_ENV_VAR, "").strip()
        if raw:
            try:
                value = max(1, int(raw))
            except ValueError:
                pass  # warned at import time by _env_int
            else:
                return self._resolved("io_concurrency", dim, plugin, value, "env")
        tuned = self._tuned(dim, plugin, op)
        if tuned is not None:
            value, source = tuned
            try:
                value = int(value)
            except (TypeError, ValueError):
                value = 0
            if value >= 1:
                return self._resolved(
                    "io_concurrency", dim, plugin,
                    min(value, _IO_CONCURRENCY_CAP), source,
                )
        default = min(16, max(8, 2 * _CPU_COUNT))
        table = self.read_bps if op == "read" else self.write_bps
        bps = table(plugin)
        if bps is None and plugin is not None:
            bps = table(None)  # best-known rate for this op
        if bps is None:
            value = default
        elif bps >= 1e9:
            # Bandwidth-bound (local SSD/tmpfs): a couple of streams per
            # core saturate the bus; more just thrash caches.
            value = min(default, max(4, 2 * _CPU_COUNT))
        elif bps <= 1e8:
            # Latency-bound (network storage): hide per-request latency
            # with every stream the cap allows.
            value = 16
        else:
            value = default
        return self._resolved(
            "io_concurrency", dim, plugin, value, "heuristic",
            bps=round(bps) if bps is not None else None,
        )

    def should_preverify(self, plugin: Optional[str] = None) -> bool:
        """``plugin``: the storage plugin the CURRENT restore reads
        from. The crossover must use THAT backend's measured read rate —
        a fast local read recorded earlier in the process must not talk
        a later object-store restore out of its near-free verify skip.
        No recorded rate for this plugin means no evidence: verify."""
        mode = preverify_mode()
        if mode == "always":
            return self._resolved("preverify", "preverify", plugin, True, "env")
        if mode == "never":
            return self._resolved("preverify", "preverify", plugin, False, "env")
        tuned = self._tuned("preverify", plugin, "read")
        if tuned is not None:
            value, source = tuned
            return self._resolved(
                "preverify", "preverify", plugin, bool(value), source
            )
        hash_bps = self.hash_bps()
        read_bps = self.read_bps(plugin) if plugin is not None else self.read_bps()
        if hash_bps is None or read_bps is None:
            # No evidence: keep the zero-byte verify path.
            return self._resolved(
                "preverify", "preverify", plugin, True, "heuristic"
            )
        # The crossover knee with the gate dead band: hovering at
        # read ~= hash * margin must not flip verification per-restore.
        value = self._banded(
            "preverify", plugin, read_bps, hash_bps * _PREVERIFY_READ_MARGIN
        )
        return self._resolved(
            "preverify", "preverify", plugin, value, "heuristic",
            read_bps=round(read_bps), hash_bps=round(hash_bps),
        )

    def should_native_io(self, plugin: Optional[str] = None, op: str = "write") -> bool:
        """Economic gate for the native I/O engine (native_io.py, under
        ``TORCHSNAPSHOT_TPU_NATIVE_IO=auto``). The fs plugin records
        per-stream native-engine rates under ``<Plugin>.native`` — the
        same EWMA tables every plugin rate lands in — so the engine is
        measured like any backend and elected like streaming:

        - **writes**: optimistic while unmeasured (the way streaming
          writes default on — queued SQEs are never worse than the
          sequential pwrite loop), deposed only when the engine's own
          measured rate falls clearly below what the pipeline achieves
          without it. The margin absorbs the mismatch between the two
          meters (the plugin-keyed rate spans the whole pipeline; the
          ``.native`` rate one stream).
        - **reads**: the streamed-read latency knee. Queue depth pays
          where per-request transport latency can hide behind it; on
          memcpy-speed local reads (page cache) the engine measurably
          loses to the mmap/pread paths, so native reads engage only on
          measured latency-bound storage (no measurement = no evidence
          = Python path, the read-side status quo bias). The engine choice
        is a tunable dimension (``native.write``/``native.read``): a
        learned profile or armed trial overrides the margin logic."""
        dim = f"native.{op}"
        tuned = self._tuned(dim, plugin, op)
        if tuned is not None:
            value, source = tuned
            return self._resolved("native", dim, plugin, bool(value), source)
        table = self._read_bps if op == "read" else self._write_bps
        with self._lock:
            native = table.get(f"{plugin}.native") if plugin else None
            base = table.get(plugin) if plugin else None
        if op == "read":
            if base is None or not self._banded(
                dim, plugin, base, _STREAM_READ_LATENCY_BPS
            ):
                return self._resolved(
                    "native", dim, plugin, False, "heuristic"
                )
            value = native is None or native >= _NATIVE_FALLBACK_MARGIN * base
            return self._resolved("native", dim, plugin, value, "heuristic")
        if native is None or base is None:
            # No evidence against it: gather measurements.
            return self._resolved("native", dim, plugin, True, "heuristic")
        value = native >= _NATIVE_FALLBACK_MARGIN * base
        return self._resolved(
            "native", dim, plugin, value, "heuristic",
            native_bps=round(native), base_bps=round(base),
        )

    def should_coop_restore(self, plugin: Optional[str] = None) -> bool:
        """Economic gate for cooperative restore fan-out (fanout.py,
        under ``TORCHSNAPSHOT_TPU_COOP_RESTORE=auto``): partitioning
        replicated reads across ranks and redistributing sub-chunks over
        the host network wins ~N× when storage bandwidth is the
        bottleneck, but on memcpy-speed local storage (page-cache reads)
        the socket copy costs more than just re-reading — the same
        latency-bound knee the streamed-read election uses. No recorded
        read rate for this restore's backend means no evidence: direct
        reads (the status quo) stay."""
        return self._knee_gate("coop_restore", plugin)

    def should_planned_reshard(self, plugin: Optional[str] = None) -> bool:
        """Economic gate for the planned-reshard tier (reshard.py, under
        ``TORCHSNAPSHOT_TPU_RESHARD=auto``): replacing R storage reads
        of a multi-requester shard with one read plus minimal peer
        region bundles wins exactly when storage bandwidth — not the
        host network — is the bottleneck, which is the same knee the
        coop-restore and streamed-read elections sit on. Memcpy-speed
        local fs (page-cache reads) stays on the direct overlap-scatter
        path; no recorded read rate means no evidence, so the status quo
        stays."""
        return self._knee_gate("planned_reshard", plugin)

    def should_seed_restore(self, plugin: Optional[str] = None) -> bool:
        """Economic gate for the fleet seeding tier (distrib.py, under
        ``TORCHSNAPSHOT_TPU_SEED_RESTORE=auto``): sourcing shareable
        chunks from peers that already hold them beats a direct storage
        read exactly when storage bandwidth — not the host network — is
        the bottleneck, the same knee as the coop-restore and planned-
        reshard elections. Unlike those, this election is PER-REPLICA
        (every seed miss independently falls back to a direct read), so
        asymmetric decisions across the fleet are safe — but the
        evidence rule is identical: no recorded read rate for this
        backend means no evidence, and direct reads stay."""
        return self._knee_gate("seed_restore", plugin)

    def _knee_gate(self, gate: str, plugin: Optional[str]) -> bool:
        """The shared latency-bound election (coop restore, planned
        reshard, seed restore): learned profile > the measured-rate
        knee with the flip-flop dead band."""
        tuned = self._tuned(gate, plugin, "read")
        if tuned is not None:
            value, source = tuned
            return self._resolved(gate, gate, plugin, bool(value), source)
        bps = self.read_bps(plugin) if plugin is not None else self.read_bps()
        value = bps is not None and self._banded(
            gate, plugin, bps, _STREAM_READ_LATENCY_BPS
        )
        return self._resolved(
            gate, gate, plugin, value, "heuristic",
            read_bps=round(bps) if bps is not None else None,
        )

    # ------------------------------------------------ closed-loop autotune

    def note_world(self, world_size: int) -> None:
        self._tuner.note_world(world_size)

    def _trial_dims(self, op: str, plugin: str) -> Dict[str, Dict[str, Any]]:
        """The dimensions this op direction may perturb, with their
        current incumbent values and env bounds. An env-pinned knob is
        never perturbed — overrides remove the dimension from the
        experiment entirely."""
        dims: Dict[str, Dict[str, Any]] = {}
        if not os.environ.get(SUB_CHUNK_ENV_VAR, "").strip():
            lo = _env_int(SUB_CHUNK_MIN_ENV_VAR, _DEFAULT_SUB_CHUNK_MIN_BYTES)
            hi = max(
                lo, _env_int(SUB_CHUNK_MAX_ENV_VAR, _DEFAULT_SUB_CHUNK_MAX_BYTES)
            )
            dims[f"sub_chunk.{op}"] = {
                "value": self.sub_chunk_bytes(plugin, op=op),
                "kind": "geom", "lo": lo, "hi": hi, "quantum": 1 << 20,
            }
        if not os.environ.get(IO_CONCURRENCY_ENV_VAR, "").strip():
            dims[f"io_concurrency.{op}"] = {
                "value": self.io_concurrency(op, plugin),
                "kind": "geom", "lo": 1, "hi": _IO_CONCURRENCY_CAP,
                "quantum": 1,
            }
        # Engine choice joins the experiment only once the native engine
        # has a measured per-stream rate for this plugin — toggling an
        # engine that never ran would score nothing.
        with self._lock:
            table = self._read_bps if op == "read" else self._write_bps
            has_native = f"{plugin}.native" in table
        if has_native:
            dims[f"native.{op}"] = {
                "value": self.should_native_io(plugin, op=op),
                "kind": "toggle",
            }
        return dims

    def begin_io_op(self, op: str, plugin: str) -> None:
        """Scheduler entry hook (execute_write_reqs / execute_read_reqs):
        publishes this op's profile key to the heartbeat plane and —
        learning modes only, scored incumbent permitting — arms at most
        one perturbation trial, so the elections that follow inside the
        op resolve it. ``never`` costs one env check."""
        mode = _autotune.autotune_mode()
        if mode == "never":
            return
        key = self._tuner.key_for(plugin, op)
        if mode in ("auto", "fresh") and key is not None:
            self._tuner.maybe_arm(op, plugin, self._trial_dims(op, plugin))
        active = self._tuner.active_trial()
        trial_dim = (
            active["dim"]
            if active is not None
            and active["op"] == op
            and active["plugin"] == plugin
            else None
        )
        # The watch `profile` column (health plane): profile key plus
        # whether this rank is running a perturbation trial. Not part of
        # the stall fingerprint (health._PROGRESS_FIELDS).
        telemetry.health.update(profile=key or "-", trial=trial_dim)

    def observe_verdict(
        self,
        op: str,
        plugin: str,
        world_size: int,
        attribution: Optional[Dict[str, Any]],
        aggregate: Optional[Dict[str, Any]] = None,
        root: Optional[str] = None,
        rank: int = 0,
    ) -> None:
        """Post-commit feedback: score the critical-path verdict of one
        committed take/restore against the incumbent profile. Called on
        EVERY rank (the in-memory learning must agree fleet-wide — all
        ranks saw the same merged attribution); rank 0 additionally
        persists the updated profile record into ``root``'s history
        journal. Never raises into the committed op."""
        mode = _autotune.autotune_mode()
        if mode == "never":
            return
        op_kind = "read" if op == "restore" else "write"
        self._tuner.note_world(world_size)
        binding = (attribution or {}).get("binding") or {}
        category = binding.get("category")
        # Score by the fleet's achieved end-to-end rate (bytes over the
        # op wall), not the binding window's busy rate: the busy rate is
        # a RESIDUAL (fused-span accounting subtracts overlapped
        # staging/hash windows), so finer chunking earns overlap credit
        # and the residual optimum drifts below the wall optimum — the
        # tuner would faithfully converge to settings the operator's
        # clock disagrees with. The binding category still keys the
        # profile and gates learning; its rate is only the fallback.
        agg = aggregate or {}
        gbps = agg.get("read_gbps" if op_kind == "read" else "write_gbps")
        if not isinstance(gbps, (int, float)) or gbps <= 0:
            gbps = binding.get("gbps")
        if (
            not isinstance(category, str)
            or not category
            or not isinstance(gbps, (int, float))
            or gbps <= 0
        ):
            # Bus-off / unattributed op: skip EXPLICITLY — a None
            # binding category must never become a learned profile key.
            telemetry.counter_add("profile_skips", 1)
            aborted = self._tuner.abort_trial(op_kind, plugin)
            telemetry.record_learn(
                op=op, plugin=plugin, skipped=True, trial_aborted=aborted
            )
            return
        # Trials only arm off storage-class verdicts: when the pipeline
        # (staging, hashing) gates the op, perturbing storage knobs is
        # noise-chasing — the score still tracks, the experiment waits.
        storage_bound = (
            telemetry.critpath.classify_category(category) == "storage"
        )
        result = self._tuner.observe(
            op_kind,
            plugin,
            category,
            float(gbps),
            learn=(mode != "pin"),
            arm=storage_bound,
        )
        telemetry.record_learn(
            op=op,
            **{k: v for k, v in result.items() if k not in ("settings", "op")},
        )
        if root is not None and rank == 0 and mode != "pin":
            record = self._tuner.profile_record(result["key"])
            if record is not None:
                record["op"] = op_kind
                telemetry.history.append_record(root, record)

    def load_profiles(self, root: str) -> int:
        """Warm-start from ``root``'s history journal: adopt the last
        persisted profile per key so the first op of this process elects
        the learned optimum, not the static default. Once per root per
        governor; ``fresh`` (relearn) and ``never`` skip."""
        mode = _autotune.autotune_mode()
        if mode in ("never", "fresh") or not root:
            return 0
        with self._lock:
            if root in self._profile_roots:
                return 0
            self._profile_roots.add(root)
        try:
            records = telemetry.history.load_profiles(root)
        except Exception:  # noqa: BLE001 - profiles are advisory
            logger.debug("profile load skipped", exc_info=True)
            return 0
        loaded = self._tuner.load(records)
        if loaded:
            logger.debug(
                "autotune: warm-started %d profile(s) from %s", loaded, root
            )
        return loaded

    def profiles(self) -> Dict[str, Dict[str, Any]]:
        """Live convergence state per profile key (introspection)."""
        return self._tuner.profiles()


def preverify_mode() -> str:
    """THE parser for ``TORCHSNAPSHOT_TPU_PREVERIFY`` — every consumer
    (the governor's gate, snapshot's explicit-instruction guard) goes
    through here so the recognized spellings can never drift between
    them. Unrecognized values fall back to ``auto``."""
    raw = os.environ.get(PREVERIFY_ENV_VAR, "auto").strip().lower()
    if raw in ("1", "always", "on", "true", "yes"):
        return "always"
    if raw in ("0", "never", "off", "false", "no"):
        return "never"
    return "auto"


_governor: Optional[IOGovernor] = None
_governor_lock = threading.Lock()


def io_governor() -> IOGovernor:
    global _governor
    if _governor is None:
        with _governor_lock:
            if _governor is None:
                _governor = IOGovernor()
    return _governor


def reset_io_governor() -> IOGovernor:
    """Replace the process governor with a fresh instance. Test/bench
    hook: the warm-start benchmark simulates "a new process on a known
    host" with it (fresh EWMA tables + profile reload). The bus rate
    listener resolves the current instance per call, so the swap is
    safe mid-process."""
    global _governor
    with _governor_lock:
        _governor = IOGovernor()
        return _governor


def preload_profiles(path: str, world_size: Optional[int] = None) -> None:
    """Load the learned profiles governing ``path``'s root (the
    snapshot's parent directory — where the history journal lives) into
    the process governor, before the op's first election. Cheap no-op
    when autotuning is off or the path has no local filesystem root;
    never raises into the op."""
    if _autotune.autotune_mode() == "never":
        return
    governor = io_governor()
    if world_size:
        governor.note_world(world_size)
    try:
        from .storage_plugin import local_fs_root

        local = local_fs_root(path)
        if local is None:
            return
        root = os.path.dirname(os.path.abspath(local.rstrip("/")))
        governor.load_profiles(root)
    except Exception:  # noqa: BLE001 - profiles are advisory
        logger.debug("profile preload skipped", exc_info=True)


def _feed_governor_rates(
    kind: str, key: Optional[str], nbytes: int, seconds: float
) -> None:
    """Telemetry-bus rate listener: achieved write/read/hash rates are
    published to the bus (telemetry.record_rate) by whoever measured
    them; the governor's EWMA tables consume them here, keeping
    ``measured_rates()`` a VIEW over bus-fed data rather than a second
    measurement mechanism."""
    governor = io_governor()
    if kind == "write":
        governor.record_write(key or "", nbytes, seconds)
    elif kind == "read":
        governor.record_read(key or "", nbytes, seconds)
    elif kind == "hash":
        governor.record_hash(nbytes, seconds)


telemetry.register_rate_listener(_feed_governor_rates)


def get_local_world_size(pg=None) -> int:
    """Number of processes on this host, via hostname all-gather
    (reference: scheduler.py:33-42)."""
    if pg is None or pg.get_world_size() == 1:
        return 1
    hostnames = pg.all_gather_object(socket.gethostname())
    return max(1, hostnames.count(socket.gethostname()))


def get_process_memory_budget_bytes(pg=None) -> int:
    env = os.environ.get(_MEMORY_BUDGET_ENV_VAR)
    if env is not None:
        budget = int(env)
        logger.info("Manually set process memory budget to %d bytes.", budget)
        return budget
    local_world_size = get_local_world_size(pg)
    available = psutil.virtual_memory().available
    budget = min(
        int(available * _AVAILABLE_MEMORY_MULTIPLIER) // local_world_size,
        _MAX_PER_RANK_MEMORY_BUDGET_BYTES,
    )
    logger.debug("Process memory budget: %d bytes.", budget)
    return budget


class _WritePipeline:
    def __init__(
        self,
        write_req: WriteReq,
        sub_chunk_bytes: Optional[int] = None,
        storage: Optional[StoragePlugin] = None,
    ) -> None:
        self.write_req = write_req
        self.staging_cost_bytes: int = (
            write_req.buffer_stager.get_staging_cost_bytes()
        )
        self.buf = None
        self.buf_size_bytes: Optional[int] = None
        self.io_skipped = False
        # Streaming election happens at construction: the stager opts in
        # for THIS sub-chunk size, and the budget then charges the
        # PLUGIN-declared retention (stager window for fs; part buffers
        # for s3; full retained stream for gcs) instead of the whole
        # entry's staging cost.
        self.sub_chunk_bytes = sub_chunk_bytes
        self.streamed = False
        if sub_chunk_bytes is not None and write_req.buffer_stager.can_stream(
            sub_chunk_bytes
        ):
            self.admission_cost_bytes: int = min(
                self.staging_cost_bytes,
                storage.stream_admission_cost(
                    self.staging_cost_bytes, sub_chunk_bytes
                ),
            )
            self.streamed = True
        else:
            self.admission_cost_bytes = self.staging_cost_bytes

    async def stage_buffer(self, executor) -> "_WritePipeline":
        faultinject.site("scheduler.stage")
        with telemetry.span(
            "stage", path=self.write_req.path, bytes=self.staging_cost_bytes
        ):
            self.buf = await self.write_req.buffer_stager.stage_buffer(executor)
            self.buf_size_bytes = memoryview(self.buf).nbytes
        # Incremental snapshots: the stager found the payload unchanged in a
        # base snapshot — drop the buffer instead of writing it.
        if getattr(self.write_req.buffer_stager, "io_skipped", False):
            self.io_skipped = True
            self.buf = None
            self.buf_size_bytes = 0
            telemetry.counter_add("bytes_deduped", self.staging_cost_bytes)
        else:
            telemetry.counter_add("bytes_staged", self.buf_size_bytes)
        return self

    @staticmethod
    async def _timed_write_chunks(chunks, plugin_key: str):
        """Per-sub-chunk latency sampler on the streamed write path: the
        time from requesting a sub-chunk to handing it to the plugin is
        one pipeline step (stage of N+1 overlapping write of N), exactly
        the distribution a stall diagnosis needs — a p99 spike here with
        a flat p50 is the signature of periodic reclaim/throttle stalls
        that averages hide. Installed only while telemetry is enabled."""
        try:
            while True:
                t0 = telemetry.monotonic()
                try:
                    chunk = await chunks.__anext__()
                except StopAsyncIteration:
                    return
                telemetry.histogram_observe(
                    "write.sub_chunk_s",
                    telemetry.monotonic() - t0,
                    key=plugin_key,
                )
                yield chunk
        finally:
            # stream_write's cleanup acloses THIS wrapper; the inner
            # stager stream must unwind with it (pooled staging buffers
            # are released in its finally blocks).
            aclose = getattr(chunks, "aclose", None)
            if aclose is not None:
                await aclose()

    async def stream_write(
        self, storage: StoragePlugin, executor
    ) -> "_WritePipeline":
        """Fused stage+write: the stager yields sub-chunks as they land
        on the host and the plugin writes each while the next stages —
        the entry's wall becomes ~max(stage, write) instead of
        stage + write. Runs as ONE task occupying one I/O slot; by the
        time it completes the entry is both staged and durably written,
        so it never enters ready_for_io."""
        stager = self.write_req.buffer_stager
        chunks = stager.stage_stream(executor, self.sub_chunk_bytes)
        if telemetry.enabled():
            chunks = self._timed_write_chunks(chunks, type(storage).__name__)
        try:
            # The forensics guard is per ENTRY, not per sub-chunk: one
            # registry insert/remove per storage op feeds the watchdog's
            # own p99 baseline (the telemetry histograms are off by
            # default, so the stall trigger cannot lean on them).
            with forensics.storage_op(
                "storage_write", path=self.write_req.path
            ), telemetry.span(
                "stream_write",
                path=self.write_req.path,
                bytes=self.staging_cost_bytes,
                sub_chunk_bytes=self.sub_chunk_bytes,
            ):
                await storage.write_stream(
                    WriteStream(
                        path=self.write_req.path,
                        nbytes=self.staging_cost_bytes,
                        chunks=chunks,
                    )
                )
        finally:
            aclose = getattr(chunks, "aclose", None)
            if aclose is not None:
                await aclose()
        self.buf_size_bytes = self.staging_cost_bytes
        telemetry.counter_add("bytes_staged", self.staging_cost_bytes)
        telemetry.counter_add("entries_streamed", 1)
        return self

    async def write_buffer(self, storage: StoragePlugin) -> "_WritePipeline":
        assert self.buf is not None
        t0 = telemetry.monotonic() if telemetry.enabled() else None
        with forensics.storage_op(
            "storage_write", path=self.write_req.path
        ), telemetry.span(
            "storage_write", path=self.write_req.path, bytes=self.buf_size_bytes
        ):
            await storage.write(WriteIO(path=self.write_req.path, buf=self.buf))
        if t0 is not None:
            telemetry.histogram_observe(
                "write.entry_s",
                telemetry.monotonic() - t0,
                key=type(storage).__name__,
            )
        self.buf = None  # release the staged buffer eagerly
        return self


class _ProgressReporter:
    """Periodic pipeline progress tables (reference: _WriteReporter,
    scheduler.py:96-175): stage counts, bytes staged/written, budget
    remaining, and RSS delta — the observability needed to diagnose a stall
    on a real pod save. Runs as an asyncio task on the pipeline's loop;
    logs at INFO every ``interval_s``.

    One sampler, three sinks: each tick emits the log table, a
    flight-recorder ``progress`` event (so an abort dump shows where the
    pipeline was, tick by tick), and the live health plane's byte/queue
    fields (telemetry.health — what ``watch`` renders). The read and
    write pipelines share ONE assembly: the read pipeline has no staging
    phase, so its staging columns are simply absent — not a second
    format string that drifts."""

    def __init__(
        self,
        op: str,
        rank: int,
        total: int,
        budget: "_MemoryBudget",
        interval_s: Optional[float] = None,
    ) -> None:
        if interval_s is None:
            # TORCHSNAPSHOT_TPU_PROGRESS_S tunes the sampling cadence —
            # the log table, the flight-recorder progress events, and the
            # heartbeat byte feed all tick together (an operator watching
            # a short take wants sub-second frames; default 5 s).
            raw = os.environ.get("TORCHSNAPSHOT_TPU_PROGRESS_S", "").strip()
            try:
                interval_s = float(raw) if raw else 5.0
            except ValueError:
                interval_s = 5.0
        self.op = op
        self.rank = rank
        self.total = total
        # Total payload bytes for this pipeline, when the caller knows it
        # (feeds the heartbeat's ETA; 0 = unknown).
        self.total_bytes = 0
        self.budget = budget
        self.interval_s = interval_s
        self.staged_count = 0
        self.staged_bytes = 0
        # Op-neutral completion counters: "written" entries for the write
        # pipeline, "consumed" reads for the read pipeline (the log wording
        # is per-op; the fields are shared).
        self.completed_count = 0
        self.completed_bytes = 0
        self.inflight_staging = 0
        self.inflight_io = 0
        self._begin = telemetry.monotonic()
        try:
            self._rss_begin = psutil.Process().memory_info().rss
        except Exception:  # pragma: no cover
            self._rss_begin = 0
        self._task: Optional[asyncio.Task] = None
        # Live binding-resource hint (critpath.live_binding over the bus
        # events recorded since the last tick) — fed into the heartbeat
        # so `watch` shows WHAT a straggler is stuck on.
        self._binding_since_id = 0

    def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self._loop())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    async def _loop(self) -> None:
        try:
            while True:
                await asyncio.sleep(self.interval_s)
                self.log_table()
        except asyncio.CancelledError:
            pass

    def log_table(self) -> None:
        try:
            rss_delta = psutil.Process().memory_info().rss - self._rss_begin
        except Exception:  # pragma: no cover
            rss_delta = 0
        elapsed = telemetry.monotonic() - self._begin
        # The periodic table doubles as the bus's queue-depth sampler:
        # gauges render as counter tracks in the exported trace.
        telemetry.gauge_set(f"{self.op}_inflight_staging", self.inflight_staging)
        telemetry.gauge_set(f"{self.op}_inflight_io", self.inflight_io)
        telemetry.gauge_set("budget_free_bytes", self.budget.available)
        is_read = self.op == "read"
        done_word = "consumed" if is_read else "written"
        cols = [f"{self.total} total"]
        if not is_read:
            cols.append(f"{self.inflight_staging} staging")
            cols.append(f"{self.staged_count} staged")
        cols.append(f"{self.inflight_io} in {'flight' if is_read else 'io'}")
        cols.append(f"{self.completed_count} {done_word}")
        vols = [] if is_read else [f"{self.staged_bytes / 1e9:.2f} GB staged"]
        vols.append(f"{self.completed_bytes / 1e9:.2f} GB {done_word}")
        logger.info(
            "[rank %d] %s progress +%.0fs | reqs: %s | %s | budget free "
            "%.2f/%.2f GB | rss delta %+.2f GB",
            self.rank,
            self.op,
            elapsed,
            ", ".join(cols),
            ", ".join(vols),
            self.budget.available / 1e9,
            self.budget.budget_bytes / 1e9,
            rss_delta / 1e9,
        )
        telemetry.flightrec.record(
            "progress",
            op=self.op,
            total=self.total,
            done=self.completed_count,
            done_bytes=self.completed_bytes,
            staged_bytes=self.staged_bytes,
            inflight_staging=self.inflight_staging,
            inflight_io=self.inflight_io,
        )
        fields: Dict[str, Any] = {
            "total_entries": self.total,
            "done_entries": self.completed_count,
            "inflight_io": self.inflight_io,
        }
        if self.total_bytes:
            fields["total_bytes"] = self.total_bytes
        if is_read:
            fields["read_bytes"] = self.completed_bytes
        else:
            fields["staged_bytes"] = self.staged_bytes
            fields["written_bytes"] = self.completed_bytes
        binding = self._live_binding(is_read)
        if binding is not None:
            fields["binding"] = binding
        telemetry.health.update(**fields)

    def _live_binding(self, is_read: bool) -> Optional[str]:
        """What this rank is currently bound on, for the heartbeat.
        With the bus on, the attribution engine's window estimate over
        the spans since the last tick; with it off, a coarse queue-shape
        heuristic — a straggler's `watch` row should say "storage_write",
        not just "stalled"."""
        if telemetry.enabled():
            from .telemetry import critpath

            evs = telemetry.events(since_id=self._binding_since_id)
            if evs:
                self._binding_since_id = max(e.get("id", 0) for e in evs)
                binding = critpath.live_binding(evs)
                if binding is not None:
                    return binding
        if self.inflight_io > 0 and self.inflight_staging == 0:
            return "storage_read" if is_read else "storage_write"
        if self.inflight_staging > 0 and self.inflight_io == 0:
            return "stage_copy" if not is_read else None
        return None


class _Throughput:
    """Tracks bytes moved + wall time to log MB/s summaries
    (reference: scheduler.py:96-175,441-442)."""

    def __init__(self, op: str, rank: int) -> None:
        self.op = op
        self.rank = rank
        self.begin = telemetry.monotonic()
        self.total_bytes = 0

    def add(self, nbytes: int) -> None:
        self.total_bytes += nbytes

    def elapsed(self) -> float:
        return max(telemetry.monotonic() - self.begin, 1e-9)

    def log_summary(self) -> None:
        elapsed = self.elapsed()
        logger.info(
            "[rank %d] %s %.1f MB in %.2fs (%.1f MB/s)",
            self.rank,
            self.op,
            self.total_bytes / 1e6,
            elapsed,
            self.total_bytes / 1e6 / elapsed,
        )


class PendingIOWork:
    """Handle over storage I/O still in flight after staging completed."""

    def __init__(
        self,
        ready_for_io: List[_WritePipeline],
        io_tasks: Set[asyncio.Task],
        storage: StoragePlugin,
        memory_budget: "_MemoryBudget",
        executor: ThreadPoolExecutor,
        throughput: _Throughput,
        event_loop: asyncio.AbstractEventLoop,
        reporter: Optional[_ProgressReporter] = None,
    ) -> None:
        self._ready_for_io = ready_for_io
        self._io_tasks = io_tasks
        self._storage = storage
        self._budget = memory_budget
        self._executor = executor
        self._throughput = throughput
        self._event_loop = event_loop
        self._reporter = reporter

    async def complete(self) -> None:
        reporter = self._reporter
        if reporter is not None:
            reporter.start()
        drain_span = telemetry.span("io_drain")
        drain_span.__enter__()
        try:
            while self._io_tasks or self._ready_for_io:
                self._dispatch_io()
                if not self._io_tasks:
                    continue
                done, pending = await asyncio.wait(
                    self._io_tasks, return_when=asyncio.FIRST_COMPLETED
                )
                self._io_tasks = pending
                for task in done:
                    pipeline = task.result()
                    self._budget.release(pipeline.buf_size_bytes)
                    self._throughput.add(pipeline.buf_size_bytes)
                    telemetry.counter_add("bytes_written", pipeline.buf_size_bytes)
                    telemetry.counter_add("entries_written", 1)
                    if reporter is not None:
                        reporter.inflight_io -= 1
                        reporter.completed_count += 1
                        reporter.completed_bytes += pipeline.buf_size_bytes
        except BaseException:
            # Same cleanup as execute_write_reqs' failure path: a write
            # failing during the drain must not orphan sibling writes or
            # leak the executor's threads.
            for task in self._io_tasks:
                task.cancel()
            if self._io_tasks:
                await asyncio.gather(*self._io_tasks, return_exceptions=True)
            self._io_tasks = set()
            self._ready_for_io.clear()
            self._executor.shutdown(wait=True)
            raise
        finally:
            drain_span.__exit__(None, None, None)
            if reporter is not None:
                reporter.stop()
        self._executor.shutdown(wait=True)
        self._throughput.log_summary()
        # Publish the ACHIEVED end-to-end write bandwidth on the bus (the
        # meter spans staging + I/O — exactly the rate the next save's
        # sub-chunk sizing and concurrency should be tuned for); the
        # governor consumes it via its registered rate listener.
        telemetry.record_rate(
            "write",
            type(self._storage).__name__,
            self._throughput.total_bytes,
            self._throughput.elapsed(),
        )

    def _dispatch_io(self) -> None:
        while (
            self._ready_for_io
            and len(self._io_tasks)
            < io_governor().io_concurrency(
                "write", type(self._storage).__name__
            )
        ):
            pipeline = self._ready_for_io.pop(0)
            self._io_tasks.add(
                self._event_loop.create_task(pipeline.write_buffer(self._storage))
            )
            if self._reporter is not None:
                self._reporter.inflight_io += 1

    def sync_complete(self, event_loop: asyncio.AbstractEventLoop) -> None:
        event_loop.run_until_complete(self.complete())

    async def abort(self) -> None:
        """Cancel in-flight storage writes and release resources.

        Used when a peer rank's failure aborts the snapshot: without this,
        dispatched writes keep running unawaited (orphaned partial objects,
        swallowed I/O errors) and the executor's threads leak."""
        self._ready_for_io.clear()
        for task in self._io_tasks:
            task.cancel()
        if self._io_tasks:
            await asyncio.gather(*self._io_tasks, return_exceptions=True)
        self._io_tasks = set()
        self._executor.shutdown(wait=True)

    def sync_abort(self, event_loop: asyncio.AbstractEventLoop) -> None:
        event_loop.run_until_complete(self.abort())


class _MemoryBudget:
    def __init__(self, budget_bytes: int) -> None:
        self.budget_bytes = budget_bytes
        self.available = budget_bytes

    def acquire(self, nbytes: int) -> None:
        self.available -= nbytes

    def release(self, nbytes: int) -> None:
        self.available += nbytes


async def execute_write_reqs(
    write_reqs: List[WriteReq],
    storage: StoragePlugin,
    memory_budget_bytes: int,
    rank: int,
    allow_streaming: bool = False,
) -> PendingIOWork:
    event_loop = asyncio.get_running_loop()
    executor = ThreadPoolExecutor(max_workers=_MAX_PER_RANK_CPU_CONCURRENCY)
    budget = _MemoryBudget(memory_budget_bytes)
    throughput = _Throughput("wrote", rank)
    reporter = _ProgressReporter("write", rank, len(write_reqs), budget)
    reporter.start()

    governor = io_governor()
    plugin_key = type(storage).__name__
    # Closed-loop hook: publish the profile key and (learning modes)
    # arm at most one perturbation trial BEFORE the elections below, so
    # this op runs it and the post-commit verdict scores it.
    governor.begin_io_op("write", plugin_key)
    # Streaming fuses staging with storage I/O, so a streamed entry's
    # write completes before this function returns — callers that rely on
    # the staging-complete consistency point RETURNING EARLY (async_take)
    # must not enable it. Only plugins that consume chunks incrementally
    # are eligible (the buffered write_stream fallback would hold a full
    # entry while the budget charged a sub-chunk window). Sub-chunk size
    # comes from measured bandwidth.
    sub_chunk = (
        governor.sub_chunk_bytes(plugin_key)
        if allow_streaming and getattr(storage, "supports_streaming", False)
        else None
    )
    io_concurrency = governor.io_concurrency("write", plugin_key)
    # Tenancy admission (tenancy/admission.py): a session armed on this
    # op's storage scales the I/O-slot cap by the tenant's bandwidth
    # share and paces each dispatched request through its token bucket.
    # None on every non-tenant op — the attribute probe is the whole
    # disabled-path cost here.
    admission = getattr(storage, "_tsnap_admission", None)
    if admission is not None:
        io_concurrency = admission.scale_concurrency(io_concurrency)

    async def _paced(coro, nbytes):
        await admission.admit(nbytes, "write", plugin_key)
        return await coro

    ready_for_staging = [
        _WritePipeline(req, sub_chunk_bytes=sub_chunk, storage=storage)
        for req in write_reqs
    ]
    reporter.total_bytes = sum(p.staging_cost_bytes for p in ready_for_staging)
    # Stage large requests first: improves budget packing and overlaps the
    # slowest DtoH copies with I/O of everything else.
    ready_for_staging.sort(key=lambda p: p.staging_cost_bytes, reverse=True)
    n_streamed = sum(1 for p in ready_for_staging if p.streamed)
    if n_streamed:
        logger.debug(
            "[rank %d] streaming %d/%d write(s) in %d MB sub-chunks",
            rank,
            n_streamed,
            len(ready_for_staging),
            (sub_chunk or 0) >> 20,
        )
    # Record the governor's write-path election (what was chosen and the
    # rates it saw): the flight recorder carries the always-on copy for
    # abort dumps/`blackbox`, the bus instant rides the per-op summary
    # for `explain`.
    telemetry.record_election(
        site="write",
        plugin=plugin_key,
        streaming=sub_chunk is not None,
        streamed_entries=n_streamed,
        sub_chunk_bytes=sub_chunk,
        io_concurrency=io_concurrency,
        write_bps=governor.write_bps(plugin_key),
    )
    staging_tasks: Set[asyncio.Task] = set()
    io_tasks: Set[asyncio.Task] = set()
    ready_for_io: List[_WritePipeline] = []
    inflight_streams = 0

    def dispatch_staging() -> None:
        nonlocal inflight_streams
        deferred: List[_WritePipeline] = []
        while ready_for_staging:
            head = ready_for_staging[0]
            # A streamed entry occupies a storage stream for its whole
            # lifetime, so streams and buffered writes share ONE
            # io_concurrency cap — counting them separately would let a
            # mixed workload run 2x the intended concurrent requests.
            if head.streamed and (
                inflight_streams + len(io_tasks) >= io_concurrency
            ):
                deferred.append(ready_for_staging.pop(0))
                continue
            cost = head.admission_cost_bytes
            if cost > budget.available:
                # Starvation escape: if nothing is in flight, admit the
                # over-budget request — otherwise it would never run.
                if staging_tasks or io_tasks or ready_for_io or deferred:
                    telemetry.counter_add("budget_defers", 1)
                    break
            pipeline = ready_for_staging.pop(0)
            budget.acquire(pipeline.admission_cost_bytes)
            if pipeline.streamed:
                inflight_streams += 1
                stream_coro = pipeline.stream_write(storage, executor)
                if admission is not None:
                    stream_coro = _paced(
                        stream_coro, pipeline.admission_cost_bytes
                    )
                staging_tasks.add(event_loop.create_task(stream_coro))
            else:
                staging_tasks.add(
                    event_loop.create_task(pipeline.stage_buffer(executor))
                )
            reporter.inflight_staging += 1
        # Stream-slot-deferred entries keep their order at the head.
        ready_for_staging[:0] = deferred

    def dispatch_io() -> None:
        # Streams count against the same cap (see dispatch_staging).
        while ready_for_io and len(io_tasks) + inflight_streams < io_concurrency:
            pipeline = ready_for_io.pop(0)
            io_coro = pipeline.write_buffer(storage)
            if admission is not None:
                # Pacing runs INSIDE the slot: a throttled tenant's
                # request occupies its (already share-scaled) slot while
                # it waits, which is exactly the backpressure intended.
                io_coro = _paced(io_coro, pipeline.admission_cost_bytes)
            io_tasks.add(event_loop.create_task(io_coro))
            reporter.inflight_io += 1

    dispatch_staging()
    try:
        while staging_tasks or ready_for_staging:
            done, _ = await asyncio.wait(
                staging_tasks | io_tasks, return_when=asyncio.FIRST_COMPLETED
            )
            for task in done:
                if task in staging_tasks:
                    staging_tasks.discard(task)
                    pipeline = task.result()
                    reporter.inflight_staging -= 1
                    reporter.staged_count += 1
                    reporter.staged_bytes += pipeline.buf_size_bytes
                    if pipeline.streamed:
                        # Fused stage+write: the entry is already on
                        # storage. Release the sub-chunk window charge and
                        # account the write here.
                        inflight_streams -= 1
                        budget.release(pipeline.admission_cost_bytes)
                        throughput.add(pipeline.buf_size_bytes)
                        telemetry.counter_add(
                            "bytes_written", pipeline.buf_size_bytes
                        )
                        telemetry.counter_add("entries_written", 1)
                        reporter.completed_count += 1
                        reporter.completed_bytes += pipeline.buf_size_bytes
                        continue
                    # The staged buffer may be smaller than the staging cost
                    # (e.g. a strided view); release the difference now.
                    budget.release(
                        pipeline.staging_cost_bytes - pipeline.buf_size_bytes
                    )
                    if not pipeline.io_skipped:
                        ready_for_io.append(pipeline)
                elif task in io_tasks:
                    io_tasks.discard(task)
                    pipeline = task.result()
                    budget.release(pipeline.buf_size_bytes)
                    throughput.add(pipeline.buf_size_bytes)
                    telemetry.counter_add("bytes_written", pipeline.buf_size_bytes)
                    telemetry.counter_add("entries_written", 1)
                    reporter.inflight_io -= 1
                    reporter.completed_count += 1
                    reporter.completed_bytes += pipeline.buf_size_bytes
            dispatch_io()
            dispatch_staging()
    except BaseException:
        # A staging/I/O failure aborts the snapshot: cancel siblings and
        # release the executor so repeated failures don't leak threads.
        reporter.stop()
        for task in staging_tasks | io_tasks:
            task.cancel()
        if staging_tasks or io_tasks:
            await asyncio.gather(
                *(staging_tasks | io_tasks), return_exceptions=True
            )
        executor.shutdown(wait=True)
        raise
    reporter.stop()

    return PendingIOWork(
        ready_for_io=ready_for_io,
        io_tasks=io_tasks,
        storage=storage,
        memory_budget=budget,
        executor=executor,
        throughput=throughput,
        event_loop=event_loop,
        reporter=reporter,
    )


def sync_execute_write_reqs(
    write_reqs: List[WriteReq],
    storage: StoragePlugin,
    memory_budget_bytes: int,
    rank: int,
    event_loop: asyncio.AbstractEventLoop,
    allow_streaming: bool = True,
) -> None:
    # Synchronous callers block until I/O drains, so fusing staging with
    # storage writes (streaming) costs them nothing semantically.
    pending = event_loop.run_until_complete(
        execute_write_reqs(
            write_reqs,
            storage,
            memory_budget_bytes,
            rank,
            allow_streaming=allow_streaming,
        )
    )
    pending.sync_complete(event_loop)


class _ReadPipeline:
    def __init__(
        self,
        read_req: ReadReq,
        sub_chunk_bytes: Optional[int] = None,
        stream_all: bool = False,
        coop_plan=None,
        peer_sub_chunk: Optional[int] = None,
    ) -> None:
        self.read_req = read_req
        self.consuming_cost_bytes: int = (
            read_req.buffer_consumer.get_consuming_cost_bytes()
        )
        # Cooperative restore fan-out (fanout.py): the plan assigns this
        # request a role — SendRole (this rank reads from storage and
        # forwards every sub-chunk to the subscribing peers), RecvRole
        # (another rank reads; the bytes arrive over the peer channel),
        # or None (plain direct read).
        self.coop_role = (
            coop_plan.take_role(read_req) if coop_plan is not None else None
        )
        self.coop_gen = 1
        self.peer_sub_chunk = peer_sub_chunk
        self.peer_streamed = False
        # Shared semaphore capping DIRECT-read fallbacks of peer-fed
        # entries at the governor's I/O concurrency (set by
        # execute_read_reqs when cooperation is active).
        self.fallback_gate: Optional[asyncio.Semaphore] = None
        if self.coop_role is not None and self.coop_role.is_recv:
            # Peer-fed: no storage I/O on the happy path, so the storage
            # streaming election below does not apply (a fallback after
            # peer failure reads buffered). Streaming eligibility is the
            # CONSUMER's alone — the peer channel always produces chunks
            # incrementally, whatever the storage plugin supports.
            self.sub_chunk_bytes = None
            self.streamed = False
            br = read_req.byte_range
            empty = br is not None and br[1] <= br[0]
            if (
                peer_sub_chunk is not None
                and not empty
                and read_req.buffer_consumer.can_stream(peer_sub_chunk)
            ):
                self.peer_streamed = True
                self.admission_cost_bytes: int = min(
                    self.consuming_cost_bytes,
                    read_req.buffer_consumer.stream_admission_cost(
                        peer_sub_chunk
                    ),
                )
            else:
                self.admission_cost_bytes = self.consuming_cost_bytes
            return
        # Streaming election happens at construction, mirroring the write
        # side: the consumer opts in for THIS sub-chunk size, and the
        # budget then charges the consumer-declared streamed retention
        # (the in-flight window for per-sub-chunk device_put and direct
        # destination fills; the full payload for verify-before-commit
        # scratch assembly) instead of the whole consuming cost.
        #
        # Under the default auto policy, full-retention consumers only
        # stream when ``stream_all`` says the storage is latency-bound
        # (or the operator forced it): on memcpy-speed local storage the
        # buffered mmap path's fewer copies beat the pipeline, and
        # streaming there would be a regression, not an optimization.
        self.sub_chunk_bytes = sub_chunk_bytes
        self.streamed = False
        br = read_req.byte_range
        empty = br is not None and br[1] <= br[0]
        if (
            sub_chunk_bytes is not None
            and not empty
            and read_req.buffer_consumer.can_stream(sub_chunk_bytes)
        ):
            window = min(
                self.consuming_cost_bytes,
                read_req.buffer_consumer.stream_admission_cost(sub_chunk_bytes),
            )
            if stream_all or window < self.consuming_cost_bytes:
                self.admission_cost_bytes: int = window
                self.streamed = True
        if not self.streamed:
            self.admission_cost_bytes = self.consuming_cost_bytes

    @property
    def is_recv(self) -> bool:
        return self.coop_role is not None and self.coop_role.is_recv

    @property
    def coop_order(self) -> int:
        """Dispatch priority class. Peer-fed entries first: they do no
        storage I/O (and are exempt from the I/O slot cap), and opening
        them early drains the peer inboxes the owners are already
        filling. Owned (forwarding) entries next, so every peer's
        receive side is fed as early as possible; plain reads last."""
        if self.coop_role is None:
            return 2
        return 0 if self.coop_role.is_recv else 1

    def _recharge(self, budget: Optional["_MemoryBudget"]) -> None:
        """The entry is about to hold its FULL payload (buffered retry
        or fallback) while the budget only charged a streamed window:
        charge the difference — possibly driving availability negative,
        like the starvation escape — so concurrent dispatch throttles
        instead of overshooting. Idempotent."""
        delta = self.consuming_cost_bytes - self.admission_cost_bytes
        if delta > 0 and budget is not None:
            budget.acquire(delta)
            self.admission_cost_bytes = self.consuming_cost_bytes

    # ---------------------------------------------------- peer-fed path

    async def _peer_stream_consume(
        self, role, consumer, executor, throughput: _Throughput
    ) -> None:
        source = role.stream()

        async def counted():
            observe = telemetry.enabled()
            while True:
                t0 = telemetry.monotonic() if observe else None
                with telemetry.span("peer_recv", cat="fanout"):
                    try:
                        chunk = await source.__anext__()
                    except StopAsyncIteration:
                        return
                if t0 is not None:
                    telemetry.histogram_observe(
                        "read.sub_chunk_s",
                        telemetry.monotonic() - t0,
                        key="peer",
                    )
                n = memoryview(chunk).nbytes
                throughput.add(n)
                telemetry.counter_add("bytes_read", n)
                telemetry.counter_add("bytes_from_peers", n)
                yield chunk

        stream = ReadStream(
            path=self.read_req.path,
            nbytes=self.consuming_cost_bytes,
            chunks=counted(),
        )
        try:
            await consumer.consume_stream(stream, executor)
        finally:
            aclose = getattr(source, "aclose", None)
            if aclose is not None:
                await aclose()

    async def _peer_read_and_consume(
        self, executor, throughput: _Throughput, budget: Optional["_MemoryBudget"]
    ) -> bool:
        """Consume this entry from its owner's forwarded sub-chunks.
        Returns False when the bytes cannot be delivered (owner death,
        abort, timeout, or integrity failure of the delivered bytes);
        the caller then degrades to a direct storage read — the fan-out
        failure contract: any peer failure costs one re-read, never a
        hang. The receiver runs the FULL verification chain itself
        (chained CRC, decompression), so a forwarding owner is never
        trusted with integrity."""
        from .fanout import PeerTransferError  # noqa: F401 (doc anchor)
        from .integrity import IntegrityError

        role = self.coop_role
        consumer = self.read_req.buffer_consumer
        path = self.read_req.path
        try:
            with telemetry.span(
                "coop_read", path=path, source=role.owner,
                bytes=self.consuming_cost_bytes,
            ):
                if self.peer_streamed:
                    try:
                        await self._peer_stream_consume(
                            role, consumer, executor, throughput
                        )
                        telemetry.counter_add("entries_read", 1)
                        telemetry.counter_add("entries_from_peers", 1)
                        return True
                    except StreamRestartRequired as e:
                        # The owner's storage stream restarted (mirror
                        # failover): pre-restart bytes are discarded
                        # WHOLESALE and the final generation arrives
                        # complete — never spliced.
                        logger.warning(
                            "peer-fed stream of %s restarting through the "
                            "buffered path: %s",
                            path,
                            e,
                        )
                        telemetry.counter_add("stream_read_restarts", 1)
                        self._recharge(budget)
                with telemetry.span("peer_recv", cat="fanout", path=path):
                    buf = await role.buffered()
                n = memoryview(buf).nbytes
                throughput.add(n)
                telemetry.counter_add("bytes_read", n)
                telemetry.counter_add("bytes_from_peers", n)
                with telemetry.span("consume", path=path, bytes=n):
                    await consumer.consume_buffer(buf, executor)
                telemetry.counter_add("entries_read", 1)
                telemetry.counter_add("entries_from_peers", 1)
                return True
        except (IOError, IntegrityError) as e:
            # IOError covers the whole transport failure family
            # (PeerTransferError, short/over-long transfers);
            # IntegrityError a checksum mismatch of peer-delivered bytes
            # — storage may still hold good bytes, so re-read directly
            # (and surface storage's own error if it does not).
            # The degraded-path exception is accounted exactly like a
            # storage retry: classify_error kind + history attrs on the
            # exception object, one taxonomy for every fallback.
            from .storage_plugins.retry import attach_fallback_history

            kind = attach_fallback_history(e)
            logger.warning(
                "peer-fed read of %s from rank %s failed (%s: %s); falling "
                "back to a direct storage read",
                path,
                role.owner,
                type(e).__name__,
                e,
            )
            telemetry.counter_add("fanout_fallbacks", 1)
            telemetry.flightrec.record(
                "fanout.fallback", key=path, owner=role.owner, kind=kind
            )
            telemetry.event(
                "fanout_fallback",
                cat="retry",
                kind=kind,
                path=path,
                source=role.owner,
                error=type(e).__name__,
            )
            self._recharge(budget)
            return False

    # ------------------------------------------------- owner forwarding

    async def _forward_buffer(self, role, buf) -> None:
        """Forward a buffered owner read to the subscribers, chunked at
        the peer sub-chunk size (one frame per chunk so receivers keep
        their incremental consume window)."""
        mv = memoryview(buf).cast("B")
        step = self.peer_sub_chunk or _DEFAULT_SUB_CHUNK_BYTES
        n = 0
        for lo in range(0, mv.nbytes, step):
            await role.chunk(self.coop_gen, n, mv[lo : lo + step])
            n += 1
        await role.end(self.coop_gen, mv.nbytes, n)

    async def _stream_read_and_consume(
        self, storage: StoragePlugin, executor, throughput: _Throughput
    ) -> bool:
        """Fused read+consume: the plugin yields sub-chunks as the
        transport delivers them and the consumer verifies/decodes each
        while the next is still in flight — the entry's restore wall
        becomes ~max(read, consume) instead of read + consume. Returns
        False when the stream demands a from-offset-0 restart
        (StreamRestartRequired); the caller then re-runs the entry
        through the buffered path.

        Under a cooperative SendRole every sub-chunk is ALSO forwarded
        to the subscribing peers with a one-send lookahead (chunk N
        ships while the local consumer decodes it), so peer consumption
        overlaps this owner's storage read; a restart bumps the
        generation so receivers discard pre-restart bytes wholesale."""
        read_io = ReadIO(
            path=self.read_req.path, byte_range=self.read_req.byte_range
        )
        consumer = self.read_req.buffer_consumer
        role = self.coop_role
        send = role if (role is not None and role.is_send) else None
        sent = {"n": 0, "bytes": 0}

        plugin_key = type(storage).__name__

        async def counted(chunks):
            pending_send = None
            observe = telemetry.enabled()
            try:
                while True:
                    t0 = telemetry.monotonic() if observe else None
                    try:
                        chunk = await chunks.__anext__()
                    except StopAsyncIteration:
                        break
                    if t0 is not None:
                        telemetry.histogram_observe(
                            "read.sub_chunk_s",
                            telemetry.monotonic() - t0,
                            key=plugin_key,
                        )
                    n = memoryview(chunk).nbytes
                    throughput.add(n)
                    telemetry.counter_add("bytes_read", n)
                    if send is not None:
                        telemetry.counter_add("bytes_from_storage", n)
                        if pending_send is not None:
                            await pending_send
                        pending_send = asyncio.get_running_loop().create_task(
                            send.chunk(self.coop_gen, sent["n"], chunk)
                        )
                        sent["n"] += 1
                        sent["bytes"] += n
                    yield chunk
                if pending_send is not None:
                    await pending_send
                    pending_send = None
            finally:
                if pending_send is not None:
                    # Unwinding mid-stream (consumer error/restart): let
                    # the in-flight frame land whole before closing.
                    try:
                        await pending_send
                    except Exception:  # noqa: BLE001 - unwind path
                        pass

        try:
            with forensics.storage_op(
                "storage_read", path=self.read_req.path
            ), telemetry.span(
                "stream_read",
                path=self.read_req.path,
                sub_chunk_bytes=self.sub_chunk_bytes,
            ) as sp:
                stream = await storage.read_stream(read_io, self.sub_chunk_bytes)
                sp.set(bytes=stream.nbytes)
                try:
                    await consumer.consume_stream(
                        ReadStream(
                            path=stream.path,
                            nbytes=stream.nbytes,
                            chunks=counted(stream.chunks),
                        ),
                        executor,
                    )
                finally:
                    aclose = getattr(stream.chunks, "aclose", None)
                    if aclose is not None:
                        await aclose()
        except StreamRestartRequired as e:
            logger.warning(
                "streamed read of %s restarting through the buffered "
                "path: %s",
                self.read_req.path,
                e,
            )
            telemetry.counter_add("stream_read_restarts", 1)
            if send is not None:
                # Subscribers must never splice post-restart bytes after
                # pre-restart ones: bump the generation (receivers drop
                # everything older) and re-forward the complete payload
                # from the buffered retry.
                self.coop_gen += 1
                await send.restart(self.coop_gen)
            return False
        if send is not None:
            await send.end(self.coop_gen, sent["bytes"], sent["n"])
        telemetry.counter_add("entries_read", 1)
        telemetry.counter_add("entries_stream_read", 1)
        return True

    async def read_and_consume(
        self,
        storage: StoragePlugin,
        executor,
        throughput: _Throughput,
        budget: Optional["_MemoryBudget"] = None,
    ) -> "_ReadPipeline":
        if self.is_recv:
            if await self._peer_read_and_consume(executor, throughput, budget):
                return self
            # Peer delivery failed (owner death / abort / timeout /
            # integrity): degrade to a direct storage read — the budget
            # difference was already re-charged. Dual-mode consumers
            # (reshard.PlannedRecvConsumer, whose peer payload is a
            # region BUNDLE rather than the stored payload) are told
            # first, so the re-read of the same request decodes as raw
            # storage bytes. The fallback is a REAL storage request that
            # dispatch's slot exemption never counted, so it takes a
            # slot here: a mass peer failure (dead owner with many
            # units) must not flood the backend with more concurrent
            # reads than the governor's cap.
            on_fallback = getattr(
                self.read_req.buffer_consumer, "on_peer_fallback", None
            )
            if on_fallback is not None:
                on_fallback()
            if self.fallback_gate is not None:
                async with self.fallback_gate:
                    await self._buffered_read_and_consume(
                        storage, executor, throughput, budget
                    )
            else:
                await self._buffered_read_and_consume(
                    storage, executor, throughput, budget
                )
            return self
        if self.streamed and await self._stream_read_and_consume(
            storage, executor, throughput
        ):
            return self
        await self._buffered_read_and_consume(storage, executor, throughput, budget)
        return self

    async def _buffered_read_and_consume(
        self,
        storage: StoragePlugin,
        executor,
        throughput: _Throughput,
        budget: Optional["_MemoryBudget"] = None,
    ) -> None:
        # The buffered retry/fallback holds the FULL payload while the
        # budget only charged the streamed window: charge the difference
        # (possibly driving availability negative, like the starvation
        # escape) so concurrent dispatch throttles instead of
        # overshooting the per-rank budget unaccounted.
        self._recharge(budget)
        read_io = ReadIO(
            path=self.read_req.path, byte_range=self.read_req.byte_range
        )
        br = read_io.byte_range
        if br is not None and br[1] <= br[0]:
            # Zero-length range (e.g. a zero-size array packed into a slab):
            # skip storage entirely — remote backends mishandle inverted or
            # empty Range headers (S3 ignores them, GCS returns 416).
            read_io.buf = bytearray()
        else:
            t0 = telemetry.monotonic() if telemetry.enabled() else None
            with forensics.storage_op(
                "storage_read", path=self.read_req.path
            ), telemetry.span("storage_read", path=self.read_req.path) as sp:
                await storage.read(read_io)
                sp.set(bytes=memoryview(read_io.buf).nbytes)
            if t0 is not None:
                telemetry.histogram_observe(
                    "read.entry_s",
                    telemetry.monotonic() - t0,
                    key=type(storage).__name__,
                )
        buf = read_io.buf
        throughput.add(len(buf))
        telemetry.counter_add("bytes_read", len(buf))
        telemetry.counter_add("entries_read", 1)
        role = self.coop_role
        if role is not None and role.is_send:
            telemetry.counter_add("bytes_from_storage", len(buf))
            # Forward BEFORE the local consume: subscribers' decode
            # pipelines start while this rank's consumer works.
            await self._forward_buffer(role, buf)
        with telemetry.span("consume", path=self.read_req.path, bytes=len(buf)):
            await self.read_req.buffer_consumer.consume_buffer(buf, executor)


async def execute_read_reqs(
    read_reqs: List[ReadReq],
    storage: StoragePlugin,
    memory_budget_bytes: int,
    rank: int,
    coop=None,
    preempt=None,
) -> None:
    event_loop = asyncio.get_running_loop()
    executor = ThreadPoolExecutor(max_workers=_MAX_PER_RANK_CPU_CONCURRENCY)
    budget = _MemoryBudget(memory_budget_bytes)
    throughput = _Throughput("read", rank)
    reporter = _ProgressReporter("read", rank, len(read_reqs), budget)
    reporter.start()

    governor = io_governor()
    plugin_key = type(storage).__name__
    # Closed-loop hook (see execute_write_reqs): trial arming must
    # precede the elections below.
    governor.begin_io_op("read", plugin_key)
    # Streamed-read election mirrors the write side: only plugins that
    # produce chunks incrementally are eligible (the buffered read_stream
    # fallback would hold a full entry while the budget charged a
    # window), and each consumer still opts in per entry via can_stream.
    # Sub-chunk size comes from the measured READ bandwidth.
    mode = stream_reads_mode()
    sub_chunk = (
        governor.sub_chunk_bytes(plugin_key, op="read")
        if mode != "never"
        and getattr(storage, "supports_streaming_reads", False)
        else None
    )
    # Full-retention consumers stream too when the storage is measurably
    # latency-bound — there, overlap hides transport latency regardless
    # of the budget charge. No measurement means no evidence: buffered.
    read_bps = governor.read_bps(plugin_key)
    stream_all = mode == "always" or (
        read_bps is not None and read_bps < _STREAM_READ_LATENCY_BPS
    )
    # Cooperative fan-out (fanout.py): ``coop`` is this key's CoopKeyPlan.
    # The peer sub-chunk size is independent of the storage plugin's
    # streaming support — the peer channel always produces chunks
    # incrementally, and owners chunk buffered forwards at this size too.
    peer_chunk = (
        governor.sub_chunk_bytes(plugin_key, op="read") if coop is not None else None
    )
    pending = [
        _ReadPipeline(
            req,
            sub_chunk_bytes=sub_chunk,
            stream_all=stream_all,
            coop_plan=coop,
            peer_sub_chunk=peer_chunk,
        )
        for req in read_reqs
    ]
    reporter.total_bytes = sum(p.consuming_cost_bytes for p in pending)
    # Peer-fed entries dispatch first (no storage I/O; draining inboxes
    # early bounds receiver-side buffering), then owned/forwarding
    # entries (peers are waiting on them), then plain reads — and within
    # each class, largest first for budget packing.
    pending.sort(key=lambda p: (p.coop_order, -p.consuming_cost_bytes))
    n_streamed = sum(1 for p in pending if p.streamed)
    if n_streamed:
        logger.debug(
            "[rank %d] streaming %d/%d read(s) in %d MB sub-chunks",
            rank,
            n_streamed,
            len(pending),
            (sub_chunk or 0) >> 20,
        )
    inflight: Set[asyncio.Task] = set()
    inflight_recv = 0
    io_concurrency = governor.io_concurrency("read", plugin_key)
    # Tenancy admission, read side (see execute_write_reqs): scaled slot
    # cap + per-request pacing. Peer-fed entries are never paced — they
    # issue no storage request (their direct fallbacks are).
    admission = getattr(storage, "_tsnap_admission", None)
    if admission is not None:
        io_concurrency = admission.scale_concurrency(io_concurrency)

    async def _paced(coro, nbytes):
        await admission.admit(nbytes, "read", plugin_key)
        return await coro

    telemetry.record_election(
        site="read",
        plugin=plugin_key,
        mode=mode,
        streaming=sub_chunk is not None,
        streamed_entries=n_streamed,
        stream_all=stream_all,
        sub_chunk_bytes=sub_chunk,
        io_concurrency=io_concurrency,
        coop=coop is not None,
        read_bps=read_bps,
    )
    if coop is not None:
        fallback_gate = asyncio.Semaphore(io_concurrency)
        for p in pending:
            if p.is_recv:
                p.fallback_gate = fallback_gate

    def dispatch() -> None:
        nonlocal inflight_recv

        def launch(pipeline: _ReadPipeline) -> None:
            nonlocal inflight_recv
            budget.acquire(pipeline.admission_cost_bytes)
            if pipeline.is_recv:
                inflight_recv += 1
            read_coro = pipeline.read_and_consume(
                storage, executor, throughput, budget
            )
            if admission is not None and not pipeline.is_recv:
                read_coro = _paced(read_coro, pipeline.admission_cost_bytes)
            inflight.add(event_loop.create_task(read_coro))
            reporter.inflight_io += 1

        while pending:
            # Preemptible background pipeline (pagein.py): while the
            # hook reports a demand fault in flight, this execution
            # trickles — at most ONE request in flight (forward progress
            # is guaranteed; a full pause would deadlock a fault that
            # waits on this very batch) — so its I/O slots, and the
            # admission share they draw from, yield to the fault.
            if preempt is not None and inflight and preempt():
                break
            head = pending[0]
            # Peer-fed entries are exempt from the I/O slot cap: they
            # issue no storage request while waiting, and capping them
            # could starve the very sends that feed them. (Their direct
            # fallbacks DO take a slot — the fallback gate below.)
            if not head.is_recv and (len(inflight) - inflight_recv) >= io_concurrency:
                break
            cost = head.admission_cost_bytes
            if cost > budget.available and inflight:
                # Budget-blocked head. Parked peer-fed entries hold
                # budget while WAITING on peers' forwards; if everything
                # in flight is peer-fed, no LOCAL work will ever release
                # budget, and the owned/plain reads that feed the fleet
                # must not sit behind them — that head-of-line stall
                # would idle every rank into the coop timeout. Admit the
                # first non-peer-fed entry over budget instead (the same
                # starvation escape the write pipeline uses); the escape
                # self-closes once any non-recv work is in flight.
                if inflight_recv == len(inflight):
                    idx = next(
                        (i for i, p in enumerate(pending) if not p.is_recv),
                        None,
                    )
                    if idx is not None:
                        telemetry.counter_add("budget_defers", 1)
                        launch(pending.pop(idx))
                        continue
                break
            launch(pending.pop(0))

    dispatch()
    try:
        while inflight or pending:
            done, inflight_set = await asyncio.wait(
                inflight, return_when=asyncio.FIRST_COMPLETED
            )
            inflight = inflight_set
            for task in done:
                pipeline = task.result()
                budget.release(pipeline.admission_cost_bytes)
                if pipeline.is_recv:
                    inflight_recv -= 1
                reporter.inflight_io -= 1
                reporter.completed_count += 1
                reporter.completed_bytes += pipeline.consuming_cost_bytes
            dispatch()
    except BaseException:
        reporter.stop()
        for task in inflight:
            task.cancel()
        if inflight:
            await asyncio.gather(*inflight, return_exceptions=True)
        executor.shutdown(wait=True)
        raise
    reporter.stop()

    executor.shutdown(wait=True)
    throughput.log_summary()
    # Achieved read bandwidth feeds the restore-side preverify economics
    # (hash vs re-read) and concurrency tuning, via the bus's governor
    # listener.
    telemetry.record_rate(
        "read", type(storage).__name__, throughput.total_bytes, throughput.elapsed()
    )


def sync_execute_read_reqs(
    read_reqs: List[ReadReq],
    storage: StoragePlugin,
    memory_budget_bytes: int,
    rank: int,
    event_loop: asyncio.AbstractEventLoop,
    coop=None,
    preempt=None,
) -> None:
    event_loop.run_until_complete(
        execute_read_reqs(
            read_reqs, storage, memory_budget_bytes, rank, coop=coop,
            preempt=preempt,
        )
    )
