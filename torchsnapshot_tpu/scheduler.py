"""Memory-budgeted async execution engine for write/read requests.

TPU-native redesign of the reference scheduler (torchsnapshot/scheduler.py):
two asyncio pipelines under a per-process host-memory budget.

Write pipeline::

    ready_for_staging -> staging -> ready_for_io -> io -> done

Staging performs the device->host boundary crossing (for jax.Arrays the
stager issues ``copy_to_host_async`` DMA and materializes a numpy view) and
serialization; it is capped by the memory budget, with a starvation escape
that admits one over-budget request when nothing is in flight (otherwise a
single huge array could deadlock the pipeline; reference: scheduler.py:255-275).
I/O concurrency is capped at 16 in-flight requests (scheduler.py:30).

``execute_write_reqs`` returns a :class:`PendingIOWork` as soon as **staging**
completes — this is the consistency point that lets ``async_take`` guarantee
that mutations after it returns do not affect the snapshot, while storage I/O
continues in the background (reference: scheduler.py:297-337).

Read pipeline:: read -> consume, with the same budget accounting
(scheduler.py:384-444).

The per-process budget is ``min(0.6 * available_memory / local_world_size,
32 GiB)``, overridable via ``TORCHSNAPSHOT_TPU_PER_RANK_MEMORY_BUDGET_BYTES``
(scheduler.py:27-65).
"""

from __future__ import annotations

import asyncio
import logging
import os
import socket
import time
from concurrent.futures import ThreadPoolExecutor
from typing import List, Optional, Set

import psutil

from .io_types import ReadReq, StoragePlugin, WriteIO, WriteReq, ReadIO

logger = logging.getLogger(__name__)

def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "").strip()
    if raw:
        try:
            return max(1, int(raw))
        except ValueError:
            logger.warning("ignoring non-integer %s=%r", name, raw)
    return default


try:
    # Respects cgroup cpusets/affinity masks: a pod limited to 2 cores on
    # a 64-core node must get the few-core defaults, not 64's.
    _CPU_COUNT = len(os.sched_getaffinity(0)) or 1
except (AttributeError, OSError):  # pragma: no cover - non-Linux
    _CPU_COUNT = os.cpu_count() or 1
IO_CONCURRENCY_ENV_VAR = "TORCHSNAPSHOT_TPU_IO_CONCURRENCY"
CPU_CONCURRENCY_ENV_VAR = "TORCHSNAPSHOT_TPU_CPU_CONCURRENCY"
# Scaled to the host rather than fixed: on few-core machines 16
# concurrent 64 MB streams + 4 copy workers thrash the cache hierarchy —
# measured 3.4x more CPU burned for the same 1 GiB restore on one core
# (and the GIL convoy inflates every op's wall time). Floors keep enough
# I/O parallelism to hide per-request latency on network storage.
_MAX_PER_RANK_IO_CONCURRENCY = _env_int(
    IO_CONCURRENCY_ENV_VAR, min(16, max(8, 2 * _CPU_COUNT))
)
_MAX_PER_RANK_CPU_CONCURRENCY = _env_int(
    CPU_CONCURRENCY_ENV_VAR, min(4, max(2, _CPU_COUNT // 2))
)
_AVAILABLE_MEMORY_MULTIPLIER = 0.6
_MAX_PER_RANK_MEMORY_BUDGET_BYTES = 32 * 1024**3
_MEMORY_BUDGET_ENV_VAR = "TORCHSNAPSHOT_TPU_PER_RANK_MEMORY_BUDGET_BYTES"


def get_local_world_size(pg=None) -> int:
    """Number of processes on this host, via hostname all-gather
    (reference: scheduler.py:33-42)."""
    if pg is None or pg.get_world_size() == 1:
        return 1
    hostnames = pg.all_gather_object(socket.gethostname())
    return max(1, hostnames.count(socket.gethostname()))


def get_process_memory_budget_bytes(pg=None) -> int:
    env = os.environ.get(_MEMORY_BUDGET_ENV_VAR)
    if env is not None:
        budget = int(env)
        logger.info("Manually set process memory budget to %d bytes.", budget)
        return budget
    local_world_size = get_local_world_size(pg)
    available = psutil.virtual_memory().available
    budget = min(
        int(available * _AVAILABLE_MEMORY_MULTIPLIER) // local_world_size,
        _MAX_PER_RANK_MEMORY_BUDGET_BYTES,
    )
    logger.debug("Process memory budget: %d bytes.", budget)
    return budget


class _WritePipeline:
    def __init__(self, write_req: WriteReq) -> None:
        self.write_req = write_req
        self.staging_cost_bytes: int = (
            write_req.buffer_stager.get_staging_cost_bytes()
        )
        self.buf = None
        self.buf_size_bytes: Optional[int] = None
        self.io_skipped = False

    async def stage_buffer(self, executor) -> "_WritePipeline":
        self.buf = await self.write_req.buffer_stager.stage_buffer(executor)
        self.buf_size_bytes = memoryview(self.buf).nbytes
        # Incremental snapshots: the stager found the payload unchanged in a
        # base snapshot — drop the buffer instead of writing it.
        if getattr(self.write_req.buffer_stager, "io_skipped", False):
            self.io_skipped = True
            self.buf = None
            self.buf_size_bytes = 0
        return self

    async def write_buffer(self, storage: StoragePlugin) -> "_WritePipeline":
        assert self.buf is not None
        await storage.write(WriteIO(path=self.write_req.path, buf=self.buf))
        self.buf = None  # release the staged buffer eagerly
        return self


class _ProgressReporter:
    """Periodic pipeline progress tables (reference: _WriteReporter,
    scheduler.py:96-175): stage counts, bytes staged/written, budget
    remaining, and RSS delta — the observability needed to diagnose a stall
    on a real pod save. Runs as an asyncio task on the pipeline's loop;
    logs at INFO every ``interval_s``."""

    def __init__(
        self,
        op: str,
        rank: int,
        total: int,
        budget: "_MemoryBudget",
        interval_s: float = 5.0,
    ) -> None:
        self.op = op
        self.rank = rank
        self.total = total
        self.budget = budget
        self.interval_s = interval_s
        self.staged_count = 0
        self.staged_bytes = 0
        # Op-neutral completion counters: "written" entries for the write
        # pipeline, "consumed" reads for the read pipeline (the log wording
        # is per-op; the fields are shared).
        self.completed_count = 0
        self.completed_bytes = 0
        self.inflight_staging = 0
        self.inflight_io = 0
        self._begin = time.monotonic()
        try:
            self._rss_begin = psutil.Process().memory_info().rss
        except Exception:  # pragma: no cover
            self._rss_begin = 0
        self._task: Optional[asyncio.Task] = None

    def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self._loop())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    async def _loop(self) -> None:
        try:
            while True:
                await asyncio.sleep(self.interval_s)
                self.log_table()
        except asyncio.CancelledError:
            pass

    def log_table(self) -> None:
        try:
            rss_delta = psutil.Process().memory_info().rss - self._rss_begin
        except Exception:  # pragma: no cover
            rss_delta = 0
        elapsed = time.monotonic() - self._begin
        if self.op == "read":
            # The read pipeline has no staging phase: report in-flight and
            # consumed counts with read-appropriate wording.
            logger.info(
                "[rank %d] read progress +%.0fs | reqs: %d total, %d in "
                "flight, %d consumed | %.2f GB consumed | budget free "
                "%.2f/%.2f GB | rss delta %+.2f GB",
                self.rank,
                elapsed,
                self.total,
                self.inflight_io,
                self.completed_count,
                self.completed_bytes / 1e9,
                self.budget.available / 1e9,
                self.budget.budget_bytes / 1e9,
                rss_delta / 1e9,
            )
            return
        logger.info(
            "[rank %d] %s progress +%.0fs | reqs: %d total, %d staging, "
            "%d staged, %d in io, %d written | %.2f GB staged, %.2f GB "
            "written | budget free %.2f/%.2f GB | rss delta %+.2f GB",
            self.rank,
            self.op,
            elapsed,
            self.total,
            self.inflight_staging,
            self.staged_count,
            self.inflight_io,
            self.completed_count,
            self.staged_bytes / 1e9,
            self.completed_bytes / 1e9,
            self.budget.available / 1e9,
            self.budget.budget_bytes / 1e9,
            rss_delta / 1e9,
        )


class _Throughput:
    """Tracks bytes moved + wall time to log MB/s summaries
    (reference: scheduler.py:96-175,441-442)."""

    def __init__(self, op: str, rank: int) -> None:
        self.op = op
        self.rank = rank
        self.begin = time.monotonic()
        self.total_bytes = 0

    def add(self, nbytes: int) -> None:
        self.total_bytes += nbytes

    def log_summary(self) -> None:
        elapsed = max(time.monotonic() - self.begin, 1e-9)
        logger.info(
            "[rank %d] %s %.1f MB in %.2fs (%.1f MB/s)",
            self.rank,
            self.op,
            self.total_bytes / 1e6,
            elapsed,
            self.total_bytes / 1e6 / elapsed,
        )


class PendingIOWork:
    """Handle over storage I/O still in flight after staging completed."""

    def __init__(
        self,
        ready_for_io: List[_WritePipeline],
        io_tasks: Set[asyncio.Task],
        storage: StoragePlugin,
        memory_budget: "_MemoryBudget",
        executor: ThreadPoolExecutor,
        throughput: _Throughput,
        event_loop: asyncio.AbstractEventLoop,
        reporter: Optional[_ProgressReporter] = None,
    ) -> None:
        self._ready_for_io = ready_for_io
        self._io_tasks = io_tasks
        self._storage = storage
        self._budget = memory_budget
        self._executor = executor
        self._throughput = throughput
        self._event_loop = event_loop
        self._reporter = reporter

    async def complete(self) -> None:
        reporter = self._reporter
        if reporter is not None:
            reporter.start()
        try:
            while self._io_tasks or self._ready_for_io:
                self._dispatch_io()
                if not self._io_tasks:
                    continue
                done, pending = await asyncio.wait(
                    self._io_tasks, return_when=asyncio.FIRST_COMPLETED
                )
                self._io_tasks = pending
                for task in done:
                    pipeline = task.result()
                    self._budget.release(pipeline.buf_size_bytes)
                    self._throughput.add(pipeline.buf_size_bytes)
                    if reporter is not None:
                        reporter.inflight_io -= 1
                        reporter.completed_count += 1
                        reporter.completed_bytes += pipeline.buf_size_bytes
        except BaseException:
            # Same cleanup as execute_write_reqs' failure path: a write
            # failing during the drain must not orphan sibling writes or
            # leak the executor's threads.
            for task in self._io_tasks:
                task.cancel()
            if self._io_tasks:
                await asyncio.gather(*self._io_tasks, return_exceptions=True)
            self._io_tasks = set()
            self._ready_for_io.clear()
            self._executor.shutdown(wait=True)
            raise
        finally:
            if reporter is not None:
                reporter.stop()
        self._executor.shutdown(wait=True)
        self._throughput.log_summary()

    def _dispatch_io(self) -> None:
        while (
            self._ready_for_io
            and len(self._io_tasks) < _MAX_PER_RANK_IO_CONCURRENCY
        ):
            pipeline = self._ready_for_io.pop(0)
            self._io_tasks.add(
                self._event_loop.create_task(pipeline.write_buffer(self._storage))
            )
            if self._reporter is not None:
                self._reporter.inflight_io += 1

    def sync_complete(self, event_loop: asyncio.AbstractEventLoop) -> None:
        event_loop.run_until_complete(self.complete())

    async def abort(self) -> None:
        """Cancel in-flight storage writes and release resources.

        Used when a peer rank's failure aborts the snapshot: without this,
        dispatched writes keep running unawaited (orphaned partial objects,
        swallowed I/O errors) and the executor's threads leak."""
        self._ready_for_io.clear()
        for task in self._io_tasks:
            task.cancel()
        if self._io_tasks:
            await asyncio.gather(*self._io_tasks, return_exceptions=True)
        self._io_tasks = set()
        self._executor.shutdown(wait=True)

    def sync_abort(self, event_loop: asyncio.AbstractEventLoop) -> None:
        event_loop.run_until_complete(self.abort())


class _MemoryBudget:
    def __init__(self, budget_bytes: int) -> None:
        self.budget_bytes = budget_bytes
        self.available = budget_bytes

    def acquire(self, nbytes: int) -> None:
        self.available -= nbytes

    def release(self, nbytes: int) -> None:
        self.available += nbytes


async def execute_write_reqs(
    write_reqs: List[WriteReq],
    storage: StoragePlugin,
    memory_budget_bytes: int,
    rank: int,
) -> PendingIOWork:
    event_loop = asyncio.get_running_loop()
    executor = ThreadPoolExecutor(max_workers=_MAX_PER_RANK_CPU_CONCURRENCY)
    budget = _MemoryBudget(memory_budget_bytes)
    throughput = _Throughput("wrote", rank)
    reporter = _ProgressReporter("write", rank, len(write_reqs), budget)
    reporter.start()

    ready_for_staging = [_WritePipeline(req) for req in write_reqs]
    # Stage large requests first: improves budget packing and overlaps the
    # slowest DtoH copies with I/O of everything else.
    ready_for_staging.sort(key=lambda p: p.staging_cost_bytes, reverse=True)
    staging_tasks: Set[asyncio.Task] = set()
    io_tasks: Set[asyncio.Task] = set()
    ready_for_io: List[_WritePipeline] = []

    def dispatch_staging() -> None:
        while ready_for_staging:
            cost = ready_for_staging[0].staging_cost_bytes
            if cost > budget.available:
                # Starvation escape: if nothing is in flight, admit the
                # over-budget request — otherwise it would never run.
                if staging_tasks or io_tasks or ready_for_io:
                    break
            pipeline = ready_for_staging.pop(0)
            budget.acquire(pipeline.staging_cost_bytes)
            staging_tasks.add(
                event_loop.create_task(pipeline.stage_buffer(executor))
            )
            reporter.inflight_staging += 1

    def dispatch_io() -> None:
        while ready_for_io and len(io_tasks) < _MAX_PER_RANK_IO_CONCURRENCY:
            pipeline = ready_for_io.pop(0)
            io_tasks.add(event_loop.create_task(pipeline.write_buffer(storage)))
            reporter.inflight_io += 1

    dispatch_staging()
    try:
        while staging_tasks or ready_for_staging:
            done, _ = await asyncio.wait(
                staging_tasks | io_tasks, return_when=asyncio.FIRST_COMPLETED
            )
            for task in done:
                if task in staging_tasks:
                    staging_tasks.discard(task)
                    pipeline = task.result()
                    # The staged buffer may be smaller than the staging cost
                    # (e.g. a strided view); release the difference now.
                    budget.release(
                        pipeline.staging_cost_bytes - pipeline.buf_size_bytes
                    )
                    if not pipeline.io_skipped:
                        ready_for_io.append(pipeline)
                    reporter.inflight_staging -= 1
                    reporter.staged_count += 1
                    reporter.staged_bytes += pipeline.buf_size_bytes
                elif task in io_tasks:
                    io_tasks.discard(task)
                    pipeline = task.result()
                    budget.release(pipeline.buf_size_bytes)
                    throughput.add(pipeline.buf_size_bytes)
                    reporter.inflight_io -= 1
                    reporter.completed_count += 1
                    reporter.completed_bytes += pipeline.buf_size_bytes
            dispatch_io()
            dispatch_staging()
    except BaseException:
        # A staging/I/O failure aborts the snapshot: cancel siblings and
        # release the executor so repeated failures don't leak threads.
        reporter.stop()
        for task in staging_tasks | io_tasks:
            task.cancel()
        if staging_tasks or io_tasks:
            await asyncio.gather(
                *(staging_tasks | io_tasks), return_exceptions=True
            )
        executor.shutdown(wait=True)
        raise
    reporter.stop()

    return PendingIOWork(
        ready_for_io=ready_for_io,
        io_tasks=io_tasks,
        storage=storage,
        memory_budget=budget,
        executor=executor,
        throughput=throughput,
        event_loop=event_loop,
        reporter=reporter,
    )


def sync_execute_write_reqs(
    write_reqs: List[WriteReq],
    storage: StoragePlugin,
    memory_budget_bytes: int,
    rank: int,
    event_loop: asyncio.AbstractEventLoop,
) -> None:
    pending = event_loop.run_until_complete(
        execute_write_reqs(write_reqs, storage, memory_budget_bytes, rank)
    )
    pending.sync_complete(event_loop)


class _ReadPipeline:
    def __init__(self, read_req: ReadReq) -> None:
        self.read_req = read_req
        self.consuming_cost_bytes: int = (
            read_req.buffer_consumer.get_consuming_cost_bytes()
        )

    async def read_and_consume(
        self, storage: StoragePlugin, executor, throughput: _Throughput
    ) -> "_ReadPipeline":
        read_io = ReadIO(
            path=self.read_req.path, byte_range=self.read_req.byte_range
        )
        br = read_io.byte_range
        if br is not None and br[1] <= br[0]:
            # Zero-length range (e.g. a zero-size array packed into a slab):
            # skip storage entirely — remote backends mishandle inverted or
            # empty Range headers (S3 ignores them, GCS returns 416).
            read_io.buf = bytearray()
        else:
            await storage.read(read_io)
        buf = read_io.buf
        throughput.add(len(buf))
        await self.read_req.buffer_consumer.consume_buffer(buf, executor)
        return self


async def execute_read_reqs(
    read_reqs: List[ReadReq],
    storage: StoragePlugin,
    memory_budget_bytes: int,
    rank: int,
) -> None:
    event_loop = asyncio.get_running_loop()
    executor = ThreadPoolExecutor(max_workers=_MAX_PER_RANK_CPU_CONCURRENCY)
    budget = _MemoryBudget(memory_budget_bytes)
    throughput = _Throughput("read", rank)
    reporter = _ProgressReporter("read", rank, len(read_reqs), budget)
    reporter.start()

    pending = [_ReadPipeline(req) for req in read_reqs]
    pending.sort(key=lambda p: p.consuming_cost_bytes, reverse=True)
    inflight: Set[asyncio.Task] = set()

    def dispatch() -> None:
        while pending and len(inflight) < _MAX_PER_RANK_IO_CONCURRENCY:
            cost = pending[0].consuming_cost_bytes
            if cost > budget.available and inflight:
                break
            pipeline = pending.pop(0)
            budget.acquire(pipeline.consuming_cost_bytes)
            inflight.add(
                event_loop.create_task(
                    pipeline.read_and_consume(storage, executor, throughput)
                )
            )
            reporter.inflight_io += 1

    dispatch()
    try:
        while inflight or pending:
            done, inflight_set = await asyncio.wait(
                inflight, return_when=asyncio.FIRST_COMPLETED
            )
            inflight = inflight_set
            for task in done:
                pipeline = task.result()
                budget.release(pipeline.consuming_cost_bytes)
                reporter.inflight_io -= 1
                reporter.completed_count += 1
                reporter.completed_bytes += pipeline.consuming_cost_bytes
            dispatch()
    except BaseException:
        reporter.stop()
        for task in inflight:
            task.cancel()
        if inflight:
            await asyncio.gather(*inflight, return_exceptions=True)
        executor.shutdown(wait=True)
        raise
    reporter.stop()

    executor.shutdown(wait=True)
    throughput.log_summary()


def sync_execute_read_reqs(
    read_reqs: List[ReadReq],
    storage: StoragePlugin,
    memory_budget_bytes: int,
    rank: int,
    event_loop: asyncio.AbstractEventLoop,
) -> None:
    event_loop.run_until_complete(
        execute_read_reqs(read_reqs, storage, memory_budget_bytes, rank)
    )
