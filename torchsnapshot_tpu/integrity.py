"""End-to-end snapshot integrity: per-entry CRC32C checksums.

A capability the reference does not have (its only corruption defense is the
metadata-last commit protocol, snapshot.py:230-237 — torn writes are
invisible, but bit rot and truncation inside a committed snapshot are not
detected). Every serialized buffer gets a CRC32C recorded in its manifest
entry at stage time and verified at consume time on restore; cost is
negligible with the native SSE4.2 path (GB/s-scale, see _native).

Checksums are written by default and verified by default when present.
Partial (byte-range sub-chunk) reads of an entry can't be verified — only
complete-payload reads are checked (the common restore path).

Env:
  TORCHSNAPSHOT_TPU_CHECKSUM=0  - don't record checksums on save
  TORCHSNAPSHOT_TPU_VERIFY=0    - don't verify checksums on restore
"""

from __future__ import annotations

import logging
import os
import zlib

from ._native import crc32c, native_available

logger = logging.getLogger(__name__)

CHECKSUM_ENV_VAR = "TORCHSNAPSHOT_TPU_CHECKSUM"
VERIFY_ENV_VAR = "TORCHSNAPSHOT_TPU_VERIFY"


class IntegrityError(RuntimeError):
    """A restored buffer's checksum did not match the manifest."""


def _env_on(name: str) -> bool:
    return os.environ.get(name, "1") not in ("0", "false", "")


def checksums_enabled() -> bool:
    return _env_on(CHECKSUM_ENV_VAR)


def verification_enabled() -> bool:
    return _env_on(VERIFY_ENV_VAR)


def compute_checksum(buf) -> str:
    """Hash at C speed whatever the environment: CRC32C via the native
    extension (SSE4.2, GB/s) when it built, else stdlib zlib CRC32 (still
    ~GB/s) under its own algorithm tag — never the pure-Python CRC32C loop,
    which would turn multi-GB saves into minutes of hashing."""
    if native_available():
        return f"crc32c:{crc32c(buf):08x}"
    data = memoryview(buf).cast("B")
    return f"crc32:{zlib.crc32(data) & 0xFFFFFFFF:08x}"


_warned_slow_crc32c = False


def verify_checksum(buf, expected: str, path: str) -> None:
    """Raise IntegrityError if ``buf`` doesn't hash to ``expected``.

    Unknown algorithms are skipped (forward compatibility: a newer writer
    may record an algorithm this build doesn't know). A crc32c checksum on
    a host where the native extension is unavailable is also skipped, with
    a one-time warning — the pure-Python fallback would slow restores by
    orders of magnitude.
    """
    algo, _, digest = expected.partition(":")
    if algo == "crc32c":
        if not native_available():
            global _warned_slow_crc32c
            if not _warned_slow_crc32c:
                _warned_slow_crc32c = True
                logger.warning(
                    "Snapshot records crc32c checksums but the native "
                    "extension is unavailable on this host; skipping "
                    "verification (pure-Python CRC32C is too slow for "
                    "checkpoint-sized data)."
                )
            return
        actual = f"{crc32c(buf):08x}"
    elif algo == "crc32":
        actual = f"{zlib.crc32(memoryview(buf).cast('B')) & 0xFFFFFFFF:08x}"
    else:
        return
    if actual != digest:
        raise IntegrityError(
            f"checksum mismatch reading {path!r}: manifest records "
            f"{algo}:{digest}, buffer hashes to {algo}:{actual} — the "
            f"snapshot data is corrupt (truncated, bit-rotted, or "
            f"overwritten since save)."
        )


class IncrementalVerifier:
    """Chained-checksum verification for a STREAMED consume.

    ``update`` advances the running CRC over each stored sub-chunk as it
    arrives (CRC32C/CRC32 chain over concatenated windows — identical to
    hashing the whole buffer, so streamed and buffered consumes of the
    same bytes accept/reject identically); ``finish`` compares against
    the manifest and raises :class:`IntegrityError` on mismatch. The
    skip semantics mirror :func:`verify_checksum` exactly: verification
    disabled, no recorded checksum, an unknown algorithm, or crc32c
    without the native extension (same one-time warning) all verify
    nothing."""

    __slots__ = ("_algo", "_value", "_digest", "_path")

    def __init__(self, expected, path: str) -> None:
        self._algo = None
        self._value = 0
        self._digest = ""
        self._path = path
        if expected is None or not verification_enabled():
            return
        algo, _, digest = expected.partition(":")
        if algo == "crc32c":
            if not native_available():
                global _warned_slow_crc32c
                if not _warned_slow_crc32c:
                    _warned_slow_crc32c = True
                    logger.warning(
                        "Snapshot records crc32c checksums but the native "
                        "extension is unavailable on this host; skipping "
                        "verification (pure-Python CRC32C is too slow for "
                        "checkpoint-sized data)."
                    )
                return
            self._algo, self._digest = "crc32c", digest
        elif algo == "crc32":
            self._algo, self._digest = "crc32", digest

    def update(self, chunk) -> None:
        if self._algo == "crc32c":
            self._value = crc32c(chunk, self._value)
        elif self._algo == "crc32":
            self._value = zlib.crc32(memoryview(chunk).cast("B"), self._value)

    def finish(self) -> None:
        if self._algo is None:
            return
        actual = f"{self._value & 0xFFFFFFFF:08x}"
        if actual != self._digest:
            raise IntegrityError(
                f"checksum mismatch reading {self._path!r}: manifest records "
                f"{self._algo}:{self._digest}, stream hashes to "
                f"{self._algo}:{actual} — the snapshot data is corrupt "
                f"(truncated, bit-rotted, or overwritten since save)."
            )
