"""End-to-end snapshot integrity: per-entry CRC32C checksums.

A capability the reference does not have (its only corruption defense is the
metadata-last commit protocol, snapshot.py:230-237 — torn writes are
invisible, but bit rot and truncation inside a committed snapshot are not
detected). Every serialized buffer gets a CRC32C recorded in its manifest
entry at stage time and verified at consume time on restore; cost is
negligible with the native SSE4.2 path (GB/s-scale, see _native).

Checksums are written by default and verified by default when present.
Partial (byte-range sub-chunk) reads of an entry can't be verified — only
complete-payload reads are checked (the common restore path).

Env:
  TORCHSNAPSHOT_TPU_CHECKSUM=0  - don't record checksums on save
  TORCHSNAPSHOT_TPU_VERIFY=0    - don't verify checksums on restore
"""

from __future__ import annotations

import os

from ._native import crc32c

CHECKSUM_ENV_VAR = "TORCHSNAPSHOT_TPU_CHECKSUM"
VERIFY_ENV_VAR = "TORCHSNAPSHOT_TPU_VERIFY"

_ALGO = "crc32c"


class IntegrityError(RuntimeError):
    """A restored buffer's checksum did not match the manifest."""


def _env_on(name: str) -> bool:
    return os.environ.get(name, "1") not in ("0", "false", "")


def checksums_enabled() -> bool:
    return _env_on(CHECKSUM_ENV_VAR)


def verification_enabled() -> bool:
    return _env_on(VERIFY_ENV_VAR)


def compute_checksum(buf) -> str:
    return f"{_ALGO}:{crc32c(buf):08x}"


def verify_checksum(buf, expected: str, path: str) -> None:
    """Raise IntegrityError if ``buf`` doesn't hash to ``expected``.

    Unknown algorithms are skipped (forward compatibility: a newer writer
    may record an algorithm this build doesn't know).
    """
    algo, _, digest = expected.partition(":")
    if algo != _ALGO:
        return
    actual = f"{crc32c(buf):08x}"
    if actual != digest:
        raise IntegrityError(
            f"checksum mismatch reading {path!r}: manifest records "
            f"{_ALGO}:{digest}, buffer hashes to {_ALGO}:{actual} — the "
            f"snapshot data is corrupt (truncated, bit-rotted, or "
            f"overwritten since save)."
        )
