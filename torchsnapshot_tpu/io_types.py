"""Layer-2/3 contracts between I/O preparation, execution, and storage.

TPU-native analogue of the reference's io_types (torchsnapshot/io_types.py:19-103):
the scheduler operates purely on bytes + cost callbacks, so it stays agnostic of
jax.Array vs numpy vs pickled objects. Buffer stagers perform the device->host
boundary crossing (async DMA via jax.Array.copy_to_host_async); buffer consumers
perform host->device materialization.
"""

from __future__ import annotations

import abc
from dataclasses import dataclass
from typing import Awaitable, Callable, List, Optional, Tuple, Union

BufferType = Union[bytes, bytearray, memoryview]


@dataclass
class WriteIO:
    """A single write of a buffer to a storage path."""

    path: str
    buf: BufferType


@dataclass
class ReadIO:
    """A single read of a storage path, optionally a byte range [lo, hi).

    ``buf`` holds the fetched payload; consumers only read it (any
    buffer-protocol object works), so plugins should assign their transport's
    native buffer (bytes included) rather than copying into a bytearray —
    the copy would transiently double per-read host memory."""

    path: str
    buf: BufferType = b""
    byte_range: Optional[Tuple[int, int]] = None


class BufferStager(abc.ABC):
    # Stagers may set ``io_skipped = True`` during stage_buffer to tell the
    # scheduler the staged payload must NOT be written (incremental
    # snapshots: the bytes already exist in a base snapshot — dedup.py).
    """Produces the bytes to be written for one write request.

    ``stage_buffer`` runs inside the scheduler's staging pipeline under the
    memory budget. For device arrays this is where the DtoH copy happens.
    """

    @abc.abstractmethod
    async def stage_buffer(self, executor=None) -> BufferType:
        ...

    @abc.abstractmethod
    def get_staging_cost_bytes(self) -> int:
        """Peak host memory the staged buffer will occupy."""
        ...


class BufferConsumer(abc.ABC):
    """Consumes the bytes read for one read request."""

    @abc.abstractmethod
    async def consume_buffer(self, buf: BufferType, executor=None) -> None:
        ...

    @abc.abstractmethod
    def get_consuming_cost_bytes(self) -> int:
        """Peak host memory needed while consuming the buffer."""
        ...


@dataclass
class WriteReq:
    path: str
    buffer_stager: BufferStager


@dataclass
class ReadReq:
    path: str
    buffer_consumer: BufferConsumer
    byte_range: Optional[Tuple[int, int]] = None
    # Incremental snapshots: when set, the payload lives in this base
    # snapshot's storage, not the snapshot being restored; the orchestrator
    # groups reads by origin and opens one plugin per origin (dedup.py).
    origin: Optional[str] = None


class StoragePlugin(abc.ABC):
    """Storage backend interface (reference: io_types.py:54-103).

    Byte-range reads are first-class: the batcher and chunked-read paths rely
    on them. Implementations must be safe to drive from an asyncio event loop.
    """

    @abc.abstractmethod
    async def write(self, write_io: WriteIO) -> None:
        ...

    @abc.abstractmethod
    async def read(self, read_io: ReadIO) -> None:
        ...

    @abc.abstractmethod
    async def delete(self, path: str) -> None:
        ...

    @abc.abstractmethod
    async def close(self) -> None:
        ...

    async def drain_background(self) -> None:
        """Wait for plugin-internal background work (e.g. mirror
        replication) to finish. The snapshot orchestrator awaits this on
        every rank before the commit barrier; default: nothing to drain."""

    def sync_close(self, event_loop) -> None:
        event_loop.run_until_complete(self.close())
