"""Layer-2/3 contracts between I/O preparation, execution, and storage.

TPU-native analogue of the reference's io_types (torchsnapshot/io_types.py:19-103):
the scheduler operates purely on bytes + cost callbacks, so it stays agnostic of
jax.Array vs numpy vs pickled objects. Buffer stagers perform the device->host
boundary crossing (async DMA via jax.Array.copy_to_host_async); buffer consumers
perform host->device materialization.
"""

from __future__ import annotations

import abc
from dataclasses import dataclass
from typing import (
    AsyncIterator,
    Awaitable,
    Callable,
    List,
    Optional,
    Tuple,
    Union,
)

BufferType = Union[bytes, bytearray, memoryview]

# In-flight sub-chunks per streamed entry on the STAGER side: one being
# written plus one staging ahead (the stager's lookahead). The scheduler
# charges at least this window for a streamed entry; plugins with extra
# retention add theirs via ``StoragePlugin.stream_admission_cost``.
STREAM_DEPTH = 2


@dataclass
class WriteIO:
    """A single write of a buffer to a storage path."""

    path: str
    buf: BufferType


@dataclass
class WriteStream:
    """An ORDERED stream of sub-chunk buffers for one storage path.

    The streaming write path lets a single entry's DtoH copy,
    serialization, and storage write overlap: the stager yields 32-64 MB
    sub-chunks as they land on the host, and the plugin writes each one
    while the next is still being staged — the entry's critical path
    becomes ~max(stage, write) instead of stage + write.

    ``nbytes`` is the exact total payload size, known before the first
    chunk is produced (plugins use it to pick a protocol — e.g. S3
    multipart vs single PUT — and to validate the stream on completion).
    ``chunks`` yields buffers whose concatenation IS the payload; each
    buffer stays valid for as long as the plugin holds a reference
    (sub-chunk slabs are recycled by the GC, never in place), so cloud
    plugins may retain consumed chunks for retry replay — at the cost of
    holding that memory until the write commits.
    """

    path: str
    nbytes: int
    chunks: AsyncIterator[BufferType]


@dataclass
class ReadIO:
    """A single read of a storage path, optionally a byte range [lo, hi).

    ``buf`` holds the fetched payload; consumers only read it (any
    buffer-protocol object works), so plugins should assign their transport's
    native buffer (bytes included) rather than copying into a bytearray —
    the copy would transiently double per-read host memory."""

    path: str
    buf: BufferType = b""
    byte_range: Optional[Tuple[int, int]] = None


@dataclass
class ReadStream:
    """An ORDERED stream of sub-chunk buffers for one storage read.

    The read-side mirror of :class:`WriteStream`: instead of the whole
    payload landing in memory before the first byte is hashed,
    decompressed, or copied to device, the plugin yields 8-256 MB
    sub-chunks as the transport produces them and the consumer verifies/
    decodes each while the next is still being fetched — the entry's
    restore wall becomes ~max(read, consume) instead of read + consume,
    and in-flight host memory is the sub-chunk window, not the payload.

    ``nbytes`` is the exact payload size, known before the first chunk
    is produced (fs: stat; s3: HEAD or the byte range; gcs: metadata
    reload or the byte range). ``chunks`` yields buffers whose
    concatenation IS the payload ``StoragePlugin.read`` would have
    returned for the same request; each buffer stays valid for as long
    as the consumer holds a reference and is never mutated in place by
    the plugin after being yielded.
    """

    path: str
    nbytes: int
    chunks: AsyncIterator[BufferType]


class StreamRestartRequired(IOError):
    """A streamed read failed mid-stream but the payload IS retrievable
    from offset 0 (e.g. a mirrored plugin's primary died after yielding
    bytes — replica bytes must never be spliced after primary bytes, so
    the whole entry has to be re-consumed from the start). The scheduler
    catches this and retries the entry through the buffered read path;
    consumers guarantee a failed ``consume_stream`` left no partial
    commit behind, which is what makes the restart safe."""


class BufferStager(abc.ABC):
    # Stagers may set ``io_skipped = True`` during stage_buffer to tell the
    # scheduler the staged payload must NOT be written (incremental
    # snapshots: the bytes already exist in a base snapshot — dedup.py).
    """Produces the bytes to be written for one write request.

    ``stage_buffer`` runs inside the scheduler's staging pipeline under the
    memory budget. For device arrays this is where the DtoH copy happens.
    """

    @abc.abstractmethod
    async def stage_buffer(self, executor=None) -> BufferType:
        ...

    @abc.abstractmethod
    def get_staging_cost_bytes(self) -> int:
        """Peak host memory the staged buffer will occupy."""
        ...

    # Optional streaming protocol. A stager that can produce its payload
    # as an ordered sequence of sub-chunk buffers (ArrayBufferStager for
    # plain uncompressed arrays) overrides both methods; the scheduler
    # then fuses staging and storage I/O for the entry — sub-chunk N
    # writes while sub-chunk N+1 stages — and charges the memory budget
    # only the in-flight sub-chunk window, not the whole entry.

    def can_stream(self, sub_chunk_bytes: int) -> bool:
        """True when this stager can stream its payload in
        ``sub_chunk_bytes`` pieces. Default: buffered staging only."""
        return False

    def stage_stream(
        self, executor, sub_chunk_bytes: int
    ) -> AsyncIterator[BufferType]:
        """Ordered sub-chunk buffers whose concatenation is exactly the
        payload ``stage_buffer`` would have produced (same bytes, same
        recorded checksum). Only called when ``can_stream`` returned
        True for the same ``sub_chunk_bytes``."""
        raise NotImplementedError


class BufferConsumer(abc.ABC):
    """Consumes the bytes read for one read request."""

    @abc.abstractmethod
    async def consume_buffer(self, buf: BufferType, executor=None) -> None:
        ...

    @abc.abstractmethod
    def get_consuming_cost_bytes(self) -> int:
        """Peak host memory needed while consuming the buffer."""
        ...

    # Optional streaming protocol — the read-side mirror of the stager's
    # can_stream/stage_stream. A consumer that can process its payload as
    # an ordered sequence of sub-chunks (incremental chained CRC,
    # incremental decompression, per-sub-chunk device_put) overrides
    # these; the scheduler then fuses the storage read with consumption
    # for the entry and charges the memory budget
    # ``stream_admission_cost`` instead of the whole payload.

    def can_stream(self, sub_chunk_bytes: int) -> bool:
        """True when this consumer can process its payload in
        ``sub_chunk_bytes`` pieces. Default: buffered consumption only."""
        return False

    def stream_admission_cost(self, sub_chunk_bytes: int) -> int:
        """Peak host memory a STREAMED consume of this request holds —
        what the scheduler charges the budget instead of
        ``get_consuming_cost_bytes``. The default is the full consuming
        cost (honest for consumers that assemble the payload in a
        scratch buffer before committing); consumers that genuinely hold
        only the in-flight window (per-sub-chunk device_put, direct
        fills of pre-existing destination memory) override with the
        window so large single-entry restores stop serializing behind
        the budget."""
        return self.get_consuming_cost_bytes()

    async def consume_stream(self, stream: ReadStream, executor=None) -> None:
        """Consume an ordered sub-chunk stream of this request's payload.

        Only called when ``can_stream`` returned True for the sub-chunk
        size the stream was produced with. On ANY mid-stream exception
        the consumer must leave its destination exactly as it found it
        when a buffered consume would have (verify-before-commit
        consumers: unmodified; pre-existing-destination fills: no worse
        than a failure between this entry's buffered sub-reads), so the
        scheduler may retry the entry buffered after a
        :class:`StreamRestartRequired`."""
        raise NotImplementedError


@dataclass
class WriteReq:
    path: str
    buffer_stager: BufferStager


@dataclass
class ReadReq:
    path: str
    buffer_consumer: BufferConsumer
    byte_range: Optional[Tuple[int, int]] = None
    # Incremental snapshots: when set, the payload lives in this base
    # snapshot's storage, not the snapshot being restored; the orchestrator
    # groups reads by origin and opens one plugin per origin (dedup.py).
    origin: Optional[str] = None


class StoragePlugin(abc.ABC):
    """Storage backend interface (reference: io_types.py:54-103).

    Byte-range reads are first-class: the batcher and chunked-read paths rely
    on them. Implementations must be safe to drive from an asyncio event loop.
    """

    # True only on plugins whose ``write_stream`` consumes chunks
    # incrementally (fs/s3/gcs). The scheduler elects streaming — and
    # charges the memory budget per sub-chunk — only when this is set:
    # against the buffered fallback below, a "streamed" entry would
    # occupy its full size while the budget charged a sub-chunk window.
    supports_streaming: bool = False

    # Read-side twin: True only on plugins whose ``read_stream``
    # produces chunks incrementally as the transport delivers them
    # (fs: pread windows; s3/gcs: ordered prefetching ranged GETs;
    # mirror: composes over its primary). The scheduler elects streamed
    # reads only when this is set — the buffered fallback below fetches
    # the whole payload before the first chunk, so a "streamed" entry
    # would hold its full size while the budget charged a window.
    supports_streaming_reads: bool = False

    def stream_admission_cost(self, nbytes: int, sub_chunk_bytes: int) -> int:
        """Peak host memory ONE streamed entry of ``nbytes`` holds while
        this plugin consumes its stream — what the scheduler charges the
        memory budget instead of the entry's full size. The default is
        the stager-side window (the chunk being written plus the chunk
        staging ahead); plugins that RETAIN consumed chunks — cloud
        retry replay, multipart part buffers — must override with their
        real retention so the per-rank budget keeps bounding actual
        memory."""
        return min(nbytes, STREAM_DEPTH * sub_chunk_bytes)

    @abc.abstractmethod
    async def write(self, write_io: WriteIO) -> None:
        ...

    async def write_stream(self, stream: WriteStream) -> None:
        """Consume an ordered sub-chunk stream into one stored object.

        Plugins that can overlap transport with staging override this
        (fs: positional pwrites into the temp file; s3: multipart parts;
        gcs: resumable-protocol chunks). This default is the BUFFERED
        fallback — it accumulates the stream and delegates to ``write``,
        so every plugin (including out-of-tree ones) keeps working when
        the scheduler elects streaming; such plugins just don't get the
        intra-entry overlap."""
        parts: List[BufferType] = []
        async for chunk in stream.chunks:
            parts.append(chunk)
        if len(parts) == 1:
            buf: BufferType = parts[0]
        else:
            assembled = bytearray(stream.nbytes)
            pos = 0
            for part in parts:
                mv = memoryview(part).cast("B")
                assembled[pos : pos + mv.nbytes] = mv
                pos += mv.nbytes
            del parts
            buf = assembled
        got = memoryview(buf).nbytes
        if got != stream.nbytes:
            raise IOError(
                f"short write stream for {stream.path!r}: produced {got} "
                f"of {stream.nbytes} bytes"
            )
        await self.write(WriteIO(path=stream.path, buf=buf))

    @abc.abstractmethod
    async def read(self, read_io: ReadIO) -> None:
        ...

    async def read_stream(
        self, read_io: ReadIO, sub_chunk_bytes: int
    ) -> ReadStream:
        """Produce an ordered sub-chunk stream for one read request.

        Plugins that can overlap transport with consumption override
        this (fs: positional pread windows with one-chunk read-ahead;
        s3/gcs: a bounded window of in-flight ranged GETs yielded in
        order). This default is the BUFFERED fallback — it performs the
        whole ``read`` up front and slices the result — so every plugin
        (including out-of-tree ones) keeps working when a caller asks
        for a stream; such plugins just don't get the intra-entry
        overlap, and the scheduler never elects streaming for them
        (``supports_streaming_reads`` is False)."""
        await self.read(read_io)
        mv = memoryview(read_io.buf).cast("B")

        async def chunks() -> AsyncIterator[BufferType]:
            for lo in range(0, mv.nbytes, sub_chunk_bytes):
                yield mv[lo : lo + sub_chunk_bytes]

        return ReadStream(path=read_io.path, nbytes=mv.nbytes, chunks=chunks())

    @abc.abstractmethod
    async def delete(self, path: str) -> None:
        ...

    @abc.abstractmethod
    async def close(self) -> None:
        ...

    async def drain_background(self) -> None:
        """Wait for plugin-internal background work (e.g. mirror
        replication) to finish. The snapshot orchestrator awaits this on
        every rank before the commit barrier; default: nothing to drain."""

    def sync_close(self, event_loop) -> None:
        event_loop.run_until_complete(self.close())
