"""Out-of-band TCP KV store + two-phase barrier for snapshot coordination.

TPU-native analogue of the reference's TCPStore + LinearBarrier
(dist_store.py:22-196). The store is the coordination backbone for *all*
snapshot metadata traffic (see pg_wrapper): it rides the host network (DCN on
a TPU pod), is fully independent of the XLA runtime, and is safe to use from
background threads — the property the async commit protocol requires
(reference: snapshot.py:1033 "no collectives in this method").

Protocol: length-prefixed pickled request/response dicts over a persistent
connection. Server-side blocking waits use a condition variable, so ``get``
blocks without client polling. One handler thread per connection — fine at
checkpoint scale (one client per process, metadata-sized payloads).
"""

from __future__ import annotations

import logging
import os
import pickle
import socket
import struct
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from . import faultinject

logger = logging.getLogger(__name__)

BARRIER_TIMEOUT_ENV_VAR = "TORCHSNAPSHOT_TPU_BARRIER_TIMEOUT"


def _read_barrier_timeout() -> float:
    """Collective/barrier deadline (seconds). Env-configurable because
    the 1800 s default is sized for pod-scale takes on slow durable
    storage — a test rig or a latency-sensitive serving job wants rank
    death during planning to fail EVERY rank fast, not half an hour
    late. Read once at import (subprocess workers inherit the env)."""
    raw = os.environ.get(BARRIER_TIMEOUT_ENV_VAR, "").strip()
    if raw:
        try:
            value = float(raw)
            if value > 0:
                return value
            logger.warning(
                "ignoring non-positive %s=%r", BARRIER_TIMEOUT_ENV_VAR, raw
            )
        except ValueError:
            logger.warning(
                "ignoring non-numeric %s=%r", BARRIER_TIMEOUT_ENV_VAR, raw
            )
    return 1800.0


DEFAULT_BARRIER_TIMEOUT_S = _read_barrier_timeout()
# Client-side response deadlines: the store SERVER is itself a peer that
# can die (it lives in rank 0's process — the same SPOF the reference's
# rank-0-hosted TCPStore has, dist_store.py:53-88). A killed server
# process RSTs its sockets and clients fail instantly; a SILENTLY dead
# host (power loss, network partition) sends nothing, so without a
# deadline a blocked recv would hang forever. Every request therefore
# bounds the wait for the server's response:
#   - ops that carry their own timeout (get/wait_any/collect) wait
#     op_timeout + RPC_GRACE_S (the server answers "timeout" at
#     op_timeout; the grace covers scheduling + network),
#   - quick ops (set/add/mset/...) wait STORE_RPC_TIMEOUT_S (in-memory
#     ops that normally answer in microseconds).
# TCP keepalive (~20 s of silence) and TCP_USER_TIMEOUT (~20 s unacked
# data) additionally tear down the connection under long-deadline
# blocking ops, so silent server death surfaces in tens of seconds, not
# at the 1800 s barrier timeout.
#
# The quick-op deadline is deliberately GENEROUS (10 min): the server
# thread shares rank 0's GIL, and a host in swap thrash or a long
# GIL-held stretch can stall it for minutes while the kernel keeps
# ACKing (so keepalive/USER_TIMEOUT never fire). A premature deadline
# here is worse than a slow one — the client latches dead and its
# liveness-registered connection's drop publishes a death key for a
# LIVE rank. The kernel-dead cases (the common ones) are still caught
# in ~20 s by the TCP-layer settings above; this deadline only backstops
# the ACKing-but-silent pathology, where 10 min still beats 30.
RPC_GRACE_S = 30.0
STORE_RPC_TIMEOUT_S = float(
    os.environ.get("TORCHSNAPSHOT_TPU_STORE_RPC_TIMEOUT", "600")
)
CONNECT_TIMEOUT_S = 30.0
# Failure-detection channel shared with pg_wrapper: the server publishes
# this key when a liveness-registered connection (one per rank) drops
# without a clean deregister. Collective waits watch it.
DEATH_KEY = "pgw/death"
_LEN = struct.Struct(">Q")


class StoreConnectionLostError(ConnectionError):
    """The coordination KV store is unreachable — its hosting process
    (rank 0 / the snapshot leader) has likely died.

    Raised by every blocked or subsequent store operation on this client
    within seconds of the loss (RST from a killed process, TCP keepalive
    or the per-request response deadline for a silent host). Nothing was
    committed: the metadata-last protocol means an in-flight snapshot
    whose coordination plane died is simply absent. Recovery: restart
    the world — a fresh store is bootstrapped by the new rank 0 — and
    restore from the last committed snapshot (docs: elasticity.rst,
    "Coordination-plane failure").
    """

    def __init__(self, addr: str, op: str, cause: BaseException) -> None:
        super().__init__(
            f"Lost connection to the coordination store at {addr} during "
            f"{op!r} ({type(cause).__name__}: {cause}). The store-hosting "
            "process (rank 0, the snapshot leader) has likely died; "
            "in-flight snapshot coordination on this rank is aborted and "
            "nothing was committed. Restart the world and restore from "
            "the last committed snapshot."
        )
        self.addr = addr
        self.op = op


def _send_msg(sock: socket.socket, obj: Any) -> None:
    payload = pickle.dumps(obj)
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n > 0:
        chunk = sock.recv(min(n, 1 << 20))
        if not chunk:
            raise ConnectionError("Store connection closed.")
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


def _recv_msg(sock: socket.socket) -> Any:
    (length,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    return pickle.loads(_recv_exact(sock, length))


class _StoreServer:
    """In-process KV server. Rank 0 hosts one; all ranks connect as clients."""

    def __init__(self, host: str = "0.0.0.0", port: int = 0) -> None:
        self._data: Dict[str, bytes] = {}
        self._cond = threading.Condition()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        # Every rank of a pod (plus async-commit clones) connects at
        # startup near-simultaneously; a short accept backlog would
        # refuse some of that storm. The kernel caps this at
        # net.core.somaxconn — listen() just must not be the limiter.
        self._sock.listen(1024)
        self.port = self._sock.getsockname()[1]
        self._shutdown = threading.Event()
        self._thread = threading.Thread(
            target=self._serve, name="tpusnapshot-store", daemon=True
        )
        self._thread.start()

    def _serve(self) -> None:
        while not self._shutdown.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(
                target=self._handle, args=(conn,), daemon=True
            ).start()

    def _handle(self, conn: socket.socket) -> None:
        liveness: Dict[str, bytes] = {}
        try:
            while True:
                req = _recv_msg(conn)
                op = req.get("op")
                if op == "register_liveness":
                    # Failure detection: if this connection drops without a
                    # deregister, publish the registered key so peers
                    # blocked in collectives raise instead of timing out.
                    liveness[req["key"]] = req["value"]
                    _send_msg(conn, {"ok": True})
                    continue
                if op == "deregister_liveness":
                    liveness.pop(req["key"], None)
                    _send_msg(conn, {"ok": True})
                    continue
                _send_msg(conn, self._dispatch(req))
        except (ConnectionError, OSError, EOFError):
            pass
        finally:
            conn.close()
            if liveness:
                with self._cond:
                    for key, value in liveness.items():
                        self._data.setdefault(key, value)
                    self._cond.notify_all()

    def _dispatch(self, req: Dict[str, Any]) -> Dict[str, Any]:
        op = req["op"]
        key = req.get("key")
        with self._cond:
            if op == "set":
                self._data[key] = req["value"]
                self._cond.notify_all()
                return {"ok": True}
            elif op == "add":
                cur = int(self._data.get(key, b"0"))
                cur += req["amount"]
                self._data[key] = str(cur).encode()
                self._cond.notify_all()
                return {"ok": True, "value": cur}
            elif op == "get":
                deadline = time.monotonic() + req["timeout"]
                while key not in self._data:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0 or not self._cond.wait(timeout=min(remaining, 1.0)):
                        if time.monotonic() >= deadline:
                            return {"ok": False, "timeout": True}
                return {"ok": True, "value": self._data[key]}
            elif op == "wait_any":
                keys = req["keys"]
                deadline = time.monotonic() + req["timeout"]
                while True:
                    for k in keys:
                        if k in self._data:
                            return {"ok": True, "key": k, "value": self._data[k]}
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return {"ok": False, "timeout": True}
                    self._cond.wait(timeout=min(remaining, 1.0))
            elif op == "mset":
                self._data.update(req["items"])
                self._cond.notify_all()
                return {"ok": True}
            elif op == "collect":
                # Block until `count` keys with `prefix` exist, then return
                # them all in one response — the server-side half of a
                # scalable all-gather (one RTT per rank instead of one per
                # peer). A stop key (error channel) short-circuits.
                prefix = req["prefix"]
                count = req["count"]
                stop_keys = req.get("stop_keys") or []
                deadline = time.monotonic() + req["timeout"]
                while True:
                    # Data completeness BEFORE stop keys (mirrors
                    # wait_any's list ordering): a completable collective
                    # must complete even if a peer's death landed after
                    # its contribution — e.g. a rank posting its piece for
                    # the job's final collective and exiting while the
                    # leader is still collecting.
                    found = {
                        k: v for k, v in self._data.items() if k.startswith(prefix)
                    }
                    if len(found) >= count:
                        return {"ok": True, "items": found}
                    for sk in stop_keys:
                        if sk in self._data:
                            return {
                                "ok": True,
                                "stopped": sk,
                                "value": self._data[sk],
                            }
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return {"ok": False, "timeout": True}
                    self._cond.wait(timeout=min(remaining, 1.0))
            elif op == "check":
                return {"ok": True, "value": key in self._data}
            elif op == "num_keys":
                return {"ok": True, "value": len(self._data)}
            elif op == "delete":
                existed = self._data.pop(key, None) is not None
                return {"ok": True, "value": existed}
            elif op == "delete_prefix":
                keep = req.get("except_keys") or []
                doomed = [
                    k
                    for k in self._data
                    if k.startswith(req["prefix"]) and k not in keep
                ]
                for k in doomed:
                    del self._data[k]
                return {"ok": True, "value": len(doomed)}
            else:
                return {"ok": False, "error": f"unknown op {op!r}"}

    def close(self) -> None:
        self._shutdown.set()
        try:
            self._sock.close()
        except OSError:
            pass


class TCPStore:
    """Client handle to a store server (optionally hosting it in-process).

    Thread-safe: calls are serialized over one connection with a lock; use
    separate TCPStore instances for genuinely concurrent use (e.g. the async
    commit thread creates its own connection).
    """

    def __init__(
        self,
        host: str,
        port: Optional[int] = None,
        is_server: bool = False,
        timeout: float = DEFAULT_BARRIER_TIMEOUT_S,
    ) -> None:
        self._server: Optional[_StoreServer] = None
        if is_server:
            self._server = _StoreServer(port=port or 0)
            port = self._server.port
            host = "127.0.0.1" if host in ("0.0.0.0", "") else host
        assert port is not None
        self.host = host
        self.port = port
        self.timeout = timeout
        self._lock = threading.Lock()
        self._dead: Optional[StoreConnectionLostError] = None
        self._sock = socket.create_connection(
            (host, port), timeout=CONNECT_TIMEOUT_S
        )
        # A TCP connect alone does not prove a STORE is on the other end:
        # on loopback, connecting to a freed ephemeral port (a dead store
        # host's port is the classic case) can simultaneous-open onto
        # itself or yield a phantom connection that dies on first use.
        # Validate with one probe round-trip: only a real server answers
        # it correctly (a self-connect echoes our own request back, which
        # fails the response check).
        try:
            if self._sock.getsockname() == self._sock.getpeername():
                raise ConnectionRefusedError(
                    f"self-connect to {host}:{port} (no server listening)"
                )
            _send_msg(self._sock, {"op": "check", "key": "__conn_probe__"})
            resp = _recv_msg(self._sock)
            if not isinstance(resp, dict) or "ok" not in resp:
                raise ConnectionRefusedError(
                    f"{host}:{port} did not answer the store probe "
                    "(not a store server)"
                )
        except ConnectionRefusedError:
            try:
                self._sock.close()
            except OSError:
                pass
            raise
        except (ConnectionError, EOFError, OSError):
            try:
                self._sock.close()
            except OSError:
                pass
            raise
        except Exception as e:
            # A non-store service on the port can answer with bytes that
            # explode anywhere inside unpickling (UnpicklingError,
            # ValueError, AttributeError, ...): that is still "not a
            # store server", and the socket must not leak.
            try:
                self._sock.close()
            except OSError:
                pass
            raise ConnectionRefusedError(
                f"{host}:{port} answered the store probe with garbage "
                f"({type(e).__name__}: {e}) — not a store server"
            ) from e
        self._sock.settimeout(None)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        # Silent-death detection at the TCP layer (a killed process RSTs
        # and needs none of this; these cover power loss / partitions):
        # - keepalive (idle 5 s + 3 probes x 5 s = ~20 s) tears down
        #   connections idle in a blocked recv;
        # - TCP_USER_TIMEOUT (~20 s) covers the case keepalive cannot:
        #   request bytes sent but never ACKed (keepalive probes are
        #   suppressed while data is outstanding — without this, that
        #   path would ride retransmission backoff for ~15 minutes).
        # Both land long before the 1800 s barrier timeout.
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_KEEPALIVE, 1)
        for opt, val in (
            ("TCP_KEEPIDLE", 5),
            ("TCP_KEEPINTVL", 5),
            ("TCP_KEEPCNT", 3),
            ("TCP_USER_TIMEOUT", 20_000),  # milliseconds
        ):
            if hasattr(socket, opt):  # Linux; harmless to skip elsewhere
                self._sock.setsockopt(
                    socket.IPPROTO_TCP, getattr(socket, opt), val
                )

    @property
    def addr(self) -> str:
        return f"{self.host}:{self.port}"

    def _request(self, req: Dict[str, Any]) -> Dict[str, Any]:
        op_timeout = req.get("timeout")
        # How long the CLIENT waits for the server's response: the op's
        # own timeout (server answers "timeout" at that point) plus
        # grace, or the quick-op RPC deadline. A deadline expiring here
        # means the SERVER went silent, not that the op timed out.
        response_deadline = (
            op_timeout + RPC_GRACE_S
            if op_timeout is not None
            else STORE_RPC_TIMEOUT_S
        )
        # OUTSIDE the lock/try: an injected transient store fault models a
        # blip that failed one request, not a torn connection — the client
        # must not latch dead (a permanent/kill plan models the latter).
        faultinject.site("dist_store.rpc")
        with self._lock:
            if self._dead is not None:
                # The connection is gone (and mid-message state would be
                # corrupt anyway): every subsequent op fails fast.
                raise self._dead
            try:
                self._sock.settimeout(response_deadline)
                _send_msg(self._sock, req)
                resp = _recv_msg(self._sock)
                self._sock.settimeout(None)
            except (ConnectionError, EOFError, OSError) as e:
                # socket.timeout is an OSError subclass, so a silent
                # server (deadline) and a dead one (RST/FIN) both land
                # here; keepalive converts long silences into errors too.
                self._dead = StoreConnectionLostError(
                    self.addr, req["op"], e
                )
                try:
                    self._sock.close()
                except OSError:
                    pass
                raise self._dead from e
        if resp.get("timeout"):
            raise TimeoutError(
                f"Store operation {req['op']!r} on {req.get('key') or req.get('keys')} "
                f"timed out after {req.get('timeout')}s."
            )
        if not resp.get("ok"):
            raise RuntimeError(f"Store error: {resp.get('error')}")
        return resp

    def set(self, key: str, value: bytes) -> None:
        self._request({"op": "set", "key": key, "value": bytes(value)})

    def get(self, key: str, timeout: Optional[float] = None) -> bytes:
        return self._request(
            {"op": "get", "key": key, "timeout": timeout or self.timeout}
        )["value"]

    def wait_any(
        self, keys: List[str], timeout: Optional[float] = None
    ) -> Tuple[str, bytes]:
        resp = self._request(
            {"op": "wait_any", "keys": keys, "timeout": timeout or self.timeout}
        )
        return resp["key"], resp["value"]

    def add(self, key: str, amount: int) -> int:
        return self._request({"op": "add", "key": key, "amount": amount})["value"]

    def mset(self, items: Dict[str, bytes]) -> None:
        """Set many keys in one round trip (scatter's leader-side write)."""
        self._request({"op": "mset", "items": {k: bytes(v) for k, v in items.items()}})

    def collect(
        self,
        prefix: str,
        count: int,
        stop_keys: Optional[List[str]] = None,
        timeout: Optional[float] = None,
    ) -> Tuple[Optional[str], Dict[str, bytes]]:
        """Block until ``count`` keys under ``prefix`` exist; return them all
        in ONE round trip. Returns ``(stopped_key, items)``: if a stop key
        (e.g. an error channel) appears first, ``stopped_key`` is set and
        ``items`` maps it to its value."""
        resp = self._request(
            {
                "op": "collect",
                "prefix": prefix,
                "count": count,
                "stop_keys": stop_keys or [],
                "timeout": timeout or self.timeout,
            }
        )
        if "stopped" in resp:
            return resp["stopped"], {resp["stopped"]: resp["value"]}
        return None, resp["items"]

    def check(self, key: str) -> bool:
        return self._request({"op": "check", "key": key})["value"]

    def num_keys(self) -> int:
        """Total number of keys currently held by the server (observability /
        store-hygiene tests)."""
        return self._request({"op": "num_keys"})["value"]

    def delete(self, key: str) -> bool:
        return self._request({"op": "delete", "key": key})["value"]

    def delete_prefix(self, prefix: str, except_keys: Optional[List[str]] = None) -> int:
        return self._request(
            {"op": "delete_prefix", "prefix": prefix, "except_keys": except_keys}
        )["value"]

    def register_liveness(self, key: str, value: bytes) -> None:
        """Publish ``key``=``value`` if THIS connection ever drops without
        ``deregister_liveness`` — the failure-detection hook: a process
        dying mid-collective makes its death visible to peers through a
        key they already watch, instead of leaving them blocked until the
        store timeout. Clones do NOT inherit registration (a background
        thread closing its connection is not a process death)."""
        self._request({"op": "register_liveness", "key": key, "value": bytes(value)})

    def deregister_liveness(self, key: str) -> None:
        self._request({"op": "deregister_liveness", "key": key})

    def clone(self) -> "TCPStore":
        """A new connection to the same server (for use from another thread)."""
        try:
            return TCPStore(
                self.host, self.port, is_server=False, timeout=self.timeout
            )
        except OSError as e:
            # The server is already gone (refused / connect deadline):
            # name the store host instead of a bare socket error.
            raise StoreConnectionLostError(self.addr, "clone", e) from e

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass
        if self._server is not None:
            self._server.close()


def create_store(
    rank: int, addr: Optional[str] = None, timeout: float = DEFAULT_BARRIER_TIMEOUT_S
) -> TCPStore:
    """Bootstrap a store: rank 0 hosts, everyone connects to ``addr``.

    ``addr`` ("host:port") must be agreed out of band — from the
    TORCHSNAPSHOT_TPU_STORE_ADDR env var, the jax.distributed coordinator, or
    the test launcher (reference analogue: dist_store.py:53-88, where rank 0
    binds a free port and broadcasts it over the default store).
    """
    if rank == 0:
        if addr is not None and ":" in addr:
            host, _, port = addr.rpartition(":")
            return TCPStore(host or "127.0.0.1", int(port), is_server=True, timeout=timeout)
        return TCPStore("127.0.0.1", None, is_server=True, timeout=timeout)
    assert addr is not None, "Non-zero ranks must be given the store address."
    host, _, port = addr.rpartition(":")
    deadline = time.monotonic() + timeout
    while True:
        try:
            return TCPStore(host, int(port), timeout=timeout)
        except (ConnectionRefusedError, OSError):
            if time.monotonic() > deadline:
                raise
            time.sleep(0.1)


# --------------------------------------------------------- peer transport
#
# Length-prefixed byte channel between RANKS — the data-plane sidecar to
# the KV store above. The store moves metadata through rank 0; the peer
# channel moves restore payload sub-chunks directly between the ranks
# that have them and the ranks that need them (fanout.py), so cooperative
# restores never funnel payload bytes through the coordination server.
# Strictly host-network + threads: safe from background threads and never
# touching device collectives, the same invariant the store itself keeps.
#
# Frame format (one frame = one protocol message):
#
#     u64 header_len | header (pickled dict) | u64 payload_len | payload
#
# The header is a tiny routing dict (op/key/gen/seq); the payload rides
# raw — payload bytes are never pickled, so multi-MB sub-chunks move with
# one copy into the receive buffer.

PEER_CONNECT_TIMEOUT_S = 30.0


def send_peer_frame(sock: socket.socket, header: Dict[str, Any], payload=None) -> None:
    """Send one frame. ``payload`` is any buffer-protocol object (or
    None). Callers serialize concurrent senders on one socket themselves
    (a lock per connection) — interleaved sendalls would corrupt the
    framing."""
    h = pickle.dumps(header)
    payload = faultinject.mutate("peer.send_frame", payload)
    mv = memoryview(payload).cast("B") if payload is not None else None
    sock.sendall(_LEN.pack(len(h)) + h + _LEN.pack(mv.nbytes if mv is not None else 0))
    if mv is not None and mv.nbytes:
        sock.sendall(mv)


def _recv_exact_into(sock: socket.socket, view: memoryview) -> None:
    got = 0
    while got < view.nbytes:
        n = sock.recv_into(view[got:])
        if not n:
            raise ConnectionError("Peer connection closed mid-frame.")
        got += n


def recv_peer_frame(
    sock: socket.socket, alloc: Optional[Any] = None
) -> Tuple[Dict[str, Any], Optional[memoryview]]:
    """Receive one frame: ``(header, payload_view_or_None)``.

    ``alloc(nbytes)`` supplies the payload buffer (e.g. a pooled staging
    slab, so repeated sub-chunk receives don't pay first-touch page
    faults on every frame); default allocates a fresh bytearray. The
    returned view stays valid for as long as the caller holds it."""
    faultinject.site("peer.recv_frame")
    (hlen,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    header = pickle.loads(_recv_exact(sock, hlen))
    (plen,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    if plen == 0:
        return header, None
    buf = alloc(plen) if alloc is not None else bytearray(plen)
    view = memoryview(buf).cast("B")
    _recv_exact_into(sock, view)
    return header, view


def peer_connect(addr: str, timeout: float = PEER_CONNECT_TIMEOUT_S) -> socket.socket:
    """Connect to a peer listener at ``"host:port"``. TCP_NODELAY so the
    small end/abort control frames aren't Nagle-delayed behind payload."""
    host, _, port = addr.rpartition(":")
    sock = socket.create_connection((host, int(port)), timeout=timeout)
    sock.settimeout(None)
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return sock


class PeerListener:
    """Accepts inbound peer-channel connections, one handler thread per
    connection (checkpoint-scale: world-1 inbound connections, payload
    frames — the same threading shape as the store server). ``handler``
    receives the raw connected socket and owns its lifecycle."""

    def __init__(self, host: str = "0.0.0.0", port: int = 0) -> None:
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self.port = self._sock.getsockname()[1]
        self._handler: Optional[Any] = None
        self._shutdown = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self, handler) -> None:
        self._handler = handler
        self._thread = threading.Thread(
            target=self._serve, name="tpusnapshot-peer-listener", daemon=True
        )
        self._thread.start()

    def _serve(self) -> None:
        while not self._shutdown.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            threading.Thread(
                target=self._handler,
                args=(conn,),
                name="tpusnapshot-peer-conn",
                daemon=True,
            ).start()

    def close(self) -> None:
        self._shutdown.set()
        try:
            self._sock.close()
        except OSError:
            pass


class LinearBarrier:
    """Two-phase (arrive/depart) store barrier with leader action in between
    and cross-rank error propagation (reference: dist_store.py:91-196).

    Usable from any thread — it only talks to the store. The async-commit
    protocol relies on this: every rank arrives after its storage I/O
    completes; the leader (rank 0) writes the snapshot metadata between the
    phases; depart releases everyone. If any rank reports an error, all other
    ranks raise instead of committing.
    """

    def __init__(
        self,
        prefix: str,
        store: TCPStore,
        rank: int,
        world_size: int,
        leader_rank: int = 0,
    ) -> None:
        self.prefix = prefix
        self.store = store
        self.rank = rank
        self.world_size = world_size
        self.leader_rank = leader_rank

    def _key(self, *parts: str) -> str:
        return "/".join((self.prefix,) + parts)

    def _err_key(self) -> str:
        return self._key("error")

    def report_error(self, err: BaseException) -> None:
        try:
            payload = pickle.dumps(err)
        except Exception:
            payload = pickle.dumps(RuntimeError(repr(err)))
        self.store.set(self._err_key(), payload)

    def _raise_if_error(self, key: str, value: bytes) -> None:
        if key == DEATH_KEY:
            raise RuntimeError(
                f"A peer rank died at barrier {self.prefix!r}."
            ) from pickle.loads(value)
        if key == self._err_key():
            err = pickle.loads(value)
            raise RuntimeError(
                f"A peer rank reported an error at barrier {self.prefix!r}."
            ) from err

    def arrive(self, timeout: Optional[float] = None) -> None:
        self.store.set(self._key("arrive", str(self.rank)), b"1")
        if self.rank == self.leader_rank:
            # One server-side collect instead of world sequential waits:
            # the leader's arrival phase is on the commit critical path.
            stopped, items = self.store.collect(
                self._key("arrive") + "/",
                self.world_size,
                stop_keys=[self._err_key(), DEATH_KEY],
                timeout=timeout,
            )
            if stopped is not None:
                self._raise_if_error(stopped, items[stopped])

    def depart(self, timeout: Optional[float] = None) -> None:
        if self.rank == self.leader_rank:
            self.store.set(self._key("depart"), b"1")
            # Reclaiming barrier keys here would race stragglers still
            # waiting on depart; when the prefix is nested under a PGWrapper
            # namespace, the retire/GC protocol reclaims them once every
            # rank has acked (pg_wrapper.PGWrapper.retire).
        else:
            key, value = self.store.wait_any(
                [self._key("depart"), self._err_key(), DEATH_KEY], timeout
            )
            self._raise_if_error(key, value)
