"""Out-of-band TCP KV store + two-phase barrier for snapshot coordination.

TPU-native analogue of the reference's TCPStore + LinearBarrier
(dist_store.py:22-196). The store is the coordination backbone for *all*
snapshot metadata traffic (see pg_wrapper): it rides the host network (DCN on
a TPU pod), is fully independent of the XLA runtime, and is safe to use from
background threads — the property the async commit protocol requires
(reference: snapshot.py:1033 "no collectives in this method").

Protocol: length-prefixed pickled request/response dicts over a persistent
connection. Server-side blocking waits use a condition variable, so ``get``
blocks without client polling. One handler thread per connection — fine at
checkpoint scale (one client per process, metadata-sized payloads).

Replication tier (killing the store SPOF)
-----------------------------------------
The store can be replicated across 2-3 hosts with a **leased leader**:

- The **leader** applies every KV op, stamps it with a monotonically
  increasing log sequence number, and *synchronously* streams it to each
  joined **standby** replica before acknowledging the client. A standby
  therefore always holds a complete copy of the data, the op log position,
  and the per-client idempotency table.
- The leader renews an **epoch-stamped lease** to each standby every
  ``lease_s / 3`` seconds. A standby that loses the leader (connection
  drop, or silence past the lease) waits out the remaining lease plus an
  index-staggered delay, probes its peers for an already-promoted leader
  to rejoin, and otherwise **assumes the lease at epoch + 1**.
- **Epoch fencing**: every replicated op carries the sender's epoch; a
  replica that has moved to a higher epoch rejects the stream
  (``stale_epoch``), which deposes the old leader — it stops serving
  (answers ``not_leader``) so its clients fail over. This composes with
  the snapshot layer's generation-fenced commit: a deposed leader can
  neither ack new client writes (clients leave it for the higher epoch)
  nor splice its op log into the promoted replica.
- **Client failover** is transparent: every mutating op carries a
  client-assigned ``(client_id, seq)`` so a replay after reconnect is
  idempotent (the server's dedup table is itself replicated), blocking
  ops (``get``/``wait_any``/``collect``) re-arm against the new leader
  with their remaining timeout, and liveness registrations are
  re-established on the new connection. With **zero** replicas
  configured the pre-replication behavior is preserved exactly: a lost
  connection latches the client dead and raises
  :class:`StoreConnectionLostError` within seconds.

What this tier is NOT: quorum consensus. At ANY replica count a network
partition that leaves the old leader reachable by some clients while a
standby assumes the lease can dual-leader the tier until fencing
evidence (a stale_epoch answer over a still-open stream) reaches the
old leader — leases and epochs narrow the window; only a majority-vote
protocol would close it, and checkpoint coordination does not warrant
one (docs/source/fault_tolerance.rst, "Coordination tier", spells out
the operator-facing consequences). Process *death* — the overwhelmingly
common failure — is handled: a killed leader RSTs every socket and the
standby takes over within ~one lease.
"""

from __future__ import annotations

import json
import logging
import os
import pickle
import random
import socket
import struct
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

from . import faultinject

logger = logging.getLogger(__name__)

BARRIER_TIMEOUT_ENV_VAR = "TORCHSNAPSHOT_TPU_BARRIER_TIMEOUT"
STORE_REPLICAS_ENV_VAR = "TORCHSNAPSHOT_TPU_STORE_REPLICAS"
STORE_LEASE_ENV_VAR = "TORCHSNAPSHOT_TPU_STORE_LEASE_S"
STORE_CONNECT_RETRIES_ENV_VAR = "TORCHSNAPSHOT_TPU_STORE_CONNECT_RETRIES"


def _read_barrier_timeout() -> float:
    """Collective/barrier deadline (seconds). Env-configurable because
    the 1800 s default is sized for pod-scale takes on slow durable
    storage — a test rig or a latency-sensitive serving job wants rank
    death during planning to fail EVERY rank fast, not half an hour
    late. Read once at import (subprocess workers inherit the env)."""
    raw = os.environ.get(BARRIER_TIMEOUT_ENV_VAR, "").strip()
    if raw:
        try:
            value = float(raw)
            if value > 0:
                return value
            logger.warning(
                "ignoring non-positive %s=%r", BARRIER_TIMEOUT_ENV_VAR, raw
            )
        except ValueError:
            logger.warning(
                "ignoring non-numeric %s=%r", BARRIER_TIMEOUT_ENV_VAR, raw
            )
    return 1800.0


def _read_env_number(var: str, default: float, *, integer: bool = False):
    """Positive-number env parser with the warn-don't-crash idiom of
    ``_read_barrier_timeout`` (a typo'd knob must degrade to the
    default, never make the coordination plane unimportable)."""
    raw = os.environ.get(var, "").strip()
    if raw:
        try:
            value = int(raw) if integer else float(raw)
            if value >= 0:
                return value
            logger.warning("ignoring negative %s=%r", var, raw)
        except ValueError:
            logger.warning("ignoring non-numeric %s=%r", var, raw)
    return default


DEFAULT_BARRIER_TIMEOUT_S = _read_barrier_timeout()
# Leader-lease duration. The leader renews every lease_s / 3; a standby
# must observe silence for a full lease before it may assume the next
# epoch, so failover completes in ~1-2 leases after a leader kill.
DEFAULT_STORE_LEASE_S = _read_env_number(STORE_LEASE_ENV_VAR, 5.0) or 5.0
# How many non-zero ranks host standby replicas in create_store (0 = the
# pre-replication single-host store).
DEFAULT_STORE_REPLICAS = int(
    _read_env_number(STORE_REPLICAS_ENV_VAR, 0, integer=True)
)
# Bounded, jittered connect retries on ConnectionRefusedError — a
# slow-starting server or a failover target still standing up refuses
# the first attempt; a wedged or garbage endpoint is NOT retried (its
# failure mode cannot improve).
DEFAULT_CONNECT_RETRIES = int(
    _read_env_number(STORE_CONNECT_RETRIES_ENV_VAR, 3, integer=True)
)
# Client-side response deadlines: the store SERVER is itself a peer that
# can die (it lives in rank 0's process — the same SPOF the reference's
# rank-0-hosted TCPStore has, dist_store.py:53-88). A killed server
# process RSTs its sockets and clients fail instantly; a SILENTLY dead
# host (power loss, network partition) sends nothing, so without a
# deadline a blocked recv would hang forever. Every request therefore
# bounds the wait for the server's response:
#   - ops that carry their own timeout (get/wait_any/collect) wait
#     op_timeout + RPC_GRACE_S (the server answers "timeout" at
#     op_timeout; the grace covers scheduling + network),
#   - quick ops (set/add/mset/...) wait STORE_RPC_TIMEOUT_S (in-memory
#     ops that normally answer in microseconds).
# TCP keepalive (~20 s of silence) and TCP_USER_TIMEOUT (~20 s unacked
# data) additionally tear down the connection under long-deadline
# blocking ops, so silent server death surfaces in tens of seconds, not
# at the 1800 s barrier timeout.
#
# The quick-op deadline is deliberately GENEROUS (10 min): the server
# thread shares rank 0's GIL, and a host in swap thrash or a long
# GIL-held stretch can stall it for minutes while the kernel keeps
# ACKing (so keepalive/USER_TIMEOUT never fire). A premature deadline
# here is worse than a slow one — the client latches dead and its
# liveness-registered connection's drop publishes a death key for a
# LIVE rank. The kernel-dead cases (the common ones) are still caught
# in ~20 s by the TCP-layer settings above; this deadline only backstops
# the ACKing-but-silent pathology, where 10 min still beats 30.
RPC_GRACE_S = 30.0
STORE_RPC_TIMEOUT_S = float(
    os.environ.get("TORCHSNAPSHOT_TPU_STORE_RPC_TIMEOUT", "600")
)
CONNECT_TIMEOUT_S = 30.0
# Injected dist_store.rpc transients model a blip that failed ONE
# request over a healthy connection; the client resends (idempotently)
# a bounded number of times before propagating.
RPC_BLIP_RETRIES = 2
# Failure-detection channel shared with pg_wrapper: the server publishes
# this key when a liveness-registered connection (one per rank) drops
# without a clean deregister. Collective waits watch it.
DEATH_KEY = "pgw/death"
# Set by the leader once the expected replica count has joined;
# create_store gates every rank on it so no coordination op can race the
# replica bootstrap (the failover window would silently shrink to zero).
REPLICAS_READY_KEY = "__store/replicas_ready__"
_LEN = struct.Struct(">Q")

# Bound on the per-client idempotency (dedup) table: clients past the
# cap are evicted least-recently-written first. Each snapshot take's
# clones mint fresh client ids, so without a bound a months-long job
# would leak the table on the leader and every standby. 4096 distinct
# recently-writing clients is far beyond checkpoint scale; an evicted
# client's in-flight replay re-applying requires 4096 other clients to
# have written since its stamp — accepted and documented.
CLIENT_SEQ_CAP = 4096

# Ops that change server state: these carry the client-assigned
# (client_id, seq) stamp and are streamed to replicas. Blocking reads
# re-arm after failover instead (their effect is idempotent by nature).
_MUTATING_OPS = frozenset(
    {
        "set",
        "add",
        "mset",
        "mset_default",
        "delete",
        "delete_if_value",
        "delete_prefix",
    }
)


def _connect_backoff_s(attempt: int, base: float = 0.25, cap: float = 2.0) -> float:
    """Jittered exponential backoff for connect/failover retries — the
    storage retry tier's formula (storage_plugins.retry.backoff_with_jitter),
    imported lazily so the coordination plane stays import-light on the
    hot bootstrap path (retry pulls in asyncio + telemetry)."""
    from .storage_plugins.retry import backoff_with_jitter

    return backoff_with_jitter(attempt, base_s=base, cap_s=cap)


class StoreConnectionLostError(ConnectionError):
    """The coordination KV store is unreachable — its hosting process
    has likely died (and, if replicas were configured, failover found no
    live leader either).

    Raised by every blocked or subsequent store operation on this client
    within seconds of the loss (RST from a killed process, TCP keepalive
    or the per-request response deadline for a silent host). Nothing was
    committed: the metadata-last protocol means an in-flight snapshot
    whose coordination plane died is simply absent. Recovery: restart
    the world — a fresh store is bootstrapped by the new rank 0 — and
    restore from the last committed snapshot (docs: elasticity.rst,
    "Coordination-plane failure").

    ``role`` names who actually died so post-failover diagnostics don't
    blame the wrong host: the default describes the classic rank-0-hosted
    single store; the failover path substitutes the observed leader
    epoch and the candidate set it exhausted.
    """

    DEFAULT_ROLE = "rank 0, the snapshot leader"

    def __init__(
        self,
        addr: str,
        op: str,
        cause: BaseException,
        role: str = DEFAULT_ROLE,
    ) -> None:
        super().__init__(
            f"Lost connection to the coordination store at {addr} during "
            f"{op!r} ({type(cause).__name__}: {cause}). The store-hosting "
            f"process ({role}) has likely died; "
            "in-flight snapshot coordination on this rank is aborted and "
            "nothing was committed. Restart the world and restore from "
            "the last committed snapshot."
        )
        self.addr = addr
        self.op = op
        self.role = role


class _DeposedError(ConnectionError):
    """A replica (or promoted ex-replica) rejected this leader's stream:
    a higher epoch exists. The leader must stop serving."""


def _send_msg(sock: socket.socket, obj: Any) -> None:
    payload = pickle.dumps(obj)
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n > 0:
        chunk = sock.recv(min(n, 1 << 20))
        if not chunk:
            raise ConnectionError("Store connection closed.")
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


def _recv_msg(sock: socket.socket) -> Any:
    (length,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    return pickle.loads(_recv_exact(sock, length))


def _try_whois(addr: str, timeout: float = 2.0) -> Optional[Dict[str, Any]]:
    """One-shot leader probe: connect, ask ``whois``, close. Returns the
    response dict or None (unreachable / not a store / self-connect)."""
    host, _, port = addr.rpartition(":")
    try:
        sock = socket.create_connection((host, int(port)), timeout=timeout)
    except (OSError, ValueError):
        return None
    try:
        sock.settimeout(timeout)
        if sock.getsockname() == sock.getpeername():
            return None  # loopback ephemeral self-connect trap
        _send_msg(sock, {"op": "whois"})
        resp = _recv_msg(sock)
        if isinstance(resp, dict) and "ok" in resp:
            return resp
        return None
    except Exception:  # noqa: BLE001 - any garbage means "not a store"
        return None
    finally:
        try:
            sock.close()
        except OSError:
            pass


class _ReplicaLink:
    """Leader-side handle to one joined standby: the (promoted) join
    connection, a lock serializing every message on it, and the
    replication bookkeeping the ``store-status`` CLI reports."""

    def __init__(self, sock: socket.socket, addr: str) -> None:
        self.sock = sock
        self.addr = addr
        # RLock: _accept_replica holds it across the full-sync send while
        # calling send() for the sync frame itself.
        self.lock = threading.RLock()
        self.index = -1
        self.acked_seq = 0
        self.last_renew = time.monotonic()
        # While the full sync is in flight, replicated ops are BUFFERED
        # into ``pending`` (guarded by the server cond) instead of
        # blocking the dispatcher on this link's lock — a slow joiner
        # must never stall the store (or starve lease renewals to the
        # other standbys) for the duration of its sync.
        self.syncing = True
        self.pending: List[Dict[str, Any]] = []

    def send(self, msg: Dict[str, Any], timeout: float) -> Dict[str, Any]:
        faultinject.site("dist_store.replica_rpc")
        with self.lock:
            self.sock.settimeout(timeout)
            # tsalint: allow[lock-blocking] deadline-bounded: settimeout on
            # the line above caps both the send and the recv; the link lock
            # only serializes this link's exchanges (the dispatcher never
            # waits on it — see the SYNCING protocol in __init__)
            _send_msg(self.sock, msg)
            resp = _recv_msg(self.sock)
        if not isinstance(resp, dict):
            raise ConnectionError(f"replica {self.addr} answered garbage")
        if resp.get("stale_epoch") or resp.get("deposed"):
            raise _DeposedError(
                f"replica {self.addr} fenced this leader off at epoch "
                f"{resp.get('epoch')}"
            )
        if not resp.get("ok"):
            raise ConnectionError(f"replica {self.addr} rejected: {resp}")
        return resp

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


class _StoreServer:
    """In-process KV server: a replicating leader, or a standby replica.

    Rank 0 (or a dedicated store-host process) hosts the leader; ranks
    1..N host standbys via :func:`host_standby`. All coordination state
    — the KV data, the op-log position, and the per-client idempotency
    table — is streamed synchronously to every joined standby, so any
    standby with an intact stream can assume leadership.

    Locking rules (deadlock-free by construction):
    - ``self._cond`` (the data lock) may be held while taking an ACTIVE
      replica link's lock (the synchronous-replication path);
    - while a link is SYNCING (its lock held across the full-sync
      exchange), nothing that holds the cond ever waits on that lock —
      replicate/lease/rs_update all buffer-or-skip syncing links — which
      is what makes the one amendment safe: the joiner's flush loop may
      take the cond briefly (to swap pending batches) while holding the
      syncing link's lock, and no cycle can form;
    - otherwise a link's lock is never held while acquiring the cond
      (failure handling re-acquires the cond only after ``send``
      returned).
    """

    def __init__(
        self,
        host: str = "0.0.0.0",
        port: int = 0,
        standby: bool = False,
        lease_s: Optional[float] = None,
        expected_replicas: int = 0,
    ) -> None:
        self._data: Dict[str, bytes] = {}
        self._cond = threading.Condition()
        self._role = "standby" if standby else "leader"
        self._epoch = 0 if standby else 1
        self._log_seq = 0
        # client_id -> (last applied seq, its response): the replay-dedup
        # table. Replicated with the data so idempotency survives failover.
        self._client_seqs: Dict[str, Tuple[int, Dict[str, Any]]] = {}
        self._lease_s = float(lease_s) if lease_s else DEFAULT_STORE_LEASE_S
        self._expected_replicas = int(expected_replicas)
        self._replicas: List[_ReplicaLink] = []  # guarded by _cond
        self._rs_version = 0
        self._joined_total = 0
        self._lease_thread: Optional[threading.Thread] = None
        # Standby-side state.
        self._leader_addr: Optional[str] = None
        self._standby_index: int = 0
        self._peers: List[Tuple[int, str]] = []  # (index, addr) of siblings
        self._last_leader_msg = time.monotonic()
        self._upstream: Optional[socket.socket] = None
        self._advertise: Optional[str] = None
        self._conns: set = set()
        self._conns_lock = threading.Lock()
        # (client_id, key) -> the connection currently holding that
        # liveness registration. A dropped connection's death-key flush
        # is skipped when the same client has since re-registered over a
        # NEWER connection (failover over a blip): the old FIN can
        # arrive arbitrarily late (server-side sockets have no
        # keepalive), and publishing then would poison a live rank.
        self._liveness_reg: Dict[Tuple[str, str], Any] = {}
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        # Every rank of a pod (plus async-commit clones) connects at
        # startup near-simultaneously; a short accept backlog would
        # refuse some of that storm. The kernel caps this at
        # net.core.somaxconn — listen() just must not be the limiter.
        self._sock.listen(1024)
        self.port = self._sock.getsockname()[1]
        self._shutdown = threading.Event()
        self._thread = threading.Thread(
            target=self._serve, name="tpusnapshot-store", daemon=True
        )
        self._thread.start()

    # ------------------------------------------------------------ accept

    def _serve(self) -> None:
        while not self._shutdown.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            with self._conns_lock:
                self._conns.add(conn)
            threading.Thread(
                target=self._handle, args=(conn,), daemon=True
            ).start()

    def _handle(self, conn: socket.socket) -> None:
        liveness: Dict[str, bytes] = {}
        conn_cid: Optional[str] = None
        promoted = False
        try:
            while True:
                req = _recv_msg(conn)
                op = req.get("op")
                if op == "register_liveness":
                    # Failure detection: if this connection drops without a
                    # deregister, publish the registered key so peers
                    # blocked in collectives raise instead of timing out.
                    liveness[req["key"]] = req["value"]
                    cid = req.get("cid")
                    if cid is not None:
                        conn_cid = cid
                        with self._conns_lock:
                            self._liveness_reg[(cid, req["key"])] = conn
                    _send_msg(conn, {"ok": True})
                    continue
                if op == "deregister_liveness":
                    liveness.pop(req["key"], None)
                    if conn_cid is not None:
                        with self._conns_lock:
                            self._liveness_reg.pop(
                                (conn_cid, req["key"]), None
                            )
                    _send_msg(conn, {"ok": True})
                    continue
                if op == "replica_join":
                    # The connection becomes the leader->replica stream;
                    # its lifecycle now belongs to the _ReplicaLink.
                    promoted = self._accept_replica(conn, req)
                    return
                _send_msg(conn, self._dispatch(req))
        except (ConnectionError, OSError, EOFError):
            pass
        finally:
            # A promoted (replica-join) connection's lifecycle belongs to
            # its _ReplicaLink from here on, but the accept-time tracking
            # entry must still go — standbys blip and rejoin for months,
            # and each cycle would otherwise leak a dead socket ref.
            with self._conns_lock:
                self._conns.discard(conn)
            if not promoted:
                conn.close()
            if liveness:
                # Publish (and replicate) the death keys: a rank dying an
                # instant before a leader failover must still be visible
                # to peers on the promoted replica. SKIP any key the same
                # client has since re-registered over a newer connection
                # — then this drop is a superseded old connection (a
                # survived blip), not a death.
                if conn_cid is not None:
                    with self._conns_lock:
                        liveness = {
                            k: v
                            for k, v in liveness.items()
                            if self._liveness_reg.get((conn_cid, k), conn)
                            is conn
                        }
                        for k in liveness:
                            self._liveness_reg.pop((conn_cid, k), None)
                with self._cond:
                    items = {
                        k: v for k, v in liveness.items() if k not in self._data
                    }
                    if items:
                        self._apply_locked({"op": "mset_default", "items": items})
                        if self._role == "leader":
                            self._log_seq += 1
                            self._replicate_locked(
                                {"op": "mset_default", "items": items}
                            )

    # ---------------------------------------------------------- dispatch

    def _dispatch(self, req: Dict[str, Any]) -> Dict[str, Any]:
        faultinject.site("dist_store.serve_op")
        op = req["op"]
        if op == "whois":
            return {
                "ok": True,
                "leader": self._role == "leader",
                "role": self._role,
                "epoch": self._epoch,
                # Lets failing-over clients size their probe budget to
                # the tier's ACTUAL lease (a standby answers whois while
                # it is still inside its fencing wait).
                "lease_s": self._lease_s,
            }
        if op == "status":
            return self._status()
        if op == "replicas":
            with self._cond:
                return {
                    "ok": True,
                    "addrs": [link.addr for link in self._replicas],
                    "rsv": self._rs_version,
                    "epoch": self._epoch,
                }
        if self._role != "leader":
            return {
                "ok": False,
                "not_leader": True,
                "role": self._role,
                "epoch": self._epoch,
            }
        cid = req.get("cid")
        cseq = req.get("cseq")
        with self._cond:
            if (
                op in _MUTATING_OPS
                and cid is not None
                and cseq is not None
            ):
                last = self._client_seqs.get(cid)
                if last is not None and cseq <= last[0]:
                    # Replay of an op this lineage already applied (the
                    # ack was lost in a failover): answer the cached
                    # response — exactly-once application.
                    return last[1]
            resp = self._apply_locked(req)
            if op in _MUTATING_OPS and resp.get("ok"):
                self._log_seq += 1
                if cid is not None and cseq is not None:
                    self._remember_client_op(cid, cseq, resp)
                self._replicate_locked(req)
                if self._role != "leader":
                    # Deposed by fencing evidence DURING the replicate:
                    # this write lives only on a dead lineage and must
                    # not be acked — not_leader makes the client replay
                    # it (idempotently) against the promoted leader.
                    return {
                        "ok": False,
                        "not_leader": True,
                        "role": self._role,
                        "epoch": self._epoch,
                    }
            if resp.get("ok"):
                # Replica-set version piggybacks on every response (one
                # small int) so clients learn about newly joined
                # replicas without polling.
                resp["rsv"] = self._rs_version
        return resp

    def _remember_client_op(self, cid: str, cseq: int, resp: Dict[str, Any]) -> None:
        """Record a client's last applied (seq, response) in the bounded
        dedup table. Recency = dict insertion order (refreshed on every
        write), evicting least-recently-writing clients past
        CLIENT_SEQ_CAP. Deterministic: leader and replicas apply the
        same ops in the same order (and sync_full copies preserve
        insertion order), so every lineage evicts identically and a
        replay after failover sees the same table. Caller holds the
        cond."""
        if cid in self._client_seqs:
            del self._client_seqs[cid]
        self._client_seqs[cid] = (cseq, resp)
        while len(self._client_seqs) > CLIENT_SEQ_CAP:
            del self._client_seqs[next(iter(self._client_seqs))]

    def _apply_locked(self, req: Dict[str, Any]) -> Dict[str, Any]:
        """Apply one op. Caller holds ``self._cond``. Deterministic given
        the data state — a replica applying the same op computes the same
        response, which keeps the replicated dedup cache consistent."""
        op = req["op"]
        key = req.get("key")
        if op == "set":
            self._data[key] = req["value"]
            self._cond.notify_all()
            return {"ok": True}
        elif op == "add":
            cur = int(self._data.get(key, b"0"))
            cur += req["amount"]
            self._data[key] = str(cur).encode()
            self._cond.notify_all()
            return {"ok": True, "value": cur}
        elif op == "get":
            deadline = time.monotonic() + req["timeout"]
            while key not in self._data:
                if self._role != "leader":
                    return {"ok": False, "not_leader": True, "epoch": self._epoch}
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not self._cond.wait(timeout=min(remaining, 1.0)):
                    if time.monotonic() >= deadline:
                        return {"ok": False, "timeout": True}
            return {"ok": True, "value": self._data[key]}
        elif op == "wait_any":
            keys = req["keys"]
            deadline = time.monotonic() + req["timeout"]
            while True:
                for k in keys:
                    if k in self._data:
                        return {"ok": True, "key": k, "value": self._data[k]}
                if self._role != "leader":
                    return {"ok": False, "not_leader": True, "epoch": self._epoch}
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return {"ok": False, "timeout": True}
                self._cond.wait(timeout=min(remaining, 1.0))
        elif op == "mset":
            self._data.update(req["items"])
            self._cond.notify_all()
            return {"ok": True}
        elif op == "mset_default":
            # setdefault semantics (the liveness flush: first death wins).
            for k, v in req["items"].items():
                self._data.setdefault(k, v)
            self._cond.notify_all()
            return {"ok": True}
        elif op == "collect":
            # Block until `count` keys with `prefix` exist, then return
            # them all in one response — the server-side half of a
            # scalable all-gather (one RTT per rank instead of one per
            # peer). A stop key (error channel) short-circuits.
            prefix = req["prefix"]
            count = req["count"]
            stop_keys = req.get("stop_keys") or []
            deadline = time.monotonic() + req["timeout"]
            while True:
                # Data completeness BEFORE stop keys (mirrors
                # wait_any's list ordering): a completable collective
                # must complete even if a peer's death landed after
                # its contribution — e.g. a rank posting its piece for
                # the job's final collective and exiting while the
                # leader is still collecting.
                found = {
                    k: v for k, v in self._data.items() if k.startswith(prefix)
                }
                if len(found) >= count:
                    return {"ok": True, "items": found}
                for sk in stop_keys:
                    if sk in self._data:
                        return {
                            "ok": True,
                            "stopped": sk,
                            "value": self._data[sk],
                        }
                if self._role != "leader":
                    return {"ok": False, "not_leader": True, "epoch": self._epoch}
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return {"ok": False, "timeout": True}
                self._cond.wait(timeout=min(remaining, 1.0))
        elif op == "check":
            return {"ok": True, "value": key in self._data}
        elif op == "num_keys":
            return {"ok": True, "value": len(self._data)}
        elif op == "delete":
            existed = self._data.pop(key, None) is not None
            return {"ok": True, "value": existed}
        elif op == "delete_if_value":
            # Conditional delete (the retraction primitive): removes the
            # key only while it still holds the caller's value. A client
            # whose liveness-registered connection dropped but whose
            # PROCESS survived (failover over a network blip) retracts
            # its own false death key with this — without ever erasing a
            # different rank's genuine death record in the same key
            # (first-death-wins setdefault keeps that value, which won't
            # match).
            matched = self._data.get(key) == req["value"]
            if matched:
                del self._data[key]
            return {"ok": True, "value": matched}
        elif op == "delete_prefix":
            keep = req.get("except_keys") or []
            doomed = [
                k
                for k in self._data
                if k.startswith(req["prefix"]) and k not in keep
            ]
            for k in doomed:
                del self._data[k]
            return {"ok": True, "value": len(doomed)}
        else:
            return {"ok": False, "error": f"unknown op {op!r}"}

    # ------------------------------------------------------- replication

    def _replica_timeout(self) -> float:
        return max(self._lease_s, 1.0)

    def _replicate_locked(self, req: Dict[str, Any]) -> None:
        """Stream one applied op to every standby, synchronously (the
        client's ack waits for the replicas' acks). Caller holds the
        cond. A failing link is DROPPED and the leader serves on,
        degraded — synchronous replication cannot skip an op for a live
        replica, so any stream error means that replica is gone."""
        if not self._replicas:
            return
        msg = {
            "op": "replicate",
            "epoch": self._epoch,
            "seq": self._log_seq,
            "req": req,
        }
        for link in list(self._replicas):
            if link.syncing:
                # Mid-sync joiner: buffer in log order (flushed by
                # _accept_replica before the link goes active). Blocking
                # here would hold the cond for the whole sync.
                link.pending.append(msg)
                continue
            try:
                link.send(msg, timeout=self._replica_timeout())
                link.acked_seq = self._log_seq
            except _DeposedError as e:
                logger.error("store leader deposed: %s", e)
                self._depose_locked()
                return
            except Exception as e:  # noqa: BLE001 - any stream failure
                logger.warning(
                    "dropping store replica %s (replication failed: %s)",
                    link.addr,
                    e,
                )
                self._drop_replica_locked(link)

    def _drop_replica_locked(self, link: _ReplicaLink) -> None:
        link.close()
        if link in self._replicas:
            self._replicas.remove(link)
            self._rs_version += 1

    def _depose_locked(self) -> None:
        """Fencing evidence arrived (a replica moved to a higher epoch):
        stop serving. Blocked waits return ``not_leader`` on their next
        wakeup so clients re-arm against the promoted leader."""
        self._role = "deposed"
        for link in self._replicas:
            link.close()
        self._replicas = []
        self._cond.notify_all()
        from .telemetry import flightrec

        flightrec.record("store.epoch", epoch=self._epoch, role="deposed")

    def _accept_replica(self, conn: socket.socket, req: Dict[str, Any]) -> bool:
        """A standby joined: full-sync it under the link lock (so no
        replicate can interleave before the snapshot lands), then
        register it. Returns True when the conn was promoted to a link."""
        addr = req["addr"]
        link = _ReplicaLink(conn, addr)
        sync_err: Optional[BaseException] = None
        with link.lock:
            # tsalint: allow[lock-order] safe against the documented
            # _cond -> lock order: this link was constructed two lines up
            # and is not yet registered in _replicas, so no other thread
            # can hold (or wait on) link.lock — the inverted edge cannot
            # close a cycle until after the lock is released
            with self._cond:
                if self._role != "leader":
                    try:
                        # tsalint: allow[lock-blocking] best-effort one-shot
                        # rejection to a conn nobody else shares: the frame
                        # fits the kernel send buffer, and OSError (incl.
                        # timeout) is swallowed — a wedged joiner cannot
                        # hold this
                        _send_msg(
                            conn,
                            {"ok": False, "not_leader": True, "epoch": self._epoch},
                        )
                    except OSError:
                        pass
                    return False
                link.index = self._joined_total
                self._joined_total += 1
                sync = {
                    "op": "sync_full",
                    "epoch": self._epoch,
                    "seq": self._log_seq,
                    "data": dict(self._data),
                    "client_seqs": dict(self._client_seqs),
                    "index": link.index,
                    "lease_s": self._lease_s,
                    "peers": [(l.index, l.addr) for l in self._replicas]
                    + [(link.index, addr)],
                }
                self._replicas.append(link)
                self._rs_version += 1
                ready = (
                    self._expected_replicas > 0
                    and len(self._replicas) >= self._expected_replicas
                )
            # cond released, link lock still held: the sync frame is
            # guaranteed to precede any replicate on this link. The
            # exchange is deadline-bounded — a hung (non-dead) joiner
            # holding this lock open-endedly would stall every mutating
            # dispatch blocked in link.send behind it. And per the class
            # locking rules, NOTHING below may acquire the cond while
            # the link lock is held: a failure is only recorded here and
            # cleaned up after the lock is released ( _replicate_locked
            # holds the cond while waiting on this lock — re-acquiring
            # the cond here would deadlock the whole store).
            deposed = False
            try:
                conn.settimeout(max(self._replica_timeout(), 30.0))
                # tsalint: allow[lock-blocking] deadline-bounded by the
                # settimeout above, and holding ONLY link.lock here is the
                # design: the cond was dropped before the sync precisely so
                # a slow joiner stalls nothing but its own link
                _send_msg(conn, sync)
                ack = _recv_msg(conn)
                conn.settimeout(None)
                if not (isinstance(ack, dict) and ack.get("ok")):
                    raise ConnectionError(f"standby {addr} rejected sync: {ack}")
                # The full sync carried the state at this log position.
                link.acked_seq = sync["seq"]
            except Exception as e:  # noqa: BLE001
                sync_err = e
            # Drain ops that applied while the sync was in flight: they
            # were buffered (dispatchers holding the cond never block on
            # a syncing link), and must land in log order before the
            # link goes active. Locking amendment: this path holds
            # link.lock and takes the cond BRIEFLY to swap batches —
            # safe because no thread ever holds the cond while waiting
            # on a SYNCING link's lock (replicate/lease/rs_update all
            # skip syncing links), so no cycle can form.
            while sync_err is None and not deposed:
                # tsalint: allow[lock-order] documented amendment (comment above): this path holds link.lock and takes the cond briefly to swap batches; no thread holds the cond while waiting on a SYNCING link's lock, so no cycle can form
                with self._cond:
                    batch = link.pending
                    link.pending = []
                    if not batch:
                        link.syncing = False
                        break
                for msg in batch:
                    try:
                        link.send(msg, timeout=self._replica_timeout())
                        link.acked_seq = msg.get("seq", link.acked_seq)
                    except _DeposedError as e:
                        logger.error("store leader deposed: %s", e)
                        deposed = True
                        break
                    except Exception as e:  # noqa: BLE001
                        sync_err = e
                        break
        if deposed:
            with self._cond:
                self._depose_locked()
            return True
        if sync_err is not None:
            logger.warning("standby %s failed to sync: %s", addr, sync_err)
            with self._cond:
                self._drop_replica_locked(link)
            return False
        logger.info(
            "store replica %s joined (index %d, epoch %d, seq %d)",
            addr,
            link.index,
            self._epoch,
            self._log_seq,
        )
        self._ensure_lease_thread()
        self._broadcast_rs_update()
        if ready:
            self._set_internal(REPLICAS_READY_KEY, b"1")
        return True

    def _set_internal(self, key: str, value: bytes) -> None:
        """A leader-originated (no client) replicated KV write."""
        with self._cond:
            if self._role != "leader":
                return
            self._apply_locked({"op": "set", "key": key, "value": value})
            self._log_seq += 1
            self._replicate_locked({"op": "set", "key": key, "value": value})

    def _broadcast_rs_update(self) -> None:
        with self._cond:
            peers = [(l.index, l.addr) for l in self._replicas]
            msg = {"op": "rs_update", "epoch": self._epoch, "peers": peers}
            # Syncing joiners must not be blocked on (their lock is
            # sync-held) — they get this update via their flush queue,
            # in order with the op stream.
            links = []
            for l in self._replicas:
                if l.syncing:
                    l.pending.append(msg)
                else:
                    links.append(l)
        for link in links:
            try:
                link.send(msg, timeout=self._replica_timeout())
            except _DeposedError as e:
                # Fencing evidence counts no matter which message drew
                # it: a replica on a higher epoch ends this leadership.
                logger.error("store leader deposed: %s", e)
                with self._cond:
                    self._depose_locked()
                return
            except Exception as e:  # noqa: BLE001
                logger.warning("dropping store replica %s (%s)", link.addr, e)
                with self._cond:
                    self._drop_replica_locked(link)

    def _ensure_lease_thread(self) -> None:
        with self._cond:
            if self._lease_thread is not None and self._lease_thread.is_alive():
                return
            self._lease_thread = threading.Thread(
                target=self._lease_loop, name="tpusnapshot-store-lease", daemon=True
            )
            self._lease_thread.start()

    def _lease_loop(self) -> None:
        from . import telemetry

        while not self._shutdown.is_set():
            time.sleep(self._lease_s / 3.0)
            if self._role != "leader" or self._shutdown.is_set():
                return
            try:
                faultinject.site("dist_store.lease_renew")
            except Exception as e:  # noqa: BLE001 - injected renewal failure
                logger.warning("lease renewal round skipped: %s", e)
                continue
            with self._cond:
                # Syncing joiners are skipped (their lock is held for
                # the whole sync; they get the stream once flushed).
                links = [l for l in self._replicas if not l.syncing]
                msg = {"op": "lease_renew", "epoch": self._epoch}
            if links:
                from .telemetry import flightrec

                flightrec.record(
                    "store.lease", epoch=self._epoch, replicas=len(links)
                )
            for link in links:
                try:
                    link.send(msg, timeout=self._replica_timeout())
                    link.last_renew = time.monotonic()
                    telemetry.counter_add("lease_renewals", 1)
                except _DeposedError as e:
                    logger.error("store leader deposed: %s", e)
                    with self._cond:
                        self._depose_locked()
                    return
                except Exception as e:  # noqa: BLE001
                    logger.warning(
                        "dropping store replica %s (lease renewal failed: %s)",
                        link.addr,
                        e,
                    )
                    with self._cond:
                        self._drop_replica_locked(link)

    # ---------------------------------------------------- standby / join

    def _join_leader(self, leader_addr: str) -> None:
        """Join ``leader_addr`` as a standby: full sync, then follow the
        op-log/lease stream on a background thread."""
        host, _, port = leader_addr.rpartition(":")
        sock = socket.create_connection(
            (host, int(port)), timeout=CONNECT_TIMEOUT_S
        )
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            advert = f"{sock.getsockname()[0]}:{self.port}"
            sock.settimeout(CONNECT_TIMEOUT_S)
            _send_msg(sock, {"op": "replica_join", "addr": advert})
            sync = _recv_msg(sock)
            if not (isinstance(sync, dict) and sync.get("op") == "sync_full"):
                raise ConnectionError(
                    f"replica join to {leader_addr} refused: {sync!r}"
                )
            with self._cond:
                self._data = dict(sync["data"])
                self._client_seqs = dict(sync["client_seqs"])
                self._epoch = sync["epoch"]
                self._log_seq = sync["seq"]
                self._standby_index = sync["index"]
                self._peers = [
                    (int(i), a)
                    for i, a in sync.get("peers", [])
                    if a != advert
                ]
                self._lease_s = float(sync.get("lease_s", self._lease_s))
                self._leader_addr = leader_addr
                self._role = "standby"
                self._cond.notify_all()
            _send_msg(sock, {"ok": True})
        except BaseException:
            try:
                sock.close()
            except OSError:
                pass
            raise
        self._upstream = sock
        self._advertise = advert
        self._last_leader_msg = time.monotonic()
        threading.Thread(
            target=self._follow, name="tpusnapshot-store-follow", daemon=True
        ).start()

    def _follow(self) -> None:
        sock = self._upstream
        sock.settimeout(max(self._lease_s, 0.2))
        while not self._shutdown.is_set() and self._role == "standby":
            try:
                msg = _recv_msg(sock)
            except socket.timeout:
                if time.monotonic() - self._last_leader_msg > self._lease_s:
                    logger.warning(
                        "store leader %s silent past the lease (%.1fs)",
                        self._leader_addr,
                        self._lease_s,
                    )
                    break
                continue
            except (ConnectionError, OSError, EOFError):
                break
            self._last_leader_msg = time.monotonic()
            op = msg.get("op")
            try:
                if msg.get("epoch", self._epoch) < self._epoch:
                    # Epoch fencing: ANY stream message declaring a
                    # lower epoch (op log, lease, rs_update) is a
                    # deposed leader's late write — refuse it so the
                    # sender learns and steps down.
                    _send_msg(
                        sock,
                        {
                            "ok": False,
                            "stale_epoch": True,
                            "epoch": self._epoch,
                        },
                    )
                    continue
                if op in ("replicate", "lease_renew"):
                    if op == "replicate":
                        req = msg["req"]
                        with self._cond:
                            resp = self._apply_locked(req)
                            self._log_seq = msg["seq"]
                            cid, cseq = req.get("cid"), req.get("cseq")
                            if cid is not None and cseq is not None:
                                self._remember_client_op(cid, cseq, resp)
                    _send_msg(sock, {"ok": True})
                elif op == "rs_update":
                    with self._cond:
                        self._peers = [
                            (int(i), a)
                            for i, a in msg.get("peers", [])
                            if a != self._advertise
                        ]
                    _send_msg(sock, {"ok": True})
                else:
                    _send_msg(sock, {"ok": True})
            except (ConnectionError, OSError, EOFError):
                break
        try:
            sock.close()
        except OSError:
            pass
        if not self._shutdown.is_set() and self._role == "standby":
            self._takeover_or_rejoin()

    def _find_live_leader(self) -> Optional[Tuple[str, int]]:
        """Probe the old leader and every known sibling; return the
        reachable leader claim with the highest epoch (addr, epoch)."""
        best: Optional[Tuple[str, int]] = None
        candidates = []
        if self._leader_addr:
            candidates.append(self._leader_addr)
        candidates.extend(a for _i, a in sorted(self._peers))
        for cand in candidates:
            info = _try_whois(cand, timeout=max(self._lease_s / 4, 0.25))
            if info and info.get("leader"):
                epoch = int(info.get("epoch", 0))
                if best is None or epoch > best[1]:
                    best = (cand, epoch)
        return best

    def _rejoin(self, leader_addr: str) -> bool:
        try:
            self._join_leader(leader_addr)
            logger.warning(
                "store standby %s rejoined leader %s (epoch %d)",
                self._advertise,
                leader_addr,
                self._epoch,
            )
            return True
        except Exception as e:  # noqa: BLE001
            logger.warning("rejoin to %s failed: %s", leader_addr, e)
            return False

    def _takeover_or_rejoin(self) -> None:
        """The upstream stream is gone. Fencing wait: the old leader's
        lease must lapse before this standby may assume. Lower join
        indices get the first shot (stagger); while waiting, probe for a
        sibling that already assumed (or the old leader, if our link
        merely broke) and rejoin it instead."""
        probe_gap = max(self._lease_s / 10.0, 0.05)
        while not self._shutdown.is_set() and self._role == "standby":
            assume_at = (
                self._last_leader_msg
                + self._lease_s
                + 0.5 * max(self._standby_index, 0)
            )
            # Guarantee a real probe window even when the lease expired
            # BEFORE we got here (the silence-detection path: by the time
            # _follow breaks, _last_leader_msg is already a full lease
            # old, making assume_at instantly past — index-0 standbys
            # would otherwise assume with ZERO probes and depose a
            # leader that merely stalled over one lease).
            assume_at = max(assume_at, time.monotonic() + 2 * probe_gap)
            while time.monotonic() < assume_at and not self._shutdown.is_set():
                found = self._find_live_leader()
                if found is not None and (
                    found[1] > self._epoch or found[0] == self._leader_addr
                ):
                    if self._rejoin(found[0]):
                        return
                time.sleep(probe_gap)
            found = self._find_live_leader()
            if found is not None and (
                # Same acceptance rule as the probe loop: a RECOVERED
                # same-epoch leader is rejoined, never deposed.
                found[1] > self._epoch
                or found[0] == self._leader_addr
            ):
                if self._rejoin(found[0]):
                    return
                continue
            with self._cond:
                if self._role != "standby":
                    return
                self._epoch += 1
                self._role = "leader"
                self._rs_version += 1
                self._replicas = []
                self._leader_addr = None
                self._cond.notify_all()
            logger.warning(
                "store standby %s assumed leadership at epoch %d "
                "(log seq %d, %d keys)",
                self._advertise,
                self._epoch,
                self._log_seq,
                len(self._data),
            )
            from .telemetry import flightrec

            flightrec.record(
                "store.epoch", epoch=self._epoch, role="leader",
                log_seq=self._log_seq,
            )
            self._ensure_lease_thread()
            return

    # ------------------------------------------------------------ status

    def _status(self) -> Dict[str, Any]:
        with self._cond:
            now = time.monotonic()
            info: Dict[str, Any] = {
                "ok": True,
                "role": self._role,
                "epoch": self._epoch,
                "log_seq": self._log_seq,
                "lease_s": self._lease_s,
                "n_keys": len(self._data),
                "rsv": self._rs_version,
            }
            if self._role == "leader":
                info["replicas"] = [
                    {
                        "addr": link.addr,
                        "index": link.index,
                        "acked_seq": link.acked_seq,
                        "lag": self._log_seq - link.acked_seq,
                        "lease_age_s": round(now - link.last_renew, 3),
                    }
                    for link in self._replicas
                ]
            elif self._role == "standby":
                info["leader"] = self._leader_addr
                info["leader_silence_s"] = round(now - self._last_leader_msg, 3)
            # deposed/closed: an ex-leader has no upstream to report —
            # "following leader None" here would mislead the on-call.
            return info

    def close(self) -> None:
        self._shutdown.set()
        try:
            self._sock.close()
        except OSError:
            pass
        with self._cond:
            self._role = "closed"
            for link in self._replicas:
                link.close()
            self._replicas = []
            self._cond.notify_all()
        if self._upstream is not None:
            try:
                self._upstream.close()
            except OSError:
                pass
        with self._conns_lock:
            conns, self._conns = list(self._conns), set()
        for conn in conns:
            try:
                conn.close()
            except OSError:
                pass


def host_standby(
    leader_addr: str,
    lease_s: Optional[float] = None,
    host: str = "0.0.0.0",
    port: int = 0,
) -> _StoreServer:
    """Host a standby replica of the store at ``leader_addr`` in this
    process: binds a listener, full-syncs from the leader, and follows
    its op-log/lease stream. On leader loss the standby assumes
    leadership per the lease protocol (module docstring). Returns the
    server handle; ``close()`` it on clean shutdown."""
    server = _StoreServer(host=host, port=port, standby=True, lease_s=lease_s)
    try:
        server._join_leader(leader_addr)
    except BaseException:
        server.close()
        raise
    return server


def probe_store_status(addr: str, timeout: float = 5.0) -> Dict[str, Any]:
    """One-shot status snapshot of the store node at ``addr`` (leader or
    standby), for the ``store-status`` CLI. Raises ConnectionError when
    nothing answering the store protocol lives there."""
    host, _, port = addr.rpartition(":")
    sock = socket.create_connection((host, int(port)), timeout=timeout)
    try:
        sock.settimeout(timeout)
        _send_msg(sock, {"op": "status"})
        resp = _recv_msg(sock)
        if not (isinstance(resp, dict) and resp.get("ok")):
            raise ConnectionError(f"{addr} did not answer the status probe: {resp!r}")
        resp["addr"] = addr
        return resp
    finally:
        try:
            sock.close()
        except OSError:
            pass


class TCPStore:
    """Client handle to a store server (optionally hosting it in-process).

    Thread-safe: calls are serialized over one connection with a lock; use
    separate TCPStore instances for genuinely concurrent use (e.g. the async
    commit thread creates its own connection).

    When the server side is replicated, failover is transparent: a lost
    connection (or a ``not_leader`` answer from a deposed leader) probes
    the known replica set, adopts the live leader with the highest epoch,
    re-registers liveness keys, and replays the in-flight op — mutating
    ops idempotently via their ``(client_id, seq)`` stamp, blocking ops
    re-armed with their remaining timeout. ``failovers`` counts adopted
    failovers on this client (also published as the ``store_failovers``
    telemetry counter).
    """

    def __init__(
        self,
        host: str,
        port: Optional[int] = None,
        is_server: bool = False,
        timeout: float = DEFAULT_BARRIER_TIMEOUT_S,
        lease_s: Optional[float] = None,
        expected_replicas: int = 0,
        connect_retries: Optional[int] = None,
        _replica_addrs: Optional[List[str]] = None,
        _bootstrap_addr: Optional[str] = None,
    ) -> None:
        self._server: Optional[_StoreServer] = None
        if is_server:
            self._server = _StoreServer(
                port=port or 0,
                lease_s=lease_s,
                expected_replicas=expected_replicas,
            )
            port = self._server.port
            host = "127.0.0.1" if host in ("0.0.0.0", "") else host
        assert port is not None
        self.host = host
        self.port = port
        self.timeout = timeout
        self._lock = threading.Lock()
        self._dead: Optional[StoreConnectionLostError] = None
        # Failover state: a stable client identity for idempotent replay,
        # the liveness keys to re-register on a new connection, the known
        # replica set, and the highest leader epoch observed.
        self._client_id = uuid.uuid4().hex
        self._mut_seq = 0
        self._liveness: Dict[str, bytes] = {}
        self._replica_addrs: List[str] = list(_replica_addrs or [])
        self._rsv = 0
        self._epoch_seen = 0
        self.failovers = 0
        # The address this client was BOOTSTRAPPED with: stable across
        # failovers (``addr`` tracks the current leader), so per-process
        # bookkeeping keyed by store identity (pg_wrapper's handshake
        # cursors) survives a mid-job leader change.
        self.bootstrap_addr = _bootstrap_addr or f"{host}:{port}"
        self._standby: Optional[_StoreServer] = None  # create_store attaches
        retries = (
            DEFAULT_CONNECT_RETRIES if connect_retries is None else connect_retries
        )
        attempt = 0
        while True:
            try:
                self._sock = self._connect_probed(host, port)
                break
            except ConnectionRefusedError as e:
                # Refused means nothing is listening YET — the one
                # connect failure a bounded, jittered retry can outwait
                # (slow server start, a failover target still binding).
                # Timeouts/garbage are not retried: they cannot improve.
                if attempt >= retries:
                    raise
                delay = _connect_backoff_s(attempt)
                attempt += 1
                logger.info(
                    "store connect to %s:%s refused (%s); retrying in "
                    "%.2fs (attempt %d/%d)",
                    host,
                    port,
                    e,
                    delay,
                    attempt,
                    retries,
                )
                time.sleep(delay)

    @staticmethod
    def _connect_probed(host: str, port: int) -> socket.socket:
        """Connect and validate that a real store server answers: the
        self-connect check, one probe round trip, and the keepalive /
        user-timeout socket configuration. Runs on EVERY connect attempt
        — initial, retried, and failover adoption alike."""
        sock = socket.create_connection((host, port), timeout=CONNECT_TIMEOUT_S)
        # A TCP connect alone does not prove a STORE is on the other end:
        # on loopback, connecting to a freed ephemeral port (a dead store
        # host's port is the classic case) can simultaneous-open onto
        # itself or yield a phantom connection that dies on first use.
        # Validate with one probe round-trip: only a real server answers
        # it correctly (a self-connect echoes our own request back, which
        # fails the response check).
        try:
            if sock.getsockname() == sock.getpeername():
                raise ConnectionRefusedError(
                    f"self-connect to {host}:{port} (no server listening)"
                )
            _send_msg(sock, {"op": "check", "key": "__conn_probe__"})
            resp = _recv_msg(sock)
            if not isinstance(resp, dict) or "ok" not in resp:
                raise ConnectionRefusedError(
                    f"{host}:{port} did not answer the store probe "
                    "(not a store server)"
                )
        except ConnectionRefusedError:
            try:
                sock.close()
            except OSError:
                pass
            raise
        except (ConnectionError, EOFError, OSError):
            try:
                sock.close()
            except OSError:
                pass
            raise
        except Exception as e:
            # A non-store service on the port can answer with bytes that
            # explode anywhere inside unpickling (UnpicklingError,
            # ValueError, AttributeError, ...): that is still "not a
            # store server", and the socket must not leak.
            try:
                sock.close()
            except OSError:
                pass
            raise ConnectionRefusedError(
                f"{host}:{port} answered the store probe with garbage "
                f"({type(e).__name__}: {e}) — not a store server"
            ) from e
        try:
            sock.settimeout(None)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            # Silent-death detection at the TCP layer (a killed process
            # RSTs and needs none of this; these cover power loss /
            # partitions):
            # - keepalive (idle 5 s + 3 probes x 5 s = ~20 s) tears down
            #   connections idle in a blocked recv;
            # - TCP_USER_TIMEOUT (~20 s) covers the case keepalive cannot:
            #   request bytes sent but never ACKed (keepalive probes are
            #   suppressed while data is outstanding — without this, that
            #   path would ride retransmission backoff for ~15 minutes).
            # Both land long before the 1800 s barrier timeout.
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_KEEPALIVE, 1)
            for opt, val in (
                ("TCP_KEEPIDLE", 5),
                ("TCP_KEEPINTVL", 5),
                ("TCP_KEEPCNT", 3),
                ("TCP_USER_TIMEOUT", 20_000),  # milliseconds
            ):
                if hasattr(socket, opt):  # Linux; harmless to skip elsewhere
                    sock.setsockopt(
                        socket.IPPROTO_TCP, getattr(socket, opt), val
                    )
        except BaseException:
            try:
                sock.close()
            except OSError:
                pass
            raise
        return sock

    @property
    def addr(self) -> str:
        return f"{self.host}:{self.port}"

    @property
    def replica_addrs(self) -> List[str]:
        """The standby replica addresses this client would fail over to.
        Lock-free snapshot read: the client lock is held for the full
        duration of a blocked collective, and observability reads must
        not wait behind it."""
        return list(self._replica_addrs)

    def local_ip(self) -> Optional[str]:
        """The local IP of the current store connection — the interface
        that reaches the coordination plane (fanout's peer-listener
        address discovery). None when it cannot be determined. Lock-free
        (see ``replica_addrs``): reads one reference atomically."""
        sock = self._sock
        try:
            return sock.getsockname()[0]
        except (OSError, AttributeError):
            return None

    # ----------------------------------------------------------- request

    def _request(self, req: Dict[str, Any]) -> Dict[str, Any]:
        op = req["op"]
        op_timeout = req.get("timeout")
        op_deadline = (
            time.monotonic() + op_timeout if op_timeout is not None else None
        )
        blips = 0
        while True:
            if op_deadline is not None:
                remaining = op_deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"Store operation {op!r} on "
                        f"{req.get('key') or req.get('keys')} timed out "
                        f"after {op_timeout}s."
                    )
                # Re-armed blocking ops carry only their REMAINING budget
                # to the (possibly new) leader.
                req["timeout"] = remaining
                response_deadline = remaining + RPC_GRACE_S
            else:
                # How long the CLIENT waits for the server's response: a
                # deadline expiring here means the SERVER went silent,
                # not that the op timed out.
                response_deadline = STORE_RPC_TIMEOUT_S
            # OUTSIDE the lock/try: an injected transient store fault
            # models a blip that failed one request over a HEALTHY
            # connection — the client resends (idempotently) instead of
            # latching dead (a permanent/kill plan models a torn store).
            try:
                faultinject.site("dist_store.rpc")
            except ConnectionError:
                if blips >= RPC_BLIP_RETRIES:
                    raise
                blips += 1
                time.sleep(0.05 * blips * (1.0 + random.random()))
                continue
            with self._lock:
                if self._dead is not None:
                    # The connection is gone (and mid-message state would
                    # be corrupt anyway): every subsequent op fails fast.
                    raise self._dead
                # Stamp mutating ops ONCE, inside the lock (the stamp
                # order must match the send order for the server's
                # per-client dedup window); replays reuse the stamp.
                # Only stamped when a failover target exists: without
                # replicas a lost connection latches this client dead and
                # no replay can ever happen, so the stamp (and the
                # server's dedup bookkeeping it triggers) would be pure
                # overhead on the disabled path. The replica cache is
                # primed by the bootstrap's replicas-ready gate before
                # any coordination op, so replicated deployments stamp
                # from the first op.
                if (
                    self._replica_addrs
                    and "cid" not in req
                    and op in _MUTATING_OPS
                ):
                    self._mut_seq += 1
                    req["cid"] = self._client_id
                    req["cseq"] = self._mut_seq
                try:
                    self._sock.settimeout(response_deadline)
                    # tsalint: allow[lock-blocking] deadline-bounded by the
                    # settimeout above; self._lock IS the client's
                    # per-connection request serialization — concurrent
                    # callers must queue behind the in-flight RPC by design
                    _send_msg(self._sock, req)
                    resp = _recv_msg(self._sock)
                    self._sock.settimeout(None)
                except (ConnectionError, EOFError, OSError) as e:
                    # socket.timeout is an OSError subclass, so a silent
                    # server (deadline) and a dead one (RST/FIN) both
                    # land here; keepalive converts long silences too.
                    # tsalint: allow[lock-blocking] failover's bounded
                    # connect-retry sleeps run under self._lock on purpose:
                    # every other request MUST queue until the new leader is
                    # adopted — releasing the lock would just let them race
                    # the same dead socket
                    self._failover_locked(e, op)
                    continue
            if resp.get("not_leader"):
                # A deposed leader (or a standby we adopted optimistically)
                # answered: find the real leader and re-issue.
                with self._lock:
                    if self._dead is not None:
                        raise self._dead
                    # tsalint: allow[lock-blocking] same deliberate hold as
                    # the exception path above: requests queue behind the
                    # bounded failover rather than racing a deposed leader
                    self._failover_locked(
                        ConnectionError(
                            f"{self.addr} is no longer the store leader "
                            f"(role {resp.get('role')!r}, epoch "
                            f"{resp.get('epoch')})"
                        ),
                        op,
                    )
                continue
            break
        self._maybe_refresh_replicas(resp)
        if resp.get("timeout"):
            raise TimeoutError(
                f"Store operation {req['op']!r} on {req.get('key') or req.get('keys')} "
                f"timed out after {op_timeout}s."
            )
        if not resp.get("ok"):
            raise RuntimeError(f"Store error: {resp.get('error')}")
        return resp

    # ---------------------------------------------------------- failover

    def _failover_budget_s(self, lease_hint_s: float) -> float:
        # A takeover needs ~lease + stagger + probe rounds; give it a few
        # leases with an absolute floor. ``lease_hint_s`` is the largest
        # lease any probed candidate reported — the env default alone
        # would abandon a failover whose server was built with a longer
        # lease passed as a parameter (the standby is REQUIRED to sit
        # out that full lease before it may assume).
        return max(4.0 * max(lease_hint_s, DEFAULT_STORE_LEASE_S), 10.0)

    def _failover_locked(self, cause: BaseException, op: str) -> None:
        """The connection failed. With replicas known: probe the
        candidate set until a live leader (at >= the highest epoch seen)
        answers, adopt it, and return — the caller replays the request.
        Without replicas: latch dead and raise (the pre-replication
        behavior — fast, loud, bounded). Caller holds ``self._lock``."""
        try:
            self._sock.close()
        except OSError:
            pass
        if not self._replica_addrs:
            self._dead = StoreConnectionLostError(self.addr, op, cause)
            raise self._dead from cause
        candidates = list(
            dict.fromkeys([self.addr, *self._replica_addrs, self.bootstrap_addr])
        )
        started = time.monotonic()
        lease_hint = 0.0
        attempt = 0
        logger.warning(
            "coordination store connection lost during %r (%s); probing "
            "failover candidates %s",
            op,
            cause,
            candidates,
        )
        while time.monotonic() < started + self._failover_budget_s(lease_hint):
            best: Optional[Tuple[int, str]] = None
            for cand in candidates:
                info = _try_whois(cand, timeout=2.0)
                if not info:
                    continue
                # Any reachable node (a standby still in its fencing
                # wait included) teaches us the tier's real lease.
                lease_hint = max(lease_hint, float(info.get("lease_s", 0.0)))
                if not info.get("leader"):
                    continue
                epoch = int(info.get("epoch", 0))
                if best is None or epoch > best[0]:
                    best = (epoch, cand)
            if best is not None and best[0] >= self._epoch_seen:
                if self._adopt_locked(best[1], best[0], cause):
                    return
            attempt += 1
            time.sleep(_connect_backoff_s(attempt, base=0.1, cap=1.0))
        self._dead = StoreConnectionLostError(
            self.addr,
            op,
            cause,
            role=(
                f"the store leader at epoch {max(self._epoch_seen, 1)}; "
                f"failover exhausted after probing {', '.join(candidates)}"
            ),
        )
        raise self._dead from cause

    def _adopt_locked(self, cand: str, epoch: int, cause: BaseException) -> bool:
        """Connect to the probed leader and re-establish this client's
        connection-scoped state (liveness registrations, replica cache).
        Returns False (to keep probing) on any failure."""
        host, _, port = cand.rpartition(":")
        try:
            sock = self._connect_probed(host, int(port))
        except (OSError, ValueError):
            return False
        try:
            # The whole adoption handshake is deadline-bounded: a
            # candidate that answered whois and then wedged (alive
            # kernel, stuck process) must cost one bounded probe, not an
            # indefinite hang with the client lock held.
            sock.settimeout(CONNECT_TIMEOUT_S)
            for key, value in self._liveness.items():
                # This PROCESS is alive — the old connection's drop may
                # already have flushed a false death record for it.
                # Retract it (conditionally: a different rank's genuine
                # death in the same key holds a different value and is
                # preserved), then re-register on the new connection.
                # Residual race: a peer blocked in a collective during
                # the gap between the flush and this retraction can
                # still observe the key — bounded by this client's next
                # op, vs. permanent poisoning without the retraction.
                _send_msg(
                    sock,
                    {"op": "delete_if_value", "key": key, "value": value},
                )
                ack = _recv_msg(sock)
                if not (isinstance(ack, dict) and ack.get("ok")):
                    raise ConnectionError(f"death-key retraction refused: {ack}")
                _send_msg(
                    sock,
                    {
                        "op": "register_liveness",
                        "key": key,
                        "value": value,
                        "cid": self._client_id,
                    },
                )
                ack = _recv_msg(sock)
                if not (isinstance(ack, dict) and ack.get("ok")):
                    raise ConnectionError(f"liveness re-register refused: {ack}")
            _send_msg(sock, {"op": "replicas"})
            rs = _recv_msg(sock)
        except Exception as e:  # noqa: BLE001 - candidate died mid-adopt
            logger.warning("failover candidate %s failed mid-adopt: %s", cand, e)
            try:
                sock.close()
            except OSError:
                pass
            return False
        sock.settimeout(None)
        self.host, self.port = host, int(port)
        self._sock = sock
        self._epoch_seen = max(self._epoch_seen, epoch)
        if isinstance(rs, dict) and rs.get("ok"):
            self._replica_addrs = [
                a for a in rs.get("addrs", []) if a != self.addr
            ]
            self._rsv = rs.get("rsv", self._rsv)
        self.failovers += 1
        from . import telemetry
        from .telemetry import flightrec

        telemetry.counter_add("store_failovers", 1)
        flightrec.record(
            "store.failover", epoch=epoch, leader=cand, cause=repr(cause)
        )
        logger.warning(
            "coordination store failover #%d: adopted leader %s (epoch %d) "
            "after %s",
            self.failovers,
            cand,
            epoch,
            cause,
        )
        return True

    def _maybe_refresh_replicas(self, resp: Dict[str, Any]) -> None:
        """Track the server's replica-set version (piggybacked on every
        response) and re-fetch the addresses when it moves — so the
        failover candidate set is warm BEFORE the leader dies."""
        rsv = resp.get("rsv")
        if rsv is None or rsv == self._rsv:
            return
        with self._lock:
            if self._dead is not None or rsv == self._rsv:
                return
            try:
                self._sock.settimeout(STORE_RPC_TIMEOUT_S)
                # tsalint: allow[lock-blocking] deadline-bounded by the
                # settimeout above, and best-effort: any socket failure just
                # returns and the next response retriggers the refresh
                _send_msg(self._sock, {"op": "replicas"})
                rs = _recv_msg(self._sock)
                self._sock.settimeout(None)
            except (ConnectionError, EOFError, OSError):
                return  # best-effort; the next response retriggers
            if isinstance(rs, dict) and rs.get("ok"):
                self._replica_addrs = [
                    a for a in rs.get("addrs", []) if a != self.addr
                ]
                self._rsv = rs.get("rsv", rsv)
                self._epoch_seen = max(
                    self._epoch_seen, int(rs.get("epoch", 0))
                )

    # --------------------------------------------------------------- api

    def set(self, key: str, value: bytes) -> None:
        self._request({"op": "set", "key": key, "value": bytes(value)})

    def get(self, key: str, timeout: Optional[float] = None) -> bytes:
        return self._request(
            {"op": "get", "key": key, "timeout": timeout or self.timeout}
        )["value"]

    def wait_any(
        self, keys: List[str], timeout: Optional[float] = None
    ) -> Tuple[str, bytes]:
        resp = self._request(
            {"op": "wait_any", "keys": keys, "timeout": timeout or self.timeout}
        )
        return resp["key"], resp["value"]

    def add(self, key: str, amount: int) -> int:
        return self._request({"op": "add", "key": key, "amount": amount})["value"]

    def mset(self, items: Dict[str, bytes]) -> None:
        """Set many keys in one round trip (scatter's leader-side write)."""
        self._request({"op": "mset", "items": {k: bytes(v) for k, v in items.items()}})

    def collect(
        self,
        prefix: str,
        count: int,
        stop_keys: Optional[List[str]] = None,
        timeout: Optional[float] = None,
    ) -> Tuple[Optional[str], Dict[str, bytes]]:
        """Block until ``count`` keys under ``prefix`` exist; return them all
        in ONE round trip. Returns ``(stopped_key, items)``: if a stop key
        (e.g. an error channel) appears first, ``stopped_key`` is set and
        ``items`` maps it to its value."""
        resp = self._request(
            {
                "op": "collect",
                "prefix": prefix,
                "count": count,
                "stop_keys": stop_keys or [],
                "timeout": timeout or self.timeout,
            }
        )
        if "stopped" in resp:
            return resp["stopped"], {resp["stopped"]: resp["value"]}
        return None, resp["items"]

    def check(self, key: str) -> bool:
        return self._request({"op": "check", "key": key})["value"]

    def num_keys(self) -> int:
        """Total number of keys currently held by the server (observability /
        store-hygiene tests)."""
        return self._request({"op": "num_keys"})["value"]

    def delete(self, key: str) -> bool:
        return self._request({"op": "delete", "key": key})["value"]

    def delete_prefix(self, prefix: str, except_keys: Optional[List[str]] = None) -> int:
        return self._request(
            {"op": "delete_prefix", "prefix": prefix, "except_keys": except_keys}
        )["value"]

    def status(self) -> Dict[str, Any]:
        """The server's replication status (role, epoch, replica lag)."""
        return self._request({"op": "status"})

    def register_liveness(self, key: str, value: bytes) -> None:
        """Publish ``key``=``value`` if THIS connection ever drops without
        ``deregister_liveness`` — the failure-detection hook: a process
        dying mid-collective makes its death visible to peers through a
        key they already watch, instead of leaving them blocked until the
        store timeout. Clones do NOT inherit registration (a background
        thread closing its connection is not a process death). The
        registration is re-established automatically on failover — it is
        scoped to the connection, and the failed-over client has a new
        one."""
        value = bytes(value)
        self._request(
            {
                "op": "register_liveness",
                "key": key,
                "value": value,
                # Client identity lets the server tell "this connection
                # was superseded by a failover re-registration" apart
                # from "this client died" when the old FIN arrives late.
                "cid": self._client_id,
            }
        )
        self._liveness[key] = value

    def deregister_liveness(self, key: str) -> None:
        self._request({"op": "deregister_liveness", "key": key})
        self._liveness.pop(key, None)

    def clone(self) -> "TCPStore":
        """A new connection to the same store (for use from another
        thread). Targets the CURRENT leader; if it just died, tries the
        known replica set before giving up."""
        # Lock-free candidate snapshot: clone() must work while another
        # thread of THIS client is blocked in a long collective (which
        # holds the client lock) — the async-commit bootstrap pattern.
        last_err: Optional[BaseException] = None
        candidates = list(
            dict.fromkeys(
                [self.addr, *list(self._replica_addrs), self.bootstrap_addr]
            )
        )
        many = len(candidates) > 1
        for cand in candidates:
            host, _, port = cand.rpartition(":")
            try:
                return TCPStore(
                    host,
                    int(port),
                    is_server=False,
                    timeout=self.timeout,
                    # With failover candidates available, don't burn the
                    # connect-retry backoff on each dead one.
                    connect_retries=0 if many else None,
                    _replica_addrs=[a for a in candidates if a != cand],
                    _bootstrap_addr=self.bootstrap_addr,
                )
            except OSError as e:
                last_err = e
        # The server is already gone (refused / connect deadline):
        # name the store host instead of a bare socket error.
        raise StoreConnectionLostError(
            self.addr, "clone", last_err or ConnectionError("unreachable")
        ) from last_err

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass
        if self._standby is not None:
            self._standby.close()
        if self._server is not None:
            self._server.close()


def create_store(
    rank: int,
    addr: Optional[str] = None,
    timeout: float = DEFAULT_BARRIER_TIMEOUT_S,
    replicas: Optional[int] = None,
    host_server: Optional[bool] = None,
    lease_s: Optional[float] = None,
) -> TCPStore:
    """Bootstrap a store: rank 0 hosts (unless ``host_server=False`` —
    the dedicated-store-host deployment), everyone connects to ``addr``.

    ``addr`` ("host:port") must be agreed out of band — from the
    TORCHSNAPSHOT_TPU_STORE_ADDR env var, the jax.distributed coordinator, or
    the test launcher (reference analogue: dist_store.py:53-88, where rank 0
    binds a free port and broadcasts it over the default store).

    ``replicas`` (default: the ``TORCHSNAPSHOT_TPU_STORE_REPLICAS`` env
    var) arms the replication tier: ranks ``1..replicas`` each host a
    standby replica of the store in-process, and EVERY rank then blocks
    until the full replica set has joined (so no coordination op can
    race the bootstrap and silently lose its failover window). The
    bootstrap therefore carries the replica set to every client — the
    leader streams the standby addresses, and clients cache them for
    transparent failover.
    """
    if replicas is None:
        replicas = DEFAULT_STORE_REPLICAS
    auto_host = host_server is None
    if host_server is None:
        host_server = rank == 0
    if host_server and auto_host and addr is not None and ":" in addr:
        # Defaulted hosting duty only: a store already serving at the
        # agreed address (a dedicated store-host deployment, or a
        # restarted rank 0 rejoining a world whose store survived) means
        # rank 0 must join as a CLIENT, not fight for the bind.
        if _try_whois(addr, timeout=2.0) is not None:
            logger.info(
                "a coordination store already serves at %s; rank %d "
                "joins as a client instead of hosting",
                addr,
                rank,
            )
            host_server = False
    if host_server:
        if addr is not None and ":" in addr:
            host, _, port = addr.rpartition(":")
            store = TCPStore(
                host or "127.0.0.1",
                int(port),
                is_server=True,
                timeout=timeout,
                lease_s=lease_s,
                expected_replicas=replicas,
            )
        else:
            store = TCPStore(
                "127.0.0.1",
                None,
                is_server=True,
                timeout=timeout,
                lease_s=lease_s,
                expected_replicas=replicas,
            )
    else:
        assert addr is not None, "Non-hosting ranks must be given the store address."
        host, _, port = addr.rpartition(":")
        deadline = time.monotonic() + timeout
        while True:
            try:
                store = TCPStore(host, int(port), timeout=timeout)
                break
            except (ConnectionRefusedError, OSError):
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.1)
    if replicas > 0:
        if not host_server and 1 <= rank <= replicas:
            store._standby = host_standby(
                f"{store.host}:{store.port}", lease_s=lease_s
            )
        try:
            store.get(REPLICAS_READY_KEY, timeout=min(timeout, 120.0))
        except Exception as e:  # noqa: BLE001 - degraded, never fatal
            logger.warning(
                "store replica set incomplete after bootstrap wait "
                "(continuing WITHOUT full failover coverage): %s",
                e,
            )
    return store


# --------------------------------------------------------- peer transport
#
# Length-prefixed byte channel between RANKS — the data-plane sidecar to
# the KV store above. The store moves metadata through rank 0; the peer
# channel moves restore payload sub-chunks directly between the ranks
# that have them and the ranks that need them (fanout.py), so cooperative
# restores never funnel payload bytes through the coordination server.
# Strictly host-network + threads: safe from background threads and never
# touching device collectives, the same invariant the store itself keeps.
#
# Frame format (one frame = one protocol message):
#
#     u64 header_len | header (pickled dict) | u64 payload_len | payload
#
# The header is a tiny routing dict (op/key/gen/seq); the payload rides
# raw — payload bytes are never pickled, so multi-MB sub-chunks move with
# one copy into the receive buffer.

PEER_CONNECT_TIMEOUT_S = 30.0


def send_peer_frame(sock: socket.socket, header: Dict[str, Any], payload=None) -> None:
    """Send one frame. ``payload`` is any buffer-protocol object (or
    None). Callers serialize concurrent senders on one socket themselves
    (a lock per connection) — interleaved sendalls would corrupt the
    framing."""
    h = pickle.dumps(header)
    payload = faultinject.mutate("peer.send_frame", payload)
    mv = memoryview(payload).cast("B") if payload is not None else None
    sock.sendall(_LEN.pack(len(h)) + h + _LEN.pack(mv.nbytes if mv is not None else 0))
    if mv is not None and mv.nbytes:
        sock.sendall(mv)


def _recv_exact_into(sock: socket.socket, view: memoryview) -> None:
    got = 0
    while got < view.nbytes:
        n = sock.recv_into(view[got:])
        if not n:
            raise ConnectionError("Peer connection closed mid-frame.")
        got += n


def recv_peer_frame(
    sock: socket.socket, alloc: Optional[Any] = None
) -> Tuple[Dict[str, Any], Optional[memoryview]]:
    """Receive one frame: ``(header, payload_view_or_None)``.

    ``alloc(nbytes)`` supplies the payload buffer (e.g. a pooled staging
    slab, so repeated sub-chunk receives don't pay first-touch page
    faults on every frame); default allocates a fresh bytearray. The
    returned view stays valid for as long as the caller holds it."""
    faultinject.site("peer.recv_frame")
    (hlen,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    header = pickle.loads(_recv_exact(sock, hlen))
    (plen,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    if plen == 0:
        return header, None
    buf = alloc(plen) if alloc is not None else bytearray(plen)
    view = memoryview(buf).cast("B")
    _recv_exact_into(sock, view)
    return header, view


def peer_connect(addr: str, timeout: float = PEER_CONNECT_TIMEOUT_S) -> socket.socket:
    """Connect to a peer listener at ``"host:port"``. TCP_NODELAY so the
    small end/abort control frames aren't Nagle-delayed behind payload."""
    host, _, port = addr.rpartition(":")
    sock = socket.create_connection((host, int(port)), timeout=timeout)
    try:
        sock.settimeout(None)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    except BaseException:
        try:
            sock.close()
        except OSError:
            pass
        raise
    return sock


class PeerListener:
    """Accepts inbound peer-channel connections, one handler thread per
    connection (checkpoint-scale: world-1 inbound connections, payload
    frames — the same threading shape as the store server). ``handler``
    receives the raw connected socket and owns its lifecycle."""

    def __init__(self, host: str = "0.0.0.0", port: int = 0) -> None:
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self.port = self._sock.getsockname()[1]
        self._handler: Optional[Any] = None
        self._shutdown = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self, handler) -> None:
        self._handler = handler
        self._thread = threading.Thread(
            target=self._serve, name="tpusnapshot-peer-listener", daemon=True
        )
        self._thread.start()

    def _serve(self) -> None:
        while not self._shutdown.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            threading.Thread(
                target=self._handler,
                args=(conn,),
                name="tpusnapshot-peer-conn",
                daemon=True,
            ).start()

    def close(self) -> None:
        self._shutdown.set()
        try:
            self._sock.close()
        except OSError:
            pass


# ------------------------------------------------------- seed registry ops
#
# The fleet-distribution tier's (distrib.py) availability metadata, kept
# under the replicated store so it rides leader failover with the rest
# of the keyspace. Namespace: ``tsnap/seed/u/<unit_id>`` catalogs a
# shareable read unit's content digest + size; ``tsnap/seed/h/<digest>/
# <holder_id>`` is one live holder's row (peer address, tree depth,
# registration seq, measured serve rate); ``tsnap/seed/dead/<holder_id>``
# is the PR 7 liveness death notice (published by the store when the
# holder's connection drops without a deregister — the ghost-key rule);
# ``tsnap/seed/upd/<base_step>/<id>`` registers a rolling-update
# receiver. These helpers are plain key codecs over the generic client
# verbs so every writer/reader agrees on one schema.

SEED_PREFIX = "tsnap/seed/"
SEED_CATALOG_PREFIX = SEED_PREFIX + "u/"
SEED_HOLDER_PREFIX = SEED_PREFIX + "h/"
SEED_DEAD_PREFIX = SEED_PREFIX + "dead/"
SEED_UPDATE_PREFIX = SEED_PREFIX + "upd/"
SEED_SEQ_KEY = SEED_PREFIX + "seq"


def seed_holder_key(digest: str, holder_id: str) -> str:
    return f"{SEED_HOLDER_PREFIX}{digest}/{holder_id}"


def seed_catalog_put(
    store: Any, unit_id: str, digest: str, nbytes: int
) -> None:
    """Publish (idempotently — content addressing makes every writer
    agree on the value) a unit's digest + size in the seed catalog."""
    row = json.dumps({"d": digest, "n": int(nbytes)})
    store.set(SEED_CATALOG_PREFIX + unit_id, row.encode("utf-8"))


def seed_catalog_get(store: Any, unit_id: str) -> Optional[Tuple[str, int]]:
    """``(digest, nbytes)`` for a cataloged unit, else None."""
    key = SEED_CATALOG_PREFIX + unit_id
    try:
        if not store.check(key):
            return None
        row = json.loads(bytes(store.get(key)).decode("utf-8"))
        return str(row["d"]), int(row["n"])
    except (ConnectionError, OSError, ValueError, KeyError, TypeError):
        return None


def seed_holder_rows(store: Any, digest: str) -> Dict[str, Dict[str, Any]]:
    """All holder rows for a digest (holder id -> parsed row). Liveness
    filtering is the CALLER's job (collect the dead prefix once per
    fetch, not once per row)."""
    prefix = f"{SEED_HOLDER_PREFIX}{digest}/"
    try:
        _, items = store.collect(prefix, 0, timeout=5.0)
    except (ConnectionError, OSError):
        return {}
    rows: Dict[str, Dict[str, Any]] = {}
    for key, raw in items.items():
        try:
            row = json.loads(bytes(raw).decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            continue
        if isinstance(row, dict):
            rows[key[len(prefix):]] = row
    return rows


class LinearBarrier:
    """Two-phase (arrive/depart) store barrier with leader action in between
    and cross-rank error propagation (reference: dist_store.py:91-196).

    Usable from any thread — it only talks to the store. The async-commit
    protocol relies on this: every rank arrives after its storage I/O
    completes; the leader (rank 0) writes the snapshot metadata between the
    phases; depart releases everyone. If any rank reports an error, all other
    ranks raise instead of committing.
    """

    def __init__(
        self,
        prefix: str,
        store: TCPStore,
        rank: int,
        world_size: int,
        leader_rank: int = 0,
    ) -> None:
        self.prefix = prefix
        self.store = store
        self.rank = rank
        self.world_size = world_size
        self.leader_rank = leader_rank

    def _key(self, *parts: str) -> str:
        return "/".join((self.prefix,) + parts)

    def _err_key(self) -> str:
        return self._key("error")

    def report_error(self, err: BaseException) -> None:
        try:
            payload = pickle.dumps(err)
        except Exception:
            payload = pickle.dumps(RuntimeError(repr(err)))
        self.store.set(self._err_key(), payload)

    def _raise_if_error(self, key: str, value: bytes) -> None:
        if key == DEATH_KEY:
            raise RuntimeError(
                f"A peer rank died at barrier {self.prefix!r}."
            ) from pickle.loads(value)
        if key == self._err_key():
            err = pickle.loads(value)
            raise RuntimeError(
                f"A peer rank reported an error at barrier {self.prefix!r}."
            ) from err

    def arrive(self, timeout: Optional[float] = None) -> None:
        self.store.set(self._key("arrive", str(self.rank)), b"1")
        if self.rank == self.leader_rank:
            # One server-side collect instead of world sequential waits:
            # the leader's arrival phase is on the commit critical path.
            stopped, items = self.store.collect(
                self._key("arrive") + "/",
                self.world_size,
                stop_keys=[self._err_key(), DEATH_KEY],
                timeout=timeout,
            )
            if stopped is not None:
                self._raise_if_error(stopped, items[stopped])

    def depart(self, timeout: Optional[float] = None) -> None:
        if self.rank == self.leader_rank:
            self.store.set(self._key("depart"), b"1")
            # Reclaiming barrier keys here would race stragglers still
            # waiting on depart; when the prefix is nested under a PGWrapper
            # namespace, the retire/GC protocol reclaims them once every
            # rank has acked (pg_wrapper.PGWrapper.retire).
        else:
            key, value = self.store.wait_any(
                [self._key("depart"), self._err_key(), DEATH_KEY], timeout
            )
            self._raise_if_error(key, value)
