"""GPipe-style SPMD pipeline parallelism over a mesh axis.

Layer-stacked parameters (leading dim = layers) shard over the ``pipe``
mesh axis so each device owns a contiguous stage of ``L/P`` layers. The
input batch is split into ``n_micro`` microbatches that flow through the
stages: at tick ``t`` stage ``s`` processes microbatch ``t - s``, then
hands its activation to stage ``s+1`` via a neighbor ``ppermute`` — the
cheapest collective on a TPU torus, and the schedule is a ``lax.scan``
(static length ``n_micro + P - 1``), so XLA sees one compiled tick body.

Bubble ticks (the pipeline fill/drain) run the same computation with a
validity mask instead of data-dependent control flow — standard SPMD
pipelining: every device executes the identical program every tick, which
is what keeps it one XLA computation with static shapes.

Backward is just ``jax.grad`` through the scan: autodiff reverses the
``ppermute`` s (activations forward, gradients backward) and produces the
standard 1F1B-free GPipe backward schedule automatically.

The reference framework has no pipeline parallelism (SURVEY.md §2's
parallelism table records the absence); at the state-dict level a
pipelined model's parameters are just layer-stacked arrays sharded over
``pipe`` — another sharded entry for the snapshot layer, restorable onto
any other stage count via overlap resharding.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

LayerFn = Callable[[Any, jax.Array], jax.Array]


def _varying_over(axes):
    """Cast-to-device-varying over any of ``axes`` a value isn't already
    varying on — scan carry initializers must declare the vma their
    outputs will have (ppermute/axis_index make carries varying)."""

    def cast(v):
        vma = getattr(jax.typeof(v), "vma", frozenset())
        missing = tuple(a for a in axes if a not in vma)
        if missing:
            return jax.lax.pcast(v, missing, to="varying")
        return v

    return cast


def _stage_apply(stage_params: Any, x: jax.Array, layer_fn: LayerFn) -> jax.Array:
    """Apply this stage's layers (leading dim = local layers) in order."""

    def body(h, layer_params):
        return layer_fn(layer_params, h), None

    out, _ = jax.lax.scan(body, x, stage_params)
    return out


def pipeline_spmd(
    stage_params: Any,
    x_micro: jax.Array,
    *,
    axis_name: str,
    layer_fn: LayerFn,
) -> jax.Array:
    """Pipeline body. Must run inside ``shard_map``.

    ``stage_params``: pytree whose leaves have leading dim ``L_local``
    (this stage's layers). ``x_micro: (M, Bm, ...)`` microbatched input
    (every stage receives it; only stage 0 reads it). Returns the
    pipelined output ``(M, Bm, ...)``, identical on every stage.
    """
    n_stages = jax.lax.axis_size(axis_name)
    stage = jax.lax.axis_index(axis_name)
    n_micro = x_micro.shape[0]
    n_ticks = n_micro + n_stages - 1
    # Activations move stage s -> s+1; no wraparound edge — stage 0 feeds
    # from x_micro, so the last stage's activation is simply not sent
    # (ppermute zero-fills receivers with no incoming edge).
    fwd_perm = [(i, i + 1) for i in range(n_stages - 1)]

    def tick(carry, t):
        act, outs = carry
        mb = t - stage  # microbatch index this stage handles at tick t
        valid = (mb >= 0) & (mb < n_micro)
        y = _stage_apply(stage_params, act, layer_fn)
        # Last stage banks its result at microbatch slot mb. The masked
        # dynamic_update_slice keeps every stage's program identical.
        write = valid & (stage == n_stages - 1)
        slot = jnp.clip(mb, 0, n_micro - 1)
        upd = jnp.where(write, y, jax.lax.dynamic_index_in_dim(outs, slot, 0, False))
        outs = jax.lax.dynamic_update_index_in_dim(outs, upd, slot, 0)
        # Hand activations to the next stage; stage 0 ingests the next
        # microbatch instead of the (meaningless) wraparound receive.
        recv = jax.lax.ppermute(y, axis_name, fwd_perm)
        nxt = jnp.clip(t + 1, 0, n_micro - 1)
        act_next = jnp.where(
            stage == 0, jax.lax.dynamic_index_in_dim(x_micro, nxt, 0, False), recv
        )
        return (act_next, outs), None

    act0 = jnp.where(
        stage == 0, x_micro[0], jnp.zeros_like(x_micro[0])
    )
    outs0 = jnp.zeros_like(x_micro)
    act0, outs0 = map(_varying_over((axis_name,)), (act0, outs0))
    (_, outs), _ = jax.lax.scan(tick, (act0, outs0), jnp.arange(n_ticks))
    # Everyone needs the outputs (e.g. for a replicated loss): zero out all
    # but the last stage's banked copy and sum over the pipe axis.
    outs = jnp.where(stage == n_stages - 1, outs, jnp.zeros_like(outs))
    return jax.lax.psum(outs, axis_name)


def _prep_pipeline(
    params: Any,
    x: jax.Array,
    mesh: Mesh,
    n_micro: int,
    pipe_axis: str,
    batch_axis: Optional[str],
):
    """Shared validation + microbatching for the pipeline entry points.

    Returns ``(batch_axis_or_None, x_micro, param_specs)``."""
    axes = set(mesh.axis_names)
    if pipe_axis not in axes:
        raise ValueError(f"mesh {mesh.axis_names} lacks pipe axis {pipe_axis!r}")
    n_stages = mesh.shape[pipe_axis]
    L = jax.tree_util.tree_leaves(params)[0].shape[0]
    if L % n_stages:
        raise ValueError(f"{L} layers not divisible by {n_stages} stages")
    B = x.shape[0]
    if B % n_micro:
        raise ValueError(f"batch {B} not divisible by n_micro {n_micro}")
    b = batch_axis if batch_axis in axes else None
    if b is not None and (B // n_micro) % mesh.shape[b]:
        raise ValueError(
            f"per-microbatch size {B // n_micro} not divisible by the "
            f"{b!r} axis size {mesh.shape[b]} (batch {B}, n_micro {n_micro})"
        )
    x_micro = x.reshape(n_micro, B // n_micro, *x.shape[1:])
    param_specs = jax.tree_util.tree_map(
        lambda leaf: P(pipe_axis, *([None] * (leaf.ndim - 1))), params
    )
    return b, x_micro, param_specs


def pipelined_apply(
    params: Any,
    x: jax.Array,
    mesh: Mesh,
    *,
    layer_fn: LayerFn,
    n_micro: int,
    pipe_axis: str = "pipe",
    batch_axis: Optional[str] = "data",
) -> jax.Array:
    """Apply layer-stacked ``params`` to ``x: (B, ...)`` through a pipeline.

    ``params`` leaves have leading dim L (total layers), sharded over
    ``pipe_axis`` (L divisible by the axis size); the batch splits into
    ``n_micro`` microbatches (B divisible by ``n_micro`` and, when present,
    by the ``batch_axis`` size — dp composes with pp on an orthogonal mesh
    axis). Output matches ``x``'s leading shape.
    """
    b, x_micro, param_specs = _prep_pipeline(
        params, x, mesh, n_micro, pipe_axis, batch_axis
    )
    B = x.shape[0]
    fn = partial(pipeline_spmd, axis_name=pipe_axis, layer_fn=layer_fn)
    out = jax.shard_map(
        fn,
        mesh=mesh,
        in_specs=(param_specs, P(None, b)),
        out_specs=P(None, b),
    )(params, x_micro)
    return out.reshape(B, *x.shape[1:])


def pipeline_1f1b_spmd(
    stage_params: Any,
    x_micro: jax.Array,
    t_micro: jax.Array,
    *,
    axis_name: str,
    layer_fn: LayerFn,
    loss_fn: Callable[[jax.Array, jax.Array], jax.Array],
    varying_axes: Optional[tuple] = None,
):
    """1F1B pipeline train tick body. Must run inside ``shard_map``.

    One-forward-one-backward schedule: every tick, each stage runs one
    microbatch forward AND one microbatch backward (masked during
    fill/drain), so the activation stash is bounded by the pipeline
    DEPTH (2·stages slots here), not by ``n_micro`` — the memory
    property that separates 1F1B from GPipe, where autodiff through the
    forward scan stashes all ``n_micro`` microbatch activations before
    any backward runs.

    Timing (flush/PipeDream-style, non-interleaved): at tick ``t`` stage
    ``s`` forwards microbatch ``t - s`` and backwards microbatch
    ``t - (2·S - 2 - s)``. The last stage's backward for a microbatch
    fires the SAME tick as its forward — the loss gradient seeds it
    directly. Activation gradients ride the reverse ``ppermute`` edge
    (one-tick latency, exactly the schedule's stage offset). Each
    stage's backward re-runs its forward via ``jax.vjp`` on the stashed
    INPUT activation (per-stage rematerialization), so only stage inputs
    are stashed, never internals.

    Returns ``(loss_sum, grads)``: the summed per-microbatch loss
    (identical on every stage) and this stage's parameter gradients
    (leading dim = local layers — exactly the ``pipe``-sharded layout
    the snapshot layer sees).
    """
    n_stages = jax.lax.axis_size(axis_name)
    stage = jax.lax.axis_index(axis_name)
    n_micro = x_micro.shape[0]
    n_ticks = n_micro + 2 * n_stages - 2
    stash_size = 2 * n_stages
    fwd_perm = [(i, i + 1) for i in range(n_stages - 1)]
    bwd_perm = [(i + 1, i) for i in range(n_stages - 1)]
    last = n_stages - 1

    def fwd(act):
        return _stage_apply(stage_params, act, layer_fn)

    def tick(carry, t):
        act_in, g_in_flight, stash, grads, loss_sum = carry
        fm = t - stage                      # fwd microbatch this tick
        bm = t - (2 * n_stages - 2 - stage)  # bwd microbatch this tick
        fwd_valid = (fm >= 0) & (fm < n_micro)
        bwd_valid = (bm >= 0) & (bm < n_micro)

        # ---- forward ------------------------------------------------
        y = fwd(act_in)
        fslot = jnp.clip(fm, 0, n_micro - 1) % stash_size
        stash = jax.lax.dynamic_update_index_in_dim(
            stash,
            jnp.where(fwd_valid, act_in, jax.lax.dynamic_index_in_dim(stash, fslot, 0, False)),
            fslot,
            0,
        )
        # Last stage: per-microbatch loss + seed gradient, this tick.
        tgt = jax.lax.dynamic_index_in_dim(
            t_micro, jnp.clip(fm, 0, n_micro - 1), 0, False
        )
        mb_loss, g_seed = jax.value_and_grad(loss_fn)(y, tgt)
        loss_sum = loss_sum + jnp.where(
            fwd_valid & (stage == last), mb_loss, 0.0
        )

        # ---- backward -----------------------------------------------
        # Gradient w.r.t. this stage's OUTPUT for microbatch bm: the
        # loss seed on the last stage (bm == fm there), else the
        # neighbor's activation gradient from the previous tick.
        g_out = jnp.where(stage == last, g_seed, g_in_flight)
        bslot = jnp.clip(bm, 0, n_micro - 1) % stash_size
        act_for_bwd = jax.lax.dynamic_index_in_dim(stash, bslot, 0, False)
        # One linearization yields both cotangents (per-stage remat).
        _, vjp_fn = jax.vjp(
            lambda p, a: _stage_apply(p, a, layer_fn), stage_params, act_for_bwd
        )
        g_params, g_act = vjp_fn(g_out)
        grads = jax.tree_util.tree_map(
            lambda acc, g: acc + jnp.where(bwd_valid, g, jnp.zeros_like(g)),
            grads,
            g_params,
        )

        # ---- comms --------------------------------------------------
        recv_act = jax.lax.ppermute(y, axis_name, fwd_perm)
        nxt = jnp.clip(t + 1, 0, n_micro - 1)
        act_next = jnp.where(
            stage == 0,
            jax.lax.dynamic_index_in_dim(x_micro, nxt, 0, False),
            recv_act,
        )
        g_next = jax.lax.ppermute(
            jnp.where(bwd_valid, g_act, jnp.zeros_like(g_act)),
            axis_name,
            bwd_perm,
        )
        return (act_next, g_next, stash, grads, loss_sum), None

    act0 = jnp.where(stage == 0, x_micro[0], jnp.zeros_like(x_micro[0]))
    g0 = jnp.zeros_like(x_micro[0])
    stash0 = jnp.zeros((stash_size,) + x_micro.shape[1:], x_micro.dtype)
    grads0 = jax.tree_util.tree_map(jnp.zeros_like, stage_params)
    loss0 = jnp.zeros((), jnp.float32)

    # The scan carry becomes device-varying over every manual mesh axis
    # the data touches (pipe always; the batch axis too under dp x pp —
    # microbatch activations and per-rank losses are data-sharded).
    # Initializers must declare the same. The GRADS accumulator is the
    # exception: the params are data-invariant, so vma-aware autodiff
    # psums their cotangent over the batch axis each tick — grads stay
    # varying over the PIPE axis only.
    want_axes = tuple(varying_axes or (axis_name,))
    carry0 = (
        *jax.tree_util.tree_map(_varying_over(want_axes), (act0, g0, stash0)),
        jax.tree_util.tree_map(_varying_over((axis_name,)), grads0),
        _varying_over(want_axes)(loss0),
    )
    (_, _, _, grads, loss_sum), _ = jax.lax.scan(
        tick, carry0, jnp.arange(n_ticks)
    )
    # Loss lives on the last stage only; share it (grads stay per-stage —
    # that IS the pipe-sharded layout).
    loss_sum = jax.lax.psum(
        jnp.where(stage == last, loss_sum, 0.0), axis_name
    )
    return loss_sum, grads


def pipelined_value_and_grad(
    params: Any,
    x: jax.Array,
    targets: jax.Array,
    mesh: Mesh,
    *,
    layer_fn: LayerFn,
    loss_fn: Callable[[jax.Array, jax.Array], jax.Array],
    n_micro: int,
    pipe_axis: str = "pipe",
    batch_axis: Optional[str] = "data",
):
    """(mean microbatch loss, param grads) via the 1F1B schedule.

    ``loss_fn(y_micro, t_micro) -> scalar`` is the per-microbatch mean
    loss. Grads come back layer-stacked and ``pipe``-sharded (same
    layout as ``pipeline_param_sharding``), averaged over microbatches
    and — when ``batch_axis`` is on the mesh — over data-parallel
    replicas.
    """
    b, x_micro, param_specs = _prep_pipeline(
        params, x, mesh, n_micro, pipe_axis, batch_axis
    )
    B = x.shape[0]
    t_micro = targets.reshape(n_micro, B // n_micro, *targets.shape[1:])

    def spmd(p, xm, tm):
        loss_sum, grads = pipeline_1f1b_spmd(
            p, xm, tm, axis_name=pipe_axis, layer_fn=layer_fn, loss_fn=loss_fn,
            varying_axes=(pipe_axis,) + ((b,) if b is not None else ()),
        )
        loss = loss_sum / n_micro
        if b is not None:
            loss = jax.lax.pmean(loss, b)
            # The params are data-INVARIANT, so the vjp already inserted
            # a psum over the data axis into their cotangent (vma-aware
            # autodiff): grads arrive as the SUM over data ranks. Divide
            # by the axis size for mean-over-the-full-microbatch
            # semantics — a pmean here would double-count.
            grads = jax.tree_util.tree_map(
                lambda g: g / mesh.shape[b], grads
            )
        grads = jax.tree_util.tree_map(lambda g: g / n_micro, grads)
        return loss, grads

    return jax.shard_map(
        spmd,
        mesh=mesh,
        in_specs=(param_specs, P(None, b), P(None, b)),
        out_specs=(P(), param_specs),
    )(params, x_micro, t_micro)


def pipeline_param_sharding(
    params: Any, mesh: Mesh, pipe_axis: str = "pipe"
) -> Any:
    """NamedShardings placing layer-stacked params on their pipeline stages
    (what ``init`` should ``device_put`` with, and exactly what the
    snapshot layer sees as sharded entries)."""
    return jax.tree_util.tree_map(
        lambda leaf: NamedSharding(
            mesh, P(pipe_axis, *([None] * (leaf.ndim - 1)))
        ),
        params,
    )
