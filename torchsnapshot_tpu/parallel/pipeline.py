"""GPipe-style SPMD pipeline parallelism over a mesh axis.

Layer-stacked parameters (leading dim = layers) shard over the ``pipe``
mesh axis so each device owns a contiguous stage of ``L/P`` layers. The
input batch is split into ``n_micro`` microbatches that flow through the
stages: at tick ``t`` stage ``s`` processes microbatch ``t - s``, then
hands its activation to stage ``s+1`` via a neighbor ``ppermute`` — the
cheapest collective on a TPU torus, and the schedule is a ``lax.scan``
(static length ``n_micro + P - 1``), so XLA sees one compiled tick body.

Bubble ticks (the pipeline fill/drain) run the same computation with a
validity mask instead of data-dependent control flow — standard SPMD
pipelining: every device executes the identical program every tick, which
is what keeps it one XLA computation with static shapes.

Backward is just ``jax.grad`` through the scan: autodiff reverses the
``ppermute`` s (activations forward, gradients backward) and produces the
standard 1F1B-free GPipe backward schedule automatically.

The reference framework has no pipeline parallelism (SURVEY.md §2's
parallelism table records the absence); at the state-dict level a
pipelined model's parameters are just layer-stacked arrays sharded over
``pipe`` — another sharded entry for the snapshot layer, restorable onto
any other stage count via overlap resharding.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

LayerFn = Callable[[Any, jax.Array], jax.Array]


def _stage_apply(stage_params: Any, x: jax.Array, layer_fn: LayerFn) -> jax.Array:
    """Apply this stage's layers (leading dim = local layers) in order."""

    def body(h, layer_params):
        return layer_fn(layer_params, h), None

    out, _ = jax.lax.scan(body, x, stage_params)
    return out


def pipeline_spmd(
    stage_params: Any,
    x_micro: jax.Array,
    *,
    axis_name: str,
    layer_fn: LayerFn,
) -> jax.Array:
    """Pipeline body. Must run inside ``shard_map``.

    ``stage_params``: pytree whose leaves have leading dim ``L_local``
    (this stage's layers). ``x_micro: (M, Bm, ...)`` microbatched input
    (every stage receives it; only stage 0 reads it). Returns the
    pipelined output ``(M, Bm, ...)``, identical on every stage.
    """
    n_stages = jax.lax.axis_size(axis_name)
    stage = jax.lax.axis_index(axis_name)
    n_micro = x_micro.shape[0]
    n_ticks = n_micro + n_stages - 1
    # Activations move stage s -> s+1; no wraparound edge — stage 0 feeds
    # from x_micro, so the last stage's activation is simply not sent
    # (ppermute zero-fills receivers with no incoming edge).
    fwd_perm = [(i, i + 1) for i in range(n_stages - 1)]

    def tick(carry, t):
        act, outs = carry
        mb = t - stage  # microbatch index this stage handles at tick t
        valid = (mb >= 0) & (mb < n_micro)
        y = _stage_apply(stage_params, act, layer_fn)
        # Last stage banks its result at microbatch slot mb. The masked
        # dynamic_update_slice keeps every stage's program identical.
        write = valid & (stage == n_stages - 1)
        slot = jnp.clip(mb, 0, n_micro - 1)
        upd = jnp.where(write, y, jax.lax.dynamic_index_in_dim(outs, slot, 0, False))
        outs = jax.lax.dynamic_update_index_in_dim(outs, upd, slot, 0)
        # Hand activations to the next stage; stage 0 ingests the next
        # microbatch instead of the (meaningless) wraparound receive.
        recv = jax.lax.ppermute(y, axis_name, fwd_perm)
        nxt = jnp.clip(t + 1, 0, n_micro - 1)
        act_next = jnp.where(
            stage == 0, jax.lax.dynamic_index_in_dim(x_micro, nxt, 0, False), recv
        )
        return (act_next, outs), None

    act0 = jnp.where(
        stage == 0, x_micro[0], jnp.zeros_like(x_micro[0])
    )
    # The carry becomes device-varying over the pipe axis inside the scan
    # (ppermute + axis_index); the initializers must declare that too.
    outs0 = jnp.zeros_like(x_micro)
    vma = getattr(jax.typeof(outs0), "vma", frozenset())
    if axis_name not in vma:
        outs0 = jax.lax.pcast(outs0, (axis_name,), to="varying")
    vma = getattr(jax.typeof(act0), "vma", frozenset())
    if axis_name not in vma:
        act0 = jax.lax.pcast(act0, (axis_name,), to="varying")
    (_, outs), _ = jax.lax.scan(tick, (act0, outs0), jnp.arange(n_ticks))
    # Everyone needs the outputs (e.g. for a replicated loss): zero out all
    # but the last stage's banked copy and sum over the pipe axis.
    outs = jnp.where(stage == n_stages - 1, outs, jnp.zeros_like(outs))
    return jax.lax.psum(outs, axis_name)


def pipelined_apply(
    params: Any,
    x: jax.Array,
    mesh: Mesh,
    *,
    layer_fn: LayerFn,
    n_micro: int,
    pipe_axis: str = "pipe",
    batch_axis: Optional[str] = "data",
) -> jax.Array:
    """Apply layer-stacked ``params`` to ``x: (B, ...)`` through a pipeline.

    ``params`` leaves have leading dim L (total layers), sharded over
    ``pipe_axis`` (L divisible by the axis size); the batch splits into
    ``n_micro`` microbatches (B divisible by ``n_micro`` and, when present,
    by the ``batch_axis`` size — dp composes with pp on an orthogonal mesh
    axis). Output matches ``x``'s leading shape.
    """
    axes = set(mesh.axis_names)
    if pipe_axis not in axes:
        raise ValueError(f"mesh {mesh.axis_names} lacks pipe axis {pipe_axis!r}")
    n_stages = mesh.shape[pipe_axis]
    L = jax.tree_util.tree_leaves(params)[0].shape[0]
    if L % n_stages:
        raise ValueError(f"{L} layers not divisible by {n_stages} stages")
    B = x.shape[0]
    if B % n_micro:
        raise ValueError(f"batch {B} not divisible by n_micro {n_micro}")

    b = batch_axis if batch_axis in axes else None
    if b is not None and (B // n_micro) % mesh.shape[b]:
        raise ValueError(
            f"per-microbatch size {B // n_micro} not divisible by the "
            f"{b!r} axis size {mesh.shape[b]} (batch {B}, n_micro {n_micro})"
        )
    x_micro = x.reshape(n_micro, B // n_micro, *x.shape[1:])

    param_specs = jax.tree_util.tree_map(
        lambda leaf: P(pipe_axis, *([None] * (leaf.ndim - 1))), params
    )
    fn = partial(pipeline_spmd, axis_name=pipe_axis, layer_fn=layer_fn)
    out = jax.shard_map(
        fn,
        mesh=mesh,
        in_specs=(param_specs, P(None, b)),
        out_specs=P(None, b),
    )(params, x_micro)
    return out.reshape(B, *x.shape[1:])


def pipeline_param_sharding(
    params: Any, mesh: Mesh, pipe_axis: str = "pipe"
) -> Any:
    """NamedShardings placing layer-stacked params on their pipeline stages
    (what ``init`` should ``device_put`` with, and exactly what the
    snapshot layer sees as sharded entries)."""
    return jax.tree_util.tree_map(
        lambda leaf: NamedSharding(
            mesh, P(pipe_axis, *([None] * (leaf.ndim - 1)))
        ),
        params,
    )
