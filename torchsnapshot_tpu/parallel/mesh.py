"""Device-mesh construction and pytree sharding helpers.

The reference framework (torchsnapshot) consumes state from externally
parallelized models (DDP replication, ShardedTensor TP layouts, FSDP —
SURVEY.md §2 "Parallelism / distribution strategies"). On TPU the analogue
is GSPMD: a `jax.sharding.Mesh` plus `NamedSharding` annotations, with XLA
inserting the collectives. This module provides the small amount of shared
machinery the models/benchmarks need to produce such state.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(
    axis_sizes: Optional[Dict[str, int]] = None,
    *,
    devices: Optional[Sequence[jax.Device]] = None,
    axis_names: Tuple[str, ...] = ("data", "model"),
) -> Mesh:
    """Build a Mesh over `devices` (default: all).

    If `axis_sizes` is given it maps axis name -> size (one axis may be -1
    to absorb the remainder) and determines the axis names. Otherwise the
    last of `axis_names` (the tp-like axis) gets the largest power-of-two
    divisor <= sqrt(n), the first absorbs the rest, and middle axes get 1 —
    a sensible dp x tp default on any device count.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if axis_sizes is None:
        model = 1
        while model * 2 <= int(math.isqrt(n)) and n % (model * 2) == 0:
            model *= 2
        axis_sizes = {name: 1 for name in axis_names}
        axis_sizes[axis_names[-1]] = model
        axis_sizes[axis_names[0]] = (n // model) * axis_sizes[axis_names[0]]
    else:
        if axis_names != ("data", "model") and tuple(axis_sizes) != axis_names:
            raise ValueError(
                f"axis_names {axis_names} conflicts with axis_sizes keys "
                f"{tuple(axis_sizes)}; pass one or the other."
            )
        axis_names = tuple(axis_sizes.keys())
        sizes = list(axis_sizes.values())
        if -1 in sizes:
            known = math.prod(s for s in sizes if s != -1)
            sizes[sizes.index(-1)] = n // known
        axis_sizes = dict(zip(axis_names, sizes))
    shape = tuple(axis_sizes[a] for a in axis_names)
    if math.prod(shape) != n:
        raise ValueError(f"mesh shape {axis_sizes} != {n} devices")
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, axis_names)


def shard_pytree(tree: Any, specs: Any, mesh: Mesh) -> Any:
    """device_put every leaf of `tree` with the matching PartitionSpec leaf.

    `specs` is a pytree with the same treedef whose leaves are
    PartitionSpec (or None for fully replicated).
    """

    def _put(x, spec):
        spec = spec if spec is not None else P()
        return jax.device_put(x, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map(
        _put, tree, specs, is_leaf=lambda x: x is None
    )


def optax_state_specs(p_specs: Any, opt_state: Any) -> Tuple[Any, ...]:
    """PartitionSpecs for an optax optimizer state given the param specs.

    Adam-family moments (mu/nu) inherit their parameter's spec; everything
    else (counts, empty states, schedule scalars) is replicated. Scalars
    must be placed ON the mesh, not left uncommitted: a restored scalar
    comes back committed, and a single-device scalar next to
    mesh-committed params is an invalid jit input mix.
    """
    import optax

    def map_entry(entry):
        if isinstance(entry, optax.ScaleByAdamState):
            return optax.ScaleByAdamState(count=P(), mu=p_specs, nu=p_specs)
        return jax.tree_util.tree_map(lambda _: P(), entry)

    return tuple(map_entry(e) for e in opt_state)
