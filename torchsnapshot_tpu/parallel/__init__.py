from .mesh import make_mesh, shard_pytree  # noqa: F401
