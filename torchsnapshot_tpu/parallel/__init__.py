from .mesh import make_mesh, shard_pytree  # noqa: F401
from .pipeline import (  # noqa: F401
    pipeline_1f1b_spmd,
    pipeline_param_sharding,
    pipeline_spmd,
    pipelined_apply,
    pipelined_value_and_grad,
)
