"""Plain-array write/read planning (reference: io_preparer.py:498-726).

The stager performs the TPU->host boundary crossing: for a jax.Array it
issues ``copy_to_host_async`` (true async DMA — no GIL workaround needed,
unlike the reference's CUDA thread-pool dance, io_preparer.py:513-523) and
materializes a zero-copy numpy view in an executor thread. numpy inputs are
viewed without copying at all.

The consumer fills a destination numpy view in-place (memory-efficient
restore, reference rationale: snapshot.py:693-700) and/or reports the value
through a callback; for jax.Array destinations the callback re-materializes
the array on device with its original sharding.
"""

from __future__ import annotations

import asyncio
import contextlib
import contextvars
import ctypes
import logging
import os
import sys
import threading
import weakref
from collections import deque
from dataclasses import dataclass
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from .. import telemetry
from ..compression import MIN_COMPRESS_BYTES
from ..io_types import BufferConsumer, BufferStager, BufferType, ReadReq, WriteReq
from ..manifest import ArrayEntry
from ..serialization import (
    Serializer,
    array_as_memoryview,
    array_from_buffer,
    array_size_bytes,
    dtype_to_string,
    string_to_dtype,
)


# When True (the default), stagers copy host-resident buffers so the staged
# bytes cannot alias caller memory — required by async_take's guarantee that
# mutations after it returns don't affect the snapshot (reference:
# snapshot.py:257-262). Snapshot.take blocks the caller until all I/O is
# drained, so it opts out: zero-copy staging halves host memory traffic.
# The flag is captured at stager construction (prepare time), so it is
# unaffected by which thread later runs the staging.
_copy_for_consistency: contextvars.ContextVar[bool] = contextvars.ContextVar(
    "tsnap_copy_for_consistency", default=True
)

logger = logging.getLogger(__name__)

# One warning per process when a device-digest dedup match inherits a
# missing checksum from a base saved with checksums disabled: unlike the
# host dedup path there are no staged bytes to recompute one from, so
# restore-time verification coverage narrows for those entries.
_warned_none_checksum = False


@contextlib.contextmanager
def zero_copy_staging():
    """Within this context, prepared stagers may alias caller memory.

    Only safe when the caller blocks until storage I/O completes
    (synchronous ``Snapshot.take``)."""
    token = _copy_for_consistency.set(False)
    try:
        yield
    finally:
        _copy_for_consistency.reset(token)


STAGING_POOL_ENV_VAR = "TORCHSNAPSHOT_TPU_STAGING_POOL_BYTES"
_DEFAULT_STAGING_POOL_BYTES = 4 << 30

STREAM_WRITES_ENV_VAR = "TORCHSNAPSHOT_TPU_STREAM_WRITES"


def streaming_enabled() -> bool:
    """Kill switch for the sub-chunk streaming write path (default on).
    The scheduler still gates streaming on the storage plugin's own
    opt-in and on the caller blocking until I/O drains (sync take)."""
    return os.environ.get(STREAM_WRITES_ENV_VAR, "1") not in ("0", "false", "")


# Pure-Python buffer exporters (__buffer__) are honored from CPython 3.12
# (PEP 688); earlier interpreters cannot express the holder pattern below,
# so they skip pooling entirely — correctness over recycling.
_BUFFER_PROTOCOL_OK = sys.version_info >= (3, 12)


class _SlabHolder:
    """Weakref-able buffer exporter that owns a pooled slab (PEP 688).

    Arrays built over this holder (``np.frombuffer(holder)``) record it —
    not the slab — as their base, and numpy's base-chain collapsing stops
    at the first non-ndarray base: every numpy view derived from the
    staged buffer therefore keeps the holder (and through it the slab)
    alive. Attaching the recycle finalizer to a plain ndarray view would
    not have this property — numpy collapses ndarray base chains, so a
    derived slice would reference the slab directly and the intermediate
    view could die (recycling the slab) while the slice still aliases it.
    """

    __slots__ = ("__weakref__", "_slab")

    def __init__(self, slab: np.ndarray) -> None:
        self._slab = slab

    def __buffer__(self, flags: int) -> memoryview:
        return memoryview(self._slab)


# Below this size a fresh np.empty beats an mmap-backed native slab
# (two syscalls + page bookkeeping for memory that fits in one page's
# worth of faults anyway) — tiny buffers skip the native pool path.
_NATIVE_SLAB_MIN_BYTES = 4096


class _StagingPool:
    """Bounded free-list of staging buffers, recycled by the GC.

    A training loop calls async_take every N minutes; without a pool each
    call allocates the full state size in fresh buffers, and on
    lazily-backed VMs first-touch page faults cost several x the copy
    itself. ``get`` returns an array over a pooled slab whose base
    carries a finalizer: when every reference dies (scheduler, storage
    plugin, a mirror's background replica, any numpy view a consumer
    derived — whoever holds it longest), the slab returns to the free
    list. GC-driven recycling means no component needs an explicit
    release call, and a buffer still referenced anywhere can never be
    handed out again.

    Slabs are NATIVE when the extension is present (``_native``'s
    pinned allocator: page-aligned for O_DIRECT/io_uring, pre-faulted
    deterministically at allocation — never lazily inside a timed
    staging copy — THP-hinted, mlock'd best-effort), recycled through a
    ``from_address`` ctypes holder, which works on every supported
    interpreter. The PEP 688 ``_SlabHolder`` path remains for
    native-absent 3.12+ hosts; pre-3.12 without the extension degrades
    to unpooled ``np.empty``. Pool traffic is published to the
    telemetry bus (``staging_pool_hits``/``_misses`` counters,
    ``staging_pool_free_bytes``/``_outstanding_bytes`` gauges) for
    ``stats`` and the ``/metrics`` exporter."""

    def __init__(self, limit_bytes: int) -> None:
        self._limit = limit_bytes
        self._lock = threading.Lock()
        self._free: dict = {}
        self._free_bytes = 0
        self._outstanding = 0
        # Slabs whose GC finalizer fired while the lock was unavailable.
        # A finalizer can run at ANY allocation point — including inside
        # this pool's own critical sections — so it must never block on
        # the lock (self-deadlock) nor mutate the counters reentrantly
        # (a += interrupted mid-op would lose one side's update).
        # Deferred returns park here (deque append is GIL-atomic, the
        # flightrec precedent) and are integrated by the next get/prewarm.
        self._deferred_native: "deque" = deque()
        self._deferred_py: "deque" = deque()
        # None = unprobed; False = unavailable (or an alloc failed —
        # never retried); True = native slabs back the pool.
        self._native: Optional[bool] = None

    def _native_ok(self) -> bool:
        if self._native is None:
            try:
                from .._native import slab_allocator_available

                self._native = bool(slab_allocator_available())
            except Exception:  # noqa: BLE001 - probe must never raise
                self._native = False
        return self._native

    def get(self, nbytes: int) -> np.ndarray:
        self._integrate_deferred()
        if nbytes < _NATIVE_SLAB_MIN_BYTES or not self._native_ok():
            return self._get_py(nbytes)
        out = self._get_native(nbytes)
        if out is None:  # allocation failure: degrade for good
            self._native = False
            # Mid-run degradation is a fleet-visible state change, not
            # debug noise: record it so blackbox shows the pool fell
            # back to Python slabs partway through an operation.
            telemetry.flightrec.record(
                "native.degrade", site="staging_pool",
                cause="native slab allocation failed", fallback="python",
            )
            self._drain_native_free()
            return self._get_py(nbytes)
        return out

    def _integrate_deferred(self) -> None:
        """Fold in returns whose finalizer could not take the lock."""
        while True:
            try:
                view = self._deferred_native.popleft()
            except IndexError:
                break
            with self._lock:
                self._outstanding -= view.nbytes
            self._store_native(view)
        while True:
            try:
                base = self._deferred_py.popleft()
            except IndexError:
                break
            with self._lock:
                self._outstanding -= base.nbytes
                if self._free_bytes + base.nbytes <= self._limit:
                    self._free.setdefault(base.nbytes, []).append(base)
                    self._free_bytes += base.nbytes

    # ------------------------------------------------ native slab path

    def _get_native(self, nbytes: int) -> Optional[np.ndarray]:
        with self._lock:
            slabs = self._free.get(nbytes)
            view = slabs.pop() if slabs else None
            if view is not None:
                self._free_bytes -= nbytes
        hit = view is not None
        if view is None:
            from .. import _native

            view = _native.slab_view(nbytes)
            if view is None:
                return None
        with self._lock:
            self._outstanding += nbytes
        # The holder aliases the slab without owning it; numpy's base-
        # chain collapsing stops at the first non-ndarray base, so every
        # derived view keeps the holder (and through its finalizer the
        # slab's pool entry) alive — same property _SlabHolder documents.
        holder = (ctypes.c_ubyte * nbytes).from_address(view.ctypes.data)
        weakref.finalize(holder, self._put_native, view)
        self._publish(hit)
        return np.frombuffer(holder, np.uint8)

    def _put_native(self, view: np.ndarray) -> None:
        # Finalizer context: may fire at any allocation point, possibly
        # while THIS thread already holds the pool lock (GC inside a
        # critical section). Never block — integrate now if the lock is
        # free, else defer to the next get/prewarm.
        if not self._lock.acquire(blocking=False):
            self._deferred_native.append(view)
            return
        try:
            self._outstanding -= view.nbytes
        finally:
            self._lock.release()
        self._store_native(view)

    def _store_native(self, view: np.ndarray) -> None:
        evict = False
        # tsalint: allow[restricted-context] safe from the _put_native finalizer: its acquire(blocking=False) gate proved this thread does NOT hold the pool lock (a holder defers instead), and no pool path blocks while holding it (lock-blocking enforces that), so this acquire cannot self-deadlock
        with self._lock:
            # After a mid-run degrade the free lists feed _get_py, which
            # must never pop an unowned native view (its eviction path
            # would drop the mmap with no munmap): free late returners.
            if self._native is False or (
                self._free_bytes + view.nbytes > self._limit
            ):
                evict = True
            else:
                self._free.setdefault(view.nbytes, []).append(view)
                self._free_bytes += view.nbytes
        if evict:
            from .. import _native

            _native.slab_free(view.ctypes.data, view.nbytes)

    def _drain_native_free(self) -> None:
        """Free every pooled native slab (the True→False degrade
        transition): sizes >= the native floor were allocated natively
        while the pool ran native, and _get_py must never inherit them.
        Sub-floor sizes (PEP 688 slabs) stay pooled."""
        from .. import _native

        drained: List[np.ndarray] = []
        with self._lock:
            for nbytes in [
                n for n in self._free if n >= _NATIVE_SLAB_MIN_BYTES
            ]:
                views = self._free.pop(nbytes)
                drained.extend(views)
                self._free_bytes -= nbytes * len(views)
        for view in drained:
            _native.slab_free(view.ctypes.data, view.nbytes)

    # --------------------------------------------------- PEP 688 path

    def _get_py(self, nbytes: int) -> np.ndarray:
        if not _BUFFER_PROTOCOL_OK:
            return np.empty(nbytes, np.uint8)
        with self._lock:
            slabs = self._free.get(nbytes)
            base = slabs.pop() if slabs else None
            if base is not None:
                self._free_bytes -= nbytes
            self._outstanding += nbytes
        hit = base is not None
        if base is None:
            base = np.empty(nbytes, np.uint8)
        holder = _SlabHolder(base)
        weakref.finalize(holder, self._put, base)
        self._publish(hit)
        return np.frombuffer(holder, np.uint8)

    def _put(self, base: np.ndarray) -> None:
        # Finalizer context — same never-block rule as _put_native.
        if not self._lock.acquire(blocking=False):
            self._deferred_py.append(base)
            return
        try:
            self._outstanding -= base.nbytes
            if self._free_bytes + base.nbytes <= self._limit:
                self._free.setdefault(base.nbytes, []).append(base)
                self._free_bytes += base.nbytes
        finally:
            self._lock.release()

    # ------------------------------------------------------- telemetry

    def _publish(self, hit: bool) -> None:
        if not telemetry.enabled():
            return
        telemetry.counter_add(
            "staging_pool_hits" if hit else "staging_pool_misses", 1
        )
        with self._lock:
            free_b, out_b = self._free_bytes, self._outstanding
        telemetry.gauge_set("staging_pool_free_bytes", free_b)
        telemetry.gauge_set("staging_pool_outstanding_bytes", out_b)

    # ---------------------------------------------------------- warmup

    def can_recycle(self) -> bool:
        """True when ``get`` actually draws from (and returns to) the
        free lists — native slabs anywhere, PEP 688 holders on 3.12+."""
        return self._native_ok() or _BUFFER_PROTOCOL_OK

    def prewarm(self, sizes: Sequence[int]) -> int:
        """Pre-fault slabs so the FIRST staging pass doesn't pay them.

        On lazily-backed VMs, first-touch page faults during the staging
        memcpy cost several times the copy itself — the reason a cold
        async_take blocks far longer than a warm one. ``sizes`` is a
        multiset of exact staged-buffer sizes (the pool's free lists are
        exact-size); slabs already pooled count toward it. Returns the
        bytes newly faulted. Bounded by the pool limit. Native slabs are
        pre-faulted by the allocator itself (deterministically, at slab
        construction), so warming them is pure allocation."""
        from collections import Counter

        self._integrate_deferred()
        native = self._native_ok()
        if not native and not _BUFFER_PROTOCOL_OK:
            return 0  # pool is never drawn from: warming would pin waste
        want = Counter(
            int(s)
            for s in sizes
            if s >= (_NATIVE_SLAB_MIN_BYTES if native else 1)
        )
        warmed = 0
        for nbytes, cnt in want.items():
            with self._lock:
                missing = cnt - len(self._free.get(nbytes, []))
                room = (self._limit - self._free_bytes) // nbytes if nbytes else 0
            for _ in range(min(missing, room)):
                if native:
                    from .. import _native

                    view = _native.slab_view(nbytes)
                    if view is None:
                        return warmed
                    self._store_native(view)
                else:
                    slab = np.empty(nbytes, np.uint8)
                    slab.fill(0)  # touch every page
                    with self._lock:
                        if self._free_bytes + nbytes <= self._limit:
                            self._free.setdefault(nbytes, []).append(slab)
                            self._free_bytes += nbytes
                warmed += nbytes
        return warmed


def _pool_limit() -> int:
    raw = os.environ.get(STAGING_POOL_ENV_VAR, "").strip()
    if raw:
        try:
            return int(raw)
        except ValueError:
            pass
    return _DEFAULT_STAGING_POOL_BYTES


_staging_pool = _StagingPool(_pool_limit())


def pooled_buffer(nbytes: int) -> np.ndarray:
    """A writable uint8 buffer drawn from the process staging pool,
    recycled by the GC when every reference dies (see _StagingPool).

    The public face of the pool for the other byte movers on the restore
    hot path — the fs plugin's pread windows (Python and native engine
    alike) and the cooperative-restore peer receiver (fanout.py) — so
    repeated sub-chunk buffers don't pay first-touch page faults on
    every window/frame. With the native extension present, buffers of at
    least ``_NATIVE_SLAB_MIN_BYTES`` are page-aligned pinned slabs —
    valid O_DIRECT/io_uring targets — and the alignment/lifetime
    contract (aligned reuse, derived views pin the slab, never recycled
    while an SQE holds it) is pinned by tests/test_native_io.py."""
    return _staging_pool.get(nbytes)


def fast_copyto(dst: np.ndarray, src: np.ndarray) -> None:
    """``np.copyto(dst, src, casting="same_kind")``, but through raw bytes
    when the dtypes match exactly and both sides are C-contiguous: numpy's
    generic same-dtype copy loop runs ~3.5x slower than memcpy for custom
    dtypes (ml_dtypes bf16/fp8) and small itemsizes, and restore copies are
    on the critical path."""
    if (
        dst.dtype == src.dtype
        and dst.flags["C_CONTIGUOUS"]
        and src.flags["C_CONTIGUOUS"]
    ):
        np.copyto(dst.reshape(-1).view(np.uint8), src.reshape(-1).view(np.uint8))
    else:
        np.copyto(dst, src, casting="same_kind")


def _is_jax_array(arr) -> bool:
    try:
        import jax

        return isinstance(arr, jax.Array)
    except ImportError:  # pragma: no cover
        return False


def array_nbytes(arr) -> int:
    """Logical byte size of a numpy or jax array."""
    return array_size_bytes(arr.shape, dtype_to_string(arr.dtype))


def to_host(arr) -> np.ndarray:
    """Synchronous DtoH materialization (numpy passthrough)."""
    if _is_jax_array(arr):
        return np.asarray(arr)
    return np.asarray(arr)


def needs_consistency_copy(arr) -> bool:
    """True when staging ``arr`` must copy so the snapshot can't alias
    caller memory: CPU-backend jax arrays materialize as zero-copy views
    of the device buffer, and numpy inputs alias caller memory directly;
    a TPU DtoH transfer already produces host-owned memory. The single
    source of the pool-draw platform rule — shared by the stager
    (ArrayBufferStager) and the warmup size planner."""
    if _is_jax_array(arr):
        return next(iter(arr.sharding.device_set)).platform == "cpu"
    return True


def iter_staged_pieces(app_state, pg=None, replicated=None, save_dtype=None):
    """Yield ``(shape, dtype_str, needs_copy, get_piece)`` for every
    piece THIS process will stage for ``app_state`` — the single source
    of the write-partition geometry, shared by the staging-pool warmup
    (byte sizes, pieces with ``needs_copy`` only) and CheckpointManager's
    fingerprint warmup (real device pieces via ``get_piece``).

    ``save_dtype`` is applied: pieces are reported at the CONVERTED
    dtype, and chunk/subdivision boundaries are recomputed at its
    itemsize, so consumers warm exactly what the real save stages.
    ``get_piece`` is a thunk returning the UNCONVERTED piece (device
    slice for jax leaves, view for numpy) — it materializes placement-
    accurate data only when called, so size-only consumers never touch
    devices; ``None`` when the piece cannot be cheaply materialized.
    Under a multi-rank ``pg``, replicated dense chunks stripe
    ``[rank::world]`` like the write partition; everything else is fully
    local.
    """
    import fnmatch

    from ..flatten import flatten
    from ..snapshot import _is_process_replicated_jax_array
    from . import chunked
    from .prepare import is_sharded_jax_array
    from .sharded import ShardedArrayIOPreparer

    if pg is not None:
        from ..pg_wrapper import PGWrapper

        wrapper = PGWrapper(pg)
        world, rank = wrapper.get_world_size(), wrapper.get_rank()
    else:
        world, rank = 1, 0
    globs = list(replicated or [])

    def _eff_dtype(logical_path: str, leaf) -> str:
        """Dtype the WRITE PLAN will stage: ``save_dtype`` downcasts
        matching leaves before staging. The decision is shared with the
        take-time converter (serialization.effective_save_dtype) so the
        two can never diverge."""
        src = dtype_to_string(leaf.dtype)
        if not save_dtype:
            return src
        from ..serialization import effective_save_dtype

        target = effective_save_dtype(logical_path, leaf.dtype, save_dtype)
        return dtype_to_string(target) if target is not None else src

    for key, stateful in app_state.items():
        state_dict = getattr(stateful, "state_dict", None)
        if state_dict is None:
            continue
        _, flattened = flatten(state_dict(), prefix=key)
        for logical_path, leaf in flattened.items():
            if is_sharded_jax_array(leaf):
                eff = _eff_dtype(logical_path, leaf)
                # Subdivision boundaries depend on itemsize, so piece
                # sizes are computed at the converted dtype.
                itemsize = string_to_dtype(eff).itemsize
                needs = needs_consistency_copy(leaf)
                for p_off, p_sz, get_piece in ShardedArrayIOPreparer._owned_pieces(
                    leaf, itemsize=itemsize
                ):
                    yield tuple(p_sz), eff, needs, get_piece
            elif _is_jax_array(leaf) or isinstance(leaf, np.ndarray):
                needs = needs_consistency_copy(leaf)
                # Only REPLICATED paths stripe across ranks in the write
                # partition; per-rank arrays are fully staged locally.
                is_repl = world > 1 and (
                    any(fnmatch.fnmatch(logical_path, g) for g in globs)
                    or _is_process_replicated_jax_array(leaf)
                )
                eff = _eff_dtype(logical_path, leaf)
                nbytes = array_size_bytes(leaf.shape, eff)
                if nbytes > chunked.DEFAULT_MAX_CHUNK_SIZE_BYTES and leaf.shape:
                    ranges = chunked.ChunkedArrayIOPreparer.chunk_ranges(
                        leaf.shape, eff
                    )
                    if is_repl:
                        ranges = ranges[rank::world]
                    rest = tuple(leaf.shape[1:])
                    for lo, hi in ranges:
                        yield (
                            (hi - lo, *rest),
                            eff,
                            needs,
                            lambda leaf=leaf, lo=lo, hi=hi: leaf[lo:hi],
                        )
                else:
                    yield tuple(leaf.shape), eff, needs, lambda leaf=leaf: leaf


def warmup_staging(app_state, pg=None, replicated=None, save_dtype=None) -> int:
    """Pre-fault the staging pool for ``app_state`` so the FIRST
    ``async_take`` blocks like a warm one.

    The pool recycles slabs between saves, so steady-state staging never
    faults pages — but the first save of a training run allocates every
    slab fresh, and on lazily-backed VMs first-touch faults during the
    staging memcpy dominate the caller-blocked interval (measured 11x the
    warm cost). Call once after building the app state (CheckpointManager
    does it on its ``warmup`` method); cheap to call again after state
    shapes change. Returns bytes newly faulted.

    No-op (returns 0) whenever staging cannot draw from the pool: the
    pool feeds only the fused copy+CRC path (``_stage_fused``), which
    needs the native extension (whose pinned slab allocator also makes
    the pool recycle on every interpreter — the PEP 688 holder covers
    native-absent 3.12+ hosts) and checksums enabled — warming slabs no
    save will ever draw would pin pool-limit bytes for nothing. Dedup
    (incremental) and compression also bypass the pool;
    CheckpointManager.warmup checks those, since they are its
    configuration rather than process state.

    Sizes mirror the write partition: for GSPMD-sharded jax arrays the
    exact owned-piece sizes this process stages; large dense arrays
    at the chunk-preparer's ranges. Under a multi-rank ``pg``, ONLY
    replicated paths stripe across ranks — ``replicated`` takes the same
    globs as ``Snapshot.take`` and process-replicated jax arrays are
    auto-detected, matching ``_calculate_replicated_paths``; everything
    else is fully staged per rank and warms fully (striping is an
    approximation of the deterministic partition; under-warming just
    faults the difference on first use). Device arrays whose staging
    needs no consistency copy (TPU-backed: DtoH already produces
    host-owned memory) are skipped.

    Geometry comes from ``iter_staged_pieces`` — the shared write-
    partition walk — so warmed sizes can never drift from what the real
    save stages."""
    from .._native import native_available
    from ..integrity import checksums_enabled

    if (
        not _staging_pool.can_recycle()
        or not native_available()
        or not checksums_enabled()
    ):
        return 0

    sizes: List[int] = [
        array_size_bytes(shape, dt)
        for shape, dt, needs_copy, _ in iter_staged_pieces(
            app_state, pg=pg, replicated=replicated, save_dtype=save_dtype
        )
        if needs_copy
    ]
    return _staging_pool.prewarm(sizes)


class ArrayBufferStager(BufferStager):
    """Stages one array into a host buffer *owned by the snapshot*.

    Staging is the consistency point of async_take: the staged buffer must
    not alias caller memory, or mutations after async_take returns would leak
    into the snapshot (reference guarantee: snapshot.py:257-262). For TPU
    arrays ``device_get`` inherently copies (DtoH DMA); on the CPU backend
    (and for numpy inputs) an explicit copy is made.
    """

    def __init__(self, arr, entry: Optional[ArrayEntry] = None) -> None:
        self.arr = arr
        # When given, the entry's checksum is recorded at stage time (the
        # manifest is gathered/committed after staging completes, so the
        # mutation is visible in the persisted metadata).
        self.entry = entry
        self.copy_for_consistency = _copy_for_consistency.get()
        from ..compression import active_codec
        from ..dedup import active_dedup_context

        self.dedup = active_dedup_context()
        self.codec = active_codec()
        # Set at stage time when the payload matched the dedup base: the
        # scheduler then releases the buffer without writing it.
        self.io_skipped = False

    def _needs_consistency_copy(self, arr) -> bool:
        """The module-level platform rule (needs_consistency_copy), gated
        by the zero_copy_staging opt-out: under sync ``Snapshot.take``
        views are safe because the caller is blocked until I/O drains."""
        if not self.copy_for_consistency:
            return False
        return needs_consistency_copy(arr)

    def _stage_sync(self, arr) -> np.ndarray:
        host = np.asarray(arr)
        if self._needs_consistency_copy(arr):
            host = np.array(host, copy=True)
        return host

    def _device_dedup_candidate(self, arr) -> bool:
        return (
            self.dedup is not None
            and self.dedup.device_digests
            and self.entry is not None
            and self.entry.byte_range is None
            and _is_jax_array(arr)
        )

    def _record_device_fingerprint(self, arr) -> Optional[str]:
        """Fingerprint ``arr`` on device and record it on the entry so
        the NEXT incremental take can match against this snapshot.
        Returns the fingerprint, or None when the array cannot be
        fingerprinted on device (host SHA-256 path takes over)."""
        from ..device_digest import device_fingerprint

        fp = device_fingerprint(arr)
        if fp is not None:
            self.entry.device_digest = fp
        return fp

    def _try_device_dedup(self, arr) -> bool:
        """Fingerprint ``arr`` on device (device_digest.py) and, when the
        base snapshot recorded the same fingerprint for this location,
        skip staging entirely — the DtoH copy never happens, only the
        16-byte fingerprint crosses to the host.

        On a match the entry's digest/checksum/codec are taken from the
        base's ref — fingerprint equality implies content equality under
        the (opt-in, non-cryptographic) trust model documented in
        device_digest.py. Unlike the host path there is no staged buffer
        here, so a base saved without checksums leaves the entry's
        checksum unset rather than recomputing one — a one-time warning
        flags the narrowed verification coverage when that happens."""
        fp = self._record_device_fingerprint(arr)
        if fp is None:
            return False
        ref = self.dedup.refs.get(self.entry.location)
        if ref is None or ref.device_digest != fp:
            return False
        nbytes = array_nbytes(arr)
        if ref.nbytes is not None and ref.nbytes != nbytes:
            return False  # same fingerprint, different size: never trust
        self.entry.digest = ref.digest
        self.entry.origin = ref.origin
        self.entry.codec = ref.codec
        self.entry.checksum = ref.checksum
        if ref.checksum is None:
            from ..integrity import checksums_enabled

            if checksums_enabled():
                global _warned_none_checksum
                if not _warned_none_checksum:
                    _warned_none_checksum = True
                    logger.warning(
                        "device-digest dedup match for %s inherits no "
                        "checksum (base snapshot was saved with checksums "
                        "disabled); restore-time verification will not "
                        "cover deduplicated entries until a full (non-"
                        "dedup) save records checksums again",
                        self.entry.location,
                    )
        return True

    def _stage_fused(self, arr) -> Optional[BufferType]:
        """Consistency copy + CRC32C fused into ONE pass over the source
        (native ts_copy_crc32c). Staging must both copy (the caller may
        mutate/donate after async_take returns) and checksum (entries are
        gathered right after staging), and the state is GBs — a second
        read pass is real wall time. Returns None when not applicable
        (no consistency copy needed, non-contiguous source, no native)."""
        from .._native import copy_crc32c, native_available

        # Check native BEFORE drawing from the pool: on a host without the
        # extension, a pooled slab grabbed here would go unused yet be
        # retained by the pool — doubling staging memory for nothing.
        if not native_available():
            return None
        if not self._needs_consistency_copy(arr):
            return None
        src = np.asarray(arr)
        if not src.flags["C_CONTIGUOUS"]:
            return None
        src_bytes = array_as_memoryview(src)
        dst = _staging_pool.get(src_bytes.nbytes)
        crc = copy_crc32c(dst, src_bytes)
        if crc is None:
            return None
        self.entry.checksum = f"crc32c:{crc:08x}"
        return memoryview(dst)

    def _active_codec(self) -> Optional[str]:
        """The codec this payload will be stored under, or None.

        Byte-ranged payloads (write-batcher slabs) never compress: slab
        offsets were planned from serialized sizes before staging runs."""
        if self.entry is None or self.codec is None:
            return None
        if self.entry.byte_range is not None:
            return None
        return self.codec

    def _stage_and_sum(self, arr) -> BufferType:
        """Runs in an executor thread: DtoH + serialize + (optional)
        compress + hash — keeping GB-scale byte work off the event-loop
        thread."""
        with telemetry.span(
            "stage_hash", cat="stager", bytes=array_nbytes(arr)
        ):
            return self._stage_and_sum_impl(arr)

    def _stage_and_sum_impl(self, arr) -> BufferType:
        codec = self._active_codec()
        if self.entry is not None and self.dedup is None and codec is None:
            from ..integrity import checksums_enabled

            if checksums_enabled():
                fused = self._stage_fused(arr)
                if fused is not None:
                    return fused
        host = self._stage_sync(arr)
        buf = array_as_memoryview(host)
        if self.entry is not None:
            from ..integrity import checksums_enabled, compute_checksum

            if self.dedup is not None:
                from ..dedup import compute_digest

                # Digest covers the UNCOMPRESSED bytes: incremental
                # chains stay stable across codec/level changes.
                digest = compute_digest(buf)
                self.entry.digest = digest
                # Slab-batched payloads (byte_range) never dedup: the
                # entry's offsets index the SLAB, not the base's file —
                # borrowing a base origin would read the base at slab
                # offsets. (The by-location match could never hit them;
                # the content-address fallback could.)
                ref = (
                    self.dedup.match(self.entry.location, digest, buf.nbytes)
                    if self.entry.byte_range is None
                    else None
                )
                if ref is not None:
                    # Unchanged since the base snapshot: record where the
                    # bytes already live and skip the storage write. The
                    # checksum/codec must describe the BASE's stored
                    # payload — that is what restore will read. A base
                    # saved without checksums: when its payload is raw
                    # its stored bytes equal this staged buffer, so
                    # compute the checksum here rather than losing verify
                    # coverage for the deduplicated entry.
                    self.entry.origin = ref.origin
                    self.entry.codec = ref.codec
                    if ref.location is not None:
                        # Content-address fallback: the base stores these
                        # bytes under its OWN path (e.g. the pool's
                        # ``po/<hex>``) — restore reads origin+location.
                        self.entry.location = ref.location
                    if ref.checksum is None and ref.codec is None:
                        if checksums_enabled():
                            self.entry.checksum = compute_checksum(buf)
                    else:
                        self.entry.checksum = ref.checksum
                    self.io_skipped = True
                    return buf
            if codec is not None and buf.nbytes >= MIN_COMPRESS_BYTES:
                from ..compression import compress

                packed = compress(codec, buf)
                # Never a size regression: incompressible payloads (bf16
                # noise, already-compressed objects) are stored raw.
                if len(packed) < buf.nbytes:
                    self.entry.codec = codec
                    buf = memoryview(packed)
            if checksums_enabled():
                # Checksum covers the STORED bytes — verification reads
                # exactly what storage returns, before decompression.
                self.entry.checksum = compute_checksum(buf)
        return buf

    # ----------------------------------------------------- streaming path

    def can_stream(self, sub_chunk_bytes: int) -> bool:
        """True when this payload can be produced as ordered sub-chunks
        (the scheduler then fuses staging with the storage write).

        Only the PLAIN path streams — the exact cases where the staged
        bytes are a straight serialization of the array: no dedup context
        (digest/skip decisions need the whole payload), no compression
        (slab offsets and codecs are whole-buffer), no batcher byte-range
        (the slab stager owns those), and a C-contiguous source (so
        sub-chunks are contiguous byte ranges of the serialized stream).
        Checksums DO stream: the CRC chains across sub-chunks
        (integrity-identical to the buffered path)."""
        if not streaming_enabled():
            return False
        if self.dedup is not None or self._active_codec() is not None:
            return False
        if self.entry is not None and self.entry.byte_range is not None:
            return False
        arr = self.arr
        shape = getattr(arr, "shape", None)
        if shape is None or 0 in tuple(shape):
            return False
        nbytes = array_nbytes(arr)
        # A stream of one chunk is a buffered write with extra hops.
        if nbytes < 2 * sub_chunk_bytes:
            return False
        if _is_jax_array(arr):
            if not getattr(arr, "is_fully_addressable", True):
                return False
            if next(iter(arr.sharding.device_set)).platform != "cpu":
                # Device-backed: sub-chunks are WHOLE-ROW slices along
                # dim 0. A row wider than the sub-chunk would make each
                # "sub-chunk" row-sized — far over the window the budget
                # charges, with the pipeline degenerating toward serial
                # — so such shapes stay on the buffered path.
                if len(shape) < 1 or shape[0] < 2:
                    return False
                row_bytes = nbytes // shape[0]
                return row_bytes <= sub_chunk_bytes
            host = np.asarray(arr)
            return host.flags["C_CONTIGUOUS"]
        if isinstance(arr, np.ndarray):
            return arr.flags["C_CONTIGUOUS"]
        return False

    def _stream_checksum_update(self, state: Optional[Tuple], chunk) -> Optional[Tuple]:
        """Advance the running checksum with ``chunk``; ``state`` is
        ``(algo, value)`` or None when checksums are off. Algorithm
        choice mirrors integrity.compute_checksum so streamed and
        buffered writes of the same bytes record identical checksums."""
        if state is None:
            return None
        algo, value = state
        if algo == "crc32c":
            from .._native import crc32c

            return (algo, crc32c(chunk, value))
        import zlib

        return (algo, zlib.crc32(memoryview(chunk).cast("B"), value))

    def _stream_checksum_init(self) -> Optional[Tuple]:
        if self.entry is None:
            return None
        from ..integrity import checksums_enabled

        if not checksums_enabled():
            return None
        from .._native import native_available

        return ("crc32c", 0) if native_available() else ("crc32", 0)

    def _stream_checksum_finish(self, state: Optional[Tuple]) -> None:
        if state is not None:
            algo, value = state
            self.entry.checksum = f"{algo}:{value & 0xFFFFFFFF:08x}"

    def _host_sub_chunk(self, mv: memoryview, lo: int, hi: int, state):
        """One host-backed sub-chunk: a zero-copy byte slice when staging
        may alias caller memory (sync take), else a pooled-slab bounce
        copy FUSED with the running CRC (one pass over the source — the
        streaming analogue of _stage_fused). Returns (buffer, state)."""
        with telemetry.span("sub_chunk_stage", cat="stager", bytes=hi - lo):
            chunk = mv[lo:hi]
            if not self.copy_for_consistency:
                return chunk, self._stream_checksum_update(state, chunk)
            dst = _staging_pool.get(hi - lo)
            if state is not None and state[0] == "crc32c":
                from .._native import copy_crc32c

                crc = copy_crc32c(dst, chunk, state[1])
                if crc is not None:
                    return memoryview(dst), ("crc32c", crc)
            np.copyto(dst, np.frombuffer(chunk, np.uint8))
            return memoryview(dst), self._stream_checksum_update(state, chunk)

    async def stage_stream(self, executor, sub_chunk_bytes: int):
        """Ordered sub-chunk buffers; concatenation == the buffered
        payload, and the entry records the identical checksum.

        Staging runs ONE SUB-CHUNK AHEAD of the consumer: chunk N+1's
        staging future is scheduled BEFORE chunk N is yielded (the
        running CRC allows it — N's checksum state exists by then), so
        while the plugin writes chunk N the executor stages N+1. That
        lookahead is the entire overlap: an async generator is otherwise
        strictly sequential with its consumer. Device-backed jax arrays
        additionally kick ``copy_to_host_async`` for slice N+1 before
        materializing slice N, so the DtoH DMA rides under the current
        slice's checksum + write as well. In-flight memory is bounded by
        the chunk being written plus the chunk being staged — the
        _STREAM_DEPTH window the scheduler's budget charges. All byte
        work runs in the executor, never on the event loop."""
        arr = self.arr
        loop = asyncio.get_running_loop()
        state = self._stream_checksum_init()
        device_backed = _is_jax_array(arr) and (
            next(iter(arr.sharding.device_set)).platform != "cpu"
        )
        if not device_backed:
            host = np.asarray(arr)
            mv = array_as_memoryview(host)
            total = mv.nbytes
            bounds = list(range(0, total, sub_chunk_bytes)) + [total]
            spans = list(zip(bounds[:-1], bounds[1:]))
            fut = loop.run_in_executor(
                executor, self._host_sub_chunk, mv, *spans[0], state
            )
            for nxt in spans[1:]:
                chunk, state = await fut
                # Lookahead: N+1 stages while the consumer writes N.
                fut = loop.run_in_executor(
                    executor, self._host_sub_chunk, mv, *nxt, state
                )
                yield chunk
            chunk, state = await fut
            yield chunk
            self._stream_checksum_finish(state)
            return

        row_bytes = max(1, array_nbytes(arr) // arr.shape[0])
        rows_per = max(1, sub_chunk_bytes // row_bytes)
        ranges = [
            (lo, min(lo + rows_per, arr.shape[0]))
            for lo in range(0, arr.shape[0], rows_per)
        ]

        def _kick(lo: int, hi: int):
            piece = arr[lo:hi]
            try:
                piece.copy_to_host_async()
            except Exception:
                pass
            return piece

        def _materialize(piece, st):
            # The DtoH landing + running CRC for one device sub-chunk
            # (the DMA itself was kicked asynchronously by _kick).
            with telemetry.span("sub_chunk_dtoh", cat="stager"):
                host = np.asarray(piece)
                if not host.flags["C_CONTIGUOUS"]:
                    host = np.ascontiguousarray(host)
                buf = array_as_memoryview(host)
                return buf, self._stream_checksum_update(st, buf)

        pieces = [_kick(*ranges[0])]
        if len(ranges) > 1:
            pieces.append(_kick(*ranges[1]))  # DMA one slice ahead
        fut = loop.run_in_executor(executor, _materialize, pieces[0], state)
        for i in range(1, len(ranges)):
            if i + 1 < len(ranges):
                pieces.append(_kick(*ranges[i + 1]))
            buf, state = await fut
            # Lookahead: slice i materializes while the consumer writes
            # slice i-1 (its DMA was kicked one iteration earlier).
            fut = loop.run_in_executor(executor, _materialize, pieces[i], state)
            pieces[i - 1] = None  # drop the written slice's device ref
            yield buf
        buf, state = await fut
        yield buf
        self._stream_checksum_finish(state)

    async def stage_buffer(self, executor=None) -> BufferType:
        arr = self.arr
        loop = asyncio.get_running_loop()
        record_fp = False
        if self._device_dedup_candidate(arr):
            ref = self.dedup.refs.get(self.entry.location)
            if ref is not None and ref.device_digest is not None:
                # A skip is possible: fingerprint BEFORE kicking the DtoH
                # DMA — a match makes the transfer unnecessary, which is
                # the entire point.
                if await loop.run_in_executor(
                    executor, self._try_device_dedup, arr
                ):
                    self.io_skipped = True
                    return memoryview(b"")
            else:
                # No base fingerprint to match (first save, or a base
                # taken without device digests): the DMA must happen, so
                # kick it first and let the recording fingerprint — pure
                # on-device compute — overlap the transfer. The dispatch
                # (kick) happens before staging; the 16-byte fetch waits
                # until after, so neither the device pass nor its
                # roundtrip ever sits ahead of the staging copy.
                record_fp = True
        if _is_jax_array(arr):
            try:
                arr.copy_to_host_async()  # kick off the DMA before blocking
            except Exception:
                pass
        pending_fp = None
        if record_fp:
            from ..device_digest import _dispatch

            pending_fp = await loop.run_in_executor(executor, _dispatch, arr)
        buf = await loop.run_in_executor(executor, self._stage_and_sum, arr)
        if pending_fp is not None:
            from ..device_digest import _finalize

            self.entry.device_digest = await loop.run_in_executor(
                executor, _finalize, arr, pending_fp
            )
        return buf

    def get_staging_cost_bytes(self) -> int:
        return array_nbytes(self.arr)


@dataclass
class DeviceMaterializer:
    """How a restored array lands on device, captured at prepare time
    (prepare.py's jax-destination branch). The buffered path keeps using
    the host-array callback (one ``device_put`` of the whole payload);
    the STREAMED path uses this instead: each sub-chunk is ``device_put``
    as it arrives, so HtoD of chunk N rides under the read of chunk N+1
    and the host never holds more than the in-flight window."""

    sharding: object
    dst_dtype: object
    needs_cast: bool
    callback: Optional[Callable]


class _ScratchSink:
    """Raw-byte sink for verify-before-commit streamed consumes: bytes
    accumulate in a scratch buffer and NOTHING touches the destination
    until the chained checksum validated — the buffered path's
    verify-then-copy safety, kept under streaming at the cost of holding
    the payload (which is why consumers using this sink declare the FULL
    consuming cost to the budget, not the window)."""

    def __init__(self, nbytes: int) -> None:
        # Pooled slab, not a fresh allocation: on lazily-backed VMs the
        # first touch of never-used memory costs several x a normal
        # fault, and a training loop restores repeatedly — the pool's
        # GC-driven recycling (see _StagingPool) hands back pre-faulted
        # slabs, and any view a consumer keeps pins the slab until it
        # dies.
        self.buf = _staging_pool.get(nbytes) if nbytes else np.empty(0, np.uint8)
        self.pos = 0

    def add(self, data) -> None:
        mv = data if isinstance(data, memoryview) else memoryview(data)
        mv = mv.cast("B")
        if self.pos + mv.nbytes > self.buf.nbytes:
            raise IOError(
                f"read stream produced more than the expected "
                f"{self.buf.nbytes} bytes"
            )
        self.buf[self.pos : self.pos + mv.nbytes] = np.frombuffer(mv, np.uint8)
        self.pos += mv.nbytes

    def finish(self) -> memoryview:
        if self.pos != self.buf.nbytes:
            raise IOError(
                f"short read stream: produced {self.pos} of "
                f"{self.buf.nbytes} bytes"
            )
        return memoryview(self.buf)


class _DeviceRowSink:
    """Per-sub-chunk HtoD sink: whole-row blocks of the decoded payload
    are ``device_put`` as they land, assembled on device at the end
    (concatenate along dim 0, then placed under the destination
    sharding). The host holds only the carry of a partial row plus the
    chunk in flight — the window the scheduler's budget charges — and
    the destination array is untouched until the checksum validated and
    the callback fires."""

    def __init__(self, entry: "ArrayEntry", dest: DeviceMaterializer) -> None:
        self.shape = tuple(entry.shape)
        self.np_dtype = string_to_dtype(entry.dtype)
        raw = array_size_bytes(self.shape, entry.dtype)
        self.row_bytes = max(1, raw // self.shape[0])
        self.row_elems = self.row_bytes // self.np_dtype.itemsize
        self.dest = dest
        self.carry = bytearray()
        self.blocks: list = []
        self.rows = 0
        self._device = None

    def add(self, data) -> None:
        import jax

        mv = data if isinstance(data, memoryview) else memoryview(data)
        self.carry += mv.cast("B")
        whole = (len(self.carry) // self.row_bytes) * self.row_bytes
        if not whole:
            return
        src = self.carry
        self.carry = bytearray(memoryview(src)[whole:])
        rows = whole // self.row_bytes
        block = np.frombuffer(
            src, dtype=self.np_dtype, count=rows * self.row_elems
        ).reshape((rows,) + self.shape[1:])
        if self._device is None:
            self._device = next(iter(self.dest.sharding.device_set))
        # device_put returns immediately (transfer proceeds in the
        # background) and `src` stays alive through the block's buffer
        # reference — and is never mutated again, so a zero-copy CPU
        # device_put is safe too.
        with telemetry.span("sub_chunk_htod", cat="consumer", bytes=whole):
            self.blocks.append(jax.device_put(block, self._device))
        self.rows += rows

    def finish(self) -> None:
        import jax
        import jax.numpy as jnp

        if self.carry:
            raise IOError(
                f"read stream ended mid-row: {len(self.carry)} trailing "
                f"bytes do not fill a {self.row_bytes}-byte row"
            )
        if self.rows != self.shape[0]:
            raise IOError(
                f"short read stream: produced {self.rows} of "
                f"{self.shape[0]} rows"
            )
        full = self.blocks[0] if len(self.blocks) == 1 else jnp.concatenate(
            self.blocks, axis=0
        )
        self.blocks = []
        restored = jax.device_put(full, self.dest.sharding)
        if self.dest.needs_cast:
            restored = restored.astype(self.dest.dst_dtype)
        if self.dest.callback is not None:
            self.dest.callback(restored)


class _IncrementalEntryDecoder:
    """Per-sub-chunk verify + decompress for one entry's streamed
    payload: the chained CRC advances over the STORED bytes exactly as
    the buffered `verify_checksum` would hash them, decompression (when
    the entry records a codec) feeds the same chunk through a streaming
    decompressor, and decoded raw bytes flow to ``sink_add``. ``finish``
    flushes the codec tail and raises on checksum mismatch BEFORE the
    caller commits anything."""

    def __init__(self, entry: "ArrayEntry", sink_add: Callable) -> None:
        from ..compression import StreamingDecompressor
        from ..integrity import IncrementalVerifier

        self.verifier = IncrementalVerifier(entry.checksum, entry.location)
        self.decomp = (
            StreamingDecompressor(
                entry.codec,
                expected_size=array_size_bytes(entry.shape, entry.dtype),
            )
            if entry.codec is not None
            else None
        )
        self.sink_add = sink_add

    def add(self, chunk) -> None:
        with telemetry.span(
            "consume_chunk", cat="consumer", bytes=memoryview(chunk).nbytes
        ):
            self.verifier.update(chunk)
            data = self.decomp.feed(chunk) if self.decomp is not None else chunk
            if memoryview(data).nbytes:
                self.sink_add(data)

    def finish(self) -> None:
        if self.decomp is not None:
            tail = self.decomp.finish()
            if tail:
                self.sink_add(tail)
        self.verifier.finish()


def _entry_stored_size(entry: "ArrayEntry") -> int:
    """Bytes storage will deliver for ``entry`` — the byte range for
    slab-packed payloads, the serialized size otherwise (compressed
    payloads' stored size isn't recorded; the raw size is the proxy the
    streaming election uses)."""
    if entry.byte_range is not None:
        lo, hi = entry.byte_range
        return max(0, hi - lo)
    return array_size_bytes(entry.shape, entry.dtype)


class ArrayBufferConsumer(BufferConsumer):
    """Deserializes into ``dst_view`` (if given) and invokes ``callback`` with
    the host array. Exactly one of the two is typically used."""

    def __init__(
        self,
        entry: ArrayEntry,
        dst_view: Optional[np.ndarray] = None,
        callback: Optional[Callable[[np.ndarray], None]] = None,
        ensure_writable: bool = True,
        device_dest: Optional[DeviceMaterializer] = None,
    ) -> None:
        self.entry = entry
        self.dst_view = dst_view
        self.callback = callback
        # User-facing host arrays (read_state_dict, host callbacks) must be
        # writable even when the storage plugin hands back immutable bytes
        # (S3/GCS); device-materialize callbacks opt out — device_put never
        # needs a writable source and the copy would be pure waste.
        self.ensure_writable = ensure_writable
        # Streamed consumes of jax destinations device_put per sub-chunk
        # through this instead of the host-array callback (which is the
        # buffered path's one-shot device_put).
        self.device_dest = device_dest

    def _deliver(self, buf: BufferType) -> None:
        """Commit a VERIFIED, DECOMPRESSED raw payload to the
        destination — the tail both the buffered and the streamed
        scratch path share."""
        arr = array_from_buffer(buf, self.entry.dtype, self.entry.shape)
        if (
            self.dst_view is None
            and self.callback is not None
            and self.ensure_writable
            and not arr.flags["WRITEABLE"]
        ):
            arr = np.array(arr)
        if self.dst_view is not None:
            fast_copyto(self.dst_view, arr)
            if self.callback is not None:
                self.callback(self.dst_view)
        elif self.callback is not None:
            self.callback(arr)

    def _consume_sync(self, buf: BufferType) -> None:
        if self.entry.checksum is not None:
            from ..integrity import verification_enabled, verify_checksum

            # This consumer always receives the entry's complete payload
            # (whole file, or the entry's byte_range within a batched slab),
            # so the recorded checksum applies directly.
            if verification_enabled():
                verify_checksum(buf, self.entry.checksum, self.entry.location)
        if self.entry.codec is not None:
            from ..compression import decompress

            buf = decompress(
                self.entry.codec,
                buf,
                expected_size=array_size_bytes(
                    self.entry.shape, self.entry.dtype
                ),
            )
        self._deliver(buf)

    async def consume_buffer(self, buf: BufferType, executor=None) -> None:
        if executor is not None:
            loop = asyncio.get_running_loop()
            await loop.run_in_executor(executor, self._consume_sync, buf)
        else:
            self._consume_sync(buf)

    def get_consuming_cost_bytes(self) -> int:
        return array_size_bytes(self.entry.shape, self.entry.dtype)

    # ----------------------------------------------------- streaming path

    def _device_sink_ok(self) -> bool:
        """The per-sub-chunk device sink applies to SINGLE-DEVICE
        destinations only: the sink assembles row blocks on one device
        (transiently ~2x the entry there — bounded, since entries
        reaching this consumer are <=512 MB by the chunking policy), and
        for a replicated multi-device destination that assembly would
        add a pointless extra broadcast hop over the buffered path's
        direct sharded device_put — those stream through the scratch
        path instead."""
        if self.dst_view is not None or self.device_dest is None:
            return False
        shape = tuple(self.entry.shape)
        if len(shape) < 1 or shape[0] < 1:
            return False
        try:
            if len(self.device_dest.sharding.device_set) != 1:
                return False
        except AttributeError:
            return False
        return True

    def _device_mode_ok(self, sub_chunk_bytes: int) -> bool:
        """Device sink AND rows no wider than the sub-chunk: wider rows
        would grow the carry past the window the budget charges — such
        shapes still use the device sink but declare full cost."""
        if not self._device_sink_ok():
            return False
        shape = tuple(self.entry.shape)
        raw = array_size_bytes(shape, self.entry.dtype)
        row_bytes = raw // shape[0]
        return 0 < row_bytes <= sub_chunk_bytes

    def can_stream(self, sub_chunk_bytes: int) -> bool:
        """This consumer streams whenever the payload spans several
        sub-chunks and its codec (if any) decompresses incrementally.
        Checksums never block: the chained CRC is bit-identical to the
        whole-buffer hash, and the skip rules (unknown algorithm, crc32c
        without the native extension, verification disabled) mirror the
        buffered path's."""
        from ..compression import StreamingDecompressor

        if _entry_stored_size(self.entry) < 2 * sub_chunk_bytes:
            return False
        return StreamingDecompressor.available(self.entry.codec)

    def stream_admission_cost(self, sub_chunk_bytes: int) -> int:
        cost = self.get_consuming_cost_bytes()
        if self._device_mode_ok(sub_chunk_bytes):
            # Chunk being decoded + the plugin's read-ahead + the row
            # carry: the window the device sink actually holds.
            from ..io_types import STREAM_DEPTH

            return min(cost, (STREAM_DEPTH + 1) * sub_chunk_bytes)
        # Scratch assembly (verify-before-commit into host memory) holds
        # the full payload — declare it honestly.
        return cost

    async def consume_stream(self, stream, executor=None) -> None:
        # Sink choice is shape-driven, not size-driven: eligible jax
        # destinations take the windowed device sink regardless of the
        # row/sub-chunk ratio (the budget already charged whichever cost
        # stream_admission_cost declared).
        if self._device_sink_ok():
            sink = _DeviceRowSink(self.entry, self.device_dest)
            scratch = None
        else:
            scratch = _ScratchSink(
                array_size_bytes(self.entry.shape, self.entry.dtype)
            )
            sink = scratch
        decoder = _IncrementalEntryDecoder(self.entry, sink.add)
        loop = asyncio.get_running_loop() if executor is not None else None

        def finish() -> None:
            decoder.finish()  # checksum mismatch raises BEFORE any commit
            if scratch is not None:
                self._deliver(scratch.finish())
            else:
                sink.finish()

        async for chunk in stream.chunks:
            if loop is not None:
                await loop.run_in_executor(executor, decoder.add, chunk)
            else:
                decoder.add(chunk)
        if loop is not None:
            await loop.run_in_executor(executor, finish)
        else:
            finish()


class ArrayIOPreparer:
    @staticmethod
    def prepare_write(
        storage_path: str, arr, replicated: bool = False
    ) -> Tuple[ArrayEntry, List[WriteReq]]:
        entry = ArrayEntry(
            location=storage_path,
            serializer=Serializer.BUFFER_PROTOCOL.value,
            dtype=dtype_to_string(arr.dtype),
            shape=list(arr.shape),
            replicated=replicated,
        )
        return entry, [
            WriteReq(path=storage_path, buffer_stager=ArrayBufferStager(arr, entry))
        ]

    @staticmethod
    def prepare_read(
        entry: ArrayEntry,
        dst_view: Optional[np.ndarray] = None,
        callback: Optional[Callable[[np.ndarray], None]] = None,
        buffer_size_limit_bytes: Optional[int] = None,
        ensure_writable: bool = True,
        device_dest: Optional[DeviceMaterializer] = None,
    ) -> List[ReadReq]:
        # Compressed payloads can't be read by byte sub-ranges (no random
        # access into the stream): whole-entry read, budget or not.
        # Entries are <=512 MB by the chunking policy, so the budget's
        # purpose (bounding single-buffer size) still roughly holds.
        if buffer_size_limit_bytes is None or entry.codec is not None:
            consumer = ArrayBufferConsumer(
                entry,
                dst_view=dst_view,
                callback=callback,
                ensure_writable=ensure_writable,
                device_dest=device_dest,
            )
            byte_range = (
                tuple(entry.byte_range) if entry.byte_range is not None else None
            )
            return [
                ReadReq(
                    path=entry.location,
                    buffer_consumer=consumer,
                    byte_range=byte_range,
                    origin=entry.origin,
                )
            ]
        return _prepare_chunked_read(entry, dst_view, callback, buffer_size_limit_bytes)


class _SlicedArrayConsumer(BufferConsumer):
    """Consumes one byte-range of a serialized array into the matching flat
    slice of the destination (chunked reads under a memory budget,
    reference: io_preparer.py:672-718)."""

    def __init__(
        self,
        entry: ArrayEntry,
        assembler: "ArrayAssembler",
        elem_lo: int,
        elem_hi: int,
    ) -> None:
        self.entry = entry
        self.assembler = assembler
        self.elem_lo = elem_lo
        self.elem_hi = elem_hi

    def _consume_sync(self, buf: BufferType) -> None:
        from ..serialization import string_to_dtype

        flat = np.frombuffer(buf, dtype=np.uint8).view(string_to_dtype(self.entry.dtype))
        self.assembler.fill_flat(self.elem_lo, self.elem_hi, flat)

    async def consume_buffer(self, buf: BufferType, executor=None) -> None:
        if executor is not None:
            loop = asyncio.get_running_loop()
            await loop.run_in_executor(executor, self._consume_sync, buf)
        else:
            self._consume_sync(buf)

    def get_consuming_cost_bytes(self) -> int:
        itemsize = array_size_bytes((1,), self.entry.dtype)
        return (self.elem_hi - self.elem_lo) * itemsize

    # ----------------------------------------------------- streaming path

    def _direct_flat_bytes(self) -> Optional[np.ndarray]:
        """The destination's raw-byte view for direct incremental fills,
        or None when bytes can't land verbatim (a same-kind dtype cast is
        pending — the buffered path's element-wise copy handles that)."""
        flat = self.assembler._flat
        if flat.dtype != string_to_dtype(self.entry.dtype):
            return None
        if not flat.flags["C_CONTIGUOUS"]:
            return None
        return flat.view(np.uint8)

    def can_stream(self, sub_chunk_bytes: int) -> bool:
        # Budget-split sub-range reads carry no checksum or codec (the
        # whole-entry consumer owns those), so streaming is a plain
        # incremental byte fill of pre-existing assembler memory — the
        # same partial-fill-on-failure semantics a buffered failure
        # between this entry's sub-reads already has.
        if self.get_consuming_cost_bytes() < 2 * sub_chunk_bytes:
            return False
        return self._direct_flat_bytes() is not None

    def stream_admission_cost(self, sub_chunk_bytes: int) -> int:
        from ..io_types import STREAM_DEPTH

        # The destination is assembler memory that pre-exists this read;
        # only the in-flight chunks are new.
        return min(
            self.get_consuming_cost_bytes(), STREAM_DEPTH * sub_chunk_bytes
        )

    async def consume_stream(self, stream, executor=None) -> None:
        itemsize = array_size_bytes((1,), self.entry.dtype)
        dst = self._direct_flat_bytes()
        base = self.elem_lo * itemsize
        total = (self.elem_hi - self.elem_lo) * itemsize
        pos = 0

        def fill(chunk) -> int:
            mv = memoryview(chunk).cast("B")
            with telemetry.span("consume_chunk", cat="consumer", bytes=mv.nbytes):
                if pos + mv.nbytes > total:
                    raise IOError(
                        f"read stream produced more than the expected "
                        f"{total} bytes for {self.entry.location}"
                    )
                dst[base + pos : base + pos + mv.nbytes] = np.frombuffer(
                    mv, np.uint8
                )
            return mv.nbytes

        loop = asyncio.get_running_loop() if executor is not None else None
        async for chunk in stream.chunks:
            if loop is not None:
                pos += await loop.run_in_executor(executor, fill, chunk)
            else:
                pos += fill(chunk)
        if pos != total:
            raise IOError(
                f"short read stream for {self.entry.location}: produced "
                f"{pos} of {total} bytes"
            )
        self.assembler.part_done()


class ArrayAssembler:
    """Accumulates partial fills of one destination array; fires ``callback``
    when the last part lands. Shared by chunked and sharded restores."""

    def __init__(
        self,
        dst: np.ndarray,
        num_parts: int,
        callback: Optional[Callable[[np.ndarray], None]] = None,
    ) -> None:
        self.dst = dst
        # reshape(-1) on a non-contiguous view returns a COPY, so flat fills
        # would be lost; assemble into a contiguous scratch instead and copy
        # back once on completion (reference covers strided/offset dst views,
        # tests/test_tensor_io_preparer.py:158-181).
        if dst.flags["C_CONTIGUOUS"]:
            self._scratch = dst
        else:
            # Seed with current contents so partially-covering fills (e.g. a
            # destination only some regions overlap) don't clobber the rest.
            self._scratch = np.ascontiguousarray(dst)
        self._flat = self._scratch.reshape(-1)
        self._remaining = num_parts
        self._lock = threading.Lock()
        self.callback = callback

    def region_view(self, index: Tuple[slice, ...]) -> np.ndarray:
        """A writable view of the assembly target for ``index``. Callers that
        write sub-regions directly (e.g. budgeted chunk reads) MUST write into
        this view, never into ``dst`` itself: when ``dst`` is non-contiguous
        the assembly happens in a scratch buffer that is copied back over
        ``dst`` on completion, which would clobber direct writes."""
        return self._scratch[index] if index else self._scratch

    def fill_flat(self, elem_lo: int, elem_hi: int, values: np.ndarray) -> None:
        fast_copyto(self._flat[elem_lo:elem_hi], values)
        self.part_done()

    def fill_region(self, index: Tuple[slice, ...], values: np.ndarray) -> None:
        fast_copyto(self.region_view(index), values)
        self.part_done()

    def part_done(self) -> None:
        # Parts are consumed concurrently from executor threads.
        with self._lock:
            self._remaining -= 1
            remaining = self._remaining
        if remaining == 0:
            if self._scratch is not self.dst:
                fast_copyto(self.dst, self._scratch)
            if self.callback is not None:
                self.callback(self.dst)


def _prepare_chunked_read(
    entry: ArrayEntry,
    dst_view: Optional[np.ndarray],
    callback: Optional[Callable[[np.ndarray], None]],
    buffer_size_limit_bytes: int,
) -> List[ReadReq]:
    itemsize = array_size_bytes((1,), entry.dtype)
    total_elems = int(np.prod(entry.shape, dtype=np.int64)) if entry.shape else 1
    elems_per_read = max(1, buffer_size_limit_bytes // itemsize)

    if dst_view is None:
        from ..serialization import string_to_dtype

        dst_view = np.empty(tuple(entry.shape), dtype=string_to_dtype(entry.dtype))

    ranges = []
    lo = 0
    while lo < total_elems:
        hi = min(lo + elems_per_read, total_elems)
        ranges.append((lo, hi))
        lo = hi
    if not ranges:
        ranges = [(0, 0)]

    assembler = ArrayAssembler(dst_view, num_parts=len(ranges), callback=callback)
    base = entry.byte_range[0] if entry.byte_range is not None else 0
    read_reqs = []
    for elem_lo, elem_hi in ranges:
        read_reqs.append(
            ReadReq(
                path=entry.location,
                buffer_consumer=_SlicedArrayConsumer(entry, assembler, elem_lo, elem_hi),
                byte_range=(base + elem_lo * itemsize, base + elem_hi * itemsize),
                origin=entry.origin,
            )
        )
    return read_reqs


def get_array_size_from_entry(entry: ArrayEntry) -> int:
    return array_size_bytes(entry.shape, entry.dtype)
