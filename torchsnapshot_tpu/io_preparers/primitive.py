"""Primitive preparer: int/float/str/bool/bytes/None inlined into metadata —
zero storage I/O (reference: io_preparer.py:801-812, prepare_read returns []).
"""

from __future__ import annotations

from typing import Any

from ..manifest import PrimitiveEntry


class PrimitivePreparer:
    @staticmethod
    def should_inline(obj: Any) -> bool:
        return type(obj).__name__ in PrimitiveEntry.supported_types()

    @staticmethod
    def prepare_write(obj: Any, replicated: bool = False) -> PrimitiveEntry:
        return PrimitiveEntry.from_object(obj, replicated=replicated)
