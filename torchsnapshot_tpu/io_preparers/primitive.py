"""Primitive preparer: int/float/str/bool/bytes/None inlined into metadata —
zero storage I/O (reference: io_preparer.py:801-812, prepare_read returns []).
"""

from __future__ import annotations

from typing import Any

from ..manifest import PrimitiveEntry


# str/bytes above this size take the object (storage I/O) path instead of
# being inlined: the metadata YAML is gathered by every rank and committed
# by rank 0, so unbounded inlining would bloat the manifest collective.
_MAX_INLINE_BYTES = 16 * 1024


class PrimitivePreparer:
    @staticmethod
    def should_inline(obj: Any) -> bool:
        if type(obj).__name__ not in PrimitiveEntry.supported_types():
            return False
        if isinstance(obj, (str, bytes)) and len(obj) > _MAX_INLINE_BYTES:
            return False
        return True

    @staticmethod
    def prepare_write(obj: Any, replicated: bool = False) -> PrimitiveEntry:
        return PrimitiveEntry.from_object(obj, replicated=replicated)
