"""Top-level write/read dispatch by value/entry type
(reference: io_preparer.py:792-892).

Also the storage layout rule: sharded entries live under ``sharded/``,
replicated under ``replicated/``, everything else under ``<rank>/``
(reference: io_preparer.py:792-798).
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional

import numpy as np

from ..io_types import ReadReq
from ..manifest import (
    ArrayEntry,
    ChunkedArrayEntry,
    Entry,
    ObjectEntry,
    PrimitiveEntry,
    ShardedArrayEntry,
)
from .array import ArrayIOPreparer
from .chunked import ChunkedArrayIOPreparer
from .object import ObjectIOPreparer


def get_storage_path(
    logical_path: str, rank: int, replicated: bool = False, sharded: bool = False
) -> str:
    if sharded:
        return f"sharded/{logical_path}"
    elif replicated:
        return f"replicated/{logical_path}"
    else:
        return f"{rank}/{logical_path}"


def _jax():
    import jax

    return jax


def is_jax_array(obj: Any) -> bool:
    try:
        import jax

        return isinstance(obj, jax.Array)
    except ImportError:  # pragma: no cover
        return False


def is_sharded_jax_array(obj: Any) -> bool:
    """True for jax.Arrays that must be saved shard-wise: any array whose
    sharding actually partitions the data across devices (GSPMD TP/FSDP/EP
    layouts, multi-host arrays). Fully-replicated and single-device arrays
    go through the plain/chunked path instead."""
    if not is_jax_array(obj):
        return False
    sharding = obj.sharding
    if getattr(sharding, "num_devices", len(sharding.device_set)) == 1:
        return False
    return not sharding.is_fully_replicated


def is_partitionable_array(obj: Any) -> bool:
    """Arrays handled by the plain/chunked path: numpy arrays/scalars and
    non-partitioned jax.Arrays."""
    if isinstance(obj, (np.ndarray, np.generic)):
        return True
    return is_jax_array(obj) and not is_sharded_jax_array(obj)


def check_restore_cast(entry_dtype: str, dst_dtype: Any, what: str) -> bool:
    """Restore semantics: the DESTINATION is the spec — shape, sharding, and
    dtype. A snapshot saved in a different dtype is cast to the destination's
    on restore, mirroring the reference's ``dst.copy_(src)`` into pre-built
    state (reference: io_preparer.py:426-427) so a jitted train step keeps
    its compiled dtype across a precision-recipe change. Divergence from
    torch: ``copy_`` casts unsafely; here only ``same_kind`` casts (float<->
    float incl. bf16/fp8, int<->int) are allowed — a float checkpoint
    restoring into int params is almost certainly a state-mapping bug, not
    an intended quantization (quantized flows store scales separately).

    Returns True when a cast is needed; raises for forbidden casts.
    """
    from ..serialization import string_to_dtype

    src = string_to_dtype(entry_dtype)
    dst = np.dtype(dst_dtype)
    if src == dst:
        return False
    if not np.can_cast(src, dst, casting="same_kind"):
        raise RuntimeError(
            f"Restoring {what}: snapshot dtype {entry_dtype} cannot be cast "
            f"to destination dtype {dst} (only same-kind casts are "
            "supported; restore into a matching-kind destination or convert "
            "the checkpoint explicitly)."
        )
    return True


def _dst_already_matches(entry: Entry, obj_out: Any) -> bool:
    """True when a jax destination already holds exactly the content the
    entry describes, proven by on-device fingerprints (device_digest.py):
    the read and the HtoD transfer can be skipped and the destination
    kept. Conservative on every edge: any missing fingerprint, dtype or
    shape difference, or unfingerprintable destination means False.
    """
    from ..device_digest import device_fingerprint, fingerprints_match
    from .array import dtype_to_string

    if isinstance(entry, ArrayEntry):
        if entry.device_digest is None or entry.byte_range is not None:
            return False
        if list(obj_out.shape) != list(entry.shape):
            return False
        if dtype_to_string(obj_out.dtype) != entry.dtype:
            return False
        return device_fingerprint(obj_out) == entry.device_digest
    if isinstance(entry, ChunkedArrayEntry):
        # All chunks must match: the jax read path materializes the whole
        # host array before one device_put, so a partial skip has nothing
        # to splice into. (Per-piece skips exist on the sharded path,
        # where reads scatter independently.)
        if list(obj_out.shape) != list(entry.shape):
            return False
        if dtype_to_string(obj_out.dtype) != entry.dtype:
            return False
        if not entry.chunks or any(
            c.array.device_digest is None for c in entry.chunks
        ):
            # fingerprints_match([]) is vacuously True; empty chunks must
            # not keep arbitrary destination content with no verification.
            return False
        # Windowed: a few chunk slices are live at a time (fingerprints
        # in a window dispatch together, then the slices are dropped), so
        # verifying a chunked array — which only exists above 512 MB —
        # never transiently duplicates its whole footprint in device
        # memory the way a full eager slice list would.
        from ..serialization import array_size_bytes

        return fingerprints_match(
            (
                (
                    array_size_bytes(c.sizes, entry.dtype),
                    lambda c=c: obj_out[
                        tuple(
                            slice(o, o + s)
                            for o, s in zip(c.offsets, c.sizes)
                        )
                    ],
                    c.array.device_digest,
                )
                for c in entry.chunks
            )
        )
    return False


def prepare_read(
    entry: Entry,
    obj_out: Any = None,
    callback: Optional[Callable[[Any], None]] = None,
    buffer_size_limit_bytes: Optional[int] = None,
    device_digests: bool = False,
    assume_verified: bool = False,
    reshard: Optional[Any] = None,
) -> List[ReadReq]:
    """Plan reads for ``entry`` into/for ``obj_out``.

    - numpy destination: filled in place (plus ``callback`` on completion);
    - jax.Array destination: a host buffer is filled, then re-materialized on
      device with the destination's sharding and reported via ``callback``;
    - no destination: a host value is materialized and reported via
      ``callback``.

    A destination whose dtype differs from the snapshot's is cast to the
    destination's dtype (``same_kind`` only — see ``check_restore_cast``).

    ``device_digests``: jax destinations already holding an entry's exact
    content (fingerprinted on device against the entry's recorded
    fingerprint) plan NO reads and keep their current array — the
    restore-side mirror of the take-side DtoH skip.

    ``assume_verified``: the destination was already proven to hold this
    entry's exact content by DISTRIBUTED digest verification (partial
    fingerprint lanes summed across processes over the coordination
    plane, snapshot.py) — plan no reads and keep it.

    ``reshard``: an active ``reshard.ReshardContext`` — sharded entries
    route multi-requester shards over the planned-peer tier (one storage
    read on an elected owner, minimal region bundles to everyone else)
    instead of N direct storage reads.

    PrimitiveEntry requires no I/O and must be handled by the caller
    (reference: io_preparer.py:888-890).
    """
    if isinstance(entry, PrimitiveEntry):
        return []

    if assume_verified:
        return []

    if (
        device_digests
        and is_jax_array(obj_out)
        and getattr(obj_out, "is_fully_addressable", False)
        and _dst_already_matches(entry, obj_out)
    ):
        return []

    if isinstance(entry, ObjectEntry):
        read_reqs, consumer = ObjectIOPreparer.prepare_read(entry)
        if callback is not None:
            consumer.set_consume_callback(callback)
        return read_reqs

    if isinstance(entry, ShardedArrayEntry):
        from .sharded import ShardedArrayIOPreparer

        return ShardedArrayIOPreparer.prepare_read(
            entry,
            obj_out,
            callback=callback,
            device_digests=device_digests,
            reshard=reshard,
        )

    if not isinstance(entry, (ArrayEntry, ChunkedArrayEntry)):
        raise TypeError(f"Unsupported entry type for read: {type(entry).__name__}")

    dst_view: Optional[np.ndarray] = None
    final_callback = callback
    # Host consumers (read_state_dict, numpy callbacks) are promised
    # writable arrays; the device-materialize path below opts out —
    # device_put never needs a writable source.
    ensure_writable = True
    device_dest = None

    if isinstance(obj_out, np.ndarray) and obj_out.flags["WRITEABLE"]:
        if list(obj_out.shape) != list(entry.shape):
            raise RuntimeError(
                f"Shape mismatch restoring {entry.location if hasattr(entry, 'location') else '<chunked>'}: "
                f"snapshot has {list(entry.shape)}, destination has {list(obj_out.shape)}."
            )
        # fast_copyto applies the same_kind cast element-wise during the
        # copy into the destination; fail before any I/O if it can't.
        check_restore_cast(entry.dtype, obj_out.dtype, "into numpy array")
        dst_view = obj_out
    elif is_jax_array(obj_out):
        jax = _jax()
        if list(obj_out.shape) != list(entry.shape):
            raise RuntimeError(
                f"Shape mismatch restoring into jax.Array: snapshot has "
                f"{list(entry.shape)}, destination has {list(obj_out.shape)}."
            )
        sharding = obj_out.sharding
        needs_cast = check_restore_cast(
            entry.dtype, obj_out.dtype, "into jax.Array"
        )
        dst_dtype = obj_out.dtype
        # No host scratch here: with dst_view=None the preparers hand the
        # callback either a zero-copy view over the read buffer (whole-file
        # reads — saves a full memcpy pass per array) or their own assembly
        # scratch (budget-split / chunked reads, which genuinely need one).
        # device_put copies host->device either way. Dtype casts run ON
        # DEVICE after the transfer: the wire moves the snapshot's (often
        # narrower) bytes and the VPU does the widening, not the host.

        def _materialize(host: np.ndarray, _cb=callback, _sharding=sharding) -> None:
            restored = jax.device_put(host, _sharding)
            if needs_cast:
                restored = restored.astype(dst_dtype)
            if _cb is not None:
                _cb(restored)

        final_callback = _materialize
        ensure_writable = False
        # STREAMED reads bypass the host-array callback: the consumer
        # device_puts each sub-chunk as it lands (HtoD of chunk N rides
        # under the read of chunk N+1) and materializes under the same
        # sharding/cast rules this callback applies buffered.
        from .array import DeviceMaterializer

        device_dest = DeviceMaterializer(
            sharding=sharding,
            dst_dtype=dst_dtype,
            needs_cast=needs_cast,
            callback=callback,
        )
    # else: no usable destination — allocate inside the preparer and report
    # the host value via callback.

    if isinstance(entry, ChunkedArrayEntry):
        return ChunkedArrayIOPreparer.prepare_read(
            entry,
            dst_view=dst_view,
            callback=final_callback,
            buffer_size_limit_bytes=buffer_size_limit_bytes,
            ensure_writable=ensure_writable,
            device_dest=device_dest,
        )
    else:
        return ArrayIOPreparer.prepare_read(
            entry,
            dst_view=dst_view,
            callback=final_callback,
            buffer_size_limit_bytes=buffer_size_limit_bytes,
            ensure_writable=ensure_writable,
            device_dest=device_dest,
        )


def prepare_write(
    obj: Any,
    logical_path: str,
    rank: int,
    replicated: bool = False,
):
    """Plan writes for a non-array, non-primitive leaf (objects).

    Arrays are planned by the orchestrator through the chunked/sharded
    preparers because chunk striping and shard deduplication need cross-rank
    agreement.
    """
    storage_path = get_storage_path(logical_path, rank, replicated=replicated)
    return ObjectIOPreparer.prepare_write(storage_path, obj, replicated=replicated)
