"""GSPMD-sharded array preparer: the resharding engine.

TPU-native redesign of the reference's ShardedTensorIOPreparer
(io_preparer.py:164-490). The shard spec is ``jax.sharding`` itself: each
shard's N-D global offsets/sizes are derived from
``sharding.devices_indices_map`` — exactly the reference's
``Shard{offsets,sizes}`` schema (manifest.py:72-76), so snapshots are
world-size- and layout-independent.

Save:
- The global device->index map is computed identically on every process.
  Unique shard *boxes* are deduplicated: GSPMD layouts routinely replicate a
  shard across processes (e.g. params sharded over 'model' and replicated
  over 'data'), and without dedup every process would write every shard
  (SURVEY §7 hard-parts). The writer for each box is chosen by a
  deterministic hash over the box, balanced across the processes that hold
  it — no communication needed.
- Each owned box is subdivided along its largest dimension to <=512 MB
  (reference: subdivide_shard, io_preparer.py:167-197) and staged via async
  DtoH DMA per sub-shard.

Restore (reference: io_preparer.py:199-246,315-389):
- Destination boxes come from the *destination* array's sharding (one host
  buffer per unique addressable box — never the full array, so host memory
  scales with 1/num_hosts).
- Each saved shard overlapping any destination box is read once and
  scattered into all overlapping regions.
- When the last region lands, the global array is materialized with
  ``jax.make_array_from_callback`` under the destination sharding (HtoD).
- A plain numpy destination (or none) acts as a single box covering the
  whole array — the ShardedTensor->Tensor path (reference:
  io_preparer.py:330-342).
"""

from __future__ import annotations

import asyncio
import hashlib
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..io_types import BufferConsumer, BufferType, ReadReq, WriteReq
from ..manifest import ArrayEntry, Shard, ShardedArrayEntry
from ..serialization import (
    array_from_buffer,
    array_size_bytes,
    dtype_to_string,
    string_to_dtype,
)
from .array import ArrayBufferStager, fast_copyto

DEFAULT_MAX_SHARD_SIZE_BYTES = 512 * 1024 * 1024

Box = Tuple[Tuple[int, int], ...]  # ((start, stop) per dim)


def _normalize_index(index: Tuple[slice, ...], shape: Tuple[int, ...]) -> Box:
    out = []
    for sl, dim in zip(index, shape):
        start, stop, step = sl.indices(dim)
        assert step == 1, "strided shardings are not supported"
        out.append((start, stop))
    # 0-d or rank-deficient index tuples: pad to full rank
    for dim in shape[len(index):]:
        out.append((0, dim))
    return tuple(out)


def _box_key(box: Box) -> str:
    return "_".join(f"{a}.{b}" for a, b in box)


def _stable_owner(box: Box, holders: List[int]) -> int:
    """Deterministic, load-spreading choice of writer among holder processes."""
    digest = hashlib.md5(_box_key(box).encode()).digest()
    return sorted(holders)[int.from_bytes(digest[:4], "big") % len(holders)]


def _overlap(
    saved_off: List[int], saved_sz: List[int], box: Box
) -> Optional[Tuple[Tuple[slice, ...], Tuple[slice, ...]]]:
    """(view into saved shard, view into destination box) or None."""
    src_slices = []
    dst_slices = []
    for (d_lo, d_hi), s_lo, s_sz in zip(box, saved_off, saved_sz):
        lo = max(s_lo, d_lo)
        hi = min(s_lo + s_sz, d_hi)
        if lo >= hi:
            return None
        src_slices.append(slice(lo - s_lo, hi - s_lo))
        dst_slices.append(slice(lo - d_lo, hi - d_lo))
    return tuple(src_slices), tuple(dst_slices)


def _subdivide(
    offsets: List[int], sizes: List[int], itemsize: int, max_bytes: int
) -> List[Tuple[List[int], List[int]]]:
    """Split a box into <=max_bytes pieces along its largest dimension."""
    nbytes = int(np.prod(sizes, dtype=np.int64)) * itemsize if sizes else itemsize
    if nbytes <= max_bytes or not sizes:
        return [(list(offsets), list(sizes))]
    dim = int(np.argmax(sizes))
    other = (nbytes // max(sizes[dim], 1)) or 1  # bytes per unit along dim
    rows_per_piece = max(1, max_bytes // other)
    pieces = []
    lo = 0
    while lo < sizes[dim]:
        hi = min(lo + rows_per_piece, sizes[dim])
        p_off = list(offsets)
        p_sz = list(sizes)
        p_off[dim] = offsets[dim] + lo
        p_sz[dim] = hi - lo
        pieces.append((p_off, p_sz))
        lo = hi
    return pieces


def _make_assembler(local: Dict[Box, Any], overlaps, piece_shape):
    """Thunk assembling a saved piece from this process's overlapping
    shard regions, on ONE local device (cross-device moves are DtoD —
    they ride ICI on TPU, never the host). Used by the restore-side
    digest check to verify a piece that no single addressable shard
    contains; called windowed by fingerprints_match, so at most a few
    assembled pieces are live at a time. The caller guarantees the
    overlap regions exactly cover the piece.

    Transient footprint is ~2x the piece's size, not 1x: the zeroed
    assembly target coexists with the device_put copies of every
    overlapping part until the last ``.at[].set`` lands. Window items
    built from this thunk must account the 2x as their cost
    (fingerprints_match's ``cost_bytes``) so a window of assembled
    pieces stays under MATCH_WINDOW_BYTES of REAL device memory."""

    def assemble():
        import jax
        import jax.numpy as jnp

        (box0, (src0, dst0)), *rest = overlaps
        part0 = local[box0][dst0] if dst0 else local[box0]
        dev = next(iter(part0.devices())) if hasattr(part0, "devices") else None
        piece = jax.device_put(jnp.zeros(piece_shape, part0.dtype), dev)
        piece = piece.at[src0].set(part0)
        for box, (src, dst) in rest:
            part = local[box][dst] if dst else local[box]
            piece = piece.at[src].set(jax.device_put(part, dev))
        return piece

    return assemble


class _ShardScatterConsumer(BufferConsumer):
    """Reads one saved shard and scatters it into every overlapping region of
    the destination boxes."""

    def __init__(
        self,
        shard: Shard,
        targets: List[Tuple[np.ndarray, Tuple[slice, ...], Tuple[slice, ...]]],
        completion: "_Completion",
    ) -> None:
        self.shard = shard
        self.targets = targets  # (dst_buffer, src_slices, dst_slices)
        self.completion = completion

    def _decode(self, buf: BufferType) -> np.ndarray:
        """Stored payload -> decoded shard array (verify -> decompress ->
        view). Shared with the planned-reshard owner consumer
        (reshard.PlannedOwnerConsumer), which must forward regions of the
        decoded array before scattering."""
        if self.shard.array.checksum is not None:
            from ..integrity import verification_enabled, verify_checksum

            # Each saved shard is read exactly once, in full.
            if verification_enabled():
                verify_checksum(
                    buf, self.shard.array.checksum, self.shard.array.location
                )
        if self.shard.array.codec is not None:
            from ..compression import decompress
            from ..serialization import array_size_bytes

            buf = decompress(
                self.shard.array.codec,
                buf,
                expected_size=array_size_bytes(
                    self.shard.array.shape, self.shard.array.dtype
                ),
            )
        return array_from_buffer(
            buf, self.shard.array.dtype, self.shard.array.shape
        )

    def _scatter(self, arr: np.ndarray) -> None:
        for dst_buf, src_slices, dst_slices in self.targets:
            target = dst_buf[dst_slices] if dst_slices else dst_buf
            fast_copyto(target, arr[src_slices] if src_slices else arr)
        self.completion.part_done()

    def _consume_sync(self, buf: BufferType) -> None:
        self._scatter(self._decode(buf))

    async def consume_buffer(self, buf: BufferType, executor=None) -> None:
        if executor is not None:
            loop = asyncio.get_running_loop()
            await loop.run_in_executor(executor, self._consume_sync, buf)
        else:
            self._consume_sync(buf)

    def get_consuming_cost_bytes(self) -> int:
        return array_size_bytes(self.shard.array.shape, self.shard.array.dtype)

    # ----------------------------------------------------- streaming path

    def can_stream(self, sub_chunk_bytes: int) -> bool:
        """Streamed shard consumes verify the chained CRC and feed
        decompression per sub-chunk WHILE later sub-chunks are still on
        the wire; the scatter into destination boxes happens only after
        the checksum validated (verify-before-commit, like the buffered
        path), so the full shard scratch is retained and the declared
        admission cost stays the default full consuming cost."""
        from ..compression import StreamingDecompressor
        from .array import _entry_stored_size

        if _entry_stored_size(self.shard.array) < 2 * sub_chunk_bytes:
            return False
        return StreamingDecompressor.available(self.shard.array.codec)

    async def consume_stream(self, stream, executor=None) -> None:
        from .array import _IncrementalEntryDecoder, _ScratchSink

        entry = self.shard.array
        scratch = _ScratchSink(array_size_bytes(entry.shape, entry.dtype))
        decoder = _IncrementalEntryDecoder(entry, scratch.add)

        def finish() -> None:
            decoder.finish()  # checksum mismatch raises BEFORE the scatter
            arr = array_from_buffer(scratch.finish(), entry.dtype, entry.shape)
            for dst_buf, src_slices, dst_slices in self.targets:
                target = dst_buf[dst_slices] if dst_slices else dst_buf
                fast_copyto(target, arr[src_slices] if src_slices else arr)

        loop = asyncio.get_running_loop() if executor is not None else None
        async for chunk in stream.chunks:
            if loop is not None:
                await loop.run_in_executor(executor, decoder.add, chunk)
            else:
                decoder.add(chunk)
        if loop is not None:
            await loop.run_in_executor(executor, finish)
        else:
            finish()
        self.completion.part_done()


class _Completion:
    def __init__(self, num_parts: int, finalize: Callable[[], None]) -> None:
        self._remaining = num_parts
        self._finalize = finalize
        self._lock = threading.Lock()

    def part_done(self) -> None:
        # Parts are consumed concurrently from executor threads.
        with self._lock:
            self._remaining -= 1
            remaining = self._remaining
        if remaining == 0:
            self._finalize()


class ShardedArrayIOPreparer:
    max_shard_size_bytes: int = DEFAULT_MAX_SHARD_SIZE_BYTES

    # ------------------------------------------------------------------ save

    @staticmethod
    def _elected_local_boxes(sharding, shape, addressable_shards):
        """Yield ``(box, data)`` for every unique shard box this process
        is ELECTED to act for: the dedup + hash-balanced election shared
        by the save-side writer partition (``_owned_pieces``) and
        restore-side distributed digest verification
        (``partial_digest_contributions``) — one definition, so the two
        sides can never disagree about ownership."""
        import jax

        process_index = jax.process_index()
        # box -> holder process indices (computed identically everywhere)
        holders: Dict[Box, List[int]] = {}
        for device, index in sharding.devices_indices_map(shape).items():
            box = _normalize_index(index, shape)
            holders.setdefault(box, []).append(device.process_index)
        local_data: Dict[Box, Any] = {}
        for shard in addressable_shards:
            box = _normalize_index(shard.index, shape)
            if box not in local_data:
                local_data[box] = shard.data
        for box in sorted(holders.keys()):
            if _stable_owner(box, holders[box]) != process_index:
                continue
            data = local_data.get(box)
            if data is None:  # pragma: no cover - owner is always a holder
                continue
            yield box, data

    @classmethod
    def _owned_pieces(cls, arr, itemsize: Optional[int] = None):
        """Yield ``(p_off, p_sz, get_piece)`` for every piece THIS process
        writes: its owned boxes (deduped, hash-balanced election), each
        subdivided to the shard size cap. ``get_piece`` is a thunk — the
        device-array slice only dispatches when called, so size-only
        consumers (the staging warmup) never materialize data. The single
        source of the write partition: prepare_write builds entries from
        it, warmup_staging sizes pool slabs from it. ``itemsize`` lets the
        warmup subdivide at the dtype a save_dtype-converted save will
        actually stage (boundaries depend on itemsize)."""
        shape = tuple(arr.shape)
        if itemsize is None:
            itemsize = string_to_dtype(dtype_to_string(arr.dtype)).itemsize

        for box, data in cls._elected_local_boxes(
            arr.sharding, shape, arr.addressable_shards
        ):
            offsets = [lo for lo, _ in box]
            sizes = [hi - lo for lo, hi in box]
            for p_off, p_sz in _subdivide(
                offsets, sizes, itemsize, cls.max_shard_size_bytes
            ):
                local_slices = tuple(
                    slice(po - o, po - o + ps)
                    for po, o, ps in zip(p_off, offsets, p_sz)
                )

                def get_piece(data=data, local_slices=local_slices):
                    return data[local_slices] if local_slices else data

                yield p_off, p_sz, get_piece

    @classmethod
    def staged_piece_sizes(cls, arr, dtype: Optional[str] = None) -> List[int]:
        """Byte sizes of the staging buffers this process will draw for
        ``arr`` (pool-warmup planning; no data is touched). ``dtype``
        overrides the array's own (save_dtype-converted saves)."""
        itemsize = string_to_dtype(
            dtype if dtype is not None else dtype_to_string(arr.dtype)
        ).itemsize
        sizes = []
        for _, p_sz, _ in cls._owned_pieces(arr, itemsize=itemsize):
            n = itemsize
            for s in p_sz:
                n *= s
            sizes.append(n)
        return sizes

    @classmethod
    def prepare_write(
        cls, storage_path_prefix: str, arr
    ) -> Tuple[ShardedArrayEntry, List[WriteReq]]:
        dtype_str = dtype_to_string(arr.dtype)
        shards: List[Shard] = []
        write_reqs: List[WriteReq] = []
        for p_off, p_sz, get_piece in cls._owned_pieces(arr):
            location = f"{storage_path_prefix}_{'_'.join(map(str, p_off))}"
            entry = ArrayEntry(
                location=location,
                serializer="buffer_protocol",
                dtype=dtype_str,
                shape=list(p_sz),
                replicated=False,
            )
            shards.append(Shard(offsets=list(p_off), sizes=list(p_sz), array=entry))
            write_reqs.append(
                WriteReq(
                    path=location,
                    buffer_stager=ArrayBufferStager(get_piece(), entry),
                )
            )
        return (
            ShardedArrayEntry(dtype=dtype_str, shape=list(arr.shape), shards=shards),
            write_reqs,
        )

    # --------------------------------------------------------------- restore

    @classmethod
    def _dst_already_matches(cls, entry: ShardedArrayEntry, obj_out) -> bool:
        """True when the destination already holds every saved piece's
        content, proven by on-device fingerprints (device_digest.py).

        Each rank verifies only pieces overlapping ITS addressable shards
        — remote pieces are verified (or read) by the rank that owns
        them; a skip here never changes what other ranks do, because the
        local decision only keeps/rebuilds the local handle of the same
        logical values. Conservative on every edge: a missing
        fingerprint, dtype difference, or a piece this rank cannot
        fingerprint locally means False (read normally).

        A piece is locally verifiable when it is contained in ONE
        addressable shard (zero-copy slice) or, failing that, when the
        UNION of this process's addressable shards covers it — the
        overlap regions are stitched together on device and the
        assembled piece fingerprinted (pod topologies: a process owning
        several boxes can verify across a layout change, e.g. a serving
        mesh transposed from the training mesh). Only a piece cut
        across PROCESS boundaries still falls back to a normal read:
        its digest covers the whole piece and no single process holds
        all of its bytes."""
        from ..device_digest import fingerprints_match

        if dtype_to_string(obj_out.dtype) != entry.dtype:
            return False
        shape = tuple(entry.shape)
        if getattr(obj_out, "is_fully_addressable", False):
            # Global slices work (XLA gathers across local devices), so
            # pieces from ANY saved sharding layout are verifiable.
            if not entry.shards or any(
                s.array.device_digest is None for s in entry.shards
            ):
                return False
            # Windowed: a few piece slices live at a time (dispatched
            # together per window, dropped before the next window), so
            # verification never duplicates the array's footprint.
            return fingerprints_match(
                (
                    (
                        array_size_bytes(s.sizes, entry.dtype),
                        lambda s=s: obj_out[
                            tuple(
                                slice(o, o + sz)
                                for o, sz in zip(s.offsets, s.sizes)
                            )
                        ],
                        s.array.device_digest,
                    )
                    for s in entry.shards
                )
            )
        # Multi-process: only shard.data (single-device) is sliceable.
        # Verify every piece overlapping an addressable box: contained in
        # one shard -> zero-copy slice; covered by the UNION of local
        # shards -> assembled on device; else unverifiable locally.
        local: Dict[Box, Any] = {}
        for s in obj_out.addressable_shards:
            local.setdefault(_normalize_index(s.index, shape), s.data)
        to_check: List[Tuple[int, Any, str]] = []  # (nbytes, thunk, digest)
        for shard in entry.shards:
            piece: Box = tuple(
                (o, o + sz) for o, sz in zip(shard.offsets, shard.sizes)
            )
            overlaps = [
                (box, ov)
                for box in local
                for ov in (_overlap(shard.offsets, shard.sizes, box),)
                if ov is not None
            ]
            if not overlaps:
                continue  # some other rank's piece
            if shard.array.device_digest is None:
                return False
            container = next(
                (
                    box
                    for box, _ in overlaps
                    if all(
                        lo >= blo and hi <= bhi
                        for (lo, hi), (blo, bhi) in zip(piece, box)
                    )
                ),
                None,
            )
            if container is not None:
                local_slices = tuple(
                    slice(lo - blo, hi - blo)
                    for (lo, hi), (blo, _) in zip(piece, container)
                )
                to_check.append(
                    (
                        array_size_bytes(shard.sizes, entry.dtype),
                        lambda c=container, ls=local_slices: local[c][ls],
                        shard.array.device_digest,
                    )
                )
                continue
            # Union coverage: distinct GSPMD boxes are disjoint, so the
            # piece is fully covered iff the overlap volumes sum to its
            # volume. A cell owned by another process means a shortfall
            # -> this piece is unverifiable here (digest spans bytes this
            # process doesn't hold).
            piece_vol = int(np.prod(shard.sizes, dtype=np.int64))
            covered = sum(
                int(
                    np.prod(
                        [s.stop - s.start for s in src], dtype=np.int64
                    )
                )
                for _, (src, _) in overlaps
            )
            if covered != piece_vol:
                return False
            piece_bytes = array_size_bytes(shard.sizes, entry.dtype)
            to_check.append(
                (
                    piece_bytes,
                    _make_assembler(local, overlaps, tuple(shard.sizes)),
                    shard.array.device_digest,
                    # Assembly transiently holds the zeroed piece PLUS
                    # device copies of the overlapping parts — ~2x the
                    # piece — so the window budget is charged 2x
                    # (ADVICE r5 low #2).
                    2 * piece_bytes,
                )
            )
        if not to_check:
            return False
        # Thunks: slices/assemblies materialize windowed inside
        # fingerprints_match, never all at once.
        return fingerprints_match(to_check)

    @classmethod
    def partial_digest_contributions(
        cls, entry: ShardedArrayEntry, obj_out
    ) -> "Optional[Dict[int, List[Tuple[str, int, Tuple[int, int, int, int]]]]]":
        """This process's contributions to DISTRIBUTED (zero-byte) digest
        verification of ``entry`` against ``obj_out``: for every unique
        destination box ELECTED to this process (the same hash election
        the save-side writer dedup uses), the partial fingerprint lanes
        of each saved piece's intersection with that box, tagged with the
        region's absolute offsets within the piece. Fingerprint lanes are
        additive over disjoint word covers (device_digest.py), so peers
        can sum every process's 16-byte partials and compare against the
        manifest — verifying a piece CUT ACROSS PROCESSES with no payload
        movement at all.

        Returns ``{piece_index: [(box_key, n_elements, lanes4), ...]}``
        (possibly empty — this process elected no boxes), or None when a
        region could not be fingerprinted on device; the caller then
        publishes non-participation so peers see incomplete coverage and
        fall back to normal reads. Dispatch is windowed: at most a few
        region slices are live at a time."""
        from ..device_digest import (
            MATCH_WINDOW,
            MATCH_WINDOW_BYTES,
            partial_dispatch,
            partial_fetch,
        )

        shape = tuple(entry.shape)
        itemsize = string_to_dtype(entry.dtype).itemsize

        # All (piece, elected-box) overlap regions, as geometry + data.
        work: List[Tuple[int, str, Tuple, Tuple, Any]] = []
        for box, data in cls._elected_local_boxes(
            obj_out.sharding, shape, obj_out.addressable_shards
        ):
            for i, shard in enumerate(entry.shards):
                ov = _overlap(shard.offsets, shard.sizes, box)
                if ov is None:
                    continue
                src_slices, dst_slices = ov
                n_elems = 1
                for sl in src_slices:
                    n_elems *= sl.stop - sl.start
                work.append(
                    (
                        i,
                        _box_key(box),
                        tuple(shard.sizes),
                        tuple(sl.start for sl in src_slices),
                        (data, dst_slices, n_elems),
                    )
                )

        out: Dict[int, List[Tuple[str, int, Tuple[int, int, int, int]]]] = {}
        # Windowed dispatch: same bounds as fingerprints_match.
        pos = 0
        while pos < len(work):
            batch = []
            batch_bytes = 0
            while (
                pos < len(work)
                and len(batch) < MATCH_WINDOW
                and batch_bytes < MATCH_WINDOW_BYTES
            ):
                i, box_key, piece_shape, offs, (data, dst_slices, n_elems) = (
                    work[pos]
                )
                nbytes = n_elems * itemsize
                if batch and batch_bytes + nbytes > MATCH_WINDOW_BYTES:
                    break
                region = data[dst_slices] if dst_slices else data
                pending = partial_dispatch(region, piece_shape, offs)
                del region
                if pending is None:
                    return None
                batch.append((i, box_key, n_elems, pending))
                batch_bytes += nbytes
                pos += 1
            for i, box_key, n_elems, pending in batch:
                out.setdefault(i, []).append(
                    (box_key, n_elems, partial_fetch(pending))
                )
        return out

    @classmethod
    def prepare_read(
        cls,
        entry: ShardedArrayEntry,
        obj_out: Any = None,
        callback: Optional[Callable[[Any], None]] = None,
        device_digests: bool = False,
        reshard: Optional[Any] = None,  # reshard.ReshardContext
    ) -> List[ReadReq]:
        shape = tuple(entry.shape)
        np_dtype = string_to_dtype(entry.dtype)

        from .prepare import check_restore_cast, is_jax_array

        if is_jax_array(obj_out):
            import jax

            if tuple(obj_out.shape) != shape:
                raise RuntimeError(
                    f"Shape mismatch restoring sharded array: snapshot has "
                    f"{list(shape)}, destination has {list(obj_out.shape)}."
                )
            if device_digests and cls._dst_already_matches(entry, obj_out):
                return []
            sharding = obj_out.sharding
            needs_cast = check_restore_cast(
                entry.dtype, obj_out.dtype, "sharded array into jax.Array"
            )
            dst_dtype = obj_out.dtype
            # one host buffer per unique addressable destination box
            boxes: Dict[Box, np.ndarray] = {}
            for device, index in sharding.addressable_devices_indices_map(
                shape
            ).items():
                box = _normalize_index(index, shape)
                if box not in boxes:
                    boxes[box] = np.empty(
                        tuple(hi - lo for lo, hi in box), dtype=np_dtype
                    )

            def finalize() -> None:
                def cb(index: Tuple[slice, ...]) -> np.ndarray:
                    return boxes[_normalize_index(index, shape)]

                restored = jax.make_array_from_callback(shape, sharding, cb)
                if needs_cast:
                    # Cast on device after the (narrower-dtype) transfer;
                    # astype preserves the destination sharding.
                    restored = restored.astype(dst_dtype)
                if callback is not None:
                    callback(restored)

            # Planned-peer source tier: with an active reshard context,
            # project EVERY rank's destination boxes out of the global
            # device->index map (identical on all ranks — no gather) and
            # let the planner claim multi-requester shards. Claimed
            # shards read from storage once (on the elected owner) and
            # arrive here as peer region bundles; everything else keeps
            # the direct tier below.
            reshard_roles = None
            if reshard is not None:
                global_boxes: Dict[int, set] = {}
                for device, index in sharding.devices_indices_map(
                    shape
                ).items():
                    global_boxes.setdefault(device.process_index, set()).add(
                        _normalize_index(index, shape)
                    )
                reshard_roles = reshard.plan_entry(
                    entry,
                    {r: sorted(bs) for r, bs in global_boxes.items()},
                )

            return cls._plan_scatter_reads(
                entry, boxes, finalize, reshard_roles=reshard_roles
            )

        # numpy / no destination: single box covering the whole array
        if isinstance(obj_out, np.ndarray) and obj_out.flags["WRITEABLE"]:
            if tuple(obj_out.shape) != shape:
                raise RuntimeError(
                    f"Shape mismatch restoring sharded array into numpy "
                    f"destination: {list(shape)} vs {list(obj_out.shape)}."
                )
            # The scatter copies cast element-wise into the destination's
            # dtype (fast_copyto, same_kind); fail before I/O if forbidden.
            check_restore_cast(
                entry.dtype, obj_out.dtype, "sharded array into numpy array"
            )
            dst = obj_out
        else:
            dst = np.empty(shape, dtype=np_dtype)
        whole: Box = tuple((0, dim) for dim in shape)
        boxes = {whole: dst}

        def finalize_np() -> None:
            if callback is not None:
                callback(dst)

        return cls._plan_scatter_reads(entry, boxes, finalize_np)

    @classmethod
    def _plan_scatter_reads(
        cls,
        entry: ShardedArrayEntry,
        boxes: Dict[Box, np.ndarray],
        finalize: Callable[[], None],
        reshard_roles: Optional[Dict[int, Any]] = None,
    ) -> List[ReadReq]:
        """One ReadReq per saved shard overlapping a destination box.

        ``reshard_roles`` (shard index -> reshard.OwnerUnit | RecvUnit)
        upgrades individual shards onto the planned-peer tier: an owner
        gets a forwarding consumer (reads storage, bundles regions out),
        a receiver gets a dual-mode consumer whose ReadReq still names
        the shard's real storage location — the peer path delivers a
        region bundle, and any peer failure re-reads the SAME request
        from storage (scheduler fallback), keeping correctness
        independent of the plan."""
        relevant: List[Tuple[int, Shard, List]] = []
        for i, shard in enumerate(entry.shards):
            targets = []
            for box, buf in boxes.items():
                ov = _overlap(shard.offsets, shard.sizes, box)
                if ov is not None:
                    src_slices, dst_slices = ov
                    targets.append((buf, src_slices, dst_slices))
            if targets:
                relevant.append((i, shard, targets))

        if not relevant:
            # nothing overlaps (e.g. zero-size destination) — finalize now
            finalize()
            return []

        completion = _Completion(len(relevant), finalize)
        read_reqs = []
        for i, shard, targets in relevant:
            consumer: Any = _ShardScatterConsumer(shard, targets, completion)
            role = reshard_roles.get(i) if reshard_roles else None
            if role is not None:
                from .. import reshard as reshard_mod

                if isinstance(role, reshard_mod.OwnerUnit):
                    consumer = reshard_mod.PlannedOwnerConsumer(
                        consumer, role
                    )
                else:
                    consumer = reshard_mod.PlannedRecvConsumer(
                        consumer, role, boxes
                    )
            byte_range = (
                tuple(shard.array.byte_range)
                if shard.array.byte_range is not None
                else None
            )
            read_reqs.append(
                ReadReq(
                    path=shard.array.location,
                    buffer_consumer=consumer,
                    byte_range=byte_range,
                    origin=shard.array.origin,
                )
            )
        return read_reqs
