"""Chunked-array preparer (reference: io_preparer.py:73-161).

Large non-sharded arrays are split into <=512 MB chunks along dim 0 so that
(a) replicated arrays can be striped across processes — each process writes a
disjoint subset of chunks and the manifests are merged — and (b) writes
pipeline through the budgeted scheduler instead of staging one giant buffer.

Chunk layout is recorded as N-D offsets/sizes (same schema as shards), so
restore is a region-fill of the destination and works for any chunk subset.

WITHIN a chunk, writes stream: each chunk's WriteReq carries an
ArrayBufferStager, whose sub-chunk streaming protocol
(``can_stream``/``stage_stream``, io_preparers/array.py) the scheduler
fuses with the storage write on sync takes — so even a single 512 MB
chunk's DtoH copy, serialization, and write overlap instead of
serializing (the chunk split bounds memory and enables striping; the
sub-chunk stream bounds the intra-chunk critical path).
"""

from __future__ import annotations

from typing import Callable, List, Optional, Tuple

import numpy as np

from ..io_types import ReadReq, WriteReq
from ..manifest import ArrayEntry, ChunkedArrayEntry, Shard
from ..serialization import array_size_bytes, dtype_to_string, string_to_dtype
from .array import ArrayAssembler, ArrayBufferStager, ArrayIOPreparer, array_nbytes

DEFAULT_MAX_CHUNK_SIZE_BYTES = 512 * 1024 * 1024


class _RegionConsumer:
    """Fills one N-D region of the destination via an ArrayAssembler."""

    def __init__(self, chunk: Shard, assembler: ArrayAssembler) -> None:
        self.chunk = chunk
        self.assembler = assembler

    def make_callback(self) -> Callable[[np.ndarray], None]:
        index = tuple(
            slice(o, o + s) for o, s in zip(self.chunk.offsets, self.chunk.sizes)
        )

        def cb(arr: np.ndarray) -> None:
            self.assembler.fill_region(index, arr)

        return cb


class ChunkedArrayIOPreparer:
    @staticmethod
    def chunk_ranges(
        shape: Tuple[int, ...],
        dtype_str: str,
        chunk_size_bytes: Optional[int] = None,
    ) -> List[Tuple[int, int]]:
        """[lo, hi) ranges along dim 0 such that each chunk <= chunk_size_bytes
        (single-row chunks if one row exceeds the limit)."""
        if chunk_size_bytes is None:
            # resolved at call time so tests can shrink the module constant
            chunk_size_bytes = DEFAULT_MAX_CHUNK_SIZE_BYTES
        if len(shape) == 0 or 0 in shape:
            return [(0, shape[0] if shape else 0)] if shape else []
        total_bytes = array_size_bytes(shape, dtype_str)
        row_bytes = total_bytes // shape[0] if shape[0] else total_bytes
        rows_per_chunk = max(1, chunk_size_bytes // max(row_bytes, 1))
        ranges = []
        lo = 0
        while lo < shape[0]:
            hi = min(lo + rows_per_chunk, shape[0])
            ranges.append((lo, hi))
            lo = hi
        return ranges

    @staticmethod
    def chunk_shards(
        shape: Tuple[int, ...],
        dtype_str: str,
        chunk_size_bytes: Optional[int] = None,
    ) -> List[Tuple[List[int], List[int]]]:
        """(offsets, sizes) per chunk; scalar arrays produce one empty-offset
        chunk covering the whole array."""
        if len(shape) == 0:
            return [([], [])]
        out = []
        for lo, hi in ChunkedArrayIOPreparer.chunk_ranges(shape, dtype_str, chunk_size_bytes):
            offsets = [lo] + [0] * (len(shape) - 1)
            sizes = [hi - lo] + list(shape[1:])
            out.append((offsets, sizes))
        return out

    @staticmethod
    def prepare_write(
        storage_path_prefix: str,
        arr,
        local_chunks: List[Tuple[List[int], List[int]]],
        replicated: bool = False,
    ) -> Tuple[ChunkedArrayEntry, List[WriteReq]]:
        """Write only ``local_chunks`` (this process's stripe) of ``arr``.

        The returned entry lists only the local chunks; the manifest gather
        merges stripes across processes into the full chunk set
        (reference: snapshot.py:954-986).
        """
        dtype_str = dtype_to_string(arr.dtype)
        chunks: List[Shard] = []
        write_reqs: List[WriteReq] = []
        for offsets, sizes in local_chunks:
            if offsets:
                index = tuple(slice(o, o + s) for o, s in zip(offsets, sizes))
                sub = arr[index]
            else:
                sub = arr
            suffix = "_".join(str(o) for o in offsets)
            location = (
                f"{storage_path_prefix}_{suffix}" if suffix else storage_path_prefix
            )
            chunk_entry, reqs = ArrayIOPreparer.prepare_write(
                location, sub, replicated=replicated
            )
            chunks.append(Shard(offsets=list(offsets), sizes=list(sizes), array=chunk_entry))
            write_reqs.extend(reqs)
        entry = ChunkedArrayEntry(
            dtype=dtype_str,
            shape=list(arr.shape),
            chunks=chunks,
            replicated=replicated,
        )
        return entry, write_reqs

    @staticmethod
    def prepare_read(
        entry: ChunkedArrayEntry,
        dst_view: Optional[np.ndarray] = None,
        callback: Optional[Callable[[np.ndarray], None]] = None,
        buffer_size_limit_bytes: Optional[int] = None,
        ensure_writable: bool = True,
        device_dest=None,
    ) -> List[ReadReq]:
        if len(entry.chunks) == 1 and list(entry.chunks[0].sizes) == list(
            entry.shape
        ):
            # Whole array in one chunk — the common case (anything under
            # the 512 MB chunk limit). Skip the assembler: its scratch is
            # a full extra memcpy pass per array (and for jax
            # destinations the device_put can consume a zero-copy view
            # over the read buffer directly). Semantics match the
            # assembler path: dst_view is filled in place, the callback
            # fires once with the complete array. device_dest forwards
            # only here — the multi-chunk path assembles regions on the
            # host and device_puts once via the completion callback.
            return ArrayIOPreparer.prepare_read(
                entry.chunks[0].array,
                dst_view=dst_view,
                callback=callback,
                buffer_size_limit_bytes=buffer_size_limit_bytes,
                ensure_writable=ensure_writable,
                device_dest=device_dest,
            )
        if dst_view is None:
            dst_view = np.empty(
                tuple(entry.shape), dtype=string_to_dtype(entry.dtype)
            )
        assembler = ArrayAssembler(
            dst_view, num_parts=len(entry.chunks), callback=callback
        )
        read_reqs: List[ReadReq] = []
        for chunk in entry.chunks:
            index = tuple(
                slice(o, o + s) for o, s in zip(chunk.offsets, chunk.sizes)
            )
            # Write through the assembler's target (its scratch when dst_view
            # is non-contiguous) — direct dst_view writes would be clobbered
            # by the assembler's completion copy-back.
            sub_dst = assembler.region_view(index if chunk.offsets else ())
            if buffer_size_limit_bytes is not None and sub_dst.flags["C_CONTIGUOUS"]:
                # Split this chunk's read into byte ranges under the budget;
                # the sub-assembler inside prepare_read notifies the outer
                # assembler once the whole chunk has landed.
                read_reqs.extend(
                    ArrayIOPreparer.prepare_read(
                        chunk.array,
                        dst_view=sub_dst,
                        callback=lambda _, a=assembler: a.part_done(),
                        buffer_size_limit_bytes=buffer_size_limit_bytes,
                    )
                )
            else:
                region = _RegionConsumer(chunk, assembler)
                read_reqs.extend(
                    ArrayIOPreparer.prepare_read(
                        chunk.array, callback=region.make_callback()
                    )
                )
        return read_reqs


def get_chunked_array_size(entry: ChunkedArrayEntry) -> int:
    return array_size_bytes(entry.shape, entry.dtype)
