from .array import ArrayBufferConsumer, ArrayBufferStager, ArrayIOPreparer
from .chunked import ChunkedArrayIOPreparer
from .object import ObjectBufferConsumer, ObjectIOPreparer
from .primitive import PrimitivePreparer
from .prepare import (
    get_storage_path,
    is_partitionable_array,
    is_sharded_jax_array,
    prepare_read,
    prepare_write,
)

__all__ = [
    "ArrayBufferConsumer",
    "ArrayBufferStager",
    "ArrayIOPreparer",
    "ChunkedArrayIOPreparer",
    "ObjectBufferConsumer",
    "ObjectIOPreparer",
    "PrimitivePreparer",
    "get_storage_path",
    "is_partitionable_array",
    "is_sharded_jax_array",
    "prepare_read",
    "prepare_write",
]
