"""Arbitrary-object preparer (reference: io_preparer.py:728-799).

Objects are pickled. Since objects can't be restored in place, the consumer
reports the deserialized value through a callback which the orchestrator uses
to replace the flattened value before inflate (reference wiring:
snapshot.py:736-745).
"""

from __future__ import annotations

import asyncio
from typing import Any, Callable, List, Optional, Tuple

from ..io_types import BufferConsumer, BufferStager, BufferType, ReadReq, WriteReq
from ..manifest import ObjectEntry
from ..serialization import Serializer, object_as_bytes, object_from_bytes


class ObjectBufferStager(BufferStager):
    def __init__(self, obj: Any, entry: Optional[ObjectEntry] = None) -> None:
        self.obj = obj
        self.entry = entry  # checksum recorded at stage time when given
        self._size_estimate: Optional[int] = None

    def _stage_and_sum(self) -> BufferType:
        buf = object_as_bytes(self.obj)
        if self.entry is not None:
            from ..integrity import checksums_enabled, compute_checksum

            if checksums_enabled():
                self.entry.checksum = compute_checksum(buf)
        return buf

    async def stage_buffer(self, executor=None) -> BufferType:
        if executor is not None:
            loop = asyncio.get_running_loop()
            return await loop.run_in_executor(executor, self._stage_and_sum)
        return self._stage_and_sum()

    def get_staging_cost_bytes(self) -> int:
        if self._size_estimate is None:
            try:
                import sys

                self._size_estimate = max(sys.getsizeof(self.obj), 1024)
            except TypeError:  # pragma: no cover
                self._size_estimate = 1024
        return self._size_estimate


class ObjectBufferConsumer(BufferConsumer):
    def __init__(self, entry: ObjectEntry) -> None:
        self.entry = entry
        self._callback: Optional[Callable[[Any], None]] = None

    def set_consume_callback(self, callback: Callable[[Any], None]) -> None:
        self._callback = callback

    def _verify_and_load(self, buf: BufferType) -> Any:
        if self.entry.checksum is not None:
            from ..integrity import verification_enabled, verify_checksum

            if verification_enabled():
                verify_checksum(buf, self.entry.checksum, self.entry.location)
        return object_from_bytes(buf)

    async def consume_buffer(self, buf: BufferType, executor=None) -> None:
        if executor is not None:
            loop = asyncio.get_running_loop()
            obj = await loop.run_in_executor(executor, self._verify_and_load, buf)
        else:
            obj = self._verify_and_load(buf)
        if self._callback is not None:
            self._callback(obj)

    def get_consuming_cost_bytes(self) -> int:
        return 1024  # unknown until deserialized; objects are small in practice


class ObjectIOPreparer:
    @staticmethod
    def prepare_write(
        storage_path: str, obj: Any, replicated: bool = False
    ) -> Tuple[ObjectEntry, List[WriteReq]]:
        entry = ObjectEntry(
            location=storage_path,
            serializer=Serializer.PICKLE.value,
            obj_type=type(obj).__name__,
            replicated=replicated,
        )
        return entry, [
            WriteReq(path=storage_path, buffer_stager=ObjectBufferStager(obj, entry))
        ]

    @staticmethod
    def prepare_read(entry: ObjectEntry) -> Tuple[List[ReadReq], ObjectBufferConsumer]:
        consumer = ObjectBufferConsumer(entry)
        return [ReadReq(path=entry.location, buffer_consumer=consumer)], consumer
