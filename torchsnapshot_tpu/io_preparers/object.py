"""Arbitrary-object preparer (reference: io_preparer.py:728-799).

Objects are pickled. Since objects can't be restored in place, the consumer
reports the deserialized value through a callback which the orchestrator uses
to replace the flattened value before inflate (reference wiring:
snapshot.py:736-745).
"""

from __future__ import annotations

import asyncio
import pickle
from typing import Any, Callable, List, Optional, Tuple

from ..io_types import BufferConsumer, BufferStager, BufferType, ReadReq, WriteReq
from ..manifest import ObjectEntry
from ..serialization import Serializer, object_as_bytes, object_from_bytes


# Below this serialized size the cost probe keeps the pickled bytes for
# reuse at stage time (most objects are small — one pickle total). Above
# it, only the size is kept: the probe must not hold GB-scale buffers
# outside the scheduler's budget accounting, so large objects pay a second
# pickle at stage time — the price of correct budgeting.
_PROBE_CACHE_LIMIT_BYTES = 4 * 1024 * 1024


class _CountingSink:
    """A pickle sink that counts bytes, buffering them only while the total
    stays under ``limit``: measures the true serialized size ahead of
    staging (the reference's cost model keyed off tensor bytes with a 2x
    torch.save factor, io_preparer.py:540-548; pickle lets us measure
    exactly), caching small payloads to avoid a double pickle."""

    __slots__ = ("nbytes", "_parts", "_limit")

    def __init__(self, limit: int = 0) -> None:
        self.nbytes = 0
        self._limit = limit
        self._parts: Optional[list] = [] if limit > 0 else None

    def write(self, b: bytes) -> int:
        self.nbytes += len(b)
        if self._parts is not None:
            if self.nbytes <= self._limit:
                self._parts.append(bytes(b))
            else:
                self._parts = None  # crossed the limit: stop buffering
        return len(b)

    def payload(self) -> Optional[bytes]:
        return b"".join(self._parts) if self._parts is not None else None


def serialized_size_bytes(obj: Any) -> int:
    sink = _CountingSink()
    pickle.dump(obj, sink, protocol=pickle.HIGHEST_PROTOCOL)
    return sink.nbytes


class ObjectBufferStager(BufferStager):
    def __init__(self, obj: Any, entry: Optional[ObjectEntry] = None) -> None:
        self.obj = obj
        self.entry = entry  # checksum + size recorded at stage time when given
        self._size_estimate: Optional[int] = None
        self._probed_bytes: Optional[bytes] = None
        from ..compression import active_codec
        from ..dedup import active_dedup_context

        self.dedup = active_dedup_context()
        self.codec = active_codec()
        self.io_skipped = False

    def _stage_and_sum(self) -> BufferType:
        if self._probed_bytes is not None:
            buf: BufferType = self._probed_bytes
            self._probed_bytes = None
        else:
            buf = object_as_bytes(self.obj)
        if self.entry is not None:
            # size records the SERIALIZED (uncompressed) bytes — it feeds
            # restore cost models and dedup size paranoia, both of which
            # reason about the logical payload.
            self.entry.size = len(buf)
            from ..integrity import checksums_enabled, compute_checksum

            if self.dedup is not None:
                from ..dedup import compute_digest

                digest = compute_digest(buf)  # uncompressed content
                self.entry.digest = digest
                ref = self.dedup.match(self.entry.location, digest, len(buf))
                if ref is not None:
                    # See ArrayBufferStager: the base's stored checksum/
                    # codec describe what restore will read; a raw
                    # checksum-less base falls back to hashing the staged
                    # (identical) bytes.
                    self.entry.origin = ref.origin
                    self.entry.codec = ref.codec
                    if ref.location is not None:
                        # Pool-swept bases store under ``po/<hex>`` — see
                        # ArrayBufferStager.
                        self.entry.location = ref.location
                    if ref.checksum is None and ref.codec is None:
                        if checksums_enabled():
                            self.entry.checksum = compute_checksum(buf)
                    else:
                        self.entry.checksum = ref.checksum
                    self.io_skipped = True
                    return buf
            from ..compression import MIN_COMPRESS_BYTES, compress

            # Objects are never slab-batched (the batcher packs arrays
            # only), so no byte_range gate is needed here.
            if self.codec is not None and len(buf) >= MIN_COMPRESS_BYTES:
                packed = compress(self.codec, buf)
                if len(packed) < len(buf):
                    self.entry.codec = self.codec
                    buf = packed
            if checksums_enabled():
                self.entry.checksum = compute_checksum(buf)  # stored bytes
        return buf

    async def stage_buffer(self, executor=None) -> BufferType:
        if executor is not None:
            loop = asyncio.get_running_loop()
            return await loop.run_in_executor(executor, self._stage_and_sum)
        return self._stage_and_sum()

    def get_staging_cost_bytes(self) -> int:
        if self._size_estimate is None:
            try:
                sink = _CountingSink(limit=_PROBE_CACHE_LIMIT_BYTES)
                pickle.dump(self.obj, sink, protocol=pickle.HIGHEST_PROTOCOL)
                self._size_estimate = max(sink.nbytes, 1024)
                self._probed_bytes = sink.payload()
            except Exception:
                # Unpicklable here -> staging will raise the real error;
                # don't let the cost probe mask it.
                self._size_estimate = 1024
        return self._size_estimate


class ObjectBufferConsumer(BufferConsumer):
    def __init__(self, entry: ObjectEntry) -> None:
        self.entry = entry
        self._callback: Optional[Callable[[Any], None]] = None

    def set_consume_callback(self, callback: Callable[[Any], None]) -> None:
        self._callback = callback

    def _verify_and_load(self, buf: BufferType) -> Any:
        if self.entry.checksum is not None:
            from ..integrity import verification_enabled, verify_checksum

            if verification_enabled():
                verify_checksum(buf, self.entry.checksum, self.entry.location)
        if self.entry.codec is not None:
            from ..compression import decompress

            buf = decompress(self.entry.codec, buf, expected_size=self.entry.size)
        return object_from_bytes(buf)

    async def consume_buffer(self, buf: BufferType, executor=None) -> None:
        if executor is not None:
            loop = asyncio.get_running_loop()
            obj = await loop.run_in_executor(executor, self._verify_and_load, buf)
        else:
            obj = self._verify_and_load(buf)
        if self._callback is not None:
            self._callback(obj)

    def get_consuming_cost_bytes(self) -> int:
        # The entry records the exact serialized size at stage time; ~2x for
        # the deserialized object alive alongside the buffer.
        if self.entry.size is not None:
            return max(2 * self.entry.size, 1024)
        return 1024  # legacy manifest without a recorded size


class ObjectIOPreparer:
    @staticmethod
    def prepare_write(
        storage_path: str, obj: Any, replicated: bool = False
    ) -> Tuple[ObjectEntry, List[WriteReq]]:
        entry = ObjectEntry(
            location=storage_path,
            serializer=Serializer.PICKLE.value,
            obj_type=type(obj).__name__,
            replicated=replicated,
        )
        return entry, [
            WriteReq(path=storage_path, buffer_stager=ObjectBufferStager(obj, entry))
        ]

    @staticmethod
    def prepare_read(entry: ObjectEntry) -> Tuple[List[ReadReq], ObjectBufferConsumer]:
        consumer = ObjectBufferConsumer(entry)
        return [
            ReadReq(
                path=entry.location, buffer_consumer=consumer, origin=entry.origin
            )
        ], consumer
