"""Device-resident array fingerprints for incremental change detection.

The host-side dedup path (dedup.py) must pay the full DtoH transfer and a
SHA-256 pass before it can discover a payload is unchanged — on TPU the
DtoH copy is exactly the scarce resource checkpointing tries to conserve
(SURVEY §7's central hard-part; the reference's CUDA analogue stages
through pinned host memory the same way, io_preparer.py:513-523). This
module computes a 128-bit position-dependent integer fingerprint of an
array ON DEVICE — one pass over the bytes at HBM bandwidth, all VPU
integer ops — and fetches only the 16-byte result. When the fingerprint
matches the one the base snapshot recorded for the same storage location,
staging skips the DtoH copy AND the storage write.

Trust model: the fingerprint is NOT cryptographic. Four independently
seeded 32-bit mixing lanes over position-tagged words give ~2^-128
collision odds for random (non-adversarial) changes — ample for "did
training mutate this weight" — but an adversary could construct a
collision. Device digests are therefore opt-in
(``Snapshot.take(..., device_digests=True)`` or
``TORCHSNAPSHOT_TPU_DEVICE_DIGESTS=1``); the default dedup path keeps
hashing the exact staged bytes with SHA-256.

Determinism: every op is integer arithmetic with defined wraparound
(xor/shift/multiply mod 2^32) — bit-identical across runs, backends
(CPU/TPU), and jit recompiles, so fingerprints recorded on one backend
match recomputations on another.
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

PREFIX = "xxh4x32"  # fingerprint scheme tag recorded in manifests

# lowbias32 (Degski) finalizer constants + four lane seeds.
_M1 = np.uint32(0x7FEB352D)
_M2 = np.uint32(0x846CA68B)
_GOLDEN = np.uint32(0x9E3779B9)
_SEEDS = (
    np.uint32(0x85EBCA6B),
    np.uint32(0xC2B2AE35),
    np.uint32(0x27D4EB2F),
    np.uint32(0x165667B1),
)


def enabled_by_env() -> bool:
    # Falsy spellings match the repo's other flags (integrity._env_on,
    # batcher.batching_enabled): an explicit "false" must never turn the
    # opt-in trust model ON.
    return os.environ.get("TORCHSNAPSHOT_TPU_DEVICE_DIGESTS", "0") not in (
        "0",
        "",
        "false",
    )


def _mix32(x):
    """Vectorized 32-bit finalizer (lowbias32): every input bit affects
    every output bit. Works on jax uint32 arrays inside jit and on numpy
    uint32 scalars outside (same wraparound semantics)."""
    x = x ^ (x >> 16)
    x = x * _M1
    x = x ^ (x >> 15)
    x = x * _M2
    x = x ^ (x >> 16)
    return x


def _fingerprint_jit(u32):
    """Core: position-tagged mix + wrapping sum per lane. ``u32`` is a
    1-D uint32 array. jit caches per input length — states have fixed
    shapes, so each array compiles once per training run."""
    import jax.numpy as jnp
    from jax import lax

    n = u32.shape[0]
    idx = lax.iota(jnp.uint32, n)
    lanes = []
    for seed in _SEEDS:
        tag = _mix32(idx * _GOLDEN + seed)
        # Wrapping uint32 sum of well-mixed position-tagged words: a
        # commutative reduce XLA turns into a fast tree reduction, with
        # position sensitivity carried by the tag.
        lanes.append(jnp.sum(_mix32(u32 ^ tag), dtype=jnp.uint32))
    return jnp.stack(lanes)


_jitted = None


def _get_jitted():
    global _jitted
    if _jitted is None:
        import jax

        _jitted = jax.jit(_fingerprint_jit)
    return _jitted


def _as_uint32_words(arr):
    """Bitcast any array to a 1-D uint32 word stream on device.

    Elements narrower than 32 bits are zero-extended per element (the
    stream is then not byte-dense, but it is a fixed deterministic
    function of the bytes, which is all a fingerprint needs); 64-bit
    elements split into two words. Raises TypeError for dtypes without a
    clean bitcast (sub-byte int4 packings).
    """
    import jax.numpy as jnp
    from jax import lax

    flat = arr.reshape(-1)
    itemsize = np.dtype(arr.dtype).itemsize if hasattr(arr.dtype, "itemsize") else 0
    if flat.dtype == jnp.bool_:
        return flat.astype(jnp.uint32)
    if itemsize == 1:
        return lax.bitcast_convert_type(flat, jnp.uint8).astype(jnp.uint32)
    if itemsize == 2:
        return lax.bitcast_convert_type(flat, jnp.uint16).astype(jnp.uint32)
    if itemsize == 4:
        return lax.bitcast_convert_type(flat, jnp.uint32)
    if itemsize == 8:
        # Adds a trailing axis of two uint32 words per element.
        return lax.bitcast_convert_type(flat, jnp.uint32).reshape(-1)
    raise TypeError(f"no uint32 bitcast for dtype {arr.dtype}")


def _dispatch(arr):
    """Kick the fingerprint computation for ``arr`` without blocking.
    Returns the in-flight device lanes array, or None if ``arr`` cannot
    be fingerprinted on device."""
    import jax

    if not isinstance(arr, jax.Array):
        return None
    if not getattr(arr, "is_fully_addressable", False):
        return None
    try:
        return _get_jitted()(_as_uint32_words(arr))
    except (TypeError, ValueError):
        # TypeError: our own rejection (no clean bitcast). ValueError: jax's
        # bitcast shape rule rejecting sub-byte packings (int4/uint4 report
        # itemsize 1 but cannot widen elementwise to uint8).
        return None


def _fold_lanes(lanes, nbytes: int) -> str:
    """Fold the byte length into 4 summed lanes and format the digest.
    THE single definition of the final fold: device_fingerprint and the
    distributed combine_partials must agree bit-exactly or cross-process
    verdicts would silently diverge from recorded fingerprints."""
    with np.errstate(over="ignore"):
        final = [
            np.uint32(lane) ^ _mix32(np.uint32(nbytes & 0xFFFFFFFF) ^ seed)
            for lane, seed in zip(np.asarray(lanes, np.uint32), _SEEDS)
        ]
    return PREFIX + ":" + "".join(f"{int(v):08x}" for v in final)


def _finalize_from_nbytes(nbytes: int, pending) -> str:
    """Fetch a dispatched computation's 16 bytes and fold in the length
    (folding on the host: the length is static per shape, and equal word
    streams of different underlying sizes stay distinct)."""
    import jax

    return _fold_lanes(jax.device_get(pending), nbytes)


def _nbytes(arr) -> int:
    return int(np.dtype(arr.dtype).itemsize) * int(
        np.prod(arr.shape, dtype=np.int64)
    )


def _finalize(arr, pending) -> str:
    return _finalize_from_nbytes(_nbytes(arr), pending)


# -------------------------------------------------------- partial lanes
#
# The lanes are COMMUTATIVE uint32 sums over position-tagged words, so a
# piece's fingerprint is ADDITIVE over any disjoint cover of its word
# stream: fingerprint(piece) = fold(sum of partial_lanes(region_i)) for
# regions partitioning the piece, each tagged with its words' absolute
# indices in the piece. This is what lets a piece CUT ACROSS PROCESSES
# be verified with zero payload movement — every process computes the
# 16-byte partial sum over the sub-region it holds, the partials ride
# the coordination plane, and their wrapping sum (plus the length fold)
# must equal the manifest's recorded fingerprint.


def _partial_jit(region, offsets, strides):
    """Lanes contribution of ``region``, an N-D sub-block of a piece:
    identical math to ``_fingerprint_jit`` except each word's tag uses
    its absolute index in the PIECE's row-major word stream, computed
    from the region's ``offsets`` and the piece's row-major ``strides``
    (both uint32 vectors, dynamic so same-shaped regions share one
    compilation)."""
    import jax.numpy as jnp
    from jax import lax

    words = _as_uint32_words(region)
    n_elem = 1
    for s in region.shape:
        n_elem *= s
    wpe = words.shape[0] // max(n_elem, 1)  # words per element (1 or 2)
    e = jnp.zeros(region.shape, jnp.uint32)
    for d in range(region.ndim):
        e = e + (
            offsets[d] + lax.broadcasted_iota(jnp.uint32, region.shape, d)
        ) * strides[d]
    if wpe == 1:
        w = e.reshape(-1)
    else:
        w = (
            e.reshape(-1, 1) * jnp.uint32(wpe)
            + lax.iota(jnp.uint32, wpe)[None, :]
        ).reshape(-1)
    lanes = []
    for seed in _SEEDS:
        tag = _mix32(w * _GOLDEN + seed)
        lanes.append(jnp.sum(_mix32(words ^ tag), dtype=jnp.uint32))
    return jnp.stack(lanes)


_partial_jitted = None


def _get_partial_jitted():
    global _partial_jitted
    if _partial_jitted is None:
        import jax

        _partial_jitted = jax.jit(_partial_jit)
    return _partial_jitted


def partial_dispatch(region, piece_shape, region_offsets):
    """Kick the partial-lanes computation for ``region``, located at
    ``region_offsets`` within a piece of shape ``piece_shape``. Returns
    the in-flight device lanes, or None when the region cannot be
    fingerprinted on device."""
    import jax
    import jax.numpy as jnp

    if not isinstance(region, jax.Array):
        return None
    if not getattr(region, "is_fully_addressable", False):
        return None
    strides = []
    acc = 1
    for dim in reversed(tuple(piece_shape)):
        strides.append(acc)
        acc *= int(dim)
    strides = list(reversed(strides))
    try:
        return _get_partial_jitted()(
            region,
            jnp.asarray(np.asarray(region_offsets, np.uint32)),
            jnp.asarray(np.asarray(strides, np.uint32)),
        )
    except (TypeError, ValueError):
        return None


def partial_fetch(pending) -> "tuple[int, int, int, int]":
    """Fetch a dispatched partial's 16 bytes (4 uint32 lanes)."""
    import jax

    lanes = np.asarray(jax.device_get(pending), dtype=np.uint32)
    return tuple(int(v) for v in lanes)


def combine_partials(lane_groups, nbytes: int) -> str:
    """Wrapping-sum partial lanes covering a whole piece and fold the
    piece's byte length — equals the piece's ``device_fingerprint`` by
    lane additivity. ``lane_groups``: iterables of 4 ints each."""
    total = np.zeros(4, np.uint32)
    with np.errstate(over="ignore"):
        for lanes in lane_groups:
            total = total + np.asarray(lanes, np.uint32)
    return _fold_lanes(total, nbytes)


_HASH_PROBE_BYTES = 16 << 20
_hash_probe_done = False


def probe_hash_throughput() -> Optional[float]:
    """One-time on-device fingerprint throughput probe, recorded into the
    scheduler's I/O governor. The restore-side preverify gate needs the
    hash side of its hash-vs-read crossover even when no fingerprint
    warmup ran in this process; a single ~16 MB fingerprint (dispatched
    twice — the first pays the jit compile, the second is the measured
    steady state) settles it for the process lifetime. Returns the
    measured bytes/sec, or None when no device fingerprinting is
    available (the gate then keeps the status-quo verify)."""
    global _hash_probe_done
    if _hash_probe_done:
        from .scheduler import io_governor

        return io_governor().hash_bps()
    _hash_probe_done = True
    try:
        import jax
        import jax.numpy as jnp

        from . import telemetry

        arr = jnp.zeros((_HASH_PROBE_BYTES // 4,), jnp.uint32)
        jax.block_until_ready(arr)
        pending = _dispatch(arr)  # compile pass, untimed
        if pending is None:
            return None
        jax.block_until_ready(pending)
        t0 = telemetry.monotonic()
        jax.block_until_ready(_dispatch(arr))
        dt = telemetry.monotonic() - t0
        # Importing the scheduler registers the governor's bus listener
        # before the rate is published.
        from .scheduler import io_governor

        governor = io_governor()
        telemetry.record_rate("hash", None, _HASH_PROBE_BYTES, dt)
        return governor.hash_bps()
    except Exception:  # pragma: no cover - probe must never break restore
        return None


def device_fingerprint(arr) -> Optional[str]:
    """128-bit fingerprint of a (fully addressable) jax array's content,
    computed on device; only 16 bytes cross to the host.

    Returns ``"xxh4x32:<32 hex>"``, or None when the array cannot be
    fingerprinted on device (unsupported dtype, non-addressable shards) —
    callers fall back to the host SHA-256 path.
    """
    pending = _dispatch(arr)
    if pending is None:
        return None
    return _finalize(arr, pending)


def fingerprint_any(value) -> "tuple[str, str]":
    """Content fingerprint + leaf kind (``"array"`` | ``"object"``) for ANY
    state leaf — the delta journal's dirty detector (journal.py).

    jax arrays use the on-device digest when dispatchable (no DtoH copy);
    host-visible arrays hash their exact bytes; everything else (python
    scalars, opaque objects) hashes its pickle. The kind tells the journal
    which serialization path round-trips the leaf.
    """
    fp = device_fingerprint(value)
    if fp is not None:
        return fp, "array"
    import hashlib

    from . import serialization

    arr = None
    if isinstance(value, np.ndarray):
        arr = value
    elif type(value).__module__.split(".")[0] == "jax" and hasattr(value, "dtype"):
        try:
            arr = np.asarray(value)
        except Exception:
            arr = None
    if arr is not None:
        data = serialization.array_as_memoryview(np.ascontiguousarray(arr))
        return "sha256:" + hashlib.sha256(data).hexdigest(), "array"
    buf = serialization.object_as_bytes(value)
    return "sha256:" + hashlib.sha256(buf).hexdigest(), "object"


# Restore-side verification window: at most MATCH_WINDOW slices AND
# MATCH_WINDOW_BYTES of slice data in flight per batch. The count bound
# amortizes the host<->device roundtrip; the BYTE bound is what actually
# limits transient device memory — sharded pieces (unlike <=512 MB
# chunks) have no size cap of their own, so a count-only window could
# still hold the whole array's footprint in slice copies.
MATCH_WINDOW = 4
MATCH_WINDOW_BYTES = 512 * 1024 * 1024


def fingerprints_match(
    items, window: int = MATCH_WINDOW, window_bytes: int = MATCH_WINDOW_BYTES
) -> bool:
    """Bounded-memory fingerprint comparison for restore-side skips.

    ``items`` is an iterable of ``(nbytes, get_slice, expected)`` or
    ``(nbytes, get_slice, expected, cost_bytes)``: ``nbytes`` the
    slice's byte size (callers know it from the manifest geometry —
    shapes x dtype — without touching the device; it must equal the
    materialized slice's size, since the digest folds the length in),
    ``get_slice`` a thunk producing the device slice to verify,
    ``expected`` the manifest-recorded digest, and ``cost_bytes`` the
    item's TRANSIENT device footprint when it exceeds ``nbytes`` —
    assembled pieces (see sharded._make_assembler) hold the zeroed
    assembly target plus device copies of the overlapping parts, ~2x
    their logical size, and must say so or a window of them would
    transiently reach ~2x the documented bound. A window of slices is
    dispatched together before the first 16-byte fetch — ~one
    host<->device roundtrip per window, not per slice (the roundtrip,
    not the hash, dominates for small/medium slices on tunneled links) —
    then the slice references are dropped before the next window
    materializes. A window closes at ``window`` slices or before the
    slice that would push it past ``window_bytes`` of COST (a single
    over-budget slice still goes alone); the budget check runs BEFORE
    ``get_slice``, so nothing is materialized twice and transient device
    memory never exceeds ~window_bytes — not the array's whole
    footprint. Returns False on the first mismatch or unfingerprintable
    slice (callers fall back to a normal read); remaining windows are
    never materialized.
    """
    if window < 1 or window_bytes < 1:
        # An empty first window would return True with ZERO verification
        # — a silent skip of arbitrary content.
        raise ValueError(
            f"window and window_bytes must be >= 1, got {window}/{window_bytes}"
        )
    it = iter(items)
    carried = None  # the item that overflowed the previous window's budget
    while True:
        pendings = []
        batch_bytes = 0
        while len(pendings) < window and batch_bytes < window_bytes:
            if carried is not None:
                item = carried
                carried = None
            else:
                try:
                    item = next(it)
                except StopIteration:
                    break
            nbytes, get_slice, expected = item[0], item[1], item[2]
            cost = item[3] if len(item) > 3 else nbytes
            if pendings and batch_bytes + cost > window_bytes:
                # Over budget with work already in flight: finalize the
                # current window first. Nothing was materialized for this
                # item yet — the size came from the manifest.
                carried = item
                break
            arr = get_slice()
            pending = _dispatch(arr)
            if pending is None:
                return False
            # Keep only (pending, nbytes): the slice buffer itself can be
            # freed as soon as the jit consumes it.
            pendings.append((pending, nbytes, expected))
            batch_bytes += cost
            del arr
        if not pendings:
            return True
        for pending, nbytes, expected in pendings:
            if _finalize_from_nbytes(nbytes, pending) != expected:
                return False
