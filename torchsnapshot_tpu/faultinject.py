"""Deterministic fault injection for the snapshot pipeline.

The library's single product is fault tolerance — a snapshot either
commits atomically or leaves nothing behind — yet until this subsystem
every fallback path (mirror failover, cooperative-restore degradation,
commit abort) was exercised only by hand-rolled monkeypatching in the
test that happened to think of it. This module makes faults a
first-class, *reproducible* input: named injection sites are threaded
through every I/O and coordination boundary, and a seeded, env-configured
fault plan decides — deterministically — which hits of which sites
misbehave, and how.

Design rules (mirroring telemetry/core.py, the other cross-cutting
subsystem):

1. **Near-zero overhead when disabled.** Production code calls
   :func:`site` / :func:`mutate` on per-sub-chunk hot paths; with no
   plan configured (the default) each call is one module-global ``None``
   check. No allocation, no lock, no clock read.
2. **Strictly stdlib, device-free.** The injector is imported by
   ``dist_store.py`` (the peer plane, which must never import jax) and
   by the fs plugin (which must import in hermetic containers).
3. **Deterministic.** Hit counters are per-site and exact; probabilistic
   triggers and corrupt offsets draw from one seeded RNG, so a fault
   schedule replays bit-identically from its plan string.
4. **One shim.** Production modules may only call :func:`site` and
   :func:`mutate`; the registry below is the single source of site
   names, and ``scripts/check_fault_sites.py`` (tier-1-enforced)
   verifies every call site uses a unique registered literal and that
   nothing reaches past the shim.

Plan grammar (``TORCHSNAPSHOT_TPU_FAULT_PLAN``, or :func:`configure`)::

    PLAN    := RULE (';' RULE)* [';' 'seed=' INT]
    RULE    := SITE '@' TRIGGER '=' ACTION [':' ARG]
    TRIGGER := N            -- exactly the Nth hit of the site (1-based)
             | N '+'        -- the Nth hit and every one after it
             | 'p' FLOAT    -- each hit independently with probability FLOAT
    ACTION  := 'transient'  -- raise InjectedTransientError (retryable class)
             | 'permanent'  -- raise InjectedPermanentError (OSError class)
             | 'delay'      -- sleep ARG seconds (default 0.05)
             | 'corrupt'    -- flip one byte (ARG = offset; default seeded)
             | 'truncate'   -- keep ARG fraction of the bytes (default 0.5)
             | 'kill'       -- SIGKILL this process at the site

Examples::

    TORCHSNAPSHOT_TPU_FAULT_PLAN='fs.pwrite@2=transient'
    TORCHSNAPSHOT_TPU_FAULT_PLAN='commit.metadata@1=kill'
    TORCHSNAPSHOT_TPU_FAULT_PLAN='s3.put_part@p0.3=transient;seed=7'
    TORCHSNAPSHOT_TPU_FAULT_PLAN='fs.pread@3=corrupt;mirror.primary_read@1+=permanent'

``corrupt``/``truncate`` only act at *data* sites (those whose call goes
through :func:`mutate`); at control sites they log once and do nothing.
See docs/source/fault_tolerance.rst for the failure model this drives.
"""

from __future__ import annotations

import logging
import os
import random
import signal
import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)

FAULT_PLAN_ENV_VAR = "TORCHSNAPSHOT_TPU_FAULT_PLAN"

# The site registry: every injection point in the package, by name. A
# site is "data" when its call passes payload bytes through mutate()
# (corrupt/truncate act there) and "control" when it only raises/delays/
# kills. scripts/check_fault_sites.py pins the package's call sites to
# exactly this set — a new site must be registered here first, and a
# registered site must actually be wired.
SITES: Dict[str, str] = {
    # filesystem plugin
    "fs.write": "data",           # buffered temp-file write
    "fs.pwrite": "data",          # streamed sub-chunk positional write
    "fs.read": "data",            # buffered / mmap read
    "fs.pread": "data",           # streamed sub-chunk positional read
    "fs.native_pwrite": "data",   # native-engine (io_uring) sub-chunk write
    "fs.native_pread": "data",    # native-engine (io_uring) sub-chunk read
    # s3 plugin
    "s3.put": "data",             # single-request PUT
    "s3.put_part": "data",        # streaming multipart part upload
    "s3.get": "data",             # (ranged) GET
    # gcs plugin
    "gcs.resumable_feed": "data",  # chunk fed to the resumable upload
    "gcs.get": "data",            # (ranged) download
    # two-tier mirror
    "mirror.primary_read": "control",
    # coordination plane
    "dist_store.rpc": "control",  # every KV-store client round trip
    "dist_store.serve_op": "control",  # server-side dispatch of one op
    "dist_store.replica_rpc": "control",  # leader->replica op-log message
    "dist_store.lease_renew": "control",  # leader lease-renewal round
    "peer.send_frame": "data",    # fan-out peer channel, sender side
    "peer.recv_frame": "control",  # fan-out peer channel, receiver side
    # pipeline
    "scheduler.stage": "control",  # per-entry staging admission
    "commit.metadata": "data",    # the .snapshot_metadata commit point
    # planned-reshard tier (reshard.py): the owner-side bundle just
    # before it hits the peer channel — corrupt/truncate exercise the
    # receiver's CRC-then-fallback contract, delay/kill the death drills
    "reshard.peer_xfer": "data",
    # delta journal (journal.py): the append site sits INSIDE one
    # record's frame (after its 8-byte prefix hit the disk), so kill
    # leaves a genuinely torn record and corrupt/truncate mangle bytes
    # whose CRCs were computed first — replay must detect all three.
    "journal.append": "data",
    "journal.replay": "data",  # payload just read, before CRC verify
    # fleet distribution tier (distrib.py): the seeded chunk as it
    # leaves the serving peer (corrupt is caught by the receiver's
    # content-address re-hash, kill is the mid-transfer seeder death
    # drill) and the epoch blob as it leaves the rolling-update pusher
    # (corrupt is caught by the receiver's record CRCs).
    "distrib.seed_xfer": "data",
    "distrib.epoch_push": "data",
    # tenancy (tenancy/): the quota gate before any payload I/O (kill
    # here must leave NO partial — the save hasn't started) and the
    # admission-table registration (a tenant that cannot register must
    # fail its op, not silently run unpaced at full bandwidth).
    "tenancy.quota_check": "control",
    "tenancy.admission": "control",
    # lazy page-in restore (pagein.py): the engine's two batch kinds.
    # Control-plane sites — they fail/kill the BACKGROUND read attempt
    # (the drills then prove the leaf degrades to a blocking direct
    # read, never a torn or stale value); payload corruption reuses the
    # storage-boundary data sites (fs.read) the reads flow through.
    "pagein.prefetch": "control",
    "pagein.fault": "control",
    # cross-region geo-replication (georep.py): the epoch blob as it
    # leaves the shipper (corrupt/truncate must be caught by the remote
    # apply's record CRCs before ANY remote byte changes; kill is the
    # shipper-death-mid-ship drill — the cursor must resume exactly-once)
    # and the remote apply step after segment bytes landed but before the
    # epoch meta publishes (permanent models a remote-tier outage: the
    # backlog must stay bounded and the foreground save unaffected).
    "georep.ship": "data",
    "georep.apply": "control",
}

KNOWN_SITES = frozenset(SITES)

_CONTROL_ACTIONS = frozenset({"transient", "permanent", "delay", "kill"})
_DATA_ACTIONS = frozenset({"corrupt", "truncate"})


class InjectedFault(Exception):
    """Marker base for every injected error (tests/chaos filter on it)."""


class InjectedTransientError(InjectedFault, ConnectionError):
    """An injected *retryable* failure: classified transient by
    ``storage_plugins.retry.is_transient_error`` (ConnectionError), so
    retry-wrapped paths retry it and unwrapped paths abort."""


class InjectedPermanentError(InjectedFault, OSError):
    """An injected *non-retryable* failure: a plain OSError, which the
    retry machinery propagates immediately and the mirror tier treats as
    a primary-read loss (its documented failover trigger)."""


@dataclass
class _Rule:
    site: str
    action: str
    arg: Optional[float]
    nth: Optional[int]        # exact hit number (1-based)
    open_ended: bool          # nth and every hit after
    prob: Optional[float]     # probabilistic trigger

    def matches(self, hit: int, rng: random.Random) -> bool:
        if self.prob is not None:
            return rng.random() < self.prob
        assert self.nth is not None
        if self.open_ended:
            return hit >= self.nth
        return hit == self.nth


def _parse_rule(text: str) -> _Rule:
    head, sep, action_part = text.partition("=")
    if not sep:
        raise ValueError(f"fault rule {text!r}: expected SITE@TRIGGER=ACTION")
    site_name, sep, trigger = head.partition("@")
    site_name = site_name.strip()
    if not sep or not trigger:
        raise ValueError(f"fault rule {text!r}: expected SITE@TRIGGER=ACTION")
    if site_name not in KNOWN_SITES:
        raise ValueError(
            f"fault rule {text!r}: unknown site {site_name!r} "
            f"(registered sites: {', '.join(sorted(KNOWN_SITES))})"
        )
    action, _, arg_str = action_part.partition(":")
    action = action.strip()
    if action not in _CONTROL_ACTIONS | _DATA_ACTIONS:
        raise ValueError(
            f"fault rule {text!r}: unknown action {action!r} (expected "
            "transient/permanent/delay/corrupt/truncate/kill)"
        )
    arg: Optional[float] = None
    if arg_str:
        try:
            arg = float(arg_str)
        except ValueError:
            raise ValueError(
                f"fault rule {text!r}: non-numeric action argument {arg_str!r}"
            ) from None
    trigger = trigger.strip()
    nth: Optional[int] = None
    open_ended = False
    prob: Optional[float] = None
    if trigger.startswith("p"):
        try:
            prob = float(trigger[1:])
        except ValueError:
            raise ValueError(
                f"fault rule {text!r}: malformed probability trigger {trigger!r}"
            ) from None
        if not (0.0 <= prob <= 1.0):
            raise ValueError(
                f"fault rule {text!r}: probability {prob} outside [0, 1]"
            )
    else:
        raw = trigger
        if raw.endswith("+"):
            open_ended = True
            raw = raw[:-1]
        try:
            nth = int(raw)
        except ValueError:
            raise ValueError(
                f"fault rule {text!r}: malformed trigger {trigger!r} "
                "(expected N, N+, or pFLOAT)"
            ) from None
        if nth < 1:
            raise ValueError(f"fault rule {text!r}: hit numbers are 1-based")
    return _Rule(
        site=site_name,
        action=action,
        arg=arg,
        nth=nth,
        open_ended=open_ended,
        prob=prob,
    )


class FaultPlan:
    """A parsed fault schedule: rules grouped by site, a seeded RNG, and
    exact per-site hit counters. Thread-safe — sites fire from the event
    loop, executor workers, and the store's handler threads alike."""

    def __init__(self, spec: str) -> None:
        self.spec = spec
        seed = 0
        rules: List[_Rule] = []
        for part in spec.split(";"):
            part = part.strip()
            if not part:
                continue
            if part.startswith("seed="):
                try:
                    seed = int(part[len("seed="):])
                except ValueError:
                    raise ValueError(
                        f"fault plan: malformed seed segment {part!r}"
                    ) from None
                continue
            rules.append(_parse_rule(part))
        if not rules:
            raise ValueError(f"fault plan {spec!r} contains no rules")
        self.seed = seed
        self._rules: Dict[str, List[_Rule]] = {}
        for rule in rules:
            self._rules.setdefault(rule.site, []).append(rule)
        self._rng = random.Random(seed)
        self._hits: Dict[str, int] = {}
        self._lock = threading.Lock()
        self._warned_sites: set = set()

    def hits(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._hits)

    def fire(self, name: str, buf: Any) -> Any:
        """Count one hit of ``name`` and apply every matching rule.

        Order within one hit: delays first, then data mutations, then a
        raise/kill — so a rule pair like ``delay + transient`` behaves
        as "slow, then fails". Returns the (possibly mutated) buffer.
        """
        with self._lock:
            hit = self._hits.get(name, 0) + 1
            self._hits[name] = hit
            fired = [
                r
                for r in self._rules.get(name, ())
                if r.matches(hit, self._rng)
            ]
            if not fired:
                return buf
            # Pre-draw the corrupt offset under the lock so concurrent
            # hits stay deterministic given a deterministic interleaving.
            offsets: Dict[int, int] = {}
            for i, rule in enumerate(fired):
                if rule.action == "corrupt" and rule.arg is None:
                    offsets[i] = self._rng.randrange(1 << 30)
        # Flight-record the trip BEFORE the action runs: a kill/raise
        # below must leave the trip in the ring (and in any dump peers
        # trigger). Lazy import — the disabled path (no plan) never
        # reaches here, and the injector stays stdlib-importable.
        from .telemetry import flightrec

        flightrec.record(
            "fault.trip",
            site=name,
            hit=hit,
            action=",".join(r.action for r in fired),
        )
        raiser: Optional[_Rule] = None
        for i, rule in enumerate(fired):
            if rule.action == "delay":
                time.sleep(rule.arg if rule.arg is not None else 0.05)
            elif rule.action == "corrupt":
                buf = self._corrupt(name, buf, rule, offsets.get(i))
            elif rule.action == "truncate":
                buf = self._truncate(name, buf, rule)
            elif raiser is None:
                raiser = rule
        if raiser is not None:
            hit_desc = f"{name} hit #{hit}"
            if raiser.action == "kill":
                logger.warning("fault injection: SIGKILL at %s", hit_desc)
                logging.shutdown()
                os.kill(os.getpid(), signal.SIGKILL)
            if raiser.action == "transient":
                raise InjectedTransientError(
                    f"injected transient fault at {hit_desc}"
                )
            raise InjectedPermanentError(
                f"injected permanent fault at {hit_desc}"
            )
        return buf

    def _data_or_warn(self, name: str, buf: Any, rule: _Rule) -> bool:
        if buf is None:
            if name not in self._warned_sites:
                self._warned_sites.add(name)
                logger.warning(
                    "fault plan rule %s@...=%s ignored: %r is a control "
                    "site (no payload bytes to mutate)",
                    name,
                    rule.action,
                    name,
                )
            return False
        return True

    def _corrupt(
        self, name: str, buf: Any, rule: _Rule, drawn_offset: Optional[int]
    ) -> Any:
        if not self._data_or_warn(name, buf, rule):
            return buf
        out = bytearray(memoryview(buf).cast("B"))
        if not out:
            return buf
        if rule.arg is not None:
            idx = min(int(rule.arg), len(out) - 1)
        else:
            idx = (drawn_offset or 0) % len(out)
        out[idx] ^= 0xFF
        logger.warning(
            "fault injection: flipped byte %d of %d at %s", idx, len(out), name
        )
        return out

    def _truncate(self, name: str, buf: Any, rule: _Rule) -> Any:
        if not self._data_or_warn(name, buf, rule):
            return buf
        mv = memoryview(buf).cast("B")
        frac = rule.arg if rule.arg is not None else 0.5
        keep = max(0, min(mv.nbytes, int(mv.nbytes * frac)))
        logger.warning(
            "fault injection: truncated %d -> %d bytes at %s",
            mv.nbytes,
            keep,
            name,
        )
        return mv[:keep]


def _plan_from_env() -> Optional[FaultPlan]:
    spec = os.environ.get(FAULT_PLAN_ENV_VAR, "").strip()
    if not spec:
        return None
    return FaultPlan(spec)


def _plan_from_env_lenient() -> Optional[FaultPlan]:
    """Import-time variant: a typo'd plan must not make the whole
    package unimportable (the fsck/verify CLIs one would diagnose with
    import this module too). Warn LOUDLY and run uninjected — the env
    parser idiom of dist_store._read_barrier_timeout. Deliberate
    configuration paths (:func:`configure`, :func:`refresh_from_env`)
    still raise, so tests and drivers fail fast on bad plans."""
    try:
        return _plan_from_env()
    except ValueError as e:
        logger.error(
            "ignoring malformed %s (running WITHOUT fault injection): %s",
            FAULT_PLAN_ENV_VAR,
            e,
        )
        return None


_plan: Optional[FaultPlan] = _plan_from_env_lenient()


def configure(spec: Optional[str]) -> None:
    """Install a fault plan programmatically (None disables). Resets the
    hit counters and the seeded RNG — the plan replays from scratch."""
    global _plan
    _plan = FaultPlan(spec) if spec else None


def disable() -> None:
    configure(None)


def refresh_from_env() -> None:
    """Re-read ``TORCHSNAPSHOT_TPU_FAULT_PLAN`` (for subprocess workers
    that mutate os.environ after this module was imported)."""
    global _plan
    _plan = _plan_from_env()


def active() -> bool:
    return _plan is not None


def active_spec() -> Optional[str]:
    return _plan.spec if _plan is not None else None


def hits() -> Dict[str, int]:
    """Per-site hit counts of the active plan ({} when disabled)."""
    return _plan.hits() if _plan is not None else {}


def site(name: str) -> None:
    """A control injection point. Disabled hot path: one global check."""
    plan = _plan
    if plan is None:
        return
    plan.fire(name, None)


def mutate(name: str, buf: Any) -> Any:
    """A data injection point: returns ``buf`` (mutated under an active
    plan's corrupt/truncate rules; verbatim otherwise). Disabled hot
    path: one global check, no copy."""
    plan = _plan
    if plan is None:
        return buf
    return plan.fire(name, buf)
