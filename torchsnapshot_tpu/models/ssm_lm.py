"""SSM language model: linear-time sequence mixing instead of attention.

A decoder-only LM whose blocks mix the sequence with the diagonal
selective SSM (ops/ssm.py — ``lax.associative_scan`` recurrence) instead
of attention: O(S) compute and O(1) state per step, the long-context
model family complementing the attention transformer. Like the
transformer, the reference ships no model code (its benchmarks build
throwaway torch models); this exists to produce realistic trainable
distributed state for the snapshot layer.

Sharding: batch over 'data'; FFN weights over 'model' (tp); with a mesh
that has a 'seq' axis, the residual stream stays sequence-sharded
end-to-end and the scan's cross-chunk carry rides one tiny all_gather per
layer (``ssm_mix_sharded``) — the SSM analogue of context parallelism.

State (params + optax state + step) is the canonical AppState the
snapshot layer checkpoints and reshards.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.ssm import init_ssm_params, ssm_mix

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class SSMConfig:
    vocab_size: int = 32768
    d_model: int = 512
    d_state: int = 16
    n_layers: int = 4
    d_ff: int = 2048
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32


def _norm_init(shape, dtype):
    return jnp.ones(shape, dtype)


def init_params(rng: jax.Array, cfg: SSMConfig) -> Params:
    c = cfg
    k_emb, k_layers = jax.random.split(rng)
    ks = jax.random.split(k_layers, 3)

    def stack(init_one):
        outs = [init_one(jax.random.fold_in(ks[0], i)) for i in range(c.n_layers)]
        return jax.tree_util.tree_map(lambda *ls: jnp.stack(ls), *outs)

    layers = {
        "ssm": stack(lambda k: init_ssm_params(k, c.d_model, c.d_state, c.param_dtype)),
        "ln1_scale": _norm_init((c.n_layers, c.d_model), c.param_dtype),
        "ln2_scale": _norm_init((c.n_layers, c.d_model), c.param_dtype),
        "ff_in": jax.random.normal(
            ks[1], (c.n_layers, c.d_model, c.d_ff), c.param_dtype
        ) * (c.d_model**-0.5),
        "ff_out": jax.random.normal(
            ks[2], (c.n_layers, c.d_ff, c.d_model), c.param_dtype
        ) * (c.d_ff**-0.5),
    }
    return {
        "embed": jax.random.normal(
            k_emb, (c.vocab_size, c.d_model), c.param_dtype
        ) * (c.d_model**-0.5),
        "layers": layers,
        "ln_f_scale": _norm_init((c.d_model,), c.param_dtype),
    }


def param_specs(cfg: SSMConfig) -> Params:
    """PartitionSpecs for a ('data','model'[,'seq']) mesh: FFN tp-sharded,
    SSM params replicated (they are tiny: O(d_model * d_state))."""
    none2 = P(None, None)
    return {
        "embed": P(None, "model"),
        "layers": {
            "ssm": {
                "log_a": none2,
                "w_bc": P(None, None, None),
                "w_dt": P(None, None, None),
                "dt_bias": none2,
                "d_skip": none2,
            },
            "ln1_scale": none2,
            "ln2_scale": none2,
            "ff_in": P(None, None, "model"),
            "ff_out": P(None, "model", None),
        },
        "ln_f_scale": P(None),
    }


def _rmsnorm(x: jax.Array, scale: jax.Array) -> jax.Array:
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x * jax.lax.rsqrt(var + 1e-6).astype(x.dtype)) * scale.astype(x.dtype)


def forward(
    params: Params,
    tokens: jax.Array,
    cfg: SSMConfig,
    *,
    mesh: Optional[Mesh] = None,
) -> jax.Array:
    """Causal LM forward: (B, S) int32 -> (B, S, vocab) logits.

    With a mesh carrying a 'seq' axis the residual stream is sequence
    sharded and each layer's scan runs sequence-parallel; otherwise the
    scan is local. Sharding constraints are no-ops with mesh=None.
    """
    c = cfg
    B, S = tokens.shape
    has_seq = mesh is not None and "seq" in mesh.axis_names
    seq_ax = "seq" if has_seq else None

    def cs(x, spec):
        if mesh is None:
            return x
        return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))

    x = params["embed"].astype(c.dtype)[tokens]  # (B, S, D)
    x = cs(x, P("data", seq_ax, None))

    def mix(params_l, h):
        if has_seq:
            from ..ops.ssm import ssm_mix_sharded

            y, _ = ssm_mix_sharded(params_l, h, mesh, seq_axis="seq")
            return y
        y, _ = ssm_mix(params_l, h)
        return y

    def block(x, layer):
        h = _rmsnorm(x, layer["ln1_scale"])
        h = cs(h, P("data", seq_ax, None))
        x = x + cs(mix(layer["ssm"], h), P("data", seq_ax, None))
        h = _rmsnorm(x, layer["ln2_scale"])
        h = jax.nn.gelu(h @ layer["ff_in"].astype(c.dtype))
        h = cs(h, P("data", seq_ax, "model"))
        x = x + cs(h @ layer["ff_out"].astype(c.dtype), P("data", seq_ax, None))
        return x, None

    x, _ = jax.lax.scan(block, x, params["layers"])
    x = _rmsnorm(x, params["ln_f_scale"])
    logits = x @ params["embed"].astype(c.dtype).T
    return cs(logits, P("data", seq_ax, "model"))


def loss_fn(
    params: Params,
    batch: Dict[str, jax.Array],
    cfg: SSMConfig,
    *,
    mesh: Optional[Mesh] = None,
) -> jax.Array:
    logits = forward(params, batch["tokens"], cfg, mesh=mesh)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    ll = jnp.take_along_axis(logp, batch["targets"][..., None], axis=-1)[..., 0]
    return -jnp.mean(ll)


def state_specs(cfg: SSMConfig, state: Dict[str, Any]) -> Dict[str, Any]:
    """PartitionSpec pytree matching init_state's output: Adam moments
    inherit their param's spec; scalars replicated ON the mesh — a
    restored scalar comes back committed, and a single-device scalar next
    to mesh-committed params is an invalid jit input mix (same rationale
    as transformer.state_specs)."""
    from ..parallel.mesh import optax_state_specs

    p_specs = param_specs(cfg)
    opt_spec = optax_state_specs(p_specs, state["opt"])
    return {"params": p_specs, "opt": opt_spec, "step": P()}


def init_state(
    rng: jax.Array,
    cfg: SSMConfig,
    tx: optax.GradientTransformation,
    *,
    mesh: Optional[Mesh] = None,
) -> Dict[str, Any]:
    params = init_params(rng, cfg)
    if mesh is not None:
        from ..parallel.mesh import shard_pytree

        params = shard_pytree(params, param_specs(cfg), mesh)
    state = {
        "params": params,
        "opt": tx.init(params),
        "step": jnp.zeros((), jnp.int32),
    }
    if mesh is not None:
        from ..parallel.mesh import shard_pytree

        state = shard_pytree(state, state_specs(cfg, state), mesh)
    return state


def make_train_step(
    cfg: SSMConfig,
    tx: optax.GradientTransformation,
    *,
    mesh: Optional[Mesh] = None,
) -> Callable:
    def step(state, batch):
        loss, grads = jax.value_and_grad(
            lambda p: loss_fn(p, batch, cfg, mesh=mesh)
        )(state["params"])
        updates, opt = tx.update(grads, state["opt"], state["params"])
        params = optax.apply_updates(state["params"], updates)
        return {"params": params, "opt": opt, "step": state["step"] + 1}, loss

    return step
