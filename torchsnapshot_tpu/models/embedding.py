"""Row-wise sharded embedding model (the torchrec/DLRM checkpointing analogue).

The reference's heaviest real-world workload is torchrec DLRM with row-wise
sharded embedding tables (tests/gpu_tests/test_torchrec.py:170-241,
benchmarks/torchrec/main.py:54-151): huge (vocab, dim) tables split along
the row axis across ranks, saved as shards and reshardable on restore.

TPU-native realization: each table is a `jax.Array` with
`NamedSharding(mesh, P(('data', 'model'), None))` — rows split over ALL
mesh devices (the row-wise layout), lookups via `jnp.take` under jit so
XLA inserts the gather collectives, plus a dense interaction MLP. The
state-dict level is just sharded arrays, so the snapshot path is identical
to any GSPMD state — which is the point: checkpointing must not care *why*
an array is sharded (SURVEY.md §5.7).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Params = Dict[str, Any]


@dataclass(frozen=True)
class EmbeddingConfig:
    n_tables: int = 8
    rows_per_table: int = 100_000
    dim: int = 64
    n_dense_features: int = 13
    mlp_hidden: Tuple[int, ...] = (256, 64)
    param_dtype: Any = field(default=jnp.float32)

    @property
    def param_count(self) -> int:
        n = self.n_tables * self.rows_per_table * self.dim
        widths = (self.n_dense_features + self.n_tables * self.dim,) + self.mlp_hidden
        for a, b in zip(widths, widths[1:] + (1,)):
            n += a * b + b
        return n


def init_params(rng: jax.Array, cfg: EmbeddingConfig) -> Params:
    keys = jax.random.split(rng, cfg.n_tables + len(cfg.mlp_hidden) + 1)
    tables = {
        f"table_{i}": jax.random.normal(
            keys[i], (cfg.rows_per_table, cfg.dim), cfg.param_dtype
        )
        * (cfg.dim**-0.5)
        for i in range(cfg.n_tables)
    }
    widths = (cfg.n_dense_features + cfg.n_tables * cfg.dim,) + cfg.mlp_hidden + (1,)
    mlp = {}
    for j, (fan_in, fan_out) in enumerate(zip(widths, widths[1:])):
        mlp[f"w{j}"] = (
            jax.random.normal(keys[cfg.n_tables + j], (fan_in, fan_out), cfg.param_dtype)
            * (fan_in**-0.5)
        )
        mlp[f"b{j}"] = jnp.zeros((fan_out,), cfg.param_dtype)
    return {"tables": tables, "mlp": mlp}


def param_specs(cfg: EmbeddingConfig) -> Params:
    """Row-wise layout: table rows split over every mesh axis; MLP replicated
    (it is tiny relative to the tables, like DLRM's dense arch). Complete
    spec pytree — matches init_params' structure exactly."""
    n_mlp = len(cfg.mlp_hidden) + 1
    mlp = {}
    for j in range(n_mlp):
        mlp[f"w{j}"] = P()
        mlp[f"b{j}"] = P()
    return {
        "tables": {f"table_{i}": P(("data", "model"), None) for i in range(cfg.n_tables)},
        "mlp": mlp,
    }


def forward(params: Params, dense: jax.Array, sparse_ids: jax.Array,
            cfg: EmbeddingConfig) -> jax.Array:
    """dense: (B, n_dense_features); sparse_ids: (B, n_tables) int32."""
    looked_up = [
        jnp.take(params["tables"][f"table_{i}"], sparse_ids[:, i], axis=0)
        for i in range(cfg.n_tables)
    ]
    x = jnp.concatenate([dense] + looked_up, axis=-1)
    n_layers = len(cfg.mlp_hidden) + 1
    for j in range(n_layers):
        x = x @ params["mlp"][f"w{j}"] + params["mlp"][f"b{j}"]
        if j < n_layers - 1:
            x = jax.nn.relu(x)
    return x[:, 0]


def loss_fn(params: Params, batch: Dict[str, jax.Array], cfg: EmbeddingConfig) -> jax.Array:
    logits = forward(params, batch["dense"], batch["sparse_ids"], cfg)
    return jnp.mean(optax.sigmoid_binary_cross_entropy(logits, batch["labels"]))


def init_state(
    rng: jax.Array,
    cfg: EmbeddingConfig,
    tx: optax.GradientTransformation,
    *,
    mesh: Optional[Mesh] = None,
) -> Dict[str, Any]:
    params = init_params(rng, cfg)
    if mesh is not None:
        from ..parallel.mesh import shard_pytree

        params = shard_pytree(params, param_specs(cfg), mesh)
    state = {
        "params": params,
        "opt_state": tx.init(params),
        "step": jnp.zeros((), jnp.int32),
    }
    if mesh is not None:
        # Commit the FULL state (scalars replicated) so restored state —
        # which comes back committed to these shardings — is resumable.
        state = shard_pytree(state, state_specs(cfg, state), mesh)
    return state


def state_specs(cfg: EmbeddingConfig, state: Dict[str, Any]) -> Dict[str, Any]:
    """PartitionSpec pytree matching init_state's output: optimizer moments
    inherit their param's spec, scalars replicated."""
    p_specs = param_specs(cfg)

    def map_opt(entry):
        if isinstance(entry, optax.ScaleByAdamState):
            return optax.ScaleByAdamState(count=P(), mu=p_specs, nu=p_specs)
        return jax.tree_util.tree_map(lambda _: P(), entry)

    opt_spec = tuple(map_opt(e) for e in state["opt_state"])
    return {"params": p_specs, "opt_state": opt_spec, "step": P()}


def make_train_step(cfg: EmbeddingConfig, tx: optax.GradientTransformation,
                    *, mesh: Optional[Mesh] = None):
    def train_step(state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(state["params"], batch, cfg)
        updates, opt_state = tx.update(grads, state["opt_state"], state["params"])
        return {
            "params": optax.apply_updates(state["params"], updates),
            "opt_state": opt_state,
            "step": state["step"] + 1,
        }, loss

    if mesh is None:
        return train_step

    def sharded_step(state, batch):
        batch = {
            k: jax.lax.with_sharding_constraint(
                v, NamedSharding(mesh, P(*(("data",) + (None,) * (v.ndim - 1))))
            )
            for k, v in batch.items()
        }
        return train_step(state, batch)

    return sharded_step
