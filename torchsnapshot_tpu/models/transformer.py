"""Flagship model: a GSPMD-sharded decoder-only transformer.

The reference (torchsnapshot) ships no model code — its benchmarks build
throwaway torch models (benchmarks/fsdp/main.py builds a 1.9B-param
transformer, benchmarks/ddp/main.py a 200x100MB-param module) purely to
produce realistic distributed state to checkpoint. This module is the
TPU-native analogue: a pure-JAX decoder-only transformer whose parameters
and training step are annotated for a ('data','model') mesh:

- dp: batch sharded over 'data'
- tp: hidden/ffn/vocab dims sharded over 'model' (Megatron-style
  column->row parallel pairs; XLA inserts the all-reduces)
- sp: the residual stream between blocks is sequence-sharded over 'model'
  (Megatron sequence parallelism), so norm/elementwise work is partitioned
  and XLA materializes all-gather/reduce-scatter at block boundaries.
- cp: with ``attn_impl="ring"`` and a mesh that has a 'seq' axis, the
  sequence dimension stays sharded end-to-end (context parallelism):
  attention runs as ring attention over the 'seq' axis (K/V rotate on the
  ICI ring, ops/ring_attention.py) and no full-sequence activation is ever
  gathered — the long-context configuration.

The state it produces (params + optax opt_state + step + PRNG key) is the
canonical AppState the snapshot layer checkpoints and reshards.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32768
    d_model: int = 512
    n_heads: int = 8
    n_layers: int = 4
    d_ff: int = 2048
    max_seq_len: int = 1024
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    # "auto" (flash on TPU; blockwise off-TPU for long seq; dense for
    # short) | "dense" | "blockwise" (pure-JAX online-softmax scan) |
    # "flash" (Pallas TPU kernel) | "ring" | "zigzag" | "ulysses" (context
    # parallel; these need a mesh with a 'seq' axis — ring/zigzag rotate
    # K/V on the ICI ring, ulysses all-to-alls seq<->head sharding).
    attn_impl: str = "auto"
    attn_block_size: int = 512
    # n_experts > 0 swaps the dense FFN for a top-2 MoE (ops/moe.py) with
    # expert weights sharded over the 'model' axis — expert parallelism.
    n_experts: int = 0
    moe_capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def param_count(self) -> int:
        c = self
        per_layer = 4 * c.d_model * c.d_model + 2 * c.d_model * c.d_ff + 2 * c.d_model
        return c.vocab_size * c.d_model + c.n_layers * per_layer + c.d_model


def init_params(rng: jax.Array, cfg: TransformerConfig) -> Params:
    """Initialize the parameter pytree (stacked-layer layout).

    Per-layer weights are stacked along a leading layer axis so the forward
    pass is a single `lax.scan` over layers — one compiled block instead of
    n_layers unrolled ones, which keeps compile time flat as depth grows.
    """
    c = cfg
    k_embed, k_attn, k_o, k_ff1, k_ff2 = jax.random.split(rng, 5)
    L, D, F = c.n_layers, c.d_model, c.d_ff

    def norm(key, shape, fan_in):
        return (
            jax.random.normal(key, shape, c.param_dtype) * (fan_in**-0.5)
        )

    layers: Dict[str, Any] = {
        "attn_qkv": norm(k_attn, (L, D, 3 * D), D),
        "attn_out": norm(k_o, (L, D, D), D),
        "ln1_scale": jnp.ones((L, D), c.param_dtype),
        "ln2_scale": jnp.ones((L, D), c.param_dtype),
    }
    if c.n_experts > 0:
        E = c.n_experts
        k_r, k_ff1, k_ff2 = jax.random.split(k_ff1, 3)
        layers["moe_router"] = norm(k_r, (L, D, E), D)
        layers["moe_w_in"] = norm(k_ff1, (L, E, D, F), D)
        layers["moe_w_out"] = norm(k_ff2, (L, E, F, D), F)
    else:
        layers["ff_in"] = norm(k_ff1, (L, D, F), D)
        layers["ff_out"] = norm(k_ff2, (L, F, D), F)
    return {
        "embed": norm(k_embed, (c.vocab_size, D), D),
        "layers": layers,
        "ln_f_scale": jnp.ones((D,), c.param_dtype),
    }


def param_specs(cfg: TransformerConfig) -> Params:
    """PartitionSpecs for each param on a ('data','model') mesh (tp layout).

    Column-parallel (output dim on 'model'): qkv, ff_in, embed.
    Row-parallel (input dim on 'model'): attn_out, ff_out.
    Norm scales replicated.
    """
    layers = {
        "attn_qkv": P(None, None, "model"),
        "attn_out": P(None, "model", None),
        "ln1_scale": P(None, None),
        "ln2_scale": P(None, None),
    }
    if cfg.n_experts > 0:
        # ep: the expert dimension shards over 'model' (router replicated).
        layers["moe_router"] = P(None, None, None)
        layers["moe_w_in"] = P(None, "model", None, None)
        layers["moe_w_out"] = P(None, "model", None, None)
    else:
        layers["ff_in"] = P(None, None, "model")
        layers["ff_out"] = P(None, "model", None)
    return {
        "embed": P(None, "model"),
        "layers": layers,
        "ln_f_scale": P(None),
    }


def _rmsnorm(x: jax.Array, scale: jax.Array) -> jax.Array:
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x * jax.lax.rsqrt(var + 1e-6).astype(x.dtype)) * scale.astype(x.dtype)


def _flash_mesh_ok(cfg: TransformerConfig, mesh: Mesh, B: int, S: int) -> bool:
    """Preconditions for routing attention through the shard_mapped flash
    kernel under a mesh: heads divide the 'model' axis when one exists,
    batch divides the 'data' axis, and S (the kernel's local sequence
    length — pass S_local for ring-flash) has a kernel-viable tile
    divisor (the kernel picks its own 512-target tiling, so the gate must
    agree with that pick). Shared by the flash and ring-flash routes."""
    from ..ops.attention import pick_block_size

    if "model" in mesh.axis_names and cfg.n_heads % mesh.shape["model"]:
        return False
    if "data" in mesh.axis_names and B % mesh.shape["data"]:
        return False
    return S > 0 and pick_block_size(S, 512) is not None


def forward(
    params: Params,
    tokens: jax.Array,
    cfg: TransformerConfig,
    *,
    mesh: Optional[Mesh] = None,
    with_aux: bool = False,
):
    """Causal LM forward: (batch, seq) int32 -> (batch, seq, vocab) logits.

    When `mesh` is given, sharding constraints implement dp/tp/sp; with
    mesh=None the same code runs single-device. With ``with_aux=True``
    returns (logits, aux_loss) — the MoE load-balancing term (0 for dense
    FFN configs).
    """
    c = cfg
    B, S = tokens.shape

    def cs(x, spec):
        if mesh is None:
            return x
        return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))

    impls = ("auto", "dense", "blockwise", "flash", "ring", "zigzag", "ulysses")
    if c.attn_impl not in impls:
        raise ValueError(f"unknown attn_impl {c.attn_impl!r}")
    if c.attn_impl == "auto":
        # Backend-aware kernel choice: the Pallas flash kernel on TPU
        # (11.7x over the blockwise scan fwd+bwd, measured) — bare on a
        # single device, shard_mapped over batch/heads under a mesh when
        # the preconditions hold (_flash_mesh_ok; a bare pallas_call has
        # no partitioning rule, so it must never see sharded operands);
        # blockwise once S outgrows one block (O(S*block) memory); dense
        # for short sequences. Never selects a cp impl — ring/zigzag/
        # ulysses are mesh topology decisions for the caller.
        if jax.default_backend() == "tpu" and (
            mesh is None or _flash_mesh_ok(c, mesh, B, S)
        ):
            impl = "flash"
        elif S > c.attn_block_size:
            impl = "blockwise"
        else:
            impl = "dense"
        c = dataclasses.replace(c, attn_impl=impl)
    # cp (ring/ulysses) keeps the sequence dim sharded over 'seq' end-to-end;
    # the Megatron-sp fallback seq-shards the residual over the tp axis
    # instead and gathers around attention/ffn.
    has_seq = mesh is not None and "seq" in mesh.axis_names
    if c.attn_impl in ("ring", "zigzag", "ulysses") and mesh is not None and not has_seq:
        raise ValueError(
            f"attn_impl={c.attn_impl!r} needs a mesh with a 'seq' axis; got "
            f"{mesh.axis_names}. Build one via make_mesh({{'data': ..., "
            f"'seq': ..., 'model': ...}})."
        )
    # mesh=None (single-device run of a cp-configured model) falls back to
    # dense attention — same math, no axis to communicate over.
    cp = c.attn_impl in ("ring", "zigzag", "ulysses") and has_seq
    res_seq_ax = "seq" if has_seq else "model"  # residual-stream seq sharding
    act_seq_ax = "seq" if cp else None  # in-block activation seq sharding

    x = params["embed"].astype(c.dtype)[tokens]  # (B, S, D)
    pos = jnp.arange(S)[None, :, None]
    dims = jnp.arange(c.d_model // 2)[None, None, :]
    inv_freq = 10000.0 ** (-2.0 * dims / c.d_model)
    # Fixed sinusoidal position encoding added to embeddings.
    angles = pos * inv_freq
    pe = jnp.concatenate([jnp.sin(angles), jnp.cos(angles)], axis=-1)
    x = x + pe.astype(c.dtype)

    # Zigzag context parallelism: apply the folded layout ONCE here and
    # invert it once at the logits — attention runs in-layout, so the 2
    # permutes per layer the naive integration would pay collapse to 2 per
    # forward. Valid only while everything between commutes with the
    # permutation: true for the dense FFN (position-wise), NOT for MoE,
    # whose capacity overflow drops tokens in token order — hoisting would
    # make training numerics depend on the parallelism layout. MoE configs
    # therefore keep the per-layer permuting wrapper.
    zz = cp and c.attn_impl == "zigzag"
    zz_hoist = zz and c.n_experts == 0
    if zz_hoist:
        from ..ops.ring_attention import zigzag_layout_indices

        zz_idx = zigzag_layout_indices(S, mesh.shape["seq"])
        zz_inv = jnp.argsort(zz_idx)
        x = jnp.take(x, zz_idx, axis=1)

    def attention(q, k, v):
        # q, k, v: (B, S, H, hd) — logical shapes; sharding via constraints.
        if cp:
            if c.attn_impl == "ulysses":
                from ..ops.ulysses import ulysses_attention_sharded

                return ulysses_attention_sharded(
                    q, k, v, mesh, causal=True,
                    inner_block_size=c.attn_block_size,
                )
            if c.attn_impl == "zigzag":
                ring_size = mesh.shape["seq"]
                # Half-shard length is the zigzag kernels' tile unit.
                if (
                    jax.default_backend() == "tpu"
                    and S % (2 * ring_size) == 0
                    and _flash_mesh_ok(c, mesh, B, S // (2 * ring_size))
                ):
                    from ..ops.ring_flash import (
                        zigzag_ring_flash_attention_sharded,
                    )

                    return zigzag_ring_flash_attention_sharded(
                        q, k, v, mesh, in_layout=zz_hoist
                    )
                from ..ops.ring_attention import zigzag_ring_attention_sharded

                return zigzag_ring_attention_sharded(
                    q, k, v, mesh, in_layout=zz_hoist
                )
            if c.attn_impl == "ring" and jax.default_backend() == "tpu":
                # The ring's inner compute dominates long-context cost;
                # run it through the Pallas flash kernel when the LOCAL
                # shard satisfies the same preconditions as the non-ring
                # flash path.
                ring_size = mesh.shape["seq"]
                if S % ring_size == 0 and _flash_mesh_ok(
                    c, mesh, B, S // ring_size
                ):
                    from ..ops.ring_flash import ring_flash_attention_sharded

                    return ring_flash_attention_sharded(
                        q, k, v, mesh, causal=True
                    )
            from ..ops.ring_attention import ring_attention_sharded

            return ring_attention_sharded(q, k, v, mesh, causal=True)
        if c.attn_impl in ("blockwise", "flash"):
            from ..ops.attention import pick_block_size

            bs = pick_block_size(S, c.attn_block_size)
            if bs is not None:
                if c.attn_impl == "flash":
                    if mesh is not None:
                        # Under a mesh the bare pallas_call would make
                        # GSPMD gather the sharded operands; shard_map the
                        # kernel over batch/heads instead (attention is
                        # embarrassingly parallel there). Falls through to
                        # blockwise when the preconditions don't hold.
                        if _flash_mesh_ok(c, mesh, B, S):
                            from ..ops.pallas_attention import (
                                flash_attention_sharded,
                            )

                            return flash_attention_sharded(
                                q, k, v, mesh, causal=True
                            )
                    else:
                        from ..ops.pallas_attention import flash_attention

                        return flash_attention(
                            q, k, v, causal=True, block_q=bs, block_k=bs
                        )
                from ..ops.attention import blockwise_attention

                return blockwise_attention(q, k, v, block_size=bs, causal=True)
        from ..ops.attention import dense_attention

        return dense_attention(q, k, v, causal=True)

    def block(carry, layer):
        x, aux = carry
        x = cs(x, P("data", res_seq_ax, None))
        h = _rmsnorm(x, layer["ln1_scale"])
        h = cs(h, P("data", act_seq_ax, None))
        qkv = h @ layer["attn_qkv"].astype(c.dtype)  # (B,S,3D)
        qkv = cs(qkv, P("data", act_seq_ax, "model"))
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(t):
            t = t.reshape(B, S, c.n_heads, c.head_dim)
            return cs(t, P("data", act_seq_ax, "model", None))

        attn = attention(heads(q), heads(k), heads(v))  # (B,S,H,hd)
        attn = attn.reshape(B, S, c.d_model)
        attn = cs(attn, P("data", act_seq_ax, "model"))
        x = x + cs(attn @ layer["attn_out"].astype(c.dtype), P("data", res_seq_ax, None))

        h = _rmsnorm(x, layer["ln2_scale"])
        if c.n_experts > 0:
            from ..ops.moe import moe_ffn

            h = cs(h, P("data", act_seq_ax, None))
            y, l_aux = moe_ffn(
                {
                    "router": layer["moe_router"],
                    "w_in": layer["moe_w_in"],
                    "w_out": layer["moe_w_out"],
                },
                h,
                capacity_factor=c.moe_capacity_factor,
            )
            x = x + cs(y, P("data", res_seq_ax, None))
            aux = aux + l_aux
        else:
            h = cs(h, P("data", act_seq_ax, None))
            h = jax.nn.gelu(h @ layer["ff_in"].astype(c.dtype))
            h = cs(h, P("data", act_seq_ax, "model"))
            x = x + cs(h @ layer["ff_out"].astype(c.dtype), P("data", res_seq_ax, None))
        return (x, aux), None

    (x, aux), _ = jax.lax.scan(block, (x, jnp.zeros((), jnp.float32)), params["layers"])
    x = cs(x, P("data", act_seq_ax, None))
    x = _rmsnorm(x, params["ln_f_scale"])
    logits = x @ params["embed"].astype(c.dtype).T
    if zz_hoist:
        logits = jnp.take(logits, zz_inv, axis=1)  # back to global order
    logits = cs(logits, P("data", act_seq_ax, "model"))
    if with_aux:
        return logits, aux
    return logits


def loss_fn(
    params: Params,
    batch: Dict[str, jax.Array],
    cfg: TransformerConfig,
    *,
    mesh: Optional[Mesh] = None,
) -> jax.Array:
    logits, aux = forward(params, batch["tokens"], cfg, mesh=mesh, with_aux=True)
    targets = batch["targets"]
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return -jnp.mean(ll) + cfg.moe_aux_weight * aux


def make_optimizer(lr: float = 1e-3) -> optax.GradientTransformation:
    return optax.adamw(lr, b1=0.9, b2=0.95, weight_decay=0.01)


def make_train_step(
    cfg: TransformerConfig,
    tx: optax.GradientTransformation,
    *,
    mesh: Optional[Mesh] = None,
) -> Callable:
    """Returns train_step(state, batch) -> (state, loss), ready to jit.

    state = {"params": ..., "opt_state": ..., "step": int32 scalar}.
    """

    def train_step(state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(
            state["params"], batch, cfg, mesh=mesh
        )
        updates, opt_state = tx.update(grads, state["opt_state"], state["params"])
        params = optax.apply_updates(state["params"], updates)
        return {
            "params": params,
            "opt_state": opt_state,
            "step": state["step"] + 1,
        }, loss

    return train_step


def init_state(
    rng: jax.Array,
    cfg: TransformerConfig,
    tx: optax.GradientTransformation,
    *,
    mesh: Optional[Mesh] = None,
) -> Dict[str, Any]:
    """Initialize {params, opt_state, step}; shard onto `mesh` if given.

    The FULL state is placed per ``state_specs`` — including replicated
    scalars (optimizer count, step). Leaving scalars uncommitted works for
    the first jit call but breaks resume-after-restore: a restored scalar
    comes back committed to its destination's sharding, and a
    single-device scalar next to mesh-committed params is an invalid jit
    input mix.
    """
    params = init_params(rng, cfg)
    if mesh is not None:
        from ..parallel.mesh import shard_pytree

        params = shard_pytree(params, param_specs(cfg), mesh)
    opt_state = tx.init(params)
    state = {
        "params": params,
        "opt_state": opt_state,
        "step": jnp.zeros((), jnp.int32),
    }
    if mesh is not None:
        from ..parallel.mesh import shard_pytree

        state = shard_pytree(state, state_specs(cfg, state), mesh)
    return state


def state_specs(cfg: TransformerConfig, state: Dict[str, Any]) -> Dict[str, Any]:
    """PartitionSpec pytree matching init_state's output.

    Adam moments inherit their param's spec; scalars replicated.
    """
    from ..parallel.mesh import optax_state_specs

    p_specs = param_specs(cfg)
    opt_spec = optax_state_specs(p_specs, state["opt_state"])
    return {"params": p_specs, "opt_state": opt_spec, "step": P()}
