from .transformer import (  # noqa: F401
    TransformerConfig,
    init_params,
    forward,
    loss_fn,
    make_train_step,
    make_optimizer,
    param_specs,
)
from . import embedding  # noqa: F401
from . import ssm_lm  # noqa: F401
