"""Incremental snapshots: skip storage writes for unchanged payloads.

A capability beyond the reference (which always rewrites every byte). When
``Snapshot.take(..., incremental_base=...)`` is given a previous snapshot,
each payload's content digest (SHA-256, computed at stage time — after the
DtoH copy, on the exact bytes that would be written) is compared against
the digest the base snapshot recorded for the payload at the same storage
location. On a match the storage write is skipped and the manifest entry
records ``origin`` = the snapshot that physically holds the bytes — chains
of incrementals resolve ``origin`` transitively, so a payload written once
is referenced directly no matter how many increments follow.

Where this wins: any training run where a large fraction of state is
frozen between snapshots — LoRA/adapter fine-tuning (frozen backbone),
embedding tables with sparse updates, EMA copies updated infrequently.
The DtoH + hash cost is still paid (correctness requires hashing the real
bytes); only the storage write is elided, which is the expensive part on
cloud storage.

Matching is by storage location, which is a deterministic function of
(logical path, replication class, chunk/shard box) and independent of
which rank writes it for ``replicated/`` and ``sharded/`` payloads. A
changed world size shifts ``<rank>/`` locations, so per-rank payloads
simply miss the index and are rewritten — correct, just not deduplicated.
Payloads the base packed into batched slabs (``batched/<uuid>``) are
never matched for the same reason.

Restore-side: entries with ``origin`` read their payload from that
snapshot's storage (see ``Snapshot._execute_read_reqs_grouped``).
Deleting a base snapshot therefore breaks incrementals built on it —
``python -m torchsnapshot_tpu info`` lists origin dependencies.
"""

from __future__ import annotations

import contextlib
import contextvars
import hashlib
from dataclasses import dataclass
from typing import Dict, Iterator, Optional

from .manifest import (
    ArrayEntry,
    ChunkedArrayEntry,
    Entry,
    ObjectEntry,
    ShardedArrayEntry,
    SnapshotMetadata,
)

DIGEST_ALGO = "sha256"


def canonical_base_url(url: str) -> str:
    """Canonical form of a base-snapshot URL for recording as an origin.

    Origins are resolved later from arbitrary working directories (restore
    on another host's job, CLI ``deps``/``verify``), so a relative path or
    symlink recorded verbatim would dangle. Filesystem paths resolve to
    their real absolute path; remote URLs pass through verbatim.
    """
    import os

    if url.startswith("fs://"):
        return "fs://" + os.path.realpath(url[len("fs://"):])
    if "://" in url:
        return url
    return os.path.realpath(url)


def compute_digest(buf) -> str:
    h = hashlib.sha256()
    h.update(memoryview(buf).cast("B"))
    return f"{DIGEST_ALGO}:{h.hexdigest()}"


@dataclass(frozen=True)
class PayloadRef:
    """Where a base snapshot holds a payload, and what its content was.

    ``checksum``/``codec`` describe the base's STORED bytes: a dedup
    match skips the write, so restore reads the base's payload — the new
    entry must record the stored form's checksum and compression, not
    this staging's (digests cover uncompressed content and stay equal;
    compressed bytes need not, e.g. across codec/level changes)."""

    digest: str
    origin: str  # snapshot URL that physically holds the bytes
    nbytes: Optional[int]
    checksum: Optional[str] = None
    codec: Optional[str] = None
    # Where the bytes live WITHIN the origin. Usually equal to the new
    # entry's own location (dedup matches by location), but a pool-swept
    # base stores its payload under a rewritten ``po/<hex>`` path — a
    # digest-fallback match must point the new entry there.
    location: Optional[str] = None
    # Device-resident fingerprint the base recorded (device_digest.py):
    # matching it skips the DtoH transfer, not just the storage write.
    device_digest: Optional[str] = None


def _iter_payload_entries(entry: Entry) -> Iterator[ArrayEntry]:
    if isinstance(entry, ArrayEntry):
        yield entry
    elif isinstance(entry, ChunkedArrayEntry):
        for chunk in entry.chunks:
            yield chunk.array
    elif isinstance(entry, ShardedArrayEntry):
        for shard in entry.shards:
            yield shard.array


class DedupContext:
    """Digest recording + (optionally) a base snapshot's payload index.

    Active during a take's prepare phase via :func:`dedup_staging`; stagers
    capture it at construction and consult it at stage time.
    """

    def __init__(
        self,
        base_path: Optional[str],
        refs: Dict[str, PayloadRef],
        device_digests: bool = False,
    ):
        self.base_path = base_path
        self.refs = refs
        # Secondary content-address index: a pool-swept base (tenancy/
        # pool.py rewrites locations to po/<hex>) no longer matches by
        # location, but its payloads are the same bytes — match() falls
        # back to the digest. First ref per digest wins (they are
        # interchangeable by construction: digest + size verified).
        self.by_digest: Dict[str, PayloadRef] = {}
        for ref in refs.values():
            self.by_digest.setdefault(ref.digest, ref)
        # When True, stagers fingerprint device arrays on device
        # (device_digest.py) and skip the DtoH copy on a base match; the
        # fingerprint is also recorded so FUTURE takes can match.
        self.device_digests = device_digests

    @classmethod
    def recording_only(cls, device_digests: bool = False) -> "DedupContext":
        return cls(base_path=None, refs={}, device_digests=device_digests)

    @classmethod
    def from_base(
        cls,
        base_path: str,
        metadata: SnapshotMetadata,
        device_digests: bool = False,
    ) -> "DedupContext":
        """Index every digest-carrying payload of ``metadata`` by location.

        ``origin`` resolves transitively: if the base itself borrowed the
        payload from an older snapshot, new entries point straight at the
        older snapshot, so restores never walk a chain.
        """
        refs: Dict[str, PayloadRef] = {}
        from .serialization import array_size_bytes

        for entry in metadata.manifest.values():
            payloads = list(_iter_payload_entries(entry))
            for p in payloads:
                if p.digest is None or p.byte_range is not None:
                    # Slab-packed payloads (byte_range) live at uuid
                    # locations a new take can never produce; skip them.
                    continue
                try:
                    nbytes = array_size_bytes(p.shape, p.dtype)
                except ValueError:
                    nbytes = None
                refs.setdefault(
                    p.location,
                    PayloadRef(
                        digest=p.digest,
                        origin=p.origin or base_path,
                        nbytes=nbytes,
                        checksum=p.checksum,
                        codec=p.codec,
                        device_digest=p.device_digest,
                        location=p.location,
                    ),
                )
            if isinstance(entry, ObjectEntry) and entry.digest is not None:
                refs.setdefault(
                    entry.location,
                    PayloadRef(
                        digest=entry.digest,
                        origin=entry.origin or base_path,
                        nbytes=entry.size,
                        checksum=entry.checksum,
                        codec=entry.codec,
                        location=entry.location,
                    ),
                )
        return cls(base_path=base_path, refs=refs, device_digests=device_digests)

    def match(self, location: str, digest: str, nbytes: int) -> Optional[PayloadRef]:
        ref = self.refs.get(location)
        if ref is None or ref.digest != digest:
            # Content-address fallback (pool-swept bases): same bytes
            # under a rewritten location still dedup — digest + size
            # agreement is the same evidence the location path demands.
            ref = self.by_digest.get(digest)
            if ref is None:
                return None
        if ref.nbytes is not None and ref.nbytes != nbytes:
            return None  # digest collision paranoia: sizes must agree
        return ref


_dedup_context: contextvars.ContextVar[Optional[DedupContext]] = contextvars.ContextVar(
    "tsnap_dedup_context", default=None
)


def active_dedup_context() -> Optional[DedupContext]:
    return _dedup_context.get()


@contextlib.contextmanager
def dedup_staging(ctx: Optional[DedupContext]):
    """Prepared stagers capture ``ctx`` for digest recording/dedup."""
    token = _dedup_context.set(ctx)
    try:
        yield
    finally:
        _dedup_context.reset(token)


def consolidate(
    src_path: str,
    dst_path: str,
    storage_options=None,
    io_concurrency: int = 4,
) -> int:
    """Materialize an incremental snapshot as a self-contained one.

    Copies every payload — local ones from ``src_path``, deduplicated ones
    from their origin snapshots — into ``dst_path``, clears ``origin`` on
    all entries (digests are kept: the consolidated snapshot can serve as
    a future incremental base), and commits the metadata last, same as a
    take. After consolidation the original bases can be deleted.

    Peak memory is ~``io_concurrency`` × the largest payload. Array chunks
    are ≤512 MB and batched slabs ~128 MB by construction, so the default
    stays around 2 GB; lower ``io_concurrency`` for snapshots holding
    giant pickled objects (the one payload type with no size bound).

    Returns the number of payload files copied.
    """
    import asyncio

    from .io_types import ReadIO, WriteIO
    from .snapshot import Snapshot
    from .storage_plugin import (
        strip_mirror_options,
        url_to_storage_plugin_in_event_loop,
    )

    # Mirror settings name the SOURCE snapshot's mirror; they must not leak
    # onto origin snapshots or the destination (the consolidated result is
    # single-tier — mirror it explicitly if desired).
    storage_options = strip_mirror_options(storage_options)
    metadata = Snapshot(src_path, storage_options=storage_options).metadata

    # One copy per distinct location; byte-ranged payloads (batched slabs)
    # share their slab file, which is copied whole so ranges stay valid.
    locations: Dict[str, Optional[str]] = {}
    for entry in metadata.manifest.values():
        payloads = list(_iter_payload_entries(entry))
        if isinstance(entry, ObjectEntry):
            payloads.append(entry)
        for p in payloads:
            locations.setdefault(p.location, p.origin)
            if p.origin is None:
                locations[p.location] = None  # prefer the local copy

    event_loop = asyncio.new_event_loop()
    # Plugin construction drives the event loop itself, so resolve every
    # source up front — inside copy_all the loop is already running.
    plugins = {
        None: url_to_storage_plugin_in_event_loop(
            dst_path, event_loop, storage_options
        )
    }
    origin_mirrors = metadata.origin_mirrors or {}
    for origin in {org or src_path for org in locations.values()}:
        opts = dict(storage_options or {})
        # Origin sources read through the origin's OWN mirror (recorded
        # at take time), so consolidation works even after a base's
        # primary tier is lost — same fallback the restore path uses.
        mirror = origin_mirrors.get(origin) or (
            metadata.mirror_url if origin == src_path else None
        )
        if mirror and canonical_base_url(mirror) != canonical_base_url(origin):
            opts["mirror_url"] = mirror
        plugins[origin] = url_to_storage_plugin_in_event_loop(
            origin, event_loop, opts or None
        )

    async def copy_all() -> None:
        sem = asyncio.Semaphore(max(1, io_concurrency))

        async def copy_one(location: str, origin: Optional[str]) -> None:
            async with sem:
                read_io = ReadIO(path=location)
                await plugins[origin or src_path].read(read_io)
                await plugins[None].write(WriteIO(path=location, buf=read_io.buf))

        await asyncio.gather(
            *(copy_one(loc, org) for loc, org in locations.items())
        )

    try:
        event_loop.run_until_complete(copy_all())
        for entry in metadata.manifest.values():
            for p in _iter_payload_entries(entry):
                p.origin = None
            if isinstance(entry, ObjectEntry):
                entry.origin = None
        # Fold committed delta-journal epochs (journal.py) into the copied
        # payloads, BEFORE the metadata commit: the consolidated snapshot
        # then equals base + replay with no journal to carry (the .journal
        # directory is never among the manifest locations copied above).
        _compact_journal(src_path, metadata, plugins[None], event_loop)
        # The consolidated snapshot is self-contained and single-tier.
        metadata.origin_mirrors = None
        metadata.mirror_url = None
        Snapshot._write_snapshot_metadata(metadata, plugins[None], event_loop)
    finally:
        for plugin in plugins.values():
            plugin.sync_close(event_loop)
        event_loop.close()
    return len(locations)


def _compact_journal(src_path, metadata, dst_plugin, event_loop) -> int:
    """Apply the final committed journal value of every journaled leaf to
    the destination payloads and their manifest entries.

    Raises ValueError when a record cannot be folded faithfully (corrupt
    journal, shape/dtype drift against the base entry, sharded or
    slab-compressed destinations) — consolidation must never silently drop
    committed state. Returns the number of records folded.
    """
    import os

    import numpy as np

    from . import journal as journal_mod
    from . import serialization
    from .integrity import compute_checksum
    from .io_types import ReadIO, WriteIO
    from .manifest import ChunkedArrayEntry as _Chunked
    from .manifest import PrimitiveEntry, ShardedArrayEntry
    from .storage_plugin import local_fs_root

    local = local_fs_root(src_path)
    if local is None:
        return 0
    jdir = os.path.join(local, journal_mod.JOURNAL_DIRNAME)
    if not os.path.isdir(jdir):
        return 0
    committed = journal_mod.committed_epochs(journal_mod.read_epoch_metas(jdir))
    if not committed:
        return 0
    updates = {}  # manifest key -> (header, payload)
    for rank_str in sorted(committed[-1].get("offsets", {}), key=int):
        rank = int(rank_str)
        ups, err, _tail = journal_mod.collect_rank_updates(jdir, rank, committed)
        if err is not None:
            raise ValueError(
                f"journal of {src_path} cannot be read ({err}); fix it with "
                "fsck before consolidating"
            )
        for key, rec in ups.items():
            updates[f"{rank}/{key}"] = rec

    def write_payload(array_entry, buf) -> None:
        """Replace one ArrayEntry/ObjectEntry's stored bytes in dst and
        refresh its integrity fields (uncompressed content in ``buf``)."""
        stored = buf
        if array_entry.codec:
            if array_entry.byte_range is not None:
                raise ValueError(
                    f"cannot compact journal into compressed slab payload "
                    f"{array_entry.location}"
                )
            from .compression import compress

            stored = compress(array_entry.codec, buf)
        if array_entry.byte_range is not None:
            lo, hi = array_entry.byte_range
            if hi - lo != len(stored):
                raise ValueError(
                    f"journal record size {len(stored)} != slab range "
                    f"[{lo}, {hi}) of {array_entry.location}"
                )
            read_io = ReadIO(path=array_entry.location)
            event_loop.run_until_complete(dst_plugin.read(read_io))
            slab = bytearray(read_io.buf)
            slab[lo:hi] = stored
            event_loop.run_until_complete(
                dst_plugin.write(WriteIO(path=array_entry.location, buf=slab))
            )
        else:
            event_loop.run_until_complete(
                dst_plugin.write(
                    WriteIO(path=array_entry.location, buf=bytes(stored))
                )
            )
        if array_entry.checksum is not None:
            array_entry.checksum = compute_checksum(stored)
        if getattr(array_entry, "digest", None) is not None:
            array_entry.digest = compute_digest(buf)
        if getattr(array_entry, "device_digest", None) is not None:
            array_entry.device_digest = None  # stale: content replaced

    folded = 0
    for mkey, (header, payload) in sorted(updates.items()):
        entry = metadata.manifest.get(mkey)
        if entry is None:
            raise ValueError(
                f"journaled key {mkey!r} has no entry in the base manifest "
                "(state grew a new leaf after the base snapshot); restore "
                "and retake instead of consolidating"
            )
        kind = header.get("kind")
        if isinstance(entry, PrimitiveEntry):
            if kind != "object":
                raise ValueError(
                    f"journaled key {mkey!r} changed type against the base "
                    "snapshot; restore and retake instead of consolidating"
                )
            value = serialization.object_from_bytes(payload)
            metadata.manifest[mkey] = PrimitiveEntry.from_object(
                value, replicated=entry.replicated
            )
        elif isinstance(entry, ObjectEntry):
            if kind != "object":
                raise ValueError(
                    f"journaled key {mkey!r} changed type against the base "
                    "snapshot; restore and retake instead of consolidating"
                )
            write_payload(entry, bytes(payload))
            if entry.size is not None:
                entry.size = len(payload)
        elif isinstance(entry, ArrayEntry):
            if kind != "array" or entry.dtype != header.get("dtype") or list(
                entry.shape
            ) != list(header.get("shape", [])):
                raise ValueError(
                    f"journaled array {mkey!r} drifted in dtype/shape "
                    "against the base snapshot; restore and retake instead "
                    "of consolidating"
                )
            write_payload(entry, payload)
        elif isinstance(entry, _Chunked):
            if kind != "array" or entry.dtype != header.get("dtype") or list(
                entry.shape
            ) != list(header.get("shape", [])):
                raise ValueError(
                    f"journaled array {mkey!r} drifted in dtype/shape "
                    "against the base snapshot; restore and retake instead "
                    "of consolidating"
                )
            arr = serialization.array_from_buffer(
                payload, header["dtype"], header["shape"]
            )
            for chunk in entry.chunks:
                box = tuple(
                    slice(o, o + s)
                    for o, s in zip(chunk.offsets, chunk.sizes)
                )
                piece = np.ascontiguousarray(arr[box])
                write_payload(
                    chunk.array, serialization.array_as_memoryview(piece)
                )
        elif isinstance(entry, ShardedArrayEntry):
            raise ValueError(
                f"journaled key {mkey!r} is a sharded array; consolidating "
                "journaled shards is not supported — restore and retake"
            )
        else:
            raise ValueError(
                f"journaled key {mkey!r} maps to unsupported entry type "
                f"{type(entry).__name__}; restore and retake"
            )
        folded += 1
    return folded
