"""Command-line snapshot inspection and maintenance.

``python -m torchsnapshot_tpu <command> <path> [...]``

The reference library has no CLI; operationally, though, "what is in this
checkpoint / is it intact / convert it" are the three questions every
on-call asks, so they get first-class commands here:

- ``info``     — version, world size, entry counts, payload bytes.
- ``ls``       — one line per logical entry: type, dtype/shape, size.
- ``cat``      — print one entry via ``Snapshot.read_object``.
- ``verify``   — re-hash every payload against its recorded checksum
  (end-to-end CRC32C integrity, see integrity.py).
- ``migrate``  — convert a reference-format (pytorch/torchsnapshot)
  snapshot to native format (tricks/torchsnapshot_interop.py).
- ``consolidate`` — materialize an incremental snapshot as a
  self-contained one so its base snapshots can be deleted (dedup.py).
- ``diff``     — compare two snapshots leaf by leaf (added/removed/
  changed/unchanged) using recorded content digests where available,
  falling back to checksum then shape/dtype.
- ``deps``     — scan a directory of snapshots and print the incremental
  origin graph: which snapshots reference which bases, and which are
  safe to delete (referenced by no other snapshot in the directory).
- ``prune``    — retention: keep the newest N snapshots in a directory,
  delete the rest EXCEPT bases that kept snapshots still reference.
  Prints the plan; ``--yes`` executes it (local filesystem only).
- ``stats``    — render the telemetry summary a take persisted next to
  ``.snapshot_metadata`` (phase walls, per-rank counters, fleet skew;
  see telemetry/ and docs/source/telemetry.rst). Answers "why was this
  take slow?" after the process is gone.

The inspection commands (``info``/``ls``/``cat``/``verify``) and
``consolidate`` work over any registered storage backend (fs://, s3://,
gs://) because they reuse the plugin layer; plain paths mean fs.
``migrate`` reads the reference format from the local filesystem only.
"""

from __future__ import annotations

import argparse
import asyncio
import sys
from typing import Any, Dict, List, Optional, Tuple

from .integrity import IntegrityError, verify_checksum
from .io_types import ReadIO
from .manifest import (
    ArrayEntry,
    ChunkedArrayEntry,
    Entry,
    ObjectEntry,
    PrimitiveEntry,
    ShardedArrayEntry,
    SnapshotMetadata,
    is_container_entry,
)
from .serialization import array_size_bytes


def _array_nbytes(entry: ArrayEntry) -> Optional[int]:
    if entry.byte_range is not None:
        return entry.byte_range[1] - entry.byte_range[0]
    try:
        return array_size_bytes(entry.shape, entry.dtype)
    except ValueError:
        return None


def _entry_payloads(
    entry: Entry,
) -> List[Tuple[str, Optional[List[int]], Optional[str], Optional[int], Optional[str]]]:
    """(location, byte_range, checksum, nbytes, origin) per payload the
    entry owns. ``origin`` is the base snapshot holding the bytes when the
    entry was deduplicated by an incremental take."""
    if isinstance(entry, ArrayEntry):
        return [
            (entry.location, entry.byte_range, entry.checksum,
             _array_nbytes(entry), entry.origin)
        ]
    if isinstance(entry, ChunkedArrayEntry):
        return [
            (c.array.location, c.array.byte_range, c.array.checksum,
             _array_nbytes(c.array), c.array.origin)
            for c in entry.chunks
        ]
    if isinstance(entry, ShardedArrayEntry):
        return [
            (s.array.location, s.array.byte_range, s.array.checksum,
             _array_nbytes(s.array), s.array.origin)
            for s in entry.shards
        ]
    if isinstance(entry, ObjectEntry):
        return [(entry.location, None, entry.checksum, entry.size, entry.origin)]
    return []


def _entry_nbytes(entry: Entry) -> Optional[int]:
    try:
        if isinstance(entry, ArrayEntry):
            if entry.byte_range is not None:
                return entry.byte_range[1] - entry.byte_range[0]
            return array_size_bytes(entry.shape, entry.dtype)
        if isinstance(entry, (ChunkedArrayEntry, ShardedArrayEntry)):
            return array_size_bytes(entry.shape, entry.dtype)
        if isinstance(entry, ObjectEntry):
            return entry.size
        if isinstance(entry, PrimitiveEntry):
            return 0  # inlined in the metadata; no storage payload
    except ValueError:
        return None
    return None


def _entry_desc(entry: Entry) -> str:
    if isinstance(entry, (ArrayEntry, ChunkedArrayEntry, ShardedArrayEntry)):
        extra = ""
        if isinstance(entry, ChunkedArrayEntry):
            extra = f" ({len(entry.chunks)} chunks)"
        elif isinstance(entry, ShardedArrayEntry):
            extra = f" ({len(entry.shards)} shards)"
        return f"{entry.dtype}{list(entry.shape)}{extra}"
    if isinstance(entry, ObjectEntry):
        return entry.obj_type
    if isinstance(entry, PrimitiveEntry):
        val = entry.readable
        return f"{entry.ptype}={val[:40]}{'…' if len(val) > 40 else ''}"
    return ""


# Shared with the telemetry stats rendering so sizes read identically
# across info/ls/stats.
from .telemetry.export import fmt_bytes as _fmt_bytes  # noqa: E402


def _load_metadata(path: str) -> SnapshotMetadata:
    from .snapshot import Snapshot

    return Snapshot(path).metadata


def cmd_info(args: argparse.Namespace) -> int:
    meta = _load_metadata(args.path)
    counts: Dict[str, int] = {}
    # Replicated entries repeat under every rank prefix but share storage;
    # dedup payloads by (location, byte_range) so sizes reflect bytes on
    # disk, not bytes times world_size (same rule cmd_verify applies).
    payloads: Dict[Tuple[str, Optional[Tuple[int, int]]], Tuple[Optional[str], Optional[int], Optional[str]]] = {}
    for entry in meta.manifest.values():
        counts[entry.type] = counts.get(entry.type, 0) + 1
        for location, byte_range, checksum, nbytes, origin in _entry_payloads(entry):
            key = (location, tuple(byte_range) if byte_range else None)
            payloads.setdefault(key, (checksum, nbytes, origin))
    local = {k: v for k, v in payloads.items() if v[2] is None}
    external = {k: v for k, v in payloads.items() if v[2] is not None}
    total = sum(n for _, n, _ in local.values() if n is not None)
    unsized = sum(1 for _, n, _ in local.values() if n is None)
    checksummed = sum(1 for c, _, _ in payloads.values() if c is not None)
    print(f"path:        {args.path}")
    print(f"version:     {meta.version}")
    print(f"world_size:  {meta.world_size}")
    print(f"entries:     {len(meta.manifest)}")
    for typ in sorted(counts):
        print(f"  {typ}: {counts[typ]}")
    print(f"payload:     {_fmt_bytes(total)}"
          + (f" (+{unsized} payloads of unknown size)" if unsized else ""))
    if external:
        ext_total = sum(n for _, n, _ in external.values() if n is not None)
        origins = sorted({o for _, _, o in external.values()})
        print(f"external:    {len(external)} payloads ({_fmt_bytes(ext_total)}) "
              f"referenced from base snapshot(s): {', '.join(origins)}")
        mirrored = meta.origin_mirrors or {}
        if all(o in mirrored for o in origins):
            print("             (every base's mirror is recorded: restore "
                  "survives loss of the bases' primary tiers)")
        else:
            print("             (bases must remain intact for restore)")
    print(f"checksums:   {checksummed}/{len(payloads)} payloads")
    # Per distinct payload like the stats above — replicated entries
    # repeat under every rank prefix but share storage.
    codec_of: Dict[Tuple[str, Optional[Tuple[int, int]]], str] = {}
    for entry in meta.manifest.values():
        subs = [entry]
        for attr in ("chunks", "shards"):
            subs.extend(s.array for s in getattr(entry, attr, []) or [])
        for sub in subs:
            codec = getattr(sub, "codec", None)
            if codec is not None:
                br = getattr(sub, "byte_range", None)
                codec_of[(sub.location, tuple(br) if br else None)] = codec
    if codec_of:
        codecs: Dict[str, int] = {}
        for codec in codec_of.values():
            codecs[codec] = codecs.get(codec, 0) + 1
        summary = ", ".join(f"{c} x{n}" for c, n in sorted(codecs.items()))
        print(f"compression: {summary}")
    return 0


def cmd_ls(args: argparse.Namespace) -> int:
    meta = _load_metadata(args.path)
    for path, entry in meta.manifest.items():
        if args.rank is not None and not path.startswith(f"{args.rank}/"):
            continue
        if is_container_entry(entry) and not args.all:
            continue
        if is_container_entry(entry) or isinstance(entry, PrimitiveEntry):
            size = ""
        else:
            size = _fmt_bytes(_entry_nbytes(entry))
        print(f"{path:60s} {entry.type:14s} {_entry_desc(entry):40s} {size}")
    return 0


def cmd_cat(args: argparse.Namespace) -> int:
    from .snapshot import Snapshot

    value = Snapshot(args.path).read_object(args.entry)
    import numpy as np

    if isinstance(value, np.ndarray) or hasattr(value, "shape"):
        arr = np.asarray(value)
        print(f"{arr.dtype}{list(arr.shape)}")
        with np.printoptions(threshold=args.limit, edgeitems=4):
            print(arr)
    else:
        print(repr(value))
    return 0


def cmd_verify(args: argparse.Namespace) -> int:
    from .storage_plugin import url_to_storage_plugin_in_event_loop

    meta = _load_metadata(args.path)
    # Replicated entries appear under every rank prefix and chunked stripes
    # can share a location: verify each distinct payload once. Payloads an
    # incremental take left in a base snapshot are verified there (grouped
    # by origin so each base's plugin opens once).
    seen: Dict[Tuple[Optional[str], str, Optional[Tuple[int, int]]], Optional[str]] = {}
    for entry in meta.manifest.values():
        for location, byte_range, checksum, _, origin in _entry_payloads(entry):
            key = (origin, location, tuple(byte_range) if byte_range else None)
            seen.setdefault(key, checksum)
    by_origin: Dict[Optional[str], List[Tuple[str, Optional[Tuple[int, int]], Optional[str]]]] = {}
    for (origin, location, byte_range), checksum in sorted(
        seen.items(), key=lambda kv: (kv[0][0] or "", kv[0][1])
    ):
        by_origin.setdefault(origin, []).append((location, byte_range, checksum))

    event_loop = asyncio.new_event_loop()
    ok = skipped = failed = 0
    origin_mirrors = meta.origin_mirrors or {}
    try:
        for origin, payloads in by_origin.items():
            # Restore-equivalent semantics: origin payloads verify through
            # the origin's recorded mirror fallback, so verify agrees with
            # what restore can actually read (incl. after primary loss).
            opts = None
            mirror = origin_mirrors.get(origin) if origin is not None else None
            if mirror:
                opts = {"mirror_url": mirror}
            storage = url_to_storage_plugin_in_event_loop(
                origin if origin is not None else args.path, event_loop, opts
            )
            where = f" [{origin}]" if origin is not None else ""
            try:
                for location, byte_range, checksum in payloads:
                    if checksum is None:
                        skipped += 1
                        if args.verbose:
                            print(f"SKIP  {location}{where} (no checksum recorded)")
                        continue
                    read_io = ReadIO(path=location, byte_range=byte_range)
                    try:
                        event_loop.run_until_complete(storage.read(read_io))
                        verify_checksum(read_io.buf, checksum, location)
                    except (IntegrityError, OSError) as e:
                        failed += 1
                        print(f"FAIL  {location}{where}: {e}")
                        continue
                    ok += 1
                    if args.verbose:
                        print(f"OK    {location}{where}")
            finally:
                storage.sync_close(event_loop)
    finally:
        event_loop.close()
    print(f"verified {ok} payloads, {skipped} without checksums, {failed} failed")
    return 1 if failed else 0


def cmd_migrate(args: argparse.Namespace) -> int:
    from .tricks.torchsnapshot_interop import (
        migrate_from_torchsnapshot,
        read_metadata,
    )

    raw = read_metadata(args.src)  # ValueError on malformed metadata
    if _looks_native(raw["manifest"]):
        print(f"{args.src} is already a native snapshot; nothing to migrate.")
        return 1
    _, state = migrate_from_torchsnapshot(args.src, args.dst, rank=args.rank)
    from .flatten import flatten

    n = len(flatten(state)[1])
    print(f"migrated {n} leaves from {args.src} -> {args.dst}")
    return 0


def _looks_native(raw_manifest: Dict[str, Any]) -> bool:
    """Distinguish a native manifest from a reference-format one.

    Container and object type names collide between the formats, so a
    bare type-set subset test misfires on tensor-free reference snapshots.
    Reference-only markers: capitalized tensor types, primitive entries
    carrying ``serialized_value``, and ``torch_save``-serialized objects.
    """
    for entry in raw_manifest.values():
        if not isinstance(entry, dict):
            raise ValueError("Malformed manifest: entries must be mappings")
        if entry.get("type") in ("Tensor", "ChunkedTensor", "ShardedTensor"):
            return False
        if "serialized_value" in entry:
            return False
        if entry.get("serializer") == "torch_save":
            return False
    return True


def _sub_payload_entries(entry: Entry) -> List[Tuple[Optional[Tuple[int, ...]], Any]]:
    """(chunk/shard box, payload-entry) pairs — the per-payload alignment
    unit for content comparison. Plain arrays/objects have one boxless
    payload; chunked/sharded entries align by their N-D (offsets, sizes)
    so each sub-entry's own digest/checksum is compared (slab-batched
    payloads share a location, so location is NOT a safe key)."""
    if isinstance(entry, (ArrayEntry, ObjectEntry)):
        return [(None, entry)]
    if isinstance(entry, ChunkedArrayEntry):
        return [
            ((*c.offsets, *c.sizes), c.array) for c in entry.chunks
        ]
    if isinstance(entry, ShardedArrayEntry):
        return [
            ((*s.offsets, *s.sizes), s.array) for s in entry.shards
        ]
    return []


def _leaf_compare(ea: Entry, eb: Entry) -> str:
    """'same' | 'changed' | 'unknown' for two leaf entries.

    Exactness degrades to the strongest evidence available on BOTH sides:
    content digests, else same-algorithm integrity checksums, else only
    structure — in which case equality is 'unknown', never claimed.
    Comparison is chunk/shard-layout-sensitive by construction: identical
    content striped differently (e.g. saved at different world sizes)
    reports as changed.
    """
    if ea.type != eb.type:
        return "changed"
    if isinstance(ea, PrimitiveEntry):
        return (
            "same"
            if (ea.ptype, ea.readable) == (eb.ptype, eb.readable)
            else "changed"
        )
    if str(getattr(ea, "dtype", None)) != str(getattr(eb, "dtype", None)):
        return "changed"
    if list(getattr(ea, "shape", []) or []) != list(getattr(eb, "shape", []) or []):
        return "changed"
    if (
        isinstance(ea, ObjectEntry)
        and ea.size is not None
        and eb.size is not None
        and ea.size != eb.size
    ):
        return "changed"
    pa = dict(_sub_payload_entries(ea))
    pb = dict(_sub_payload_entries(eb))
    if set(pa) != set(pb):
        return "changed"  # different chunk/shard layout
    unknown = False
    for box, sub_a in pa.items():
        sub_b = pb[box]
        if sub_a.digest is not None and sub_b.digest is not None:
            # Digests cover the uncompressed content — codec-independent.
            if sub_a.digest != sub_b.digest:
                return "changed"
        elif (
            sub_a.checksum is not None
            and sub_b.checksum is not None
            and sub_a.checksum.partition(":")[0] == sub_b.checksum.partition(":")[0]
            # Checksums cover the STORED bytes: only comparable when both
            # sides stored the same form (same codec, or both raw) —
            # identical content saved raw vs compressed hashes differently.
            and getattr(sub_a, "codec", None) == getattr(sub_b, "codec", None)
        ):
            if sub_a.checksum != sub_b.checksum:
                return "changed"
        else:
            unknown = True
    return "unknown" if unknown else "same"


def cmd_diff(args: argparse.Namespace) -> int:
    meta_a = _load_metadata(args.a)
    meta_b = _load_metadata(args.b)

    def leaves(meta):
        return {
            p: e for p, e in meta.manifest.items() if not is_container_entry(e)
        }

    a, b = leaves(meta_a), leaves(meta_b)
    added = sorted(set(b) - set(a))
    removed = sorted(set(a) - set(b))
    changed, unchanged, uncertain = [], [], []
    for p in sorted(set(a) & set(b)):
        status = _leaf_compare(a[p], b[p])
        if status == "changed":
            changed.append(p)
        elif status == "same":
            unchanged.append(p)
        else:
            uncertain.append(p)
    for p in added:
        print(f"+ {p}")
    for p in removed:
        print(f"- {p}")
    for p in changed:
        print(f"~ {p}  ({_entry_desc(b[p])})")
    if args.verbose:
        for p in unchanged:
            print(f"= {p}")
        for p in uncertain:
            print(f"? {p}  (structure equal; no digest/checksum common to "
                  "both snapshots)")
    print(
        f"{len(added)} added, {len(removed)} removed, {len(changed)} changed, "
        f"{len(unchanged)} unchanged"
        + (f", {len(uncertain)} indeterminate" if uncertain else "")
    )
    return 1 if (added or removed or changed) else 0


def _canon_snapshot_url(url: str) -> str:
    """Canonical comparable form of a snapshot path/URL (fs:// == bare).

    Matches the canonicalization applied to origins at record time
    (dedup.canonical_base_url), plus fs://-vs-bare equivalence; realpath
    (not abspath) so symlinked checkpoint directories compare equal.
    """
    import os

    if url.startswith("fs://"):
        url = url[len("fs://"):]
    if "://" in url:
        return url  # remote URL: compare verbatim
    return os.path.realpath(url)


def cmd_deps(args: argparse.Namespace) -> int:
    import os

    dirpath = args.dir
    names, origins_of, _, _ = _scan_snapshot_dir(dirpath)
    snapshots = sorted(names)
    if not snapshots:
        print(f"no snapshots found under {dirpath}")
        return 2

    # origin URL -> set of snapshot names (in this dir) referencing it
    referenced: Dict[str, set] = {}
    for name, origins in origins_of.items():
        for origin in origins:
            referenced.setdefault(_canon_snapshot_url(origin), set()).add(name)

    canon_of = {
        name: _canon_snapshot_url(os.path.join(dirpath, name))
        for name in snapshots
    }
    safe = []
    for name in snapshots:
        dependents = referenced.get(canon_of[name], set())
        origins = origins_of[name]
        tag = ""
        if origins:
            tag += " <- bases: " + ", ".join(
                os.path.basename(o) for o in sorted(origins)
            )
        if dependents:
            tag += " [REQUIRED by " + ", ".join(sorted(dependents)) + "]"
        else:
            safe.append(name)
        print(f"{name}{tag}")
    local_canon = set(canon_of.values())
    external = {
        o
        for origins in origins_of.values()
        for o in origins
        if _canon_snapshot_url(o) not in local_canon
    }
    for o in sorted(external):
        print(f"(external base outside this directory: {o})")
    print(
        "safe to delete (no dependents here): "
        + (", ".join(safe) if safe else "none")
    )
    return 0


def _scan_snapshot_dir(dirpath: str):
    """(snapshots sorted by mtime asc, {name: origin set},
    {name: {origin: locations referenced in it}}) for a directory."""
    import os

    names = sorted(
        (
            name
            for name in os.listdir(dirpath)
            if os.path.isfile(os.path.join(dirpath, name, ".snapshot_metadata"))
        ),
        # Name tiebreaker: mtime granularity can collide (1s filesystems,
        # rsync-flattened trees); retention decisions must be deterministic.
        key=lambda n: (
            os.path.getmtime(os.path.join(dirpath, n, ".snapshot_metadata")),
            n,
        ),
    )
    origins_of = {}
    origin_locations_of = {}
    payloads_of = {}
    for name in names:
        meta = _load_metadata(os.path.join(dirpath, name))
        origins = set()
        locations = {}
        own = {}
        for entry in meta.manifest.values():
            for location, _, checksum, nbytes, origin in _entry_payloads(entry):
                if origin is not None:
                    origins.add(origin)
                    locations.setdefault(origin, {})[location] = (checksum, nbytes)
                else:
                    own[location] = (checksum, nbytes)
        origins_of[name] = origins
        origin_locations_of[name] = locations
        payloads_of[name] = own
    return names, origins_of, origin_locations_of, payloads_of


def cmd_prune(args: argparse.Namespace) -> int:
    import os

    from .retention import apply_retention, plan_retention

    if "://" in args.dir and not args.dir.startswith("fs://"):
        print("error: prune operates on local filesystem directories only",
              file=sys.stderr)
        return 2
    dirpath = args.dir[len("fs://"):] if args.dir.startswith("fs://") else args.dir
    if args.keep < 1:
        print("error: --keep must be >= 1", file=sys.stderr)
        return 2
    # One scan for both discovery and the plan: the keep-N policy is
    # evaluated inside plan_retention on its own scan, so a snapshot
    # committing concurrently can never be discovered-but-unprotected.
    plan = plan_retention(dirpath, args.keep)
    if not (plan.keep or plan.spared or plan.doomed):
        print(f"no snapshots found under {dirpath}")
        return 2
    unresolved, doomed = plan.unresolved, plan.doomed
    for name in plan.keep:
        print(f"keep    {name}")
    for name, by_name in plan.spared:
        suffix = ", matched by name" if by_name else ""
        print(f"keep    {name}  (base of a kept snapshot{suffix})")
    for name in doomed:
        print(f"delete  {name}")
    if unresolved:
        print(
            "warning: kept snapshot(s) depend on base(s) that resolve to no "
            "snapshot in this directory (moved tree, different mount path, "
            "or a base stored elsewhere):",
            file=sys.stderr,
        )
        for canon in sorted(unresolved):
            print(f"warning:   {canon}", file=sys.stderr)
    if not doomed:
        print("nothing to prune")
        return 0
    if not args.yes:
        print(f"dry run: would delete {len(doomed)} snapshot(s); "
              "re-run with --yes to execute")
        return 0
    if unresolved and not args.ignore_missing_bases:
        print(
            "refusing --yes: cannot prove the snapshots marked for deletion "
            "are not the unresolved base(s) above under a different name. "
            "Verify the bases exist (python -m torchsnapshot_tpu deps), then "
            "re-run with --ignore-missing-bases to delete anyway.",
            file=sys.stderr,
        )
        return 2
    n = apply_retention(dirpath, plan)
    print(f"deleted {n} snapshot(s)")
    return 0


def cmd_stats(args: argparse.Namespace) -> int:
    """Render the telemetry summary a take persisted next to its
    metadata (telemetry/export.py) — "why was this take slow?" answered
    after the fact, from any registered storage backend."""
    import json

    from .storage_plugin import url_to_storage_plugin_in_event_loop
    from .telemetry import (
        TELEMETRY_SUMMARY_FNAME,
        merge_summaries,
        render_summary_document,
    )

    event_loop = asyncio.new_event_loop()
    storage = url_to_storage_plugin_in_event_loop(args.path, event_loop, None)
    try:
        read_io = ReadIO(path=TELEMETRY_SUMMARY_FNAME)
        try:
            event_loop.run_until_complete(storage.read(read_io))
        except Exception as e:  # noqa: BLE001
            # Broad on purpose: a missing object surfaces as OSError on
            # fs but as botocore ClientError (NoSuchKey) / google-api
            # NotFound on the cloud plugins — the friendly hint must work
            # on every registered backend. The original error is included
            # so genuine transport problems stay diagnosable.
            print(
                f"error: could not read {TELEMETRY_SUMMARY_FNAME} from "
                f"{args.path} ({type(e).__name__}: {e}). If the snapshot "
                "exists, it was likely taken without telemetry — save "
                "with TORCHSNAPSHOT_TPU_TELEMETRY=1 to record a summary.",
                file=sys.stderr,
            )
            return 2
    finally:
        storage.sync_close(event_loop)
        event_loop.close()
    try:
        doc = json.loads(bytes(read_io.buf).decode("utf-8"))
    except ValueError as e:
        print(f"error: malformed telemetry summary: {e}", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(doc, indent=1))
        return 0
    if not doc.get("fleet"):
        # Documents written by future/foreign producers may omit the
        # merged view; re-derive it so the rendering stays complete.
        doc["fleet"] = merge_summaries(doc.get("ranks") or [])
    print(render_summary_document(doc, verbose=args.verbose))
    return 0


def cmd_consolidate(args: argparse.Namespace) -> int:
    from .dedup import consolidate

    n = consolidate(args.src, args.dst)
    print(f"consolidated {args.src} -> {args.dst} ({n} payloads copied; "
          "no base snapshots required)")
    return 0


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="python -m torchsnapshot_tpu",
        description="Inspect, verify, and migrate snapshots.",
    )
    sub = parser.add_subparsers(dest="command", required=True)

    p = sub.add_parser("info", help="summarize a snapshot")
    p.add_argument("path")
    p.set_defaults(fn=cmd_info)

    p = sub.add_parser("ls", help="list entries")
    p.add_argument("path")
    p.add_argument("--rank", type=int, default=None, help="only this rank's entries")
    p.add_argument("--all", action="store_true", help="include container entries")
    p.set_defaults(fn=cmd_ls)

    p = sub.add_parser("cat", help="print one entry (RANK/logical/path)")
    p.add_argument("path")
    p.add_argument("entry")
    p.add_argument("--limit", type=int, default=64, help="max array elements printed")
    p.set_defaults(fn=cmd_cat)

    p = sub.add_parser("verify", help="re-hash payloads against recorded checksums")
    p.add_argument("path")
    p.add_argument("-v", "--verbose", action="store_true")
    p.set_defaults(fn=cmd_verify)

    p = sub.add_parser(
        "stats",
        help="render the persisted telemetry summary of a take "
             "(requires TORCHSNAPSHOT_TPU_TELEMETRY=1 at save time)",
    )
    p.add_argument("path")
    p.add_argument("--json", action="store_true", help="dump the raw document")
    p.add_argument("-v", "--verbose", action="store_true",
                   help="include all spans and measured rates")
    p.set_defaults(fn=cmd_stats)

    p = sub.add_parser(
        "migrate", help="convert a reference-format snapshot to native format"
    )
    p.add_argument("src")
    p.add_argument("dst")
    p.add_argument("--rank", type=int, default=0)
    p.set_defaults(fn=cmd_migrate)

    p = sub.add_parser(
        "consolidate",
        help="materialize an incremental snapshot as a self-contained one",
    )
    p.add_argument("src")
    p.add_argument("dst")
    p.set_defaults(fn=cmd_consolidate)

    p = sub.add_parser("diff", help="compare two snapshots leaf by leaf")
    p.add_argument("a")
    p.add_argument("b")
    p.add_argument("-v", "--verbose", action="store_true",
                   help="also list unchanged/indeterminate leaves")
    p.set_defaults(fn=cmd_diff)

    p = sub.add_parser(
        "deps", help="origin graph of a directory of snapshots"
    )
    p.add_argument("dir")
    p.set_defaults(fn=cmd_deps)

    p = sub.add_parser(
        "prune",
        help="keep the newest N snapshots (and bases they require); "
             "delete the rest",
    )
    p.add_argument("dir")
    p.add_argument("--keep", type=int, required=True,
                   help="number of newest snapshots to keep")
    p.add_argument("--yes", action="store_true",
                   help="actually delete (default: print the plan)")
    p.add_argument("--ignore-missing-bases", action="store_true",
                   help="delete even when kept snapshots reference bases "
                        "that resolve to nothing in this directory")
    p.set_defaults(fn=cmd_prune)
    return parser


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    try:
        return args.fn(args)
    except (FileNotFoundError, RuntimeError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
