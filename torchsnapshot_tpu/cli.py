"""Command-line snapshot inspection and maintenance.

``python -m torchsnapshot_tpu <command> <path> [...]``

The reference library has no CLI; operationally, though, "what is in this
checkpoint / is it intact / convert it" are the three questions every
on-call asks, so they get first-class commands here:

- ``info``     — version, world size, entry counts, payload bytes.
- ``ls``       — one line per logical entry: type, dtype/shape, size.
- ``cat``      — print one entry via ``Snapshot.read_object``.
- ``verify``   — re-hash every payload against its recorded checksum
  (end-to-end CRC32C integrity, see integrity.py).
- ``fsck``     — full consistency check: manifest<->payload existence/
  size/CRC agreement, incremental-chain (deps) integrity, orphan and
  partial-commit detection, and delta-journal integrity (torn tails,
  orphan epochs, corrupt committed records; internal artifact dirs are
  recognized via ``INTERNAL_ARTIFACTS``, one registry); ``--repair``
  quarantines orphans and truncates torn journal tails under
  ``.fsck_quarantine/``. Exit codes: 0 clean, 1 findings, 2 cannot-check
  (see docs/source/fault_tolerance.rst).
- ``migrate``  — convert a reference-format (pytorch/torchsnapshot)
  snapshot to native format (tricks/torchsnapshot_interop.py).
- ``consolidate`` — materialize an incremental snapshot as a
  self-contained one so its base snapshots can be deleted (dedup.py).
- ``diff``     — compare two snapshots leaf by leaf (added/removed/
  changed/unchanged) using recorded content digests where available,
  falling back to checksum then shape/dtype.
- ``deps``     — scan a directory of snapshots and print the incremental
  origin graph: which snapshots reference which bases, and which are
  safe to delete (referenced by no other snapshot in the directory).
- ``prune``    — retention: keep the newest N snapshots in a directory,
  delete the rest EXCEPT bases that kept snapshots still reference.
  Prints the plan; ``--yes`` executes it (local filesystem only).
- ``stats``    — render the telemetry summary a take persisted next to
  ``.snapshot_metadata`` (phase walls, per-rank counters, fleet skew;
  see telemetry/ and docs/source/telemetry.rst). Answers "why was this
  take slow?" after the process is gone. ``--trend`` renders the
  checkpoint history journal of a ROOT directory and exits non-zero on
  a p50 regression; ``--openmetrics`` emits the summary in OpenMetrics
  text format for scrape pipelines.
- ``explain``  — critical-path attribution of a take/restore
  (telemetry/critpath.py): which resource (staging copy, hash, storage
  write/read, decode, collective wait) bound the wall clock, on which
  rank, at what measured rate, and what to tune. Exit code 1 means
  storage-bound, 0 pipeline-bound — benches assert the ROADMAP claim
  with it.
- ``plan``     — dry-run the minimal-movement reshard plan (reshard.py)
  for restoring under a different layout at a different world size:
  per-entry and total storage bytes (planned vs direct) and
  peer-channel bundle bytes, from manifest geometry alone.
- ``blackbox`` — merge the per-rank flight-recorder dumps an aborted
  operation left under ``<snapshot>/.flight/`` into one causal
  cross-rank timeline: who deserted whom at which barrier, store
  failovers with epochs, refused (stale) commits with generations
  (telemetry/flightrec.py; always on by default).
- ``watch``    — live fleet view of an in-flight take/restore from the
  heartbeat keys every rank publishes through the coordination store:
  per-rank phase/bytes/ETA, stalled-rank flags, and skew — visible
  BEFORE the barrier timeout turns a stall into an abort.
- ``store-status`` — probe a live coordination-store node (leader or
  standby): role, epoch, op-log position, per-replica lag and lease age
  (dist_store replication tier; docs/source/fault_tolerance.rst).

- ``georep-status`` — the geo-replication plane of a snapshot ROOT:
  remote cursor position, last applied generation, backlog epochs and
  measured lag (georep.py; docs/source/fault_tolerance.rst,
  "Cross-region disaster recovery").

The inspection commands (``info``/``ls``/``cat``/``verify``) and
``consolidate`` work over any registered storage backend (fs://, s3://,
gs://) because they reuse the plugin layer; plain paths mean fs.
``migrate`` reads the reference format from the local filesystem only.
"""

from __future__ import annotations

import argparse
import asyncio
import os
import sys
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from .analysis import runner as analysis_runner
from .integrity import IntegrityError, verify_checksum
from .io_types import ReadIO
from .journal import JOURNAL_DIRNAME
from .manifest import (
    ArrayEntry,
    ChunkedArrayEntry,
    Entry,
    ObjectEntry,
    PrimitiveEntry,
    ShardedArrayEntry,
    SnapshotMetadata,
    is_container_entry,
)
from .serialization import array_size_bytes


def _array_nbytes(entry: ArrayEntry) -> Optional[int]:
    if entry.byte_range is not None:
        return entry.byte_range[1] - entry.byte_range[0]
    try:
        return array_size_bytes(entry.shape, entry.dtype)
    except ValueError:
        return None


def _entry_payloads_ex(
    entry: Entry,
) -> List[
    Tuple[
        str,
        Optional[List[int]],
        Optional[str],
        Optional[int],
        Optional[str],
        Optional[str],
    ]
]:
    """(location, byte_range, checksum, nbytes, origin, codec) per payload
    the entry owns. ``origin`` is the base snapshot holding the bytes when
    the entry was deduplicated by an incremental take; ``codec`` the
    compression codec (stored size != ``nbytes`` when set)."""
    if isinstance(entry, ArrayEntry):
        return [
            (entry.location, entry.byte_range, entry.checksum,
             _array_nbytes(entry), entry.origin, entry.codec)
        ]
    if isinstance(entry, ChunkedArrayEntry):
        return [
            (c.array.location, c.array.byte_range, c.array.checksum,
             _array_nbytes(c.array), c.array.origin, c.array.codec)
            for c in entry.chunks
        ]
    if isinstance(entry, ShardedArrayEntry):
        return [
            (s.array.location, s.array.byte_range, s.array.checksum,
             _array_nbytes(s.array), s.array.origin, s.array.codec)
            for s in entry.shards
        ]
    if isinstance(entry, ObjectEntry):
        return [
            (entry.location, None, entry.checksum, entry.size, entry.origin,
             getattr(entry, "codec", None))
        ]
    return []


def _entry_payloads(
    entry: Entry,
) -> List[Tuple[str, Optional[List[int]], Optional[str], Optional[int], Optional[str]]]:
    """(location, byte_range, checksum, nbytes, origin) — the historical
    5-tuple view (tests and external tooling unpack it); fsck uses the
    codec-aware ``_entry_payloads_ex``."""
    return [p[:5] for p in _entry_payloads_ex(entry)]


def _entry_nbytes(entry: Entry) -> Optional[int]:
    try:
        if isinstance(entry, ArrayEntry):
            if entry.byte_range is not None:
                return entry.byte_range[1] - entry.byte_range[0]
            return array_size_bytes(entry.shape, entry.dtype)
        if isinstance(entry, (ChunkedArrayEntry, ShardedArrayEntry)):
            return array_size_bytes(entry.shape, entry.dtype)
        if isinstance(entry, ObjectEntry):
            return entry.size
        if isinstance(entry, PrimitiveEntry):
            return 0  # inlined in the metadata; no storage payload
    except ValueError:
        return None
    return None


def _entry_desc(entry: Entry) -> str:
    if isinstance(entry, (ArrayEntry, ChunkedArrayEntry, ShardedArrayEntry)):
        extra = ""
        if isinstance(entry, ChunkedArrayEntry):
            extra = f" ({len(entry.chunks)} chunks)"
        elif isinstance(entry, ShardedArrayEntry):
            extra = f" ({len(entry.shards)} shards)"
        return f"{entry.dtype}{list(entry.shape)}{extra}"
    if isinstance(entry, ObjectEntry):
        return entry.obj_type
    if isinstance(entry, PrimitiveEntry):
        val = entry.readable
        return f"{entry.ptype}={val[:40]}{'…' if len(val) > 40 else ''}"
    return ""


# Shared with the telemetry stats rendering so sizes read identically
# across info/ls/stats.
from .telemetry.export import fmt_bytes as _fmt_bytes  # noqa: E402


def _load_metadata(path: str) -> SnapshotMetadata:
    from .snapshot import Snapshot

    return Snapshot(path).metadata


def cmd_info(args: argparse.Namespace) -> int:
    meta = _load_metadata(args.path)
    counts: Dict[str, int] = {}
    # Replicated entries repeat under every rank prefix but share storage;
    # dedup payloads by (location, byte_range) so sizes reflect bytes on
    # disk, not bytes times world_size (same rule cmd_verify applies).
    payloads: Dict[Tuple[str, Optional[Tuple[int, int]]], Tuple[Optional[str], Optional[int], Optional[str]]] = {}
    for entry in meta.manifest.values():
        counts[entry.type] = counts.get(entry.type, 0) + 1
        for location, byte_range, checksum, nbytes, origin in _entry_payloads(entry):
            key = (location, tuple(byte_range) if byte_range else None)
            payloads.setdefault(key, (checksum, nbytes, origin))
    local = {k: v for k, v in payloads.items() if v[2] is None}
    external = {k: v for k, v in payloads.items() if v[2] is not None}
    total = sum(n for _, n, _ in local.values() if n is not None)
    unsized = sum(1 for _, n, _ in local.values() if n is None)
    checksummed = sum(1 for c, _, _ in payloads.values() if c is not None)
    print(f"path:        {args.path}")
    print(f"version:     {meta.version}")
    print(f"world_size:  {meta.world_size}")
    print(f"entries:     {len(meta.manifest)}")
    for typ in sorted(counts):
        print(f"  {typ}: {counts[typ]}")
    print(f"payload:     {_fmt_bytes(total)}"
          + (f" (+{unsized} payloads of unknown size)" if unsized else ""))
    if external:
        ext_total = sum(n for _, n, _ in external.values() if n is not None)
        origins = sorted({o for _, _, o in external.values()})
        print(f"external:    {len(external)} payloads ({_fmt_bytes(ext_total)}) "
              f"referenced from base snapshot(s): {', '.join(origins)}")
        mirrored = meta.origin_mirrors or {}
        if all(o in mirrored for o in origins):
            print("             (every base's mirror is recorded: restore "
                  "survives loss of the bases' primary tiers)")
        else:
            print("             (bases must remain intact for restore)")
    print(f"checksums:   {checksummed}/{len(payloads)} payloads")
    # Per distinct payload like the stats above — replicated entries
    # repeat under every rank prefix but share storage.
    codec_of: Dict[Tuple[str, Optional[Tuple[int, int]]], str] = {}
    for entry in meta.manifest.values():
        subs = [entry]
        for attr in ("chunks", "shards"):
            subs.extend(s.array for s in getattr(entry, attr, []) or [])
        for sub in subs:
            codec = getattr(sub, "codec", None)
            if codec is not None:
                br = getattr(sub, "byte_range", None)
                codec_of[(sub.location, tuple(br) if br else None)] = codec
    if codec_of:
        codecs: Dict[str, int] = {}
        for codec in codec_of.values():
            codecs[codec] = codecs.get(codec, 0) + 1
        summary = ", ".join(f"{c} x{n}" for c, n in sorted(codecs.items()))
        print(f"compression: {summary}")
    return 0


def cmd_ls(args: argparse.Namespace) -> int:
    meta = _load_metadata(args.path)
    for path, entry in meta.manifest.items():
        if args.rank is not None and not path.startswith(f"{args.rank}/"):
            continue
        if is_container_entry(entry) and not args.all:
            continue
        if is_container_entry(entry) or isinstance(entry, PrimitiveEntry):
            size = ""
        else:
            size = _fmt_bytes(_entry_nbytes(entry))
        print(f"{path:60s} {entry.type:14s} {_entry_desc(entry):40s} {size}")
    return 0


def cmd_cat(args: argparse.Namespace) -> int:
    from .snapshot import Snapshot

    value = Snapshot(args.path).read_object(args.entry)
    import numpy as np

    if isinstance(value, np.ndarray) or hasattr(value, "shape"):
        arr = np.asarray(value)
        print(f"{arr.dtype}{list(arr.shape)}")
        with np.printoptions(threshold=args.limit, edgeitems=4):
            print(arr)
    else:
        print(repr(value))
    return 0


def _payloads_by_origin(
    meta: SnapshotMetadata,
) -> Dict[Optional[str], List[Tuple]]:
    """Distinct stored payloads grouped by origin, in deterministic order:
    ``{origin: [(location, byte_range, checksum, nbytes, codec), ...]}``.

    Replicated entries appear under every rank prefix and slab-batched
    sub-entries share a location under different byte ranges — each
    distinct ``(origin, location, byte_range)`` is listed exactly once.
    Payloads an incremental take left in a base snapshot group under that
    base's URL so its plugin opens once. Shared by ``verify`` and
    ``fsck`` — the two must never disagree on what "every payload" means.
    """
    seen: Dict[Tuple[Optional[str], str, Optional[Tuple[int, int]]], Tuple] = {}
    for entry in meta.manifest.values():
        for location, byte_range, checksum, nbytes, origin, codec in (
            _entry_payloads_ex(entry)
        ):
            key = (origin, location, tuple(byte_range) if byte_range else None)
            seen.setdefault(key, (checksum, nbytes, codec))
    by_origin: Dict[Optional[str], List[Tuple]] = {}
    for (origin, location, byte_range), info in sorted(
        seen.items(), key=lambda kv: (kv[0][0] or "", kv[0][1])
    ):
        by_origin.setdefault(origin, []).append((location, byte_range) + info)
    return by_origin


def _origin_storage_options(
    origin: Optional[str],
    meta: SnapshotMetadata,
    storage_options: Optional[Dict[str, Any]] = None,
) -> Optional[Dict[str, Any]]:
    """Plugin options for reading payloads at ``origin`` (None = the
    snapshot itself), restore-equivalent: an origin reads through ITS
    recorded mirror fallback — never through this snapshot's mirror
    settings — so verify/fsck agree with what restore can actually read
    (including after a base's primary loss). The snapshot's OWN tier
    likewise defaults to its recorded ``mirror_url`` when the caller
    supplied none: a mirrored snapshot whose primary payloads were lost
    restores fine through the failover, and fsck must say so instead of
    raising a false missing-payload alarm on a degraded-but-healthy
    deployment."""
    if origin is None:
        # An explicitly-present mirror_url key (even None) is the
        # caller's word — e.g. {"mirror_url": None} audits the primary
        # tier alone.
        if meta.mirror_url and "mirror_url" not in (storage_options or {}):
            return {**(storage_options or {}), "mirror_url": meta.mirror_url}
        return storage_options
    from .storage_plugin import strip_mirror_options

    opts = strip_mirror_options(storage_options)
    mirror = (meta.origin_mirrors or {}).get(origin)
    if mirror:
        opts = {**(opts or {}), "mirror_url": mirror}
    return opts


def cmd_verify(args: argparse.Namespace) -> int:
    from .storage_plugin import url_to_storage_plugin_in_event_loop

    meta = _load_metadata(args.path)
    by_origin = _payloads_by_origin(meta)

    event_loop = asyncio.new_event_loop()
    ok = skipped = failed = 0
    try:
        for origin, payloads in by_origin.items():
            storage = url_to_storage_plugin_in_event_loop(
                origin if origin is not None else args.path,
                event_loop,
                _origin_storage_options(origin, meta),
            )
            where = f" [{origin}]" if origin is not None else ""
            try:
                for location, byte_range, checksum, _nbytes, _codec in payloads:
                    if checksum is None:
                        skipped += 1
                        if args.verbose:
                            print(f"SKIP  {location}{where} (no checksum recorded)")
                        continue
                    read_io = ReadIO(path=location, byte_range=byte_range)
                    try:
                        event_loop.run_until_complete(storage.read(read_io))
                        verify_checksum(read_io.buf, checksum, location)
                    except (IntegrityError, OSError) as e:
                        failed += 1
                        print(f"FAIL  {location}{where}: {e}")
                        continue
                    ok += 1
                    if args.verbose:
                        print(f"OK    {location}{where}")
            finally:
                storage.sync_close(event_loop)
    finally:
        event_loop.close()
    print(f"verified {ok} payloads, {skipped} without checksums, {failed} failed")
    return 1 if failed else 0


# ------------------------------------------------------------------- fsck
#
# ``verify`` answers "do the payload bytes match their checksums"; fsck
# answers the on-call's bigger question — "is this snapshot DIRECTORY in
# a state the restore path will accept, and if not, what exactly is
# wrong". It layers manifest<->payload existence/size agreement, chained
# CRC verification, incremental-chain (deps) integrity, orphan/partial-
# commit detection, and an optional quarantine repair, with CI-friendly
# exit codes: 0 clean, 1 findings, 2 cannot-check.


@dataclass(frozen=True)
class InternalArtifact:
    """One class of internal (non-payload) artifact a COMMITTED snapshot
    may legitimately carry alongside its manifest-referenced payloads."""

    name: str
    files: Tuple[str, ...] = ()  # exact snapshot-relative paths
    prefixes: Tuple[str, ...] = ()  # top-level directory names


#: The single registry of internal artifacts fsck must not flag as
#: orphans. Grown ad hoc across PRs (telemetry, critpath, quarantine,
#: flight recorder) as scattered literals inside the orphan scan; any new
#: artifact class registers HERE, in one place, or fsck will quarantine
#: it. ``.snapshot_metadata`` is a literal (not imported from .snapshot)
#: to keep this module's top-level imports light.
INTERNAL_ARTIFACTS: Tuple[InternalArtifact, ...] = (
    InternalArtifact("metadata", files=(".snapshot_metadata",)),
    InternalArtifact(
        "telemetry", files=(".snapshot_telemetry",), prefixes=(".telemetry",)
    ),
    InternalArtifact("critpath", files=(".snapshot_critpath",)),
    InternalArtifact("quarantine", prefixes=(".fsck_quarantine",)),
    InternalArtifact("flight", prefixes=(".flight",)),
    # Delta journal (journal.py): fenced epoch segments between full
    # snapshots. Exempt from the orphan scan, but NOT unchecked — it has
    # its own fsck pass (_fsck_journal) with dedicated finding classes.
    InternalArtifact("journal", prefixes=(JOURNAL_DIRNAME,)),
    # Geo-replication (georep.py): the durable cursor a remote-tier step
    # directory carries. Exempt from the orphan scan, but NOT unchecked —
    # _fsck_georep cross-checks it against the directory's own journal
    # state (finding class georep-stale-cursor). In-flight ship temps use
    # the shared ``.tmp.`` naming, so the temp-file class already covers
    # them.
    InternalArtifact("georep", files=(".georep_cursor.json",)),
)


def internal_artifact_class(rel: str) -> Optional[str]:
    """The registered internal-artifact class owning the snapshot-relative
    path ``rel``, or None for payload/user data."""
    import os

    top = rel.split(os.sep, 1)[0].split("/", 1)[0]
    for art in INTERNAL_ARTIFACTS:
        if rel in art.files or top in art.prefixes:
            return art.name
    return None


class FsckReport:
    """Findings grouped by class. ``findings`` holds what is wrong NOW
    (after any repair); ``repaired`` what --repair quarantined."""

    #: finding classes --repair may quarantine (never payload data).
    #: journal-torn-tail is special-cased in _fsck_repair: only the bytes
    #: PAST the committed offset are quarantined, then the segment is
    #: truncated back to its committed length.
    REPAIRABLE = (
        "orphan",
        "temp-file",
        "stale-fence",
        "journal-torn-tail",
        "journal-orphan-epoch",
        "georep-stale-cursor",
    )

    def __init__(self) -> None:
        self.findings: List[Tuple[str, str, str]] = []  # (class, where, what)
        self.repaired: List[Tuple[str, str]] = []  # (class, where)
        self.payloads_ok = 0
        self.payloads_skipped = 0
        #: rel segment path -> committed offset, for torn-tail repair
        self.journal_tails: Dict[str, int] = {}

    def add(self, cls: str, where: str, what: str) -> None:
        self.findings.append((cls, where, what))

    def classes(self) -> set:
        return {c for c, _, _ in self.findings}

    @property
    def clean(self) -> bool:
        return not self.findings


def _fsck_local_dir(path: str) -> Optional[str]:
    """The local directory behind ``path`` (orphan scan / repair surface),
    or None for remote backends."""
    from .storage_plugin import local_fs_root

    return local_fs_root(path)


def _is_not_found_error(exc: BaseException) -> bool:
    from .storage_plugins.retry import is_not_found_error

    return is_not_found_error(exc)


def _classify_read_failure(exc: BaseException, dep_cls: Optional[str]) -> str:
    """Map a payload-read exception to a finding class. fsck's job is to
    diagnose, so NO read failure may escape as a crash: unknown backend
    errors degrade to io-error (dangling-dep inside an origin chain)."""
    if _is_not_found_error(exc):
        return dep_cls or "missing-payload"
    if isinstance(exc, EOFError):
        return "truncated-payload"
    return dep_cls or "io-error"


def _fsck_payload_checks(
    path: str,
    meta: SnapshotMetadata,
    storage_options: Optional[Dict[str, Any]],
    report: FsckReport,
    echo,
    verbose: bool,
) -> None:
    from .storage_plugin import url_to_storage_plugin_in_event_loop

    by_origin = _payloads_by_origin(meta)
    event_loop = asyncio.new_event_loop()
    try:
        for origin, payloads in by_origin.items():
            dep_cls = "dangling-dep" if origin is not None else None
            where_tag = f" [{origin}]" if origin is not None else ""
            opts = _origin_storage_options(origin, meta, storage_options)
            if origin is not None:
                # Deps integrity: the base snapshot itself must still be a
                # committed, readable snapshot — a payload read succeeding
                # against an uncommitted rubble directory proves little.
                from .snapshot import Snapshot

                try:
                    Snapshot(origin, storage_options=opts).metadata
                except Exception as e:  # noqa: BLE001
                    report.add(
                        "dangling-dep",
                        origin,
                        f"base snapshot unreadable ({type(e).__name__}: {e})",
                    )
            try:
                storage = url_to_storage_plugin_in_event_loop(
                    origin if origin is not None else path, event_loop, opts
                )
            except Exception as e:  # noqa: BLE001
                report.add(
                    dep_cls or "io-error",
                    origin or path,
                    f"cannot open storage ({type(e).__name__}: {e})",
                )
                continue
            origin_dir = _fsck_local_dir(origin if origin is not None else path)
            if (opts or {}).get("mirror_url"):
                # A mirror fallback is in play: the primary's stat proves
                # nothing (restore reads through the failover), so every
                # check must go through the plugin like restore does.
                origin_dir = None
            try:
                for location, byte_range, checksum, nbytes, codec in payloads:
                    where = f"{location}{where_tag}"
                    # Existence/size agreement first, via stat where the
                    # backend is a local filesystem with no mirror tier:
                    # catches truncation without reading (and without
                    # tripping SIGBUS on an mmap of a range past EOF).
                    if origin_dir is not None:
                        import os

                        fpath = os.path.join(origin_dir, location)
                        if not os.path.exists(fpath):
                            report.add(
                                dep_cls or "missing-payload", where,
                                "payload file missing",
                            )
                            continue
                        fsize = os.path.getsize(fpath)
                        need = None
                        if byte_range is not None:
                            need = byte_range[1]
                        elif codec is None and nbytes is not None:
                            need = nbytes
                        if need is not None and fsize < need:
                            report.add(
                                "truncated-payload", where,
                                f"file is {fsize} bytes; manifest needs "
                                f"{need}",
                            )
                            continue
                    read_io = ReadIO(
                        path=location,
                        byte_range=tuple(byte_range) if byte_range else None,
                    )
                    try:
                        event_loop.run_until_complete(storage.read(read_io))
                    except Exception as e:  # noqa: BLE001
                        report.add(
                            _classify_read_failure(e, dep_cls),
                            where,
                            f"{type(e).__name__}: {e}",
                        )
                        continue
                    buf = read_io.buf
                    if (
                        codec is None
                        and byte_range is None
                        and nbytes is not None
                        and len(buf) != nbytes
                    ):
                        report.add(
                            "size-mismatch", where,
                            f"stored {len(buf)} bytes; manifest says {nbytes}",
                        )
                        continue
                    if checksum is None:
                        report.payloads_skipped += 1
                        if verbose:
                            echo(f"SKIP  {where} (no checksum recorded)")
                        continue
                    try:
                        verify_checksum(buf, checksum, location)
                    except IntegrityError as e:
                        report.add("checksum-mismatch", where, str(e))
                        continue
                    report.payloads_ok += 1
                    if verbose:
                        echo(f"OK    {where}")
            finally:
                storage.sync_close(event_loop)
    finally:
        event_loop.close()


def _fsck_orphan_scan(
    local_dir: str, meta: SnapshotMetadata, report: FsckReport
) -> None:
    import os

    from .snapshot import SNAPSHOT_FENCE_FNAME

    referenced = set()
    for entry in meta.manifest.values():
        for location, _, _, _, origin, _ in _entry_payloads_ex(entry):
            if origin is None:
                referenced.add(os.path.normpath(location))

    internal_prefixes = tuple(
        p for art in INTERNAL_ARTIFACTS for p in art.prefixes
    )
    for dirpath, dirnames, filenames in os.walk(local_dir):
        rel_dir = os.path.relpath(dirpath, local_dir)
        top = (rel_dir.split(os.sep, 1)[0] if rel_dir != "." else "")
        if top in internal_prefixes:
            dirnames[:] = []
            continue
        for fname in sorted(filenames):
            rel = os.path.normpath(
                os.path.join(rel_dir, fname) if rel_dir != "." else fname
            )
            if rel in referenced or internal_artifact_class(rel) is not None:
                continue
            if rel == SNAPSHOT_FENCE_FNAME:
                report.add(
                    "stale-fence", rel,
                    "commit fence outlived a committed snapshot (interrupted "
                    "fence cleanup, or a foreign in-flight take)",
                )
                continue
            if ".tmp." in rel:
                report.add(
                    "temp-file", rel,
                    "write temp file left behind by a dead writer",
                )
            else:
                report.add("orphan", rel, "not referenced by the manifest")
        if rel_dir != "." and not filenames and not dirnames:
            report.add("orphan", rel_dir, "empty directory")


def _fsck_journal(local_dir: str, report: FsckReport) -> None:
    """The journal artifact class (journal.py): epoch-chain contiguity,
    committed-region CRC verification, torn-tail detection, and orphan
    epoch metas. Finding classes:

    - ``journal-torn-tail``    (repairable): bytes past the last committed
      offset — a writer died mid-append. Replay already ignores them; the
      repair quarantines the tail bytes and truncates the segment.
    - ``journal-orphan-epoch`` (repairable): an epoch meta past a gap in
      the chain, or unparseable — it never committed on the surviving
      chain and must never be replayed.
    - ``journal-corrupt-record`` (NOT repairable): the committed region of
      a segment fails CRC/parse, or a committed segment is missing/short.
      The journal is unreplayable past the damage; restore falls back to
      the base snapshot. Retake a full snapshot.
    - a leftover ``.journal/.fence`` reuses the ``stale-fence`` class: the
      epoch writer died between planting the fence and committing.
    """
    import os

    from . import journal as journal_mod

    jdir = os.path.join(local_dir, JOURNAL_DIRNAME)
    if not os.path.isdir(jdir):
        return

    def rel(name: str) -> str:
        return os.path.join(JOURNAL_DIRNAME, name)

    metas = journal_mod.read_epoch_metas(jdir)
    committed = journal_mod.committed_epochs(metas)
    committed_ids = {m.get("epoch") for m in committed}

    try:
        names = sorted(os.listdir(jdir))
    except OSError as e:
        report.add("io-error", JOURNAL_DIRNAME, f"cannot list journal: {e}")
        return

    seg_ranks = set()
    for name in names:
        if name == journal_mod.FENCE_FNAME:
            report.add(
                "stale-fence", rel(name),
                "journal epoch fence outlived its epoch (writer died "
                "mid-epoch; the uncommitted epoch is already ignored)",
            )
            continue
        seg_m = journal_mod._SEGMENT_RE.match(name)
        if seg_m is not None:
            seg_ranks.add(int(seg_m.group(1)))
            continue
        meta_m = journal_mod._EPOCH_META_RE.match(name)
        if meta_m is not None:
            epoch = int(meta_m.group(1))
            if epoch not in committed_ids:
                parsed = any(m.get("epoch") == epoch for m in metas)
                report.add(
                    "journal-orphan-epoch", rel(name),
                    f"epoch {epoch} past a gap in the committed chain "
                    "(never replayed)" if parsed
                    else "unparseable epoch metadata (never replayed)",
                )
            continue
        if ".tmp." in name:
            report.add(
                "temp-file", rel(name),
                "write temp file left behind by a dead writer",
            )
        else:
            report.add("orphan", rel(name), "not a journal artifact")

    # Committed-region integrity + torn tails, against the LAST committed
    # epoch's offsets (they are monotonic across the chain by protocol).
    offsets = committed[-1].get("offsets", {}) if committed else {}
    for rank in sorted(seg_ranks | {int(r) for r in offsets}):
        seg_rel = rel(journal_mod.segment_name(rank))
        seg_path = os.path.join(local_dir, seg_rel)
        limit = int(offsets.get(str(rank), 0))
        if not os.path.exists(seg_path):
            if limit > 0:
                report.add(
                    "journal-corrupt-record", seg_rel,
                    f"committed segment missing ({limit} byte(s) recorded)",
                )
            continue
        if limit > 0:
            _, error = journal_mod.scan_segment(seg_path, limit)
            if error is not None:
                report.add(
                    "journal-corrupt-record", seg_rel,
                    f"committed region unreplayable: {error} — restore "
                    "falls back to the base snapshot; retake a full "
                    "snapshot",
                )
                continue  # size vs limit is meaningless past corruption
        try:
            size = os.path.getsize(seg_path)
        except OSError:
            continue
        if size > limit:
            report.add(
                "journal-torn-tail", seg_rel,
                f"{size - limit} uncommitted byte(s) past the committed "
                f"offset {limit} (writer died mid-append; never replayed)",
            )
            report.journal_tails[seg_rel] = limit


def _fsck_georep(local_dir: str, report: FsckReport) -> None:
    """The geo-replication artifact class (georep.py): the durable
    replication cursor a remote-tier step directory carries. Finding
    class:

    - ``georep-stale-cursor`` (repairable): the cursor is unparseable or
      disagrees with the directory's OWN committed state — it names a
      base step other than the directory's, claims more epochs than the
      committed chain holds, or carries a generation the committed
      metadata does not. The shipper never trusts the cursor blindly (it
      re-probes the remote metadata and re-derives it), so the repair
      simply quarantines the file.
    """
    import json as json_mod
    import os

    from . import georep as georep_mod
    from . import journal as journal_mod

    cpath = os.path.join(local_dir, georep_mod.CURSOR_FNAME)
    if not os.path.isfile(cpath):
        return
    rel = georep_mod.CURSOR_FNAME
    try:
        with open(cpath, "r") as f:
            cur = json_mod.load(f)
        if not isinstance(cur, dict):
            raise ValueError("not a JSON object")
        epoch = int(cur["epoch"])
        base_step = int(cur["base_step"])
        gen = cur.get("gen")
    except (OSError, ValueError, KeyError, TypeError) as e:
        report.add(
            "georep-stale-cursor", rel,
            f"unparseable replication cursor ({type(e).__name__}: {e}) — "
            "the shipper re-derives it; safe to quarantine",
        )
        return
    dir_m = georep_mod._STEP_RE.match(os.path.basename(local_dir.rstrip(os.sep)))
    if dir_m is not None and int(dir_m.group(1)) != base_step:
        report.add(
            "georep-stale-cursor", rel,
            f"cursor names base step {base_step}, directory is "
            f"step {int(dir_m.group(1))}",
        )
        return
    jdir = os.path.join(local_dir, JOURNAL_DIRNAME)
    committed = journal_mod.committed_epochs(journal_mod.read_epoch_metas(jdir))
    if epoch > len(committed):
        report.add(
            "georep-stale-cursor", rel,
            f"cursor claims epoch {epoch} applied; the committed chain "
            f"here holds {len(committed)} epoch(s)",
        )
        return
    if epoch >= 1 and committed[epoch - 1].get("gen") != gen:
        report.add(
            "georep-stale-cursor", rel,
            f"cursor carries generation {gen!r} for epoch {epoch}; the "
            f"committed metadata says {committed[epoch - 1].get('gen')!r}",
        )


def _fsck_repair(local_dir: str, report: FsckReport, echo) -> None:
    """Quarantine repairable findings under ``.fsck_quarantine/``
    (preserving relative paths) — never deletes, never touches payload
    data, so a mistaken repair is always reversible by moving back."""
    import os
    import shutil

    quarantine = os.path.join(local_dir, ".fsck_quarantine")
    remaining: List[Tuple[str, str, str]] = []
    for cls, where, what in report.findings:
        if cls not in FsckReport.REPAIRABLE:
            remaining.append((cls, where, what))
            continue
        if cls == "journal-torn-tail":
            # Repair in place: quarantine only the bytes PAST the
            # committed offset, then truncate the segment back to its
            # committed length — the committed records stay replayable.
            seg = os.path.join(local_dir, where)
            limit = report.journal_tails.get(where, 0)
            dst = os.path.join(quarantine, where + ".tail")
            try:
                with open(seg, "rb") as f:
                    f.seek(limit)
                    tail = f.read()
                os.makedirs(os.path.dirname(dst), exist_ok=True)
                with open(dst, "wb") as f:
                    f.write(tail)
                os.truncate(seg, limit)
            except OSError as e:
                remaining.append((cls, where, f"{what} (repair failed: {e})"))
                continue
            report.repaired.append((cls, where))
            echo(
                f"TRUNCATED    {where} -> committed offset {limit} "
                f"(tail in .fsck_quarantine/{where}.tail)"
            )
            continue
        src = os.path.join(local_dir, where)
        dst = os.path.join(quarantine, where)
        try:
            os.makedirs(os.path.dirname(dst) or quarantine, exist_ok=True)
            shutil.move(src, dst)
            # Prune directories the move emptied — a leftover empty
            # temp dir would re-surface as an orphan on the next fsck.
            parent = os.path.dirname(src)
            while (
                os.path.realpath(parent) != os.path.realpath(local_dir)
                and os.path.isdir(parent)
                and not os.listdir(parent)
            ):
                os.rmdir(parent)
                parent = os.path.dirname(parent)
        except OSError as e:
            remaining.append((cls, where, f"{what} (repair failed: {e})"))
            continue
        report.repaired.append((cls, where))
        echo(f"QUARANTINED  {where} -> .fsck_quarantine/{where}")
    report.findings = remaining


def run_fsck(
    path: str,
    storage_options: Optional[Dict[str, Any]] = None,
    repair: bool = False,
    verbose: bool = False,
    echo=print,
) -> Tuple[int, FsckReport]:
    """Full snapshot consistency check. Returns (exit_code, report):
    0 clean, 1 findings survived (corruption, orphans not repaired,
    partial commit), 2 cannot-check (no snapshot there at all)."""
    import os

    from .manifest import CorruptSnapshotError
    from .snapshot import (
        SNAPSHOT_FENCE_FNAME,
        SNAPSHOT_METADATA_FNAME,
        Snapshot,
    )

    report = FsckReport()
    local_dir = _fsck_local_dir(path)
    try:
        meta = Snapshot(path, storage_options=storage_options).metadata
    except CorruptSnapshotError as e:
        report.add("corrupt-metadata", SNAPSHOT_METADATA_FNAME, e.detail)
        echo(f"CORRUPT  {SNAPSHOT_METADATA_FNAME}: {e.detail}")
        echo(
            "fsck: metadata unreadable — treat the snapshot as uncommitted "
            "(payloads not checked)"
        )
        return 1, report
    except Exception as e:  # noqa: BLE001
        if not _is_not_found_error(e):
            # Transport/auth/backend failure: we cannot tell anything
            # about the snapshot — that's cannot-check (2), reported as
            # a diagnosis through the caller's echo, never a traceback.
            echo(
                f"error: cannot read snapshot metadata at {path} "
                f"({type(e).__name__}: {e})"
            )
            return 2, report
        # No commit point. Distinguish "a dead writer's partial directory"
        # (a finding) from "nothing resembling a snapshot" (cannot-check).
        if local_dir is not None and not os.path.isdir(local_dir):
            echo(f"error: {path} does not exist")
            return 2, report
        residue: List[str] = []
        if local_dir is not None:
            for dirpath, _, filenames in os.walk(local_dir):
                for fname in filenames:
                    residue.append(
                        os.path.relpath(os.path.join(dirpath, fname), local_dir)
                    )
        if residue:
            fence = SNAPSHOT_FENCE_FNAME in residue
            report.add(
                "partial-commit",
                path,
                f"{len(residue)} file(s) but no {SNAPSHOT_METADATA_FNAME}"
                + (" (commit fence present: writer died mid-take)" if fence
                   else ""),
            )
            echo(
                f"PARTIAL  {path}: {len(residue)} file(s), no "
                f"{SNAPSHOT_METADATA_FNAME} — an uncommitted take; the "
                "snapshot never existed. Safe to delete (the manager "
                "reclaims it on the next save)."
            )
            return 1, report
        echo(f"error: no snapshot at {path}")
        return 2, report

    _fsck_payload_checks(path, meta, storage_options, report, echo, verbose)
    if local_dir is not None:
        _fsck_orphan_scan(local_dir, meta, report)
        _fsck_journal(local_dir, report)
        _fsck_georep(local_dir, report)
    else:
        echo("note: remote backend — orphan scan skipped (payload and "
             "chain checks only)")

    if repair and local_dir is not None and report.findings:
        _fsck_repair(local_dir, report, echo)

    for cls, where, what in report.findings:
        echo(f"{cls.upper():18s} {where}: {what}")
    echo(
        f"fsck {path}: {report.payloads_ok} payload(s) verified, "
        f"{report.payloads_skipped} without checksums, "
        f"{len(report.findings)} finding(s)"
        + (f", {len(report.repaired)} quarantined" if report.repaired else "")
    )
    return (1 if report.findings else 0), report


def cmd_fsck(args: argparse.Namespace) -> int:
    code, _ = run_fsck(
        args.path,
        repair=args.repair,
        verbose=args.verbose,
    )
    return code


def cmd_migrate(args: argparse.Namespace) -> int:
    from .tricks.torchsnapshot_interop import (
        migrate_from_torchsnapshot,
        read_metadata,
    )

    raw = read_metadata(args.src)  # ValueError on malformed metadata
    if _looks_native(raw["manifest"]):
        print(f"{args.src} is already a native snapshot; nothing to migrate.")
        return 1
    _, state = migrate_from_torchsnapshot(args.src, args.dst, rank=args.rank)
    from .flatten import flatten

    n = len(flatten(state)[1])
    print(f"migrated {n} leaves from {args.src} -> {args.dst}")
    return 0


def _looks_native(raw_manifest: Dict[str, Any]) -> bool:
    """Distinguish a native manifest from a reference-format one.

    Container and object type names collide between the formats, so a
    bare type-set subset test misfires on tensor-free reference snapshots.
    Reference-only markers: capitalized tensor types, primitive entries
    carrying ``serialized_value``, and ``torch_save``-serialized objects.
    """
    for entry in raw_manifest.values():
        if not isinstance(entry, dict):
            raise ValueError("Malformed manifest: entries must be mappings")
        if entry.get("type") in ("Tensor", "ChunkedTensor", "ShardedTensor"):
            return False
        if "serialized_value" in entry:
            return False
        if entry.get("serializer") == "torch_save":
            return False
    return True


def _sub_payload_entries(entry: Entry) -> List[Tuple[Optional[Tuple[int, ...]], Any]]:
    """(chunk/shard box, payload-entry) pairs — the per-payload alignment
    unit for content comparison. Plain arrays/objects have one boxless
    payload; chunked/sharded entries align by their N-D (offsets, sizes)
    so each sub-entry's own digest/checksum is compared (slab-batched
    payloads share a location, so location is NOT a safe key)."""
    if isinstance(entry, (ArrayEntry, ObjectEntry)):
        return [(None, entry)]
    if isinstance(entry, ChunkedArrayEntry):
        return [
            ((*c.offsets, *c.sizes), c.array) for c in entry.chunks
        ]
    if isinstance(entry, ShardedArrayEntry):
        return [
            ((*s.offsets, *s.sizes), s.array) for s in entry.shards
        ]
    return []


def _leaf_compare(ea: Entry, eb: Entry) -> str:
    """'same' | 'changed' | 'unknown' for two leaf entries.

    Exactness degrades to the strongest evidence available on BOTH sides:
    content digests, else same-algorithm integrity checksums, else only
    structure — in which case equality is 'unknown', never claimed.
    Comparison is chunk/shard-layout-sensitive by construction: identical
    content striped differently (e.g. saved at different world sizes)
    reports as changed.
    """
    if ea.type != eb.type:
        return "changed"
    if isinstance(ea, PrimitiveEntry):
        return (
            "same"
            if (ea.ptype, ea.readable) == (eb.ptype, eb.readable)
            else "changed"
        )
    if str(getattr(ea, "dtype", None)) != str(getattr(eb, "dtype", None)):
        return "changed"
    if list(getattr(ea, "shape", []) or []) != list(getattr(eb, "shape", []) or []):
        return "changed"
    if (
        isinstance(ea, ObjectEntry)
        and ea.size is not None
        and eb.size is not None
        and ea.size != eb.size
    ):
        return "changed"
    pa = dict(_sub_payload_entries(ea))
    pb = dict(_sub_payload_entries(eb))
    if set(pa) != set(pb):
        return "changed"  # different chunk/shard layout
    unknown = False
    for box, sub_a in pa.items():
        sub_b = pb[box]
        if sub_a.digest is not None and sub_b.digest is not None:
            # Digests cover the uncompressed content — codec-independent.
            if sub_a.digest != sub_b.digest:
                return "changed"
        elif (
            sub_a.checksum is not None
            and sub_b.checksum is not None
            and sub_a.checksum.partition(":")[0] == sub_b.checksum.partition(":")[0]
            # Checksums cover the STORED bytes: only comparable when both
            # sides stored the same form (same codec, or both raw) —
            # identical content saved raw vs compressed hashes differently.
            and getattr(sub_a, "codec", None) == getattr(sub_b, "codec", None)
        ):
            if sub_a.checksum != sub_b.checksum:
                return "changed"
        else:
            unknown = True
    return "unknown" if unknown else "same"


def cmd_diff(args: argparse.Namespace) -> int:
    meta_a = _load_metadata(args.a)
    meta_b = _load_metadata(args.b)

    def leaves(meta):
        return {
            p: e for p, e in meta.manifest.items() if not is_container_entry(e)
        }

    a, b = leaves(meta_a), leaves(meta_b)
    added = sorted(set(b) - set(a))
    removed = sorted(set(a) - set(b))
    changed, unchanged, uncertain = [], [], []
    for p in sorted(set(a) & set(b)):
        status = _leaf_compare(a[p], b[p])
        if status == "changed":
            changed.append(p)
        elif status == "same":
            unchanged.append(p)
        else:
            uncertain.append(p)
    for p in added:
        print(f"+ {p}")
    for p in removed:
        print(f"- {p}")
    for p in changed:
        print(f"~ {p}  ({_entry_desc(b[p])})")
    if args.verbose:
        for p in unchanged:
            print(f"= {p}")
        for p in uncertain:
            print(f"? {p}  (structure equal; no digest/checksum common to "
                  "both snapshots)")
    print(
        f"{len(added)} added, {len(removed)} removed, {len(changed)} changed, "
        f"{len(unchanged)} unchanged"
        + (f", {len(uncertain)} indeterminate" if uncertain else "")
    )
    return 1 if (added or removed or changed) else 0


def _canon_snapshot_url(url: str) -> str:
    """Canonical comparable form of a snapshot path/URL (fs:// == bare).

    Matches the canonicalization applied to origins at record time
    (dedup.canonical_base_url), plus fs://-vs-bare equivalence; realpath
    (not abspath) so symlinked checkpoint directories compare equal.
    """
    import os

    if url.startswith("fs://"):
        url = url[len("fs://"):]
    if "://" in url:
        return url  # remote URL: compare verbatim
    return os.path.realpath(url)


def cmd_deps(args: argparse.Namespace) -> int:
    import os

    dirpath = args.dir
    names, origins_of, _, _ = _scan_snapshot_dir(dirpath)
    snapshots = sorted(names)
    if not snapshots:
        print(f"no snapshots found under {dirpath}")
        return 2

    # origin URL -> set of snapshot names (in this dir) referencing it
    referenced: Dict[str, set] = {}
    for name, origins in origins_of.items():
        for origin in origins:
            referenced.setdefault(_canon_snapshot_url(origin), set()).add(name)

    canon_of = {
        name: _canon_snapshot_url(os.path.join(dirpath, name))
        for name in snapshots
    }
    safe = []
    for name in snapshots:
        dependents = referenced.get(canon_of[name], set())
        origins = origins_of[name]
        tag = ""
        if origins:
            tag += " <- bases: " + ", ".join(
                os.path.basename(o) for o in sorted(origins)
            )
        if dependents:
            tag += " [REQUIRED by " + ", ".join(sorted(dependents)) + "]"
        else:
            safe.append(name)
        print(f"{name}{tag}")
    local_canon = set(canon_of.values())
    external = {
        o
        for origins in origins_of.values()
        for o in origins
        if _canon_snapshot_url(o) not in local_canon
    }
    for o in sorted(external):
        print(f"(external base outside this directory: {o})")
    print(
        "safe to delete (no dependents here): "
        + (", ".join(safe) if safe else "none")
    )
    return 0


def _scan_snapshot_dir(dirpath: str):
    """(snapshots sorted by mtime asc, {name: origin set},
    {name: {origin: locations referenced in it}}) for a directory."""
    import os

    names = sorted(
        (
            name
            for name in os.listdir(dirpath)
            if os.path.isfile(os.path.join(dirpath, name, ".snapshot_metadata"))
        ),
        # Name tiebreaker: mtime granularity can collide (1s filesystems,
        # rsync-flattened trees); retention decisions must be deterministic.
        key=lambda n: (
            os.path.getmtime(os.path.join(dirpath, n, ".snapshot_metadata")),
            n,
        ),
    )
    origins_of = {}
    origin_locations_of = {}
    payloads_of = {}
    for name in names:
        meta = _load_metadata(os.path.join(dirpath, name))
        origins = set()
        locations = {}
        own = {}
        for entry in meta.manifest.values():
            for location, _, checksum, nbytes, origin in _entry_payloads(entry):
                if origin is not None:
                    origins.add(origin)
                    locations.setdefault(origin, {})[location] = (checksum, nbytes)
                else:
                    own[location] = (checksum, nbytes)
        origins_of[name] = origins
        origin_locations_of[name] = locations
        payloads_of[name] = own
    return names, origins_of, origin_locations_of, payloads_of


def cmd_prune(args: argparse.Namespace) -> int:
    import os

    from .retention import apply_retention, plan_retention

    if "://" in args.dir and not args.dir.startswith("fs://"):
        print("error: prune operates on local filesystem directories only",
              file=sys.stderr)
        return 2
    dirpath = args.dir[len("fs://"):] if args.dir.startswith("fs://") else args.dir
    if args.keep < 1:
        print("error: --keep must be >= 1", file=sys.stderr)
        return 2
    # One scan for both discovery and the plan: the keep-N policy is
    # evaluated inside plan_retention on its own scan, so a snapshot
    # committing concurrently can never be discovered-but-unprotected.
    plan = plan_retention(dirpath, args.keep)
    if not (plan.keep or plan.spared or plan.doomed):
        print(f"no snapshots found under {dirpath}")
        return 2
    unresolved, doomed = plan.unresolved, plan.doomed
    for name in plan.keep:
        print(f"keep    {name}")
    for name, by_name in plan.spared:
        suffix = ", matched by name" if by_name else ""
        print(f"keep    {name}  (base of a kept snapshot{suffix})")
    for name in doomed:
        print(f"delete  {name}")
    if unresolved:
        print(
            "warning: kept snapshot(s) depend on base(s) that resolve to no "
            "snapshot in this directory (moved tree, different mount path, "
            "or a base stored elsewhere):",
            file=sys.stderr,
        )
        for canon in sorted(unresolved):
            print(f"warning:   {canon}", file=sys.stderr)
    if not doomed:
        print("nothing to prune")
        return 0
    if not args.yes:
        print(f"dry run: would delete {len(doomed)} snapshot(s); "
              "re-run with --yes to execute")
        return 0
    if unresolved and not args.ignore_missing_bases:
        print(
            "refusing --yes: cannot prove the snapshots marked for deletion "
            "are not the unresolved base(s) above under a different name. "
            "Verify the bases exist (python -m torchsnapshot_tpu deps), then "
            "re-run with --ignore-missing-bases to delete anyway.",
            file=sys.stderr,
        )
        return 2
    n = apply_retention(dirpath, plan)
    print(f"deleted {n} snapshot(s)")
    return 0


def cmd_trend(args: argparse.Namespace) -> int:
    """``stats --trend``: render the checkpoint-history trajectory for a
    ROOT directory (the parent of the step snapshots) and exit non-zero
    on a p50 regression — CI-pluggable perf-regression detection from
    the journal every committed take appends."""
    from .telemetry import history

    records = history.load_history(args.path)
    if not records:
        print(
            f"error: no usable checkpoint history at {args.path} (expected "
            f"{history.HISTORY_FNAME} in the snapshot ROOT directory — it "
            "is appended by every committed take)",
            file=sys.stderr,
        )
        return 2
    threshold = args.trend_threshold
    verdicts = [
        history.detect_regression(
            records, metric=args.trend_metric, threshold=threshold
        )
    ]
    print(history.render_trend(records, verdicts))
    return 1 if any(v.get("regressed") for v in verdicts) else 0


def cmd_stats(args: argparse.Namespace) -> int:
    """Render the telemetry summary a take persisted next to its
    metadata (telemetry/export.py) — "why was this take slow?" answered
    after the fact, from any registered storage backend. ``--trend``
    switches to the checkpoint-history view (see cmd_trend);
    ``--openmetrics`` emits the summary as an OpenMetrics exposition."""
    import json

    from .storage_plugin import url_to_storage_plugin_in_event_loop
    from .telemetry import (
        TELEMETRY_SUMMARY_FNAME,
        merge_summaries,
        render_openmetrics,
        render_summary_document,
    )

    if args.trend:
        return cmd_trend(args)

    event_loop = asyncio.new_event_loop()
    storage = url_to_storage_plugin_in_event_loop(args.path, event_loop, None)
    try:
        read_io = ReadIO(path=TELEMETRY_SUMMARY_FNAME)
        try:
            event_loop.run_until_complete(storage.read(read_io))
        except Exception as e:  # noqa: BLE001
            # Broad on purpose: a missing object surfaces as OSError on
            # fs but as botocore ClientError (NoSuchKey) / google-api
            # NotFound on the cloud plugins — the friendly hint must work
            # on every registered backend. The original error is included
            # so genuine transport problems stay diagnosable.
            print(
                f"error: could not read {TELEMETRY_SUMMARY_FNAME} from "
                f"{args.path} ({type(e).__name__}: {e}). If the snapshot "
                "exists, it was likely taken without telemetry — save "
                "with TORCHSNAPSHOT_TPU_TELEMETRY=1 to record a summary.",
                file=sys.stderr,
            )
            return 2
    finally:
        storage.sync_close(event_loop)
        event_loop.close()
    try:
        doc = json.loads(bytes(read_io.buf).decode("utf-8"))
    except ValueError as e:
        print(f"error: malformed telemetry summary: {e}", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(doc, indent=1))
        return 0
    if not doc.get("fleet"):
        # Documents written by future/foreign producers may omit the
        # merged view; re-derive it so the rendering stays complete.
        doc["fleet"] = merge_summaries(doc.get("ranks") or [])
    if args.openmetrics:
        sys.stdout.write(render_openmetrics(doc))
        return 0
    print(render_summary_document(doc, verbose=args.verbose))
    return 0


def _read_snapshot_json(
    path: str, fname: str
) -> Tuple[Optional[Dict[str, Any]], Optional[BaseException]]:
    """Load one JSON control file from a snapshot over its storage
    plugin (any backend). Returns ``(doc, None)`` on success,
    ``(None, None)`` when the file simply is not there (or is not a
    JSON object), and ``(None, error)`` on a TRANSPORT/auth/parse
    failure — callers must surface the latter instead of folding it
    into "not recorded" (the cmd_stats lesson: a genuine backend error
    disguised as a telemetry hint sends the on-call the wrong way)."""
    import json

    from .storage_plugins.retry import is_not_found_error
    from .storage_plugin import url_to_storage_plugin_in_event_loop

    event_loop = asyncio.new_event_loop()
    try:
        storage = url_to_storage_plugin_in_event_loop(path, event_loop, None)
        try:
            read_io = ReadIO(path=fname)
            event_loop.run_until_complete(storage.read(read_io))
            doc = json.loads(bytes(read_io.buf).decode("utf-8"))
            return (doc, None) if isinstance(doc, dict) else (None, None)
        finally:
            storage.sync_close(event_loop)
    except Exception as e:  # noqa: BLE001
        if is_not_found_error(e):
            return None, None
        return None, e
    finally:
        event_loop.close()


def cmd_explain(args: argparse.Namespace) -> int:
    """Render a take/restore's critical-path attribution: the chain of
    per-rank segments that gated commit, the binding resource with its
    achieved rate (cross-checked against the governor's measured rates),
    the straggler delta, and a tuning hint (telemetry/critpath.py).

    Exit codes: 0 pipeline/coordination-bound, 1 STORAGE-bound, 2 no
    attribution available — so a bench can assert the ROADMAP
    "pipeline-bound" claim with one subprocess call."""
    import json

    from .telemetry import TELEMETRY_SUMMARY_FNAME, critpath

    if getattr(args, "profiles", False):
        # The governor's learned-profile story for this root: per
        # profile key the converged settings, smoothed score, and the
        # recent perturbation trail — the full closed-loop decision
        # trail (autotune.py; persisted by scheduler.observe_verdict
        # into the history journal).
        from .telemetry import history

        target = args.path.rstrip("/")
        records = history.load_profiles(target)
        if not records:
            # ``explain --profiles <snapshot>`` should work too: the
            # journal lives in the PARENT directory (the manager root).
            records = history.load_profiles(
                os.path.dirname(os.path.abspath(target))
            )
        if not records:
            print(
                f"error: no learned profiles at {args.path} (expected "
                "profile records in .telemetry_history.jsonl — recorded "
                "when takes/restores run with TORCHSNAPSHOT_TPU_AUTOTUNE "
                "unset or 'auto').",
                file=sys.stderr,
            )
            return 2
        if args.json:
            print(json.dumps(records, indent=1))
        else:
            print(history.render_profiles(records))
        return 0
    doc, err = _read_snapshot_json(args.path, critpath.ATTRIBUTION_FNAME)
    if doc is None or not doc.get("fleet"):
        # Fallback: re-derive from the telemetry summary document's
        # per-rank attribution blobs (older takes, or a rank-0 persist
        # failure that still landed the summary).
        tel, tel_err = _read_snapshot_json(args.path, TELEMETRY_SUMMARY_FNAME)
        err = err or tel_err
        doc = critpath.derive_document_from_telemetry(tel) if tel else None
    if doc is None or not doc.get("fleet"):
        if err is not None:
            # A transport/auth/corruption failure is NOT "telemetry was
            # off" — surface the real error so the on-call fixes the
            # backend instead of re-running a save.
            print(
                f"error: cannot read attribution records at {args.path} "
                f"({type(err).__name__}: {err})",
                file=sys.stderr,
            )
            return 2
        print(
            f"error: no critical-path attribution at {args.path} (expected "
            f"{critpath.ATTRIBUTION_FNAME} next to .snapshot_metadata). "
            "Attribution is recorded when the take/restore ran with "
            "TORCHSNAPSHOT_TPU_TELEMETRY=1.",
            file=sys.stderr,
        )
        return 2
    if args.json:
        print(json.dumps(doc, indent=1))
    else:
        print(critpath.render_attribution(doc, verbose=args.verbose))
    return critpath.binding_exit_code(doc)


def cmd_plan(args: argparse.Namespace) -> int:
    """Dry-run the minimal-movement reshard plan (reshard.py) for
    restoring this snapshot under a DIFFERENT layout at a DIFFERENT
    world size — the byte accounting an on-call wants BEFORE committing
    a topology change: what the existing direct path would read from
    storage fleet-wide, what the planner would read instead, and how
    many bytes ride the peer channel.

    The destination layout is a LayoutSpec dict (the same
    ``{version, mesh, rules}`` shape ``Snapshot.take(..., layout=)``
    records in the metadata), loaded from a JSON file. The plan is pure
    geometry on the manifest: no storage payload is touched.

    Exit codes: 0 plan computed, 2 the layout file or an entry's
    geometry is unusable."""
    import json

    from .layout import LayoutSpec
    from .manifest import ShardedArrayEntry
    from .reshard import plan_summary

    meta = _load_metadata(args.path)
    try:
        with open(args.layout) as f:
            dst = LayoutSpec.from_dict(json.load(f))
    except (OSError, ValueError, TypeError, KeyError) as e:
        print(
            f"error: cannot load destination layout {args.layout}: "
            f"{type(e).__name__}: {e}",
            file=sys.stderr,
        )
        return 2
    rows = []
    totals = {
        "shards": 0,
        "planned_units": 0,
        "direct_bytes_from_storage": 0,
        "planned_bytes_from_storage": 0,
        "planned_peer_bytes": 0,
    }
    seen = set()
    bad = 0
    # Sharded entries repeat under every rank prefix but describe the
    # same global array; plan each logical entry once.
    for path, entry in meta.manifest.items():
        if not isinstance(entry, ShardedArrayEntry):
            continue
        logical = path.split("/", 1)[1] if "/" in path else path
        if logical in seen:
            continue
        seen.add(logical)
        try:
            spec = dst.spec_for(logical, len(entry.shape))
            boxes = dst.boxes_by_rank(entry.shape, spec, args.world)
        except ValueError as e:
            rows.append({"path": logical, "error": str(e)})
            bad += 1
            continue
        s = plan_summary(entry, boxes, args.min_requesters)
        s["path"] = logical
        s["spec"] = [list(dims) for dims in spec]
        rows.append(s)
        for k in totals:
            totals[k] += s[k]
    if args.json:
        print(
            json.dumps(
                {"world": args.world, "entries": rows, "totals": totals},
                indent=1,
            )
        )
        return 2 if bad else 0
    print(f"plan: {args.path} -> world {args.world} under {args.layout}")
    for s in rows:
        if "error" in s:
            print(f"  {s['path']:50s} UNPLANNABLE: {s['error']}")
            continue
        print(
            f"  {s['path']:50s} {s['shards']:4d} shard(s) "
            f"{s['planned_units']:4d} unit(s)  storage "
            f"{_fmt_bytes(s['planned_bytes_from_storage']):>10s} "
            f"(direct {_fmt_bytes(s['direct_bytes_from_storage'])})  "
            f"peer {_fmt_bytes(s['planned_peer_bytes'])}"
        )
    if not rows:
        print("  (no sharded entries: a pure layout change moves nothing)")
        return 0
    direct = totals["direct_bytes_from_storage"]
    planned = totals["planned_bytes_from_storage"]
    reduction = direct / planned if planned else float("inf")
    print(
        f"totals: storage {_fmt_bytes(planned)} planned vs "
        f"{_fmt_bytes(direct)} direct ({reduction:.1f}x reduction), "
        f"peer {_fmt_bytes(totals['planned_peer_bytes'])}, "
        f"{totals['planned_units']}/{totals['shards']} unit(s) claimed"
    )
    return 2 if bad else 0


def cmd_consolidate(args: argparse.Namespace) -> int:
    from .dedup import consolidate

    n = consolidate(args.src, args.dst)
    print(f"consolidated {args.src} -> {args.dst} ({n} payloads copied; "
          "no base snapshots required)")
    return 0


def cmd_blackbox(args: argparse.Namespace) -> int:
    """Merge the per-rank flight-recorder dumps of an aborted operation
    into one causal cross-rank timeline: who deserted whom at which
    barrier, which rank adopted which store epoch, which commit was
    refused at which generation (telemetry/flightrec.py;
    docs/source/telemetry.rst, "Flight recorder"). Stack dumps from the
    hang watchdog (telemetry/forensics.py) merge into the same report:
    DESERTION findings name where each waiter actually sat, and a rank
    whose consecutive dumps share one non-idle leaf frame earns a WEDGE
    finding. Exit codes: 0 dumps found with no findings, 1 findings,
    2 neither flight dumps nor stack dumps."""
    import json

    from .telemetry import flightrec, forensics

    dumps = flightrec.load_dumps(args.path)
    stacks = forensics.load_stack_dumps(args.path)
    # A hang that resolved on its own leaves stack dumps but no ring
    # dumps (the op never aborted) — that wreck is still readable.
    if not dumps and not stacks:
        print(
            f"error: no flight dumps under {args.path}/{flightrec.FLIGHT_DIR}/ "
            "— ring dumps are written per rank when an operation aborts, "
            "stack dumps when the hang watchdog fires (both on by default; "
            "TORCHSNAPSHOT_TPU_FLIGHTREC=0 / TORCHSNAPSHOT_TPU_FORENSICS=0 "
            "disable them)",
            file=sys.stderr,
        )
        return 2
    merged = flightrec.merge_timeline(dumps)
    forensics.merge_stack_findings(merged, stacks)
    if args.json:
        print(json.dumps(merged, indent=1, default=repr))
    else:
        print(flightrec.render_timeline(merged, verbose=args.verbose))
    return 1 if merged.get("findings") else 0


def cmd_watch(args: argparse.Namespace) -> int:
    """Render the in-flight fleet from the heartbeat keys ranks publish
    through the coordination store (telemetry/health.py): per-rank
    phase/bytes/ETA, stalled-rank flags, and skew — BEFORE the barrier
    timeout turns a stall into an abort. Survives a store-leader
    failover the same way every client does (transparent adoption);
    with the whole tier down it degrades to a retry line, never a
    crash. ``--dump RANK`` posts a forensic request key the target
    rank's hang watchdog polls (telemetry/forensics.py); the returned
    wedge frame renders inline on that rank's row."""
    import json as _json
    import time as _time  # frame pacing, not measurement

    from .dist_store import TCPStore
    from .telemetry import forensics, health

    host, _, port_str = args.addr.rpartition(":")
    if not host or not port_str.isdigit():
        print(f'error: --addr must be "host:port", got {args.addr!r}',
              file=sys.stderr)
        return 2
    tracker = health.FleetTracker(stall_s=args.stall)
    store = None
    ticks = 0
    dump_sent = False
    wedged: dict = {}
    while True:
        try:
            if store is None:
                store = TCPStore(
                    host,
                    int(port_str),
                    is_server=False,
                    timeout=max(args.interval * 2, 5.0),
                    connect_retries=0,
                )
            # The request key survives a leader failover with the rest
            # of the keyspace; re-sent only until one set() succeeds.
            if getattr(args, "dump", None) is not None and not dump_sent:
                store.set(
                    f"{forensics.FORENSIC_REQ_PREFIX}{args.dump}", b"1"
                )
                dump_sent = True
            fleet = health.read_fleet(store)
            ages = tracker.observe(fleet)
            # Poll ONLY the requested rank's answer, and stop once it
            # lands: every extra round trip is load on the same store
            # the hung job depends on.
            if (
                getattr(args, "dump", None) is not None
                and args.dump not in wedged
            ):
                out_key = f"{forensics.FORENSIC_OUT_PREFIX}{args.dump}"
                try:
                    if store.check(out_key):
                        payload = _json.loads(
                            store.get(out_key).decode("utf-8")
                        )
                        if payload.get("wedge"):
                            wedged[args.dump] = str(payload["wedge"])
                except Exception:  # noqa: BLE001 - annotation, not data
                    pass
            frame = health.render_fleet(
                fleet, ages, args.stall, wedged=wedged or None
            )
        except Exception as e:  # noqa: BLE001 - degrade, keep watching
            # Keep the store object when we have one: its cached replica
            # set is what makes the NEXT poll fail over transparently. A
            # dead bootstrap connection is rebuilt from scratch.
            if store is not None and getattr(store, "_dead", None) is not None:
                try:
                    store.close()
                except Exception:  # noqa: BLE001
                    pass
                store = None
            frame = (
                f"store unreachable at {args.addr} "
                f"({type(e).__name__}: {e}); retrying"
            )
        ticks += 1
        print(f"--- watch {args.addr} tick {ticks}")
        print(frame, flush=True)
        if args.ticks and ticks >= args.ticks:
            if store is not None:
                try:
                    store.close()
                except Exception:  # noqa: BLE001
                    pass
            return 0
        _time.sleep(args.interval)


def cmd_store_status(args: argparse.Namespace) -> int:
    """Probe a coordination-store node (leader or standby) and print its
    replication status: role, epoch, op-log position, per-replica lag and
    lease age — the drill-debugging view of the failover tier
    (docs/source/fault_tolerance.rst, "Coordination tier")."""
    import json

    from .dist_store import probe_store_status

    try:
        info = probe_store_status(args.addr, timeout=args.timeout)
    except (ConnectionError, OSError, ValueError) as e:
        print(
            f"error: no store node answering at {args.addr} "
            f"({type(e).__name__}: {e})",
            file=sys.stderr,
        )
        return 2
    if args.json:
        print(json.dumps(info, indent=1, sort_keys=True))
        return 0
    role = info.get("role")
    print(
        f"{info.get('addr')}: role={role} epoch={info.get('epoch')} "
        f"log_seq={info.get('log_seq')} keys={info.get('n_keys')} "
        f"lease={info.get('lease_s')}s"
    )
    if role == "leader":
        replicas = info.get("replicas") or []
        if not replicas:
            print(
                "  no replicas joined — the store is a single point of "
                "failure (set TORCHSNAPSHOT_TPU_STORE_REPLICAS to arm "
                "failover)"
            )
        for rep in replicas:
            print(
                f"  replica[{rep.get('index')}] {rep.get('addr')}  "
                f"acked_seq={rep.get('acked_seq')} lag={rep.get('lag')} "
                f"lease_age={rep.get('lease_age_s')}s"
            )
    elif role == "standby":
        print(
            f"  following leader {info.get('leader')} "
            f"(last leader message {info.get('leader_silence_s')}s ago)"
        )
    elif role == "deposed":
        print(
            "  DEPOSED ex-leader: a higher epoch exists; clients have "
            "failed over to it"
        )
    return 0


def cmd_georep_status(args: argparse.Namespace) -> int:
    """Report the geo-replication plane of a snapshot ROOT: the latest
    committed step vs the remote tier's durable cursor — base shipped or
    not, last applied epoch + generation, backlog in epochs, and the
    measured lag (the RPO exposure a region loss right now would add).
    Exit 0 caught up, 1 behind, 2 cannot-check (no committed step, or no
    remote tier configured and none given with --remote)."""
    import json

    from . import georep

    info = georep.status(args.path, remote_root=args.remote)
    if args.json:
        print(json.dumps(info, indent=1, sort_keys=True))
    else:
        if not info.get("enabled"):
            print(
                f"{args.path}: geo-replication not configured (set "
                f"{georep.GEOREP_ENV_VAR} or pass --remote)"
            )
            return 2
        if info.get("step") is None:
            print(f"{args.path}: no committed step to replicate")
            return 2
        print(
            f"{args.path}: step {info['step']} -> {info['remote']}  "
            f"({info.get('local_epochs', 0)} committed epoch(s), "
            f"generation {info.get('local_gen')})"
        )
        if not info.get("base_replicated"):
            print("  base: NOT replicated (no remote cursor/metadata)")
        else:
            print(
                f"  cursor: epoch {info.get('applied_epoch')} applied, "
                f"generation {info.get('applied_gen')}"
            )
        backlog = info.get("backlog_epochs") or 0
        lag = info.get("lag_s")
        if backlog:
            print(
                f"  BEHIND by {backlog} epoch(s); oldest unreplicated "
                f"state is {lag}s old"
            )
        else:
            print("  caught up (replication lag 0.0s)")
    if not info.get("enabled") or info.get("step") is None:
        return 2
    return 1 if (info.get("backlog_epochs") or 0) else 0


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="python -m torchsnapshot_tpu",
        description="Inspect, verify, and migrate snapshots.",
    )
    sub = parser.add_subparsers(dest="command", required=True)

    p = sub.add_parser("info", help="summarize a snapshot")
    p.add_argument("path")
    p.set_defaults(fn=cmd_info)

    p = sub.add_parser("ls", help="list entries")
    p.add_argument("path")
    p.add_argument("--rank", type=int, default=None, help="only this rank's entries")
    p.add_argument("--all", action="store_true", help="include container entries")
    p.set_defaults(fn=cmd_ls)

    p = sub.add_parser("cat", help="print one entry (RANK/logical/path)")
    p.add_argument("path")
    p.add_argument("entry")
    p.add_argument("--limit", type=int, default=64, help="max array elements printed")
    p.set_defaults(fn=cmd_cat)

    p = sub.add_parser("verify", help="re-hash payloads against recorded checksums")
    p.add_argument("path")
    p.add_argument("-v", "--verbose", action="store_true")
    p.set_defaults(fn=cmd_verify)

    p = sub.add_parser(
        "fsck",
        help="full consistency check: payload existence/size/CRC, "
             "incremental deps, orphans, partial commits "
             "(exit 0 clean / 1 findings / 2 cannot-check)",
    )
    p.add_argument("path")
    p.add_argument("--repair", action="store_true",
                   help="quarantine orphans/temp files under "
                        ".fsck_quarantine/ (never deletes payload data)")
    p.add_argument("-v", "--verbose", action="store_true")
    p.set_defaults(fn=cmd_fsck)

    p = sub.add_parser(
        "stats",
        help="render the persisted telemetry summary of a take "
             "(requires TORCHSNAPSHOT_TPU_TELEMETRY=1 at save time); "
             "--trend renders the checkpoint history of a ROOT directory "
             "and exits 1 on a p50 regression; --openmetrics emits the "
             "summary as an OpenMetrics exposition",
    )
    p.add_argument("path")
    p.add_argument("--json", action="store_true", help="dump the raw document")
    p.add_argument("-v", "--verbose", action="store_true",
                   help="include all spans and measured rates")
    p.add_argument("--trend", action="store_true",
                   help="render .telemetry_history.jsonl of a snapshot ROOT "
                        "and gate on p50 regression (exit 1)")
    p.add_argument("--trend-metric", default="wall_s",
                   choices=["wall_s", "write_gbps", "read_gbps",
                            "replication_lag_s"],
                   help="history metric to gate on (default wall_s). "
                        "Constrained: a typo'd metric would match no "
                        "records and silently disarm the CI gate")
    p.add_argument("--trend-threshold", type=float, default=None,
                   help="p50 regression threshold as a fraction (default "
                        "TORCHSNAPSHOT_TPU_TREND_THRESHOLD or 0.25)")
    p.add_argument("--openmetrics", action="store_true",
                   help="emit the summary in OpenMetrics text format")
    p.set_defaults(fn=cmd_stats)

    p = sub.add_parser(
        "explain",
        help="critical-path attribution of a take/restore: binding "
             "resource + measured rate, per-segment critical path, "
             "straggler delta, tuning hint (exit 0 pipeline-bound / "
             "1 storage-bound / 2 no attribution)",
    )
    p.add_argument("path")
    p.add_argument("--json", action="store_true",
                   help="dump the raw attribution document")
    p.add_argument("-v", "--verbose", action="store_true",
                   help="include the governor's recorded elections")
    p.add_argument("--profiles", action="store_true",
                   help="render the autotuner's learned I/O profiles for "
                        "this root instead: per profile key the converged "
                        "settings, score, and recent perturbation trail")
    p.set_defaults(fn=cmd_explain)

    p = sub.add_parser(
        "plan",
        help="dry-run the minimal-movement reshard plan for restoring "
             "under a different layout/world: per-entry and total "
             "storage bytes (planned vs direct) and peer-channel bytes",
    )
    p.add_argument("path")
    p.add_argument("layout", help="destination LayoutSpec JSON file "
                                  "({version, mesh, rules})")
    p.add_argument("--world", type=int, required=True,
                   help="destination world size")
    p.add_argument("--min-requesters", type=int, default=2,
                   help="claim threshold: shards with fewer overlapping "
                        "ranks stay on direct reads (default 2)")
    p.add_argument("--json", action="store_true",
                   help="dump the per-entry plan accounting as JSON")
    p.set_defaults(fn=cmd_plan)

    p = sub.add_parser(
        "blackbox",
        help="merge per-rank flight-recorder dumps (<snapshot>/.flight/) "
             "into one causal cross-rank timeline with findings "
             "(exit 0 clean / 1 findings / 2 no dumps)",
    )
    p.add_argument("path")
    p.add_argument("--json", action="store_true", help="dump the merged view")
    p.add_argument("-v", "--verbose", action="store_true",
                   help="show the full timeline (default: last 200 events)")
    p.set_defaults(fn=cmd_blackbox)

    p = sub.add_parser(
        "watch",
        help="live fleet view of an in-flight take/restore from the "
             "coordination store's heartbeat keys: per-rank phase/bytes/"
             "ETA, stalled ranks, skew",
    )
    p.add_argument("addr", help='coordination store address, "host:port"')
    p.add_argument("--interval", type=float, default=1.0,
                   help="seconds between frames (default 1.0)")
    p.add_argument("--stall", type=float, default=5.0,
                   help="flag a rank STALLED after this many seconds "
                        "without heartbeat progress (default 5.0)")
    p.add_argument("--ticks", type=int, default=0,
                   help="render N frames then exit (0 = forever)")
    p.add_argument("--dump", type=int, default=None, metavar="RANK",
                   help="request a live thread-stack dump from RANK's "
                        "hang watchdog; the wedged frame renders inline "
                        "on that rank's row")
    p.set_defaults(fn=cmd_watch)

    p = sub.add_parser(
        "migrate", help="convert a reference-format snapshot to native format"
    )
    p.add_argument("src")
    p.add_argument("dst")
    p.add_argument("--rank", type=int, default=0)
    p.set_defaults(fn=cmd_migrate)

    p = sub.add_parser(
        "consolidate",
        help="materialize an incremental snapshot as a self-contained one",
    )
    p.add_argument("src")
    p.add_argument("dst")
    p.set_defaults(fn=cmd_consolidate)

    p = sub.add_parser("diff", help="compare two snapshots leaf by leaf")
    p.add_argument("a")
    p.add_argument("b")
    p.add_argument("-v", "--verbose", action="store_true",
                   help="also list unchanged/indeterminate leaves")
    p.set_defaults(fn=cmd_diff)

    p = sub.add_parser(
        "deps", help="origin graph of a directory of snapshots"
    )
    p.add_argument("dir")
    p.set_defaults(fn=cmd_deps)

    p = sub.add_parser(
        "prune",
        help="keep the newest N snapshots (and bases they require); "
             "delete the rest",
    )
    p.add_argument("dir")
    p.add_argument("--keep", type=int, required=True,
                   help="number of newest snapshots to keep")
    p.add_argument("--yes", action="store_true",
                   help="actually delete (default: print the plan)")
    p.add_argument("--ignore-missing-bases", action="store_true",
                   help="delete even when kept snapshots reference bases "
                        "that resolve to nothing in this directory")
    p.set_defaults(fn=cmd_prune)

    p = sub.add_parser(
        "store-status",
        help="probe a coordination-store node: leader addr/epoch, "
             "replica lag, lease age",
    )
    p.add_argument("addr", help='store node address, "host:port"')
    p.add_argument("--timeout", type=float, default=5.0)
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_store_status)

    p = sub.add_parser(
        "georep-status",
        help="report the geo-replication plane of a snapshot ROOT: "
             "remote cursor position, last applied generation, backlog "
             "epochs, measured lag (exit 0 caught up / 1 behind / "
             "2 cannot-check)",
    )
    p.add_argument("path", help="snapshot ROOT directory (the primary)")
    p.add_argument("--remote", default=None,
                   help="remote tier root URL (default "
                        "TORCHSNAPSHOT_TPU_GEOREP)")
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_georep_status)

    p = sub.add_parser(
        "lint",
        help="run the tsalint static analyzer over the package "
             "(concurrency, finalizer-context, resource-lifecycle, "
             "env-registry, and the five legacy invariant lints)",
    )
    analysis_runner.add_lint_arguments(p)
    p.set_defaults(fn=analysis_runner.cli_main)
    return parser


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    try:
        return args.fn(args)
    except (FileNotFoundError, RuntimeError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
