"""Snapshot manifest: entry type hierarchy, metadata YAML, elasticity rules.

TPU-native analogue of the reference's manifest (torchsnapshot/manifest.py):

- ``ArrayEntry`` describes one serialized array buffer (the reference's
  TensorEntry, manifest.py:37-69) — location, serializer, dtype, shape,
  replicated flag, optional byte range (set by the write batcher).
- ``ShardedArrayEntry`` describes a GSPMD-sharded jax.Array as a list of
  ``Shard``s with N-D global offsets/sizes (reference: manifest.py:72-85).
  The shard spec is derived from jax.sharding.NamedSharding at save time.
- ``ChunkedArrayEntry`` describes a large non-sharded array split along dim 0
  so replicated arrays can be striped across processes (manifest.py:88-102).
- ``ObjectEntry``/``PrimitiveEntry`` cover pickled objects and metadata-inlined
  primitives (manifest.py:105-242).
- Container entries (dict/ordered-dict/list/tuple/namedtuple) record structure
  for ``inflate``; tuples/namedtuples are an extension for JAX pytrees (optax
  states are namedtuples).

``SnapshotMetadata`` is persisted as YAML (``.snapshot_metadata``) and written
*last* — it is the commit point of a snapshot. ``get_available_entries``
implements the elasticity rules (manifest.py:324-382): per-rank entries go to
their owner only, replicated entries to everyone, sharded entries are merged
across ranks and go to everyone; container entries are excluded.
"""

from __future__ import annotations

import base64
import json
import struct
from dataclasses import asdict, dataclass, field
from typing import Any, Dict, List, Optional, Tuple, TypeVar

import yaml

try:  # libyaml is ~10x faster for large manifests
    from yaml import CSafeDumper as _Dumper, CSafeLoader as _Loader
except ImportError:  # pragma: no cover
    from yaml import SafeDumper as _Dumper, SafeLoader as _Loader


class CorruptSnapshotError(RuntimeError):
    """``.snapshot_metadata`` exists but cannot be decoded.

    Before this exception, a torn or zero-byte metadata file surfaced as
    whatever the decoder tripped over first — ``JSONDecodeError``,
    ``yaml.YAMLError``, ``KeyError: 'manifest'``, ``UnicodeDecodeError``
    — none of which tell an operator the one thing that matters: the
    snapshot should be treated as UNCOMMITTED. The commit protocol makes
    this state near-impossible for the library's own writers (temp-file +
    atomic rename), so a corrupt metadata file means out-of-band damage:
    a non-atomic copy (``cp``/``rsync`` mid-write), storage-layer
    truncation, or a foreign writer. ``fsck`` reports it as the
    ``corrupt-metadata`` finding class.
    """

    def __init__(self, path: str, detail: str) -> None:
        super().__init__(
            f"Snapshot metadata at {path!r} is unreadable ({detail}). This "
            "usually means a torn or partial commit reached the metadata "
            "file through an out-of-band channel (non-atomic copy, storage "
            "truncation) — the library's own commit is atomic. Treat the "
            "snapshot as uncommitted and restore from the previous "
            "committed snapshot; run `python -m torchsnapshot_tpu fsck` "
            "for a full diagnosis."
        )
        self.path = path
        self.detail = detail


@dataclass
class Entry:
    type: str


@dataclass
class ArrayEntry(Entry):
    location: str
    serializer: str
    dtype: str
    shape: List[int]
    replicated: bool
    byte_range: Optional[List[int]] = None  # [lo, hi) within location
    checksum: Optional[str] = None  # "<algo>:<hexdigest>" of the payload
    # Incremental snapshots (dedup.py): content digest recorded at stage
    # time, and — for payloads reused from a base snapshot — the URL of the
    # snapshot that physically holds the bytes. Omitted from the YAML when
    # unset so non-incremental snapshots keep their on-disk format.
    digest: Optional[str] = None  # "sha256:<hexdigest>" of the payload
    origin: Optional[str] = None  # base snapshot URL holding the payload
    # Payload compression (compression.py): canonical codec spec of the
    # STORED bytes ("zstd:3"); checksum covers the stored bytes, digest
    # the uncompressed ones. Omitted from YAML when unset.
    codec: Optional[str] = None
    # Device-resident fingerprint (device_digest.py, "xxh4x32:<hex>"):
    # lets a future incremental take detect the payload unchanged WITHOUT
    # a DtoH transfer. Omitted when unset.
    device_digest: Optional[str] = None

    def __init__(
        self,
        location: str,
        serializer: str,
        dtype: str,
        shape: List[int],
        replicated: bool,
        byte_range: Optional[List[int]] = None,
        checksum: Optional[str] = None,
        digest: Optional[str] = None,
        origin: Optional[str] = None,
        codec: Optional[str] = None,
        device_digest: Optional[str] = None,
    ) -> None:
        super().__init__(type="array")
        self.location = location
        self.serializer = serializer
        self.dtype = dtype
        self.shape = list(shape)
        self.replicated = replicated
        self.byte_range = list(byte_range) if byte_range is not None else None
        self.checksum = checksum
        self.digest = digest
        self.origin = origin
        self.codec = codec
        self.device_digest = device_digest


@dataclass
class Shard:
    offsets: List[int]
    sizes: List[int]
    array: ArrayEntry


@dataclass
class ShardedArrayEntry(Entry):
    dtype: str
    shape: List[int]
    shards: List[Shard]

    def __init__(self, dtype: str, shape: List[int], shards: List[Shard]) -> None:
        super().__init__(type="sharded_array")
        self.dtype = dtype
        self.shape = list(shape)
        self.shards = shards


@dataclass
class ChunkedArrayEntry(Entry):
    dtype: str
    shape: List[int]
    chunks: List[Shard]
    replicated: bool

    def __init__(
        self, dtype: str, shape: List[int], chunks: List[Shard], replicated: bool
    ) -> None:
        super().__init__(type="chunked_array")
        self.dtype = dtype
        self.shape = list(shape)
        self.chunks = chunks
        self.replicated = replicated


@dataclass
class ObjectEntry(Entry):
    location: str
    serializer: str
    obj_type: str
    replicated: bool
    checksum: Optional[str] = None  # "<algo>:<hexdigest>" of the payload
    size: Optional[int] = None  # serialized bytes, recorded at stage time
    digest: Optional[str] = None  # "sha256:<hexdigest>" (see ArrayEntry)
    origin: Optional[str] = None  # base snapshot URL holding the payload
    codec: Optional[str] = None  # compression of the stored bytes

    def __init__(
        self,
        location: str,
        serializer: str,
        obj_type: str,
        replicated: bool,
        checksum: Optional[str] = None,
        size: Optional[int] = None,
        digest: Optional[str] = None,
        origin: Optional[str] = None,
        codec: Optional[str] = None,
    ) -> None:
        super().__init__(type="object")
        self.location = location
        self.serializer = serializer
        self.obj_type = obj_type
        self.replicated = replicated
        self.checksum = checksum
        self.size = size
        self.digest = digest
        self.origin = origin
        self.codec = codec


_PRIMITIVE_TYPES = ("int", "float", "str", "bool", "bytes", "NoneType")


@dataclass
class PrimitiveEntry(Entry):
    """A primitive value inlined into the metadata — zero storage I/O.

    Floats are stored as both a human-readable repr and big-endian IEEE-754
    hex so restore is bit-exact (the reference used base64+struct,
    manifest.py:146-242); bytes are base64.
    """

    ptype: str
    readable: str
    replicated: bool

    def __init__(self, ptype: str, readable: str, replicated: bool) -> None:
        super().__init__(type="primitive")
        self.ptype = ptype
        self.readable = readable
        self.replicated = replicated

    @classmethod
    def supported_types(cls) -> Tuple[str, ...]:
        return _PRIMITIVE_TYPES

    @classmethod
    def from_object(cls, obj: Any, replicated: bool = False) -> "PrimitiveEntry":
        tname = type(obj).__name__
        if tname == "bool":  # before int: bool is a subclass of int
            return cls("bool", str(obj), replicated)
        elif tname == "int":
            return cls("int", str(obj), replicated)
        elif tname == "float":
            return cls("float", struct.pack(">d", obj).hex(), replicated)
        elif tname == "str":
            return cls("str", obj, replicated)
        elif tname == "bytes":
            return cls("bytes", base64.b64encode(obj).decode("ascii"), replicated)
        elif tname == "NoneType":
            return cls("NoneType", "", replicated)
        raise TypeError(f"Unsupported primitive type: {tname}")

    def get_value(self) -> Any:
        if self.ptype == "bool":
            return self.readable == "True"
        elif self.ptype == "int":
            return int(self.readable)
        elif self.ptype == "float":
            return struct.unpack(">d", bytes.fromhex(self.readable))[0]
        elif self.ptype == "str":
            return self.readable
        elif self.ptype == "bytes":
            return base64.b64decode(self.readable)
        elif self.ptype == "NoneType":
            return None
        raise TypeError(f"Unsupported primitive type: {self.ptype}")


@dataclass
class ListEntry(Entry):
    def __init__(self) -> None:
        super().__init__(type="list")


@dataclass
class TupleEntry(Entry):
    def __init__(self) -> None:
        super().__init__(type="tuple")


@dataclass
class NamedTupleEntry(Entry):
    module: str
    qualname: str
    fields: List[str]

    def __init__(self, module: str, qualname: str, fields: List[str]) -> None:
        super().__init__(type="namedtuple")
        self.module = module
        self.qualname = qualname
        self.fields = list(fields)


@dataclass
class DictEntry(Entry):
    keys: List[Any]  # original key objects (str | int); order matters

    def __init__(self, keys: List[Any]) -> None:
        super().__init__(type="dict")
        self.keys = list(keys)


@dataclass
class OrderedDictEntry(Entry):
    keys: List[Any]

    def __init__(self, keys: List[Any]) -> None:
        super().__init__(type="ordered_dict")
        self.keys = list(keys)


T = TypeVar("T", bound=Entry)
Manifest = Dict[str, T]

_CONTAINER_TYPES = (
    ListEntry,
    TupleEntry,
    NamedTupleEntry,
    DictEntry,
    OrderedDictEntry,
)


def is_container_entry(entry: Entry) -> bool:
    return isinstance(entry, _CONTAINER_TYPES)


def is_replicated(entry: Entry) -> bool:
    return (
        isinstance(entry, (ArrayEntry, ObjectEntry, ChunkedArrayEntry, PrimitiveEntry))
        and entry.replicated
    )


def _array_entry_from_dict(d: Dict[str, Any]) -> ArrayEntry:
    # Direct construction bypassing __init__'s defensive list() copies:
    # the dict comes from our own json.loads, whose lists are already
    # fresh. At 50k shard leaves the kwargs/copy path was most of the
    # manifest parse time.
    e = ArrayEntry.__new__(ArrayEntry)
    e.type = "array"
    e.location = d["location"]
    e.serializer = d["serializer"]
    e.dtype = d["dtype"]
    e.shape = d["shape"]
    e.replicated = d["replicated"]
    e.byte_range = d.get("byte_range")
    e.checksum = d.get("checksum")
    e.digest = d.get("digest")
    e.origin = d.get("origin")
    e.codec = d.get("codec")
    e.device_digest = d.get("device_digest")
    return e


def _shard_from_dict(d: Dict[str, Any]) -> Shard:
    return Shard(
        offsets=d["offsets"],
        sizes=d["sizes"],
        array=_array_entry_from_dict(d["array"]),
    )


def entry_from_dict(d: Dict[str, Any]) -> Entry:
    type_name = d["type"]
    if type_name == "array":
        return _array_entry_from_dict(d)
    d = dict(d)
    d.pop("type")
    if type_name == "sharded_array":
        return ShardedArrayEntry(
            dtype=d["dtype"],
            shape=d["shape"],
            shards=[_shard_from_dict(s) for s in d["shards"]],
        )
    elif type_name == "chunked_array":
        return ChunkedArrayEntry(
            dtype=d["dtype"],
            shape=d["shape"],
            chunks=[_shard_from_dict(c) for c in d["chunks"]],
            replicated=d["replicated"],
        )
    elif type_name == "object":
        return ObjectEntry(**d)
    elif type_name == "primitive":
        return PrimitiveEntry(**d)
    elif type_name == "list":
        return ListEntry()
    elif type_name == "tuple":
        return TupleEntry()
    elif type_name == "namedtuple":
        return NamedTupleEntry(**d)
    elif type_name == "dict":
        return DictEntry(**d)
    elif type_name == "ordered_dict":
        return OrderedDictEntry(**d)
    raise ValueError(f"Unknown manifest entry type: {type_name!r}")


_STRIPPED_WHEN_NONE = ("digest", "origin", "codec", "device_digest")
_FIELD_NAME_CACHE: Dict[type, List[str]] = {}


def _array_entry_to_dict(e: "ArrayEntry") -> Dict[str, Any]:
    # Field-declaration order — the serialization contract.
    out: Dict[str, Any] = {
        "type": e.type,
        "location": e.location,
        "serializer": e.serializer,
        "dtype": e.dtype,
        "shape": e.shape,
        "replicated": e.replicated,
        "byte_range": e.byte_range,
        "checksum": e.checksum,
    }
    if e.digest is not None:
        out["digest"] = e.digest
    if e.origin is not None:
        out["origin"] = e.origin
    if e.codec is not None:
        out["codec"] = e.codec
    if e.device_digest is not None:
        out["device_digest"] = e.device_digest
    return out


def _shard_to_dict(s: "Shard") -> Dict[str, Any]:
    return {
        "offsets": s.offsets,
        "sizes": s.sizes,
        "array": _array_entry_to_dict(s.array),
    }


def _entry_to_dict(obj: Any) -> Any:
    """Shallow dataclass→dict conversion in field-declaration order (the
    serialization contract asdict established), dropping the
    incremental/compression fields while None.

    The shard-carrying entry types get direct, loop-free builders: a
    70B-GSPMD manifest is ~50k Shard/ArrayEntry leaves, and the generic
    per-field walk's dispatch overhead (~16 ns × millions of leaf values)
    dominated emit time."""
    from dataclasses import fields, is_dataclass

    cls = type(obj)
    if cls is ArrayEntry:
        return _array_entry_to_dict(obj)
    if cls is ShardedArrayEntry:
        return {
            "type": obj.type,
            "dtype": obj.dtype,
            "shape": obj.shape,
            "shards": [_shard_to_dict(s) for s in obj.shards],
        }
    if cls is ChunkedArrayEntry:
        return {
            "type": obj.type,
            "dtype": obj.dtype,
            "shape": obj.shape,
            "chunks": [_shard_to_dict(s) for s in obj.chunks],
            "replicated": obj.replicated,
        }
    if is_dataclass(obj) and not isinstance(obj, type):
        names = _FIELD_NAME_CACHE.get(cls)
        if names is None:
            names = [f.name for f in fields(cls)]
            _FIELD_NAME_CACHE[cls] = names
        out: Dict[str, Any] = {}
        for name in names:
            value = getattr(obj, name)
            if value is None and name in _STRIPPED_WHEN_NONE:
                continue
            out[name] = _entry_to_dict(value)
        return out
    if isinstance(obj, list):
        return [_entry_to_dict(v) for v in obj]
    return obj


@dataclass
class SnapshotMetadata:
    version: str
    world_size: int
    manifest: Manifest
    # Two-tier + incremental composition (omitted from YAML when unset):
    # the mirror this snapshot replicated to, and — for incremental
    # snapshots — each origin snapshot's mirror, so deduplicated payloads
    # stay restorable from the durable tier after the origin's primary is
    # lost (see storage_plugins/mirror.py).
    mirror_url: Optional[str] = None
    origin_mirrors: Optional[Dict[str, str]] = None
    # The SOURCE partition-rule layout this snapshot was taken under
    # (layout.LayoutSpec.to_dict() — mesh axes + regex rules + dtype
    # policies), when the caller declared one via Snapshot.take(...,
    # layout=...). Purely descriptive metadata: restores never require
    # it (the destination arrays' real shardings are authoritative), but
    # `tstpu plan` uses it to dry-run a reshard into a destination rule
    # set without opening a device. Omitted from YAML when unset.
    layout: Optional[Dict[str, Any]] = None

    def to_yaml(self) -> str:
        """Serialize to the on-disk metadata format.

        Since round 4 this emits compact JSON — which is valid YAML, so
        builds that parse ``.snapshot_metadata`` with a YAML loader keep
        reading new snapshots. The switch is a scalability fix: a
        70B-scale GSPMD manifest is ~50k shard entries / ~18 MB, which
        libyaml emits in ~10 s and parses in ~15 s, vs ~0.3 s for JSON
        (pinned by tests/test_manifest_golden.py, with a legacy YAML
        fixture covering pre-round-4 snapshots).
        """
        # Hand-rolled conversion instead of dataclasses.asdict: asdict
        # deep-copies every leaf (~0.7 s of a 50k-shard manifest's 1.0 s
        # emit) where serialization only needs a shallow walk. Field
        # order matches asdict (declaration order, type first) — pinned
        # byte-exact by tests/test_manifest_golden.py. Optional fields
        # (digest/origin/codec) are omitted while unset so snapshots not
        # using them keep their on-disk format; absent keys read back as
        # None.
        d: Dict[str, Any] = {
            "version": self.version,
            "world_size": self.world_size,
            "manifest": {
                path: _entry_to_dict(entry)
                for path, entry in self.manifest.items()
            },
        }
        if self.mirror_url:
            d["mirror_url"] = self.mirror_url
        if self.origin_mirrors:
            d["origin_mirrors"] = self.origin_mirrors
        if self.layout:
            d["layout"] = self.layout
        # allow_nan=False: a non-finite float would silently emit
        # JSON-invalid tokens; no entry field legitimately carries one
        # (primitives serialize through reprs).
        return json.dumps(d, separators=(",", ":"), allow_nan=False) + "\n"

    @classmethod
    def from_yaml(cls, yaml_str: str) -> "SnapshotMetadata":
        """Parse metadata: JSON fast path, YAML fallback for snapshots
        written before the round-4 format switch."""
        try:
            d = json.loads(yaml_str)
        except json.JSONDecodeError:
            d = yaml.load(yaml_str, Loader=_Loader)
        manifest: Manifest = {
            path: entry_from_dict(entry) for path, entry in d["manifest"].items()
        }
        return cls(
            version=d["version"],
            world_size=d["world_size"],
            manifest=manifest,
            mirror_url=d.get("mirror_url"),
            origin_mirrors=d.get("origin_mirrors"),
            layout=d.get("layout"),
        )


def get_available_entries(manifest: Manifest, rank: int) -> Manifest:
    """Local view of a global manifest for ``rank`` under the elasticity rules.

    - per-rank entries: available only to the rank that saved them;
    - replicated entries: available to all ranks (including ranks beyond the
      saving world size);
    - sharded entries: shards merged across all ranks, available to all;
    - container entries are structural only and excluded.

    Mirrors reference behavior (manifest.py:324-382) including the rule that a
    rank that saved its own copy of a replicated entry reads its own copy.
    """
    grouped: Dict[str, Dict[int, Entry]] = {}
    for path, entry in manifest.items():
        entry_rank_str, _, local_path = path.partition("/")
        grouped.setdefault(local_path, {})[int(entry_rank_str)] = entry

    local_manifest: Manifest = {}
    for local_path, group in grouped.items():
        entries = list(group.values())
        first = entries[0]
        if isinstance(first, ShardedArrayEntry):
            merged: List[Shard] = [s for e in entries for s in e.shards]
            local_manifest[local_path] = ShardedArrayEntry(
                dtype=first.dtype, shape=first.shape, shards=merged
            )
        elif isinstance(
            first, (ArrayEntry, ObjectEntry, ChunkedArrayEntry, PrimitiveEntry)
        ):
            if rank in group:
                local_manifest[local_path] = group[rank]
            elif first.replicated:
                local_manifest[local_path] = first
        elif is_container_entry(first):
            pass
        else:
            raise RuntimeError(
                f"Unknown entry type: {type(first).__name__} ({first.type})."
            )
    return local_manifest


def get_manifest_for_rank(metadata: SnapshotMetadata, rank: int) -> Manifest:
    """Rank-local manifest including container entries (used by inflate).

    For ranks beyond the saving world size, rank 0's container structure is
    used — valid because such ranks may only load replicated/sharded entries,
    whose structure is identical across ranks.
    """
    container_rank = rank if rank < metadata.world_size else 0
    available = get_available_entries(metadata.manifest, rank)
    prefix = f"{container_rank}/"
    for path, entry in metadata.manifest.items():
        if not is_container_entry(entry):
            continue
        if path.startswith(prefix):
            available[path[len(prefix):]] = entry
        elif path == str(container_rank):  # the rank-root container
            available[""] = entry
    return available
