"""Cross-rank telemetry aggregation: per-rank summaries -> fleet view.

On distributed takes/restores the per-rank summary dicts (core.OpRecorder
.finish) are gathered over the existing KV-store collective plane
(pg_wrapper.all_gather_object — the same channel the manifest gather
uses; telemetry never touches device collectives) and merged here into
one fleet view: who was slowest, how skewed the ranks were, and the
aggregate byte counters. The merge is pure dict math so it can run
anywhere — rank 0 at commit time, the ``stats`` CLI re-deriving a view
from a persisted document, or a test constructing synthetic summaries.

A rank whose telemetry was disabled contributes ``None`` (the gather is
unconditional so env skew can never desync the collective order); the
merge simply reports how many ranks contributed.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

# Counters that sum meaningfully across ranks. Everything else (gauges,
# span stats) stays per-rank in the persisted document.
_SUMMED_COUNTERS = (
    "bytes_written",
    "bytes_read",
    "bytes_staged",
    "bytes_deduped",
    "bytes_to_peers",
    "entries_written",
    "entries_streamed",
    "entries_read",
    "retry_attempts",
    "retry_backoff_s",
    "budget_defers",
    # Degradation counters (PR 4/6 machinery): a fleet that failed over
    # mid-take must SAY so in the persisted summary — these existed on
    # the bus but vanished post-hoc until the observability PR.
    "store_failovers",
    "lease_renewals",
    "fanout_fallbacks",
    "mirror_failovers",
    # Delta journal (journal.py): epoch appends and restore-side replay,
    # plus torn-tail truncations — the RPO story in one summary row.
    "journal_appends",
    "journal_bytes",
    "journal_replays",
    "journal_truncations",
    # Fleet distribution tier (distrib.py): bytes sourced from seeding
    # peers instead of storage, local chunk-cache hits, and rolling-
    # update epoch bytes pushed — the seed-vs-storage mix in one row.
    "bytes_from_seeders",
    "seed_cache_hits",
    "epoch_push_bytes",
    # Multi-tenant plane (tenancy/): quota evictions and pooled-payload
    # reclaim, plus remote roots where retention could not run — the
    # per-tenant capacity story in one row.
    "retention_skipped",
    "quota_evictions",
    "pool_bytes_released",
    # Lazy page-in restore (pagein.py): demand faults vs speculative
    # prefetch and the bytes paged after restore() returned — the
    # serve-before-restored story in one row.
    "pages_faulted",
    "pages_prefetched",
    "pagein_bytes",
    # Closed-loop autotune (scheduler.IOGovernor / autotune.py): ops
    # whose verdict carried no binding category and were therefore
    # skipped by profile learning — a high count means the tuner is
    # flying blind (telemetry bus off / attribution failing).
    "profile_skips",
    # Cross-region geo-replication (georep.py): what the rank-0 shipper
    # moved, what it refused (CRC rejects, splice refusals), and what it
    # shed under backlog pressure — the DR-tier health in one row.
    "georep_bases_shipped",
    "georep_epochs_shipped",
    "georep_bytes_shipped",
    "georep_ship_errors",
    "georep_frames_rejected",
    "georep_splice_refusals",
    "georep_steps_dropped",
)


def merge_histograms(
    rank_summaries: List[Optional[Dict[str, Any]]]
) -> Dict[str, Dict[str, Dict[str, Any]]]:
    """Bucket-wise sum of every rank's latency histograms.

    Histograms share the fixed log2 ladder (core.HISTOGRAM_BOUNDS), so
    the merge is element-wise addition per ``(name, key)`` family —
    short/long counts lists (version skew) are padded, never dropped."""
    merged: Dict[str, Dict[str, Dict[str, Any]]] = {}
    for summary in rank_summaries:
        if not isinstance(summary, dict):
            continue
        for name, by_key in (summary.get("histograms") or {}).items():
            for key, hist in by_key.items():
                counts = list(hist.get("counts") or [])
                tgt = merged.setdefault(name, {}).setdefault(
                    key, {"counts": [], "count": 0, "sum": 0.0}
                )
                if len(tgt["counts"]) < len(counts):
                    tgt["counts"].extend(
                        [0] * (len(counts) - len(tgt["counts"]))
                    )
                for i, n in enumerate(counts):
                    tgt["counts"][i] += n
                tgt["count"] += hist.get("count") or 0
                tgt["sum"] = round(tgt["sum"] + (hist.get("sum") or 0.0), 6)
    return merged


def merge_summaries(
    rank_summaries: List[Optional[Dict[str, Any]]]
) -> Optional[Dict[str, Any]]:
    """Merge gathered per-rank summaries into the fleet view.

    Returns None when no rank contributed (telemetry off everywhere).
    """
    present = [
        (i, s) for i, s in enumerate(rank_summaries) if isinstance(s, dict)
    ]
    if not present:
        return None
    walls = [(s.get("wall_s", 0.0), i) for i, s in present]
    wall_max, slowest = max(walls)
    wall_min, fastest = min(walls)
    aggregate: Dict[str, float] = {}
    for _, s in present:
        for key in _SUMMED_COUNTERS:
            val = (s.get("counters") or {}).get(key)
            if val:
                aggregate[key] = aggregate.get(key, 0) + val
    if aggregate.get("bytes_written") and wall_max > 0:
        # Fleet bandwidth over the op's critical path: everyone's bytes
        # over the slowest rank's wall (the time the TRAINING LOOP paid).
        # Unrounded: tiny test payloads would round to 0.
        aggregate["write_gbps"] = aggregate["bytes_written"] / wall_max / 1e9
    if aggregate.get("bytes_read") and wall_max > 0:
        aggregate["read_gbps"] = aggregate["bytes_read"] / wall_max / 1e9
    histograms = merge_histograms([s for _, s in present])
    return {
        "world_size": len(rank_summaries),
        "reporting": len(present),
        "op": present[0][1].get("op"),
        "wall_s_max": round(wall_max, 6),
        "wall_s_min": round(wall_min, 6),
        "skew_s": round(wall_max - wall_min, 6),
        "slowest_rank": slowest,
        "fastest_rank": fastest,
        "aggregate": aggregate,
        **({"histograms": histograms} if histograms else {}),
    }
