"""Telemetry exporters: Chrome/Perfetto trace JSON and the persisted
per-take summary.

Three consumers, three formats:

- :func:`chrome_trace` — the raw event list as Chrome's Trace Event
  format (``{"traceEvents": [...]}``), loadable in Perfetto /
  ``chrome://tracing``. Spans become ``ph: "X"`` complete events on the
  thread (tid) that ran them — executor lanes, the event loop, and the
  background commit thread render as separate tracks; counters/gauges
  become ``ph: "C"`` counter tracks.
- the persisted summary — ``Snapshot.take`` writes the cross-rank
  gathered per-op summaries plus the merged fleet view (aggregate.py)
  to :data:`TELEMETRY_SUMMARY_FNAME` next to ``.snapshot_metadata``, so
  ``python -m torchsnapshot_tpu stats <path>`` can answer "why was this
  take slow?" long after the process is gone.
- the plain-dict API — ``telemetry.last_summary()`` /
  ``telemetry.last_fleet()`` (core.py) for programmatic scraping
  (bench.py embeds them into its artifact).

Timestamps: events carry raw ``time.monotonic()`` seconds; the trace
exporter rebases to the earliest event and converts to the microseconds
Chrome expects, so ``ts`` is always >= 0 and mutually consistent within
one process's trace. Cross-rank traces are per-rank files — monotonic
clocks are not comparable across hosts, and Perfetto renders each file's
pid lane independently.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

from . import core

# Persisted next to .snapshot_metadata by rank 0 after the commit.
TELEMETRY_SUMMARY_FNAME = ".snapshot_telemetry"
# Per-rank Chrome traces, written by each telemetry-enabled rank.
TRACE_DIR = ".telemetry"


def trace_path_for_rank(rank: int) -> str:
    return f"{TRACE_DIR}/rank_{rank}.trace.json"


def chrome_trace(
    events: Optional[List[Dict[str, Any]]] = None,
    pid: int = 0,
    process_name: str = "torchsnapshot_tpu",
) -> Dict[str, Any]:
    """Convert recorded events to Chrome Trace Event format.

    ``events`` defaults to everything recorded in this process;
    ``pid`` labels the process lane (use the rank on distributed ops).
    """
    if events is None:
        events = core.events()
    trace: List[Dict[str, Any]] = [
        {
            "ph": "M",
            "name": "process_name",
            "pid": pid,
            "tid": 0,
            "args": {"name": f"{process_name} (rank {pid})"},
        }
    ]
    if not events:
        return {"traceEvents": trace, "displayTimeUnit": "ms"}
    t0 = min(e["ts"] for e in events)

    def us(seconds: float) -> int:
        return int(round((seconds - t0) * 1e6))

    for ev in sorted(events, key=lambda e: e["ts"]):
        ph = ev.get("ph")
        # tids are Python thread idents (large); compact them for the UI.
        tid = ev.get("tid", 0) % 100_000
        if ph == "span":
            out = {
                "ph": "X",
                "name": ev["name"],
                "cat": ev.get("cat", "pipeline"),
                "pid": pid,
                "tid": tid,
                "ts": us(ev["ts"]),
                "dur": max(0, int(round(ev["dur"] * 1e6))),
            }
            args = dict(ev.get("args") or {})
            if ev.get("parent") is not None:
                args["parent"] = ev["parent"]
            if args:
                out["args"] = args
            trace.append(out)
        elif ph == "counter":
            trace.append(
                {
                    "ph": "C",
                    "name": ev["name"],
                    "cat": ev.get("cat", "counter"),
                    "pid": pid,
                    "tid": 0,
                    "ts": us(ev["ts"]),
                    "args": {ev["name"]: ev.get("value", 0)},
                }
            )
        else:  # instant
            trace.append(
                {
                    "ph": "i",
                    "s": "t",
                    "name": ev["name"],
                    "cat": ev.get("cat", "event"),
                    "pid": pid,
                    "tid": tid,
                    "ts": us(ev["ts"]),
                    "args": ev.get("args") or {},
                }
            )
    return {"traceEvents": trace, "displayTimeUnit": "ms"}


def chrome_trace_json(
    events: Optional[List[Dict[str, Any]]] = None, pid: int = 0
) -> str:
    return json.dumps(chrome_trace(events, pid=pid))


def write_chrome_trace(
    path: str, events: Optional[List[Dict[str, Any]]] = None, pid: int = 0
) -> None:
    """Write a Chrome trace of ``events`` to a local file."""
    with open(path, "w") as f:
        f.write(chrome_trace_json(events, pid=pid))


# ------------------------------------------------------------ summary file


def build_summary_document(
    op: str,
    world_size: int,
    rank_summaries: List[Optional[Dict[str, Any]]],
    fleet: Optional[Dict[str, Any]],
) -> Dict[str, Any]:
    return {
        "version": 1,
        "op": op,
        "world_size": world_size,
        "ranks": rank_summaries,
        "fleet": fleet,
    }


def fmt_bytes(n: Optional[float]) -> str:
    """THE byte formatter for operator-facing output (cli.py info/ls and
    the stats rendering below share it, so sizes read identically across
    commands)."""
    if n is None:
        return "?"
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{n:g}B"
        n /= 1024
    return f"{n}B"


_fmt_bytes = fmt_bytes


def render_summary_document(doc: Dict[str, Any], verbose: bool = False) -> str:
    """Human-readable rendering of a persisted summary document (the
    ``stats`` CLI command's output)."""
    lines: List[str] = []
    lines.append(f"op:          {doc.get('op')}")
    lines.append(f"world_size:  {doc.get('world_size')}")
    fleet = doc.get("fleet")
    ranks = [r for r in (doc.get("ranks") or []) if r]
    if fleet:
        lines.append(f"fleet wall:  {fleet.get('wall_s_max', 0):.3f}s "
                     f"(slowest rank {fleet.get('slowest_rank')}, "
                     f"skew {fleet.get('skew_s', 0):.3f}s)")
        agg = fleet.get("aggregate") or {}
        if agg.get("bytes_written"):
            lines.append(
                f"written:     {_fmt_bytes(agg['bytes_written'])} aggregate"
                + (
                    f" ({agg['write_gbps']:.2f} GB/s fleet)"
                    if agg.get("write_gbps")
                    else ""
                )
            )
        if agg.get("bytes_read"):
            lines.append(f"read:        {_fmt_bytes(agg['bytes_read'])} aggregate")
        if agg.get("bytes_deduped"):
            lines.append(f"deduped:     {_fmt_bytes(agg['bytes_deduped'])} skipped")
        if agg.get("bytes_to_peers"):
            lines.append(
                f"peer bytes:  {_fmt_bytes(agg['bytes_to_peers'])} redistributed"
            )
        if agg.get("retry_attempts"):
            lines.append(f"retries:     {agg['retry_attempts']:.0f} attempts")
        # Degradation counters: zero is the healthy (and silent) case;
        # any non-zero value is the headline of a post-mortem.
        degraded = [
            f"{label}={agg[key]:.0f}"
            for key, label in (
                ("store_failovers", "store"),
                ("mirror_failovers", "mirror"),
                ("fanout_fallbacks", "fanout"),
            )
            if agg.get(key)
        ]
        if degraded:
            lines.append(f"failovers:   {', '.join(degraded)}")
        if agg.get("lease_renewals"):
            lines.append(
                f"lease:       {agg['lease_renewals']:.0f} renewal round(s)"
            )
    for summary in ranks:
        lines.append("")
        lines.append(
            f"rank {summary.get('rank')}: {summary.get('op')} "
            f"{summary.get('wall_s', 0):.3f}s"
        )
        phases = summary.get("phases") or {}
        if phases:
            lines.append(
                "  phases:   "
                + ", ".join(f"{n}={dt:.3f}s" for n, dt in phases.items())
            )
        counters = summary.get("counters") or {}
        for key in sorted(counters):
            val = counters[key]
            shown = _fmt_bytes(val) if key.startswith("bytes_") else f"{val:g}"
            lines.append(f"  {key}: {shown}")
        spans = summary.get("spans") or {}
        order = sorted(
            spans.items(), key=lambda kv: kv[1].get("total_s", 0), reverse=True
        )
        if not verbose:
            order = order[:8]
        for name, agg in order:
            lines.append(
                f"  span {name}: x{agg['count']} total {agg['total_s']:.3f}s "
                f"max {agg['max_s']:.3f}s"
            )
        if verbose and summary.get("rates"):
            lines.append(f"  rates: {summary['rates']}")
        if summary.get("dropped_events"):
            lines.append(f"  dropped_events: {summary['dropped_events']}")
    return "\n".join(lines)


# -------------------------------------------------------------- openmetrics

_METRIC_PREFIX = "torchsnapshot_tpu"


def _om_escape(value: str) -> str:
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def render_openmetrics(doc: Dict[str, Any]) -> str:
    """Render a persisted summary document in OpenMetrics text format
    (``stats --openmetrics``), so a scrape sidecar can lift a take's
    counters into Prometheus without parsing our JSON.

    Counter families end in ``_total`` per the spec; per-rank samples
    carry a ``rank`` label; the exposition ends with ``# EOF``."""
    lines: List[str] = []
    op = doc.get("op") or "unknown"
    fleet = doc.get("fleet") or {}
    agg = fleet.get("aggregate") or {}
    ranks = [r for r in (doc.get("ranks") or []) if isinstance(r, dict)]

    counter_keys = sorted(
        k for k, v in agg.items()
        if isinstance(v, (int, float)) and not k.endswith("_gbps")
    )
    for key in counter_keys:
        # Per the OpenMetrics spec the TYPE/HELP lines name the metric
        # FAMILY (no suffix); only the sample carries ``_total``. Strict
        # parsers (prometheus_client) reject a _total-suffixed family as
        # a name clash with its own sample.
        family = f"{_METRIC_PREFIX}_{key}"
        lines.append(f"# TYPE {family} counter")
        lines.append(f"# HELP {family} fleet-summed {key} for the last {op}")
        lines.append(f'{family}_total{{op="{_om_escape(op)}"}} {agg[key]:g}')
    gauge_rows = [
        ("fleet_wall_seconds", fleet.get("wall_s_max")),
        ("fleet_skew_seconds", fleet.get("skew_s")),
        ("fleet_write_gbps", agg.get("write_gbps")),
        ("fleet_read_gbps", agg.get("read_gbps")),
        ("world_size", doc.get("world_size")),
        ("reporting_ranks", fleet.get("reporting")),
    ]
    for key, value in gauge_rows:
        if value is None:
            continue
        name = f"{_METRIC_PREFIX}_{key}"
        lines.append(f"# TYPE {name} gauge")
        lines.append(f'{name}{{op="{_om_escape(op)}"}} {value:g}')
    if ranks:
        name = f"{_METRIC_PREFIX}_rank_wall_seconds"
        lines.append(f"# TYPE {name} gauge")
        for summary in ranks:
            lines.append(
                f'{name}{{op="{_om_escape(op)}",'
                f'rank="{summary.get("rank", 0)}"}} '
                f"{summary.get('wall_s', 0):g}"
            )
    lines.append("# EOF")
    return "\n".join(lines) + "\n"
