"""Telemetry exporters: Chrome/Perfetto trace JSON and the persisted
per-take summary.

Three consumers, three formats:

- :func:`chrome_trace` — the raw event list as Chrome's Trace Event
  format (``{"traceEvents": [...]}``), loadable in Perfetto /
  ``chrome://tracing``. Spans become ``ph: "X"`` complete events on the
  thread (tid) that ran them — executor lanes, the event loop, and the
  background commit thread render as separate tracks; counters/gauges
  become ``ph: "C"`` counter tracks.
- the persisted summary — ``Snapshot.take`` writes the cross-rank
  gathered per-op summaries plus the merged fleet view (aggregate.py)
  to :data:`TELEMETRY_SUMMARY_FNAME` next to ``.snapshot_metadata``, so
  ``python -m torchsnapshot_tpu stats <path>`` can answer "why was this
  take slow?" long after the process is gone.
- the plain-dict API — ``telemetry.last_summary()`` /
  ``telemetry.last_fleet()`` (core.py) for programmatic scraping
  (bench.py embeds them into its artifact).

Timestamps: events carry raw ``time.monotonic()`` seconds; the trace
exporter rebases to the earliest event and converts to the microseconds
Chrome expects, so ``ts`` is always >= 0 and mutually consistent within
one process's trace. Cross-rank traces are per-rank files — monotonic
clocks are not comparable across hosts, and Perfetto renders each file's
pid lane independently.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

from . import core

# Persisted next to .snapshot_metadata by rank 0 after the commit.
TELEMETRY_SUMMARY_FNAME = ".snapshot_telemetry"
# Per-rank Chrome traces, written by each telemetry-enabled rank.
TRACE_DIR = ".telemetry"


def trace_path_for_rank(rank: int) -> str:
    return f"{TRACE_DIR}/rank_{rank}.trace.json"


def chrome_trace(
    events: Optional[List[Dict[str, Any]]] = None,
    pid: int = 0,
    process_name: str = "torchsnapshot_tpu",
) -> Dict[str, Any]:
    """Convert recorded events to Chrome Trace Event format.

    ``events`` defaults to everything recorded in this process;
    ``pid`` labels the process lane (use the rank on distributed ops).
    """
    if events is None:
        events = core.events()
    trace: List[Dict[str, Any]] = [
        {
            "ph": "M",
            "name": "process_name",
            "pid": pid,
            "tid": 0,
            "args": {"name": f"{process_name} (rank {pid})"},
        }
    ]
    if not events:
        return {"traceEvents": trace, "displayTimeUnit": "ms"}
    t0 = min(e["ts"] for e in events)

    def us(seconds: float) -> int:
        return int(round((seconds - t0) * 1e6))

    for ev in sorted(events, key=lambda e: e["ts"]):
        ph = ev.get("ph")
        # tids are Python thread idents (large); compact them for the UI.
        tid = ev.get("tid", 0) % 100_000
        if ph == "span":
            out = {
                "ph": "X",
                "name": ev["name"],
                "cat": ev.get("cat", "pipeline"),
                "pid": pid,
                "tid": tid,
                "ts": us(ev["ts"]),
                "dur": max(0, int(round(ev["dur"] * 1e6))),
            }
            args = dict(ev.get("args") or {})
            if ev.get("parent") is not None:
                args["parent"] = ev["parent"]
            if args:
                out["args"] = args
            trace.append(out)
        elif ph == "counter":
            trace.append(
                {
                    "ph": "C",
                    "name": ev["name"],
                    "cat": ev.get("cat", "counter"),
                    "pid": pid,
                    "tid": 0,
                    "ts": us(ev["ts"]),
                    "args": {ev["name"]: ev.get("value", 0)},
                }
            )
        else:  # instant
            trace.append(
                {
                    "ph": "i",
                    "s": "t",
                    "name": ev["name"],
                    "cat": ev.get("cat", "event"),
                    "pid": pid,
                    "tid": tid,
                    "ts": us(ev["ts"]),
                    "args": ev.get("args") or {},
                }
            )
    return {"traceEvents": trace, "displayTimeUnit": "ms"}


def chrome_trace_json(
    events: Optional[List[Dict[str, Any]]] = None, pid: int = 0
) -> str:
    return json.dumps(chrome_trace(events, pid=pid))


def write_chrome_trace(
    path: str, events: Optional[List[Dict[str, Any]]] = None, pid: int = 0
) -> None:
    """Write a Chrome trace of ``events`` to a local file."""
    with open(path, "w") as f:
        f.write(chrome_trace_json(events, pid=pid))


# ------------------------------------------------------------ summary file


def build_summary_document(
    op: str,
    world_size: int,
    rank_summaries: List[Optional[Dict[str, Any]]],
    fleet: Optional[Dict[str, Any]],
) -> Dict[str, Any]:
    return {
        "version": 1,
        "op": op,
        "world_size": world_size,
        "ranks": rank_summaries,
        "fleet": fleet,
    }


def fmt_bytes(n: Optional[float]) -> str:
    """THE byte formatter for operator-facing output (cli.py info/ls and
    the stats rendering below share it, so sizes read identically across
    commands)."""
    if n is None:
        return "?"
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{n:g}B"
        n /= 1024
    return f"{n}B"


_fmt_bytes = fmt_bytes


def render_summary_document(doc: Dict[str, Any], verbose: bool = False) -> str:
    """Human-readable rendering of a persisted summary document (the
    ``stats`` CLI command's output)."""
    lines: List[str] = []
    lines.append(f"op:          {doc.get('op')}")
    lines.append(f"world_size:  {doc.get('world_size')}")
    fleet = doc.get("fleet")
    ranks = [r for r in (doc.get("ranks") or []) if r]
    if fleet:
        lines.append(f"fleet wall:  {fleet.get('wall_s_max', 0):.3f}s "
                     f"(slowest rank {fleet.get('slowest_rank')}, "
                     f"skew {fleet.get('skew_s', 0):.3f}s)")
        agg = fleet.get("aggregate") or {}
        if agg.get("bytes_written"):
            lines.append(
                f"written:     {_fmt_bytes(agg['bytes_written'])} aggregate"
                + (
                    f" ({agg['write_gbps']:.2f} GB/s fleet)"
                    if agg.get("write_gbps")
                    else ""
                )
            )
        if agg.get("bytes_read"):
            lines.append(f"read:        {_fmt_bytes(agg['bytes_read'])} aggregate")
        if agg.get("bytes_deduped"):
            lines.append(f"deduped:     {_fmt_bytes(agg['bytes_deduped'])} skipped")
        if agg.get("bytes_to_peers"):
            lines.append(
                f"peer bytes:  {_fmt_bytes(agg['bytes_to_peers'])} redistributed"
            )
        # Fleet seeding tier (distrib.py): the seed-vs-storage byte mix
        # of a fleet restore — ``read`` above is what actually hit
        # storage, this is what arrived from peers instead.
        if agg.get("bytes_from_seeders"):
            lines.append(
                f"seeded:      {_fmt_bytes(agg['bytes_from_seeders'])} "
                "from peers"
                + (
                    f" ({agg['seed_cache_hits']:.0f} cache hit(s))"
                    if agg.get("seed_cache_hits")
                    else ""
                )
            )
        if agg.get("retry_attempts"):
            lines.append(f"retries:     {agg['retry_attempts']:.0f} attempts")
        # Degradation counters: zero is the healthy (and silent) case;
        # any non-zero value is the headline of a post-mortem.
        degraded = [
            f"{label}={agg[key]:.0f}"
            for key, label in (
                ("store_failovers", "store"),
                ("mirror_failovers", "mirror"),
                ("fanout_fallbacks", "fanout"),
            )
            if agg.get(key)
        ]
        if degraded:
            lines.append(f"failovers:   {', '.join(degraded)}")
        if agg.get("lease_renewals"):
            lines.append(
                f"lease:       {agg['lease_renewals']:.0f} renewal round(s)"
            )
        # Delta-journal column: appended epochs on the write side, replays
        # and torn-tail truncations on the restore side (journal.py).
        journal_bits = []
        if agg.get("journal_appends"):
            journal_bits.append(
                f"{agg['journal_appends']:.0f} append(s) "
                f"({_fmt_bytes(agg.get('journal_bytes', 0))})"
            )
        if agg.get("journal_replays"):
            journal_bits.append(f"{agg['journal_replays']:.0f} replay(s)")
        if agg.get("journal_truncations"):
            journal_bits.append(
                f"{agg['journal_truncations']:.0f} torn tail(s) truncated"
            )
        if agg.get("epoch_push_bytes"):
            journal_bits.append(
                f"{_fmt_bytes(agg['epoch_push_bytes'])} pushed to replicas"
            )
        if journal_bits:
            lines.append(f"journal:     {', '.join(journal_bits)}")
    for summary in ranks:
        lines.append("")
        lines.append(
            f"rank {summary.get('rank')}: {summary.get('op')} "
            f"{summary.get('wall_s', 0):.3f}s"
        )
        phases = summary.get("phases") or {}
        if phases:
            lines.append(
                "  phases:   "
                + ", ".join(f"{n}={dt:.3f}s" for n, dt in phases.items())
            )
        counters = summary.get("counters") or {}
        for key in sorted(counters):
            val = counters[key]
            shown = _fmt_bytes(val) if key.startswith("bytes_") else f"{val:g}"
            lines.append(f"  {key}: {shown}")
        spans = summary.get("spans") or {}
        order = sorted(
            spans.items(), key=lambda kv: kv[1].get("total_s", 0), reverse=True
        )
        if not verbose:
            order = order[:8]
        for name, agg in order:
            lines.append(
                f"  span {name}: x{agg['count']} total {agg['total_s']:.3f}s "
                f"max {agg['max_s']:.3f}s"
            )
        if verbose and summary.get("rates"):
            lines.append(f"  rates: {summary['rates']}")
        if verbose:
            for row in summary.get("governor") or []:
                args = ", ".join(
                    f"{k}={v}" for k, v in row.items() if k != "site"
                )
                lines.append(f"  governor[{row.get('site', '?')}]: {args}")
        if summary.get("dropped_events"):
            lines.append(f"  dropped_events: {summary['dropped_events']}")
    hist = (fleet or {}).get("histograms") or {}
    if hist:
        lines.append("")
        lines.append("latency histograms (fleet, bucket-wise sums):")
        lines.extend(render_histogram_lines(hist))
    return "\n".join(lines)


def render_histogram_lines(
    histograms: Dict[str, Dict[str, Dict[str, Any]]]
) -> List[str]:
    """Human-readable one-liners for a histogram table (shared by the
    ``stats`` fleet rendering and the ``explain`` CLI): approximate
    p50/p95/max from the log2 buckets, labeled by family and key."""
    from .core import HISTOGRAM_BOUNDS, histogram_quantile

    lines: List[str] = []
    for name in sorted(histograms):
        for key in sorted(histograms[name]):
            hist = histograms[name][key]
            count = hist.get("count") or 0
            if not count:
                continue
            p50 = histogram_quantile(hist, 0.5)
            p95 = histogram_quantile(hist, 0.95)
            counts = hist.get("counts") or []
            top = None
            for i in range(len(counts) - 1, -1, -1):
                if counts[i]:
                    top = (
                        HISTOGRAM_BOUNDS[i]
                        if i < len(HISTOGRAM_BOUNDS)
                        else float("inf")
                    )
                    break
            label = f"{name}[{key}]" if key else name
            lines.append(
                f"  {label}: n={count} p50<={_fmt_s(p50)} "
                f"p95<={_fmt_s(p95)} max<={_fmt_s(top)} "
                f"sum={_fmt_s(hist.get('sum'))}"
            )
    return lines


def _fmt_s(seconds: Optional[float]) -> str:
    if seconds is None:
        return "?"
    if seconds == float("inf"):
        return "inf"
    if seconds >= 1.0:
        return f"{seconds:.3g}s"
    if seconds >= 1e-3:
        return f"{seconds * 1e3:.3g}ms"
    return f"{seconds * 1e6:.3g}us"


# -------------------------------------------------------------- openmetrics

_METRIC_PREFIX = "torchsnapshot_tpu"


def _om_escape(value: str) -> str:
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def om_family_name(name: str) -> str:
    """Prefixed, spec-legal metric family name: every character outside
    ``[a-zA-Z0-9_:]`` becomes ``_`` (histogram names like
    ``write.sub_chunk_s`` carry dots)."""
    safe = "".join(
        c if (c.isascii() and (c.isalnum() or c in "_:")) else "_"
        for c in name
    )
    return f"{_METRIC_PREFIX}_{safe}"


def _om_label_str(labels: Dict[str, Any]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{_om_escape(v)}"' for k, v in labels.items() if v is not None
    )
    return "{" + inner + "}" if inner else ""


def om_histogram_lines(
    name: str,
    by_key: Dict[str, Dict[str, Any]],
    extra_labels: Optional[Dict[str, Any]] = None,
    help_text: Optional[str] = None,
) -> List[str]:
    """One OpenMetrics histogram family from a bus histogram snapshot
    (``{key: {"counts": [...], "count": n, "sum": s}}``): cumulative
    ``_bucket`` samples over the fixed log2 ladder, ``+Inf`` equal to
    ``_count``, plus ``_count``/``_sum`` — the shape strict parsers
    (prometheus_client) demand. Shared by ``stats --openmetrics`` and
    the live /metrics exporter so the two can never drift."""
    from .core import HISTOGRAM_BOUNDS

    family = om_family_name(name)
    lines = [f"# TYPE {family} histogram"]
    if help_text:
        lines.append(f"# HELP {family} {_om_escape(help_text)}")
    for key in sorted(by_key):
        hist = by_key[key]
        labels = dict(extra_labels or {})
        if key:
            labels["key"] = key
        cumulative = 0
        counts = hist.get("counts") or []
        for i, bound in enumerate(HISTOGRAM_BOUNDS):
            cumulative += counts[i] if i < len(counts) else 0
            bl = dict(labels)
            bl["le"] = repr(bound)
            lines.append(f"{family}_bucket{_om_label_str(bl)} {cumulative}")
        bl = dict(labels)
        bl["le"] = "+Inf"
        total = hist.get("count") or 0
        lines.append(f"{family}_bucket{_om_label_str(bl)} {total}")
        lines.append(f"{family}_count{_om_label_str(labels)} {total}")
        lines.append(
            f"{family}_sum{_om_label_str(labels)} {hist.get('sum') or 0:g}"
        )
    return lines


def render_openmetrics(doc: Dict[str, Any]) -> str:
    """Render a persisted summary document in OpenMetrics text format
    (``stats --openmetrics``), so a scrape sidecar can lift a take's
    counters into Prometheus without parsing our JSON.

    Counter families end in ``_total`` per the spec; per-rank samples
    carry a ``rank`` label; the exposition ends with ``# EOF``."""
    lines: List[str] = []
    op = doc.get("op") or "unknown"
    fleet = doc.get("fleet") or {}
    agg = fleet.get("aggregate") or {}
    ranks = [r for r in (doc.get("ranks") or []) if isinstance(r, dict)]

    counter_keys = sorted(
        k for k, v in agg.items()
        if isinstance(v, (int, float)) and not k.endswith("_gbps")
    )
    for key in counter_keys:
        # Per the OpenMetrics spec the TYPE/HELP lines name the metric
        # FAMILY (no suffix); only the sample carries ``_total``. Strict
        # parsers (prometheus_client) reject a _total-suffixed family as
        # a name clash with its own sample.
        family = f"{_METRIC_PREFIX}_{key}"
        lines.append(f"# TYPE {family} counter")
        lines.append(f"# HELP {family} fleet-summed {key} for the last {op}")
        lines.append(f'{family}_total{{op="{_om_escape(op)}"}} {agg[key]:g}')
    gauge_rows = [
        ("fleet_wall_seconds", fleet.get("wall_s_max")),
        ("fleet_skew_seconds", fleet.get("skew_s")),
        ("fleet_write_gbps", agg.get("write_gbps")),
        ("fleet_read_gbps", agg.get("read_gbps")),
        ("world_size", doc.get("world_size")),
        ("reporting_ranks", fleet.get("reporting")),
    ]
    for key, value in gauge_rows:
        if value is None:
            continue
        name = f"{_METRIC_PREFIX}_{key}"
        lines.append(f"# TYPE {name} gauge")
        lines.append(f'{name}{{op="{_om_escape(op)}"}} {value:g}')
    if ranks:
        name = f"{_METRIC_PREFIX}_rank_wall_seconds"
        lines.append(f"# TYPE {name} gauge")
        for summary in ranks:
            lines.append(
                f'{name}{{op="{_om_escape(op)}",'
                f'rank="{summary.get("rank", 0)}"}} '
                f"{summary.get('wall_s', 0):g}"
            )
    # Fleet latency histograms (bucket-wise sums across ranks) as real
    # OpenMetrics histogram families — the distribution view the scalar
    # counters above cannot carry.
    for hname, by_key in sorted((fleet.get("histograms") or {}).items()):
        lines.extend(
            om_histogram_lines(hname, by_key, extra_labels={"op": op})
        )
    lines.append("# EOF")
    return "\n".join(lines) + "\n"
