"""Checkpoint telemetry: one observability subsystem for the pipeline.

Enable with ``TORCHSNAPSHOT_TPU_TELEMETRY=1``. See core.py for the event
bus (spans/counters/gauges/rates), export.py for the Chrome-trace and
persisted-summary formats, aggregate.py for the cross-rank fleet merge,
and docs/source/telemetry.rst for the operator guide.

Typical programmatic use::

    from torchsnapshot_tpu import telemetry
    telemetry.set_enabled(True)
    Snapshot.take(path, app_state)
    summary = telemetry.last_summary()       # plain dict
    telemetry.write_chrome_trace("take.json")  # load in Perfetto
"""

from .core import (  # noqa: F401
    HISTOGRAM_BOUNDS,
    TELEMETRY_ENV_VAR,
    OpRecorder,
    Span,
    annotate_next_op,
    begin_op,
    counter_add,
    counters,
    dropped_events,
    enabled,
    event,
    events,
    gauge_set,
    gauges,
    histogram_observe,
    histogram_quantile,
    histograms,
    last_attribution,
    last_fleet,
    last_summary,
    monotonic,
    record_rate,
    refresh_from_env,
    register_rate_listener,
    reset,
    set_enabled,
    set_last_attribution,
    set_last_fleet,
    span,
)
from .export import (  # noqa: F401
    TELEMETRY_SUMMARY_FNAME,
    TRACE_DIR,
    build_summary_document,
    chrome_trace,
    chrome_trace_json,
    fmt_bytes,
    render_openmetrics,
    render_summary_document,
    trace_path_for_rank,
    write_chrome_trace,
)
from .aggregate import merge_histograms, merge_summaries  # noqa: F401
# The always-on observability planes (ISSUE 7): the flight recorder
# (bounded ring + abort dumps + blackbox merge, event registry in
# taxonomy.py), the live health plane (heartbeats over the coordination
# store), and the per-root checkpoint history (trend/regression
# detection). Imported as submodules — their APIs are namespaced
# (flightrec.record, health.update, ...), matching how the pipeline
# calls them. NOTE the registry module is named ``taxonomy`` (not
# ``events``) so it can never shadow the ``events()`` scrape function
# exported from core above.
from . import flightrec, health, history, taxonomy  # noqa: F401, E402
# The stall-forensics plane (ISSUE 13): an always-on hang watchdog that
# samples thread stacks, self-triggers on overdue collectives / slow
# storage ops / frozen progress, answers remote dump requests from
# `watch --dump`, and feeds the WEDGE finding class into `blackbox`.
# Imported after flightrec/health — it consumes both.
from . import forensics  # noqa: F401, E402
# The performance-attribution plane (ISSUE 8): critpath reconstructs the
# cross-rank critical path of a take/restore and names the binding
# resource (the `explain` CLI's engine); promexp serves the live
# OpenMetrics endpoint (TORCHSNAPSHOT_TPU_METRICS_PORT). Namespaced like
# the other planes (critpath.build_attribution, promexp.maybe_start).
from . import critpath, promexp  # noqa: F401, E402


def record_election(**fields) -> None:
    """Record one IOGovernor election on BOTH planes: the always-on
    flight recorder (so ``blackbox`` shows what the governor chose
    before an abort) and, bus permitting, a ``cat="governor"`` instant
    the OpRecorder folds into ``summary["governor"]`` (what ``explain
    -v``/``stats -v`` render and ``.snapshot_critpath`` persists).
    One helper so an election site can never wire half the pair."""
    flightrec.record("governor.elect", **fields)
    event("governor_elect", cat="governor", **fields)


def record_learn(**fields) -> None:
    """Record one autotuner verdict (scheduler.IOGovernor.
    observe_verdict) on the same two planes as elections: the flight
    recorder (``governor.learn`` — the perturb/score/revert trail in
    ``blackbox``) and, bus permitting, a ``cat="governor"`` instant that
    rides ``summary["governor"]`` into ``explain -v``."""
    flightrec.record("governor.learn", **fields)
    event("governor_learn", cat="governor", **fields)
