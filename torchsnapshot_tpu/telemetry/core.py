"""Process-local telemetry event bus: spans, counters, gauges, rates.

The ONE measurement mechanism for the save/restore pipeline. Before this
subsystem, instrumentation was siloed: ``scheduler._ProgressReporter`` /
``_Throughput`` only produced log lines, ``IOGovernor`` kept private EWMA
tables, ``rss_profiler`` sampled into caller-supplied lists, and the
cloud-retry machinery swallowed attempt counts entirely. Every one of
those now reports INTO this bus; the governor consumes rates FROM it
(see :func:`register_rate_listener`); exporters (export.py) turn the
recorded events into a Chrome/Perfetto trace, a compact per-op summary
persisted next to ``.snapshot_metadata``, or a plain dict.

Design constraints, in priority order:

1. **Near-zero overhead when disabled.** The pipeline calls ``span()`` /
   ``counter_add()`` on per-sub-chunk hot paths; with telemetry off
   (the default) each call is one module-global flag check returning a
   shared no-op singleton — no allocation, no lock, no clock read.
   Enablement: ``TORCHSNAPSHOT_TPU_TELEMETRY=1`` (read once at import;
   :func:`set_enabled` flips it programmatically for tests/benchmarks).
2. **Thread-safety.** One snapshot op spans the caller thread, the
   asyncio event-loop thread, executor worker threads, and (async takes)
   a background commit thread. Event appends take one lock; span
   parenting is thread-local (a span started on an executor thread is a
   root of that thread's lane — exactly how Chrome traces model tids).
3. **Monotonic time only.** :data:`monotonic` is THE blessed clock for
   pipeline timing; a lint (scripts/check_timing_lint.py) forbids raw
   ``time.monotonic()``/``perf_counter()`` timing elsewhere in the
   package so measurements can never silently fork off the bus again.
4. **Bounded memory.** Events are capped (``TORCHSNAPSHOT_TPU_TELEMETRY_
   MAX_EVENTS``, default 200k); overflow drops-and-counts rather than
   growing without bound on a pathological op.
"""

from __future__ import annotations

import contextvars
import math
import os
import threading
import time
import weakref
from typing import Any, Callable, Dict, List, Optional, Tuple

TELEMETRY_ENV_VAR = "TORCHSNAPSHOT_TPU_TELEMETRY"
MAX_EVENTS_ENV_VAR = "TORCHSNAPSHOT_TPU_TELEMETRY_MAX_EVENTS"
_DEFAULT_MAX_EVENTS = 200_000

# The blessed monotonic clock for ALL pipeline timing (spans, rates,
# throughput meters). Deadline/timeout bookkeeping (dist_store, the test
# launcher) may keep raw time.monotonic; measurement may not.
monotonic = time.monotonic


def _env_enabled() -> bool:
    raw = os.environ.get(TELEMETRY_ENV_VAR, "").strip().lower()
    return raw in ("1", "on", "true", "yes", "always")


_enabled: bool = _env_enabled()


def enabled() -> bool:
    return _enabled


def set_enabled(value: bool) -> None:
    """Programmatic override of the env gate (tests, bench trials)."""
    global _enabled
    _enabled = bool(value)


def refresh_from_env() -> bool:
    """Re-read ``TORCHSNAPSHOT_TPU_TELEMETRY`` and the event cap
    (subprocess workers that mutate os.environ after import call this)."""
    global _max_events
    _max_events = _read_max_events()
    set_enabled(_env_enabled())
    return _enabled


# ------------------------------------------------------------------ events

_lock = threading.Lock()
_events: List[Dict[str, Any]] = []
_counters: Dict[str, float] = {}
_gauges: Dict[str, float] = {}
_dropped = 0
_next_id = 0
# Per-context (per-thread AND per-asyncio-task: create_task snapshots the
# context) stack of open span ids. An immutable tuple + token reset keeps
# LIFO correct even when concurrent coroutines interleave span enter/exit
# on one event-loop thread — a plain thread-local list would leak there.
_span_stack: "contextvars.ContextVar[Tuple[int, ...]]" = contextvars.ContextVar(
    "tsnap_telemetry_spans", default=()
)


def _read_max_events() -> int:
    raw = os.environ.get(MAX_EVENTS_ENV_VAR, "").strip()
    try:
        return max(1, int(raw)) if raw else _DEFAULT_MAX_EVENTS
    except ValueError:
        return _DEFAULT_MAX_EVENTS


# Resolved ONCE (and on refresh_from_env): the cap is consulted on every
# event append under the global lock — re-parsing the env var there would
# serialize all producer threads behind redundant string work.
_max_events = _read_max_events()


def _append(ev: Dict[str, Any]) -> None:
    global _dropped, _next_id
    with _lock:
        if len(_events) >= _max_events:
            _dropped += 1
            return
        _next_id += 1
        ev["id"] = _next_id
        _events.append(ev)


class _NullSpan:
    """Shared no-op span: what ``span()`` returns when telemetry is off.
    A singleton so the disabled hot path allocates nothing."""

    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc: object) -> None:
        return None

    def set(self, **args: Any) -> None:
        return None


_NULL_SPAN = _NullSpan()


class Span:
    """A timed region. Use as a context manager::

        with telemetry.span("stage", bytes=n):
            ...

    Nesting is thread-local: spans entered on the same thread while this
    one is open become its children (``parent`` in the event record).
    The event is appended at exit with monotonic ``ts``/``dur`` seconds.
    """

    __slots__ = ("name", "cat", "args", "_ts", "_parent", "_tid", "_id", "_tok")

    def __init__(self, name: str, cat: str, args: Optional[Dict[str, Any]]):
        self.name = name
        self.cat = cat
        self.args = args

    def set(self, **args: Any) -> None:
        """Attach/overwrite args after entry (e.g. bytes known at exit)."""
        if self.args is None:
            self.args = {}
        self.args.update(args)

    def __enter__(self) -> "Span":
        global _next_id
        stack = _span_stack.get()
        self._parent = stack[-1] if stack else None
        self._tid = threading.get_ident()
        # The span's event id is allocated at ENTRY so children opened
        # while this span is live can record their real parent id (the
        # event itself is appended at exit, carrying this id; the events
        # list is ordered by completion, ids by start).
        with _lock:
            _next_id += 1
            self._id = _next_id
        self._tok = _span_stack.set(stack + (self._id,))
        self._ts = monotonic()
        return self

    def __exit__(self, *exc: object) -> None:
        dur = monotonic() - self._ts
        try:
            _span_stack.reset(self._tok)
        except ValueError:  # pragma: no cover - exit in a foreign context
            pass
        ev = {
            "ph": "span",
            "id": self._id,
            "name": self.name,
            "cat": self.cat,
            "ts": self._ts,
            "dur": dur,
            "tid": self._tid,
            "parent": self._parent,
        }
        if self.args:
            ev["args"] = self.args
        global _dropped
        with _lock:
            if len(_events) >= _max_events:
                _dropped += 1
                return
            _events.append(ev)


def span(name: str, cat: str = "pipeline", **args: Any):
    """A timed nested region, or the shared no-op when disabled."""
    if not _enabled:
        return _NULL_SPAN
    return Span(name, cat, args or None)


def event(name: str, cat: str = "event", **args: Any) -> None:
    """An instant (zero-duration) event."""
    if not _enabled:
        return
    _append(
        {
            "ph": "instant",
            "name": name,
            "cat": cat,
            "ts": monotonic(),
            "tid": threading.get_ident(),
            "args": args or None,
        }
    )


def _sample_locked(name: str, cat: str, value: float) -> None:
    """Append a counter/gauge sample. CALLER HOLDS _lock: the sample must
    land in the same critical section as the value mutation, or two
    concurrent adders can record totals out of order and a monotone
    Perfetto counter track would dip backwards."""
    global _dropped, _next_id
    if len(_events) >= _max_events:
        _dropped += 1
        return
    _next_id += 1
    _events.append(
        {
            "ph": "counter",
            "id": _next_id,
            "name": name,
            "cat": cat,
            "ts": monotonic(),
            "tid": threading.get_ident(),
            "value": value,
        }
    )


def counter_add(name: str, value: float = 1) -> None:
    """Accumulate a monotone counter (bytes written, retry attempts...).

    A trace sample event is recorded in the same critical section so
    Perfetto can render the counter track over time, in order."""
    if not _enabled:
        return
    with _lock:
        total = _counters.get(name, 0) + value
        _counters[name] = total
        _sample_locked(name, "counter", total)


def gauge_set(name: str, value: float) -> None:
    """Set a point-in-time gauge (queue depth, RSS delta, budget free)."""
    if not _enabled:
        return
    with _lock:
        _gauges[name] = value
        _sample_locked(name, "gauge", value)


# -------------------------------------------------------------- histograms

# Fixed log2 bucket ladder for every latency histogram: upper bounds
# 2^-20 s (~1 µs) .. 2^7 s (128 s), one bucket per power of two, plus the
# implicit +Inf overflow. Fixed (not per-instrument) so fleet merges,
# the OpenMetrics exposition, and cross-take comparisons are always
# bucket-compatible — adaptive buckets cannot be summed across ranks.
_HIST_LOW_EXP = -20
_HIST_HIGH_EXP = 7
HISTOGRAM_BOUNDS: Tuple[float, ...] = tuple(
    2.0 ** k for k in range(_HIST_LOW_EXP, _HIST_HIGH_EXP + 1)
)
_N_BUCKETS = len(HISTOGRAM_BOUNDS) + 1  # + the +Inf overflow bucket


def _bucket_index(seconds: float) -> int:
    """Index of the smallest bound >= ``seconds`` (log2 ladder), or the
    overflow slot. ``math.frexp`` gives seconds = m * 2^e with m in
    [0.5, 1): seconds <= 2^e always, and seconds <= 2^(e-1) exactly when
    m == 0.5 — two float ops, no log() call on the hot path."""
    if seconds <= HISTOGRAM_BOUNDS[0]:
        return 0
    m, e = math.frexp(seconds)
    idx = e - _HIST_LOW_EXP - (1 if m == 0.5 else 0)
    return idx if idx < _N_BUCKETS else _N_BUCKETS - 1


class _Histogram:
    __slots__ = ("counts", "count", "sum")

    def __init__(self) -> None:
        self.counts = [0] * _N_BUCKETS
        self.count = 0
        self.sum = 0.0

    def as_dict(self) -> Dict[str, Any]:
        return {
            "counts": list(self.counts),
            "count": self.count,
            "sum": round(self.sum, 6),
        }


# {name: {key or "": _Histogram}} — ``name`` must be registered in
# taxonomy.HISTOGRAM_NAMES (lint-pinned, like the flight-event registry);
# ``key`` is the free-form label (storage plugin class, collective verb).
_histograms: Dict[str, Dict[str, _Histogram]] = {}


def histogram_observe(name: str, seconds: float, key: Optional[str] = None) -> None:
    """Record one latency observation into the fixed log2-bucket
    histogram ``name`` (labeled by ``key``). One flag check when
    telemetry is disabled; enabled cost is the bucket math plus one
    uncontended lock round — cheap enough for per-sub-chunk call sites,
    and unlike counters it records NO per-observation trace event.

    ``name`` must be a literal registered in
    ``taxonomy.HISTOGRAM_NAMES`` (scripts/check_event_taxonomy.py
    enforces it)."""
    if not _enabled:
        return
    idx = _bucket_index(seconds)
    with _lock:
        by_key = _histograms.get(name)
        if by_key is None:
            by_key = _histograms[name] = {}
        hist = by_key.get(key or "")
        if hist is None:
            hist = by_key[key or ""] = _Histogram()
        hist.counts[idx] += 1
        hist.count += 1
        hist.sum += seconds


def histograms() -> Dict[str, Dict[str, Dict[str, Any]]]:
    """A JSON-able snapshot of every histogram:
    ``{name: {key: {"counts": [...], "count": n, "sum": s}}}`` with
    counts parallel to :data:`HISTOGRAM_BOUNDS` plus a final +Inf slot."""
    with _lock:
        return {
            name: {key: h.as_dict() for key, h in by_key.items()}
            for name, by_key in _histograms.items()
        }


def histogram_quantile(hist: Dict[str, Any], q: float) -> Optional[float]:
    """Approximate quantile from a histogram dict (bucket upper bound at
    rank ceil(q*count)); None when empty. Good to a factor of 2 by
    construction — the resolution the log2 ladder buys."""
    count = hist.get("count") or 0
    if count <= 0:
        return None
    target = max(1, math.ceil(q * count))
    running = 0
    for i, n in enumerate(hist.get("counts") or []):
        running += n
        if running >= target:
            return (
                HISTOGRAM_BOUNDS[i]
                if i < len(HISTOGRAM_BOUNDS)
                else HISTOGRAM_BOUNDS[-1] * 2
            )
    return HISTOGRAM_BOUNDS[-1] * 2


def _histograms_delta(
    since: Dict[str, Dict[str, Dict[str, Any]]]
) -> Dict[str, Dict[str, Dict[str, Any]]]:
    """Histograms accumulated since a prior :func:`histograms` snapshot
    (bucket-wise subtraction; empty deltas elided) — what an OpRecorder
    reports so one op's summary never inherits the previous op's tail."""
    out: Dict[str, Dict[str, Dict[str, Any]]] = {}
    for name, by_key in histograms().items():
        for key, hist in by_key.items():
            base = (since.get(name) or {}).get(key)
            if base is not None:
                delta_count = hist["count"] - base["count"]
                if delta_count <= 0:
                    continue
                counts = [
                    n - b for n, b in zip(hist["counts"], base["counts"])
                ]
                hist = {
                    "counts": counts,
                    "count": delta_count,
                    "sum": round(hist["sum"] - base["sum"], 6),
                }
            elif hist["count"] <= 0:
                continue
            out.setdefault(name, {})[key] = hist
    return out


# ------------------------------------------------------------------- rates

# Rate observations (achieved storage/hash bandwidth) flow THROUGH the bus
# to registered listeners — the I/O governor registers itself at
# scheduler import, keeping its EWMA tables (and measured_rates() view)
# fed without the bus importing the scheduler. Listeners run regardless
# of the enabled flag: adaptive tuning must keep working with telemetry
# off; only the recorded event is gated.
_rate_listeners: List[Callable[[str, Optional[str], int, float], None]] = []


def register_rate_listener(
    fn: Callable[[str, Optional[str], int, float], None]
) -> None:
    if fn not in _rate_listeners:
        _rate_listeners.append(fn)


def record_rate(kind: str, key: Optional[str], nbytes: int, seconds: float) -> None:
    """Publish an achieved rate: ``kind`` in {"write","read","hash"},
    ``key`` the storage-plugin class name (None for hash)."""
    for fn in _rate_listeners:
        try:
            fn(kind, key, nbytes, seconds)
        except Exception:  # pragma: no cover - listeners must not break I/O
            pass
    if not _enabled:
        return
    _append(
        {
            "ph": "instant",
            "name": f"rate:{kind}",
            "cat": "rate",
            "ts": monotonic(),
            "tid": threading.get_ident(),
            "args": {
                "kind": kind,
                "key": key,
                "nbytes": nbytes,
                "seconds": seconds,
                "bps": (nbytes / seconds) if seconds > 0 else None,
            },
        }
    )


# ---------------------------------------------------------------- scraping


def events(since_id: int = 0) -> List[Dict[str, Any]]:
    """A snapshot (shallow copies) of recorded events with id > since_id."""
    with _lock:
        return [dict(e) for e in _events if e.get("id", 0) > since_id]


def counters() -> Dict[str, float]:
    with _lock:
        return dict(_counters)


def gauges() -> Dict[str, float]:
    with _lock:
        return dict(_gauges)


def dropped_events() -> int:
    return _dropped


def reset() -> None:
    """Drop all recorded state (tests; long-lived processes between ops)."""
    global _dropped
    with _lock:
        _events.clear()
        _counters.clear()
        _gauges.clear()
        _histograms.clear()
        _dropped = 0


# --------------------------------------------------------------- op scopes


# Recorders that have begun but not finished. begin_op trims the event
# buffer down to what the oldest still-live recorder can reference, so a
# long-lived training process saving every N steps never fills the event
# cap and goes dark — the fate of every unbounded-buffer profiler. A
# WeakSet so a recorder abandoned by a failed async take (finish never
# called) stops pinning history once collected.
_live_recorders: "weakref.WeakSet" = weakref.WeakSet()


class OpRecorder:
    """Brackets one logical operation (a take, a restore) so its summary
    covers only events/counter deltas recorded while it was open.

    Created by :func:`begin_op` (always — even disabled, so callers don't
    branch); ``finish()`` returns the per-op summary dict, or None when
    telemetry was disabled for the whole op."""

    def __init__(self, op: str, rank: int) -> None:
        self.op = op
        self.rank = rank
        self._enabled_at_start = _enabled
        self._t0 = monotonic()
        self._final_events: Optional[List[Dict[str, Any]]] = None
        with _lock:
            # Trim events no live op can still export: keeps the buffer
            # bounded by ops, not by process lifetime.
            marks = [r._event_mark for r in _live_recorders]
            cutoff = min(marks, default=_next_id)
            if _events and cutoff > 0:
                _events[:] = [e for e in _events if e["id"] > cutoff]
            self._event_mark = _next_id
            self._counters0 = dict(_counters)
            self._hist0 = {
                name: {key: h.as_dict() for key, h in by_key.items()}
                for name, by_key in _histograms.items()
            }
            self._dropped0 = _dropped
            self._annotations = dict(_pending_annotations)
            _pending_annotations.clear()
        _live_recorders.add(self)

    def finish(
        self, extra: Optional[Dict[str, Any]] = None
    ) -> Optional[Dict[str, Any]]:
        # Capture the op's events BEFORE leaving _live_recorders: the
        # moment this recorder stops being live, a concurrent begin_op
        # (next take starting while the async commit thread exports) may
        # trim them from the buffer. The cached list also serves the
        # trace export that runs after finish().
        evs = self.events()
        self._final_events = evs
        _live_recorders.discard(self)
        if not (self._enabled_at_start or _enabled):
            return None
        wall = monotonic() - self._t0
        spans: Dict[str, Dict[str, float]] = {}
        op_gauges: Dict[str, float] = {}
        elections: List[Dict[str, Any]] = []
        for ev in evs:
            if ev["ph"] == "counter" and ev.get("cat") == "gauge":
                # Only gauges SET during this op: a restore must not
                # inherit the previous take's final queue depths.
                op_gauges[ev["name"]] = ev.get("value", 0)
            if ev["ph"] == "instant" and ev.get("cat") == "governor":
                # IOGovernor elections recorded during this op ride the
                # persisted summary, so `explain` can show what the
                # governor chose and why (the flight recorder carries the
                # always-on copy for abort dumps).
                elections.append(dict(ev.get("args") or {}))
            if ev["ph"] != "span":
                continue
            agg = spans.setdefault(
                ev["name"], {"count": 0, "total_s": 0.0, "max_s": 0.0}
            )
            agg["count"] += 1
            agg["total_s"] += ev["dur"]
            agg["max_s"] = max(agg["max_s"], ev["dur"])
        for agg in spans.values():
            agg["total_s"] = round(agg["total_s"], 6)
            agg["max_s"] = round(agg["max_s"], 6)
        now = counters()
        deltas = {
            k: v - self._counters0.get(k, 0)
            for k, v in now.items()
            if v != self._counters0.get(k, 0)
        }
        summary: Dict[str, Any] = {
            "op": self.op,
            "rank": self.rank,
            "wall_s": round(wall, 6),
            "spans": spans,
            "counters": deltas,
            "gauges": op_gauges,
            "dropped_events": _dropped - self._dropped0,
        }
        hist = _histograms_delta(self._hist0)
        if hist:
            summary["histograms"] = hist
        if elections:
            summary["governor"] = elections
        if self._annotations:
            summary["annotations"] = self._annotations
        if extra:
            summary.update(extra)
        _set_last_summary(summary)
        return summary

    def abandon(self) -> None:
        """Release this recorder WITHOUT producing a summary (abort
        paths). A recorder abandoned merely by dropping the reference
        stops pinning the event buffer only when the cyclic GC collects
        it — and an abort's exception/traceback cycle can keep the frame
        (and so the recorder) alive arbitrarily long, during which every
        later op's begin_op trims nothing and the buffer runs into the
        cap. Explicit release closes that window; idempotent, and safe
        to call after finish()."""
        _live_recorders.discard(self)

    def events(self) -> List[Dict[str, Any]]:
        """Events recorded since this op began (for per-op trace export).

        Counter samples are rebased to the op's start so an exported
        trace's counter tracks read 0 -> bytes-this-op, not the
        process-cumulative totals of every previous op. After finish()
        the capture is served from the recorder's own cache (the live
        buffer may have been trimmed by the next op by then)."""
        if self._final_events is not None:
            return [dict(e) for e in self._final_events]
        evs = events(since_id=self._event_mark)
        for ev in evs:
            if ev.get("ph") == "counter" and ev.get("cat") == "counter":
                base = self._counters0.get(ev["name"], 0)
                if base:
                    ev["value"] = ev["value"] - base
        return evs


def begin_op(op: str, rank: int = 0) -> OpRecorder:
    return OpRecorder(op, rank)


# Annotations queued for the NEXT op to begin: layers that sit ABOVE the
# operation call (CheckpointManager knows the step/mode before invoking
# Snapshot.take, which creates the recorder) attach context here and the
# recorder folds it into the persisted summary.
_pending_annotations: Dict[str, Any] = {}


def annotate_next_op(**args: Any) -> None:
    """Attach key/values to the summary of the next take/restore to
    begin (e.g. ``step=1000, mode="async"`` from the manager)."""
    with _lock:
        _pending_annotations.update(args)


# Last finished per-op summary / fleet view / critical-path attribution,
# for programmatic scraping (bench.py embeds these; user code can poll
# after a take).
_last_summary: Optional[Dict[str, Any]] = None
_last_fleet: Optional[Dict[str, Any]] = None
_last_attribution: Optional[Dict[str, Any]] = None


def _set_last_summary(summary: Dict[str, Any]) -> None:
    global _last_summary
    _last_summary = summary


def set_last_fleet(view: Optional[Dict[str, Any]]) -> None:
    global _last_fleet
    _last_fleet = view


def set_last_attribution(view: Optional[Dict[str, Any]]) -> None:
    global _last_attribution
    _last_attribution = view


def last_summary() -> Optional[Dict[str, Any]]:
    """The most recent per-op summary finished in this process."""
    return _last_summary


def last_fleet() -> Optional[Dict[str, Any]]:
    """The most recent cross-rank merged view (distributed ops only)."""
    return _last_fleet


def last_attribution() -> Optional[Dict[str, Any]]:
    """The most recent merged critical-path attribution (critpath.py)."""
    return _last_attribution
