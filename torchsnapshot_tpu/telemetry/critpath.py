"""Critical-path attribution: which resource bound the wall clock of a
take/restore, and on which rank.

The telemetry bus records WHAT happened (spans, counters, rates); this
module answers the operator's actual question — "why was this take
slow?" — with a defensible attribution instead of a span dump. Three
steps:

1. **Per-rank attribution** (:func:`build_attribution`): the rank's span
   events are mapped onto a FIXED category taxonomy (:data:`CATEGORIES`
   — staging copy, hash, storage write/read, decode/verify, peer
   transfer, collective wait) and each category's busy time is the
   UNION of its span intervals, so concurrent sub-chunk writes count
   once. Wall time no category covers is scheduler idle (budget defers,
   event-loop gaps). The op is also cut into *segments* at collective
   boundaries — pg_wrapper's ``collective_wait`` spans carry the
   ``(ns, cseq)`` causal key every rank of one collective shares — with
   per-segment category breakdowns.
2. **Cross-rank critical path** (:func:`merge_attributions`): collective
   keys align segments across ranks (the same stitching idea the flight
   recorder's blackbox merge uses — causal keys, never clocks). Within
   each segment, the rank that took longest to reach the next collective
   is the one that gated the fleet; the critical path is that chain, and
   fleet attribution sums the gating rank's categories per segment. The
   waiting peers' ``collective_wait`` time is deliberately EXCLUDED —
   waiting is a symptom; the binding resource lives on the rank being
   waited for.
3. **The verdict**: the binding category (largest share of the critical
   path), its class (``storage`` / ``pipeline`` / ``coordination``), the
   achieved rate over the binding window cross-checked against the
   governor's measured rates, the straggler delta, and a concrete tuning
   hint. ``python -m torchsnapshot_tpu explain <path>`` renders it; the
   exit code distinguishes storage-bound (1) from pipeline-bound (0) so
   benches can assert the ROADMAP "Python-pipeline-bound" claim.

Persistence: rank 0 writes the merged record to
``.snapshot_critpath`` next to ``.snapshot_telemetry`` (compact — the
full per-rank attributions ride the telemetry document's rank
summaries), and the binding category rides the checkpoint-history
journal so trend queries can ask "when did we become storage-bound?".
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

#: Persisted next to .snapshot_telemetry by rank 0 after the commit.
ATTRIBUTION_FNAME = ".snapshot_critpath"

#: The fixed attribution taxonomy. Pinned: fleet merges, the history
#: journal, and the explain rendering all key on these names.
CATEGORIES: Tuple[str, ...] = (
    "stage_copy",       # DtoH copy + serialization (staging)
    "hash",             # fingerprint/digest passes
    "storage_write",    # bytes moving to the storage tier
    "storage_read",     # bytes moving from the storage tier
    "decode",           # verify/decompress/HtoD on the restore side
    "peer_transfer",    # cooperative fan-out byte redistribution
    "native_io",        # blocked on the native engine (io_uring reap/drain)
    "collective_wait",  # blocked inside a KV-store collective
    "sched_idle",       # wall no instrumented work covered (budget
                        # defers, event-loop gaps, un-spanned work)
)

#: Span name -> category, for spans whose WHOLE duration is one
#: resource. Spans not listed here or in :data:`FUSED_SPANS` (io_drain
#: and other containers) attribute through their children, never
#: themselves.
SPAN_CATEGORIES: Dict[str, str] = {
    "stage_hash": "hash",
    "sub_chunk_stage": "stage_copy",
    "sub_chunk_dtoh": "stage_copy",
    "storage_write": "storage_write",
    "storage_read": "storage_read",
    "consume": "decode",
    "consume_chunk": "decode",
    "sub_chunk_htod": "decode",
    "coop_read": "peer_transfer",
    "peer_send": "peer_transfer",
    "peer_recv": "peer_transfer",
    # Planned-reshard tier (reshard.py): plan computation and owner-side
    # region-bundle forwarding ride the peer_transfer lane — both exist
    # only to replace storage reads with peer traffic, so attribution
    # groups them with the coop fan-out they extend.
    "reshard_plan": "peer_transfer",
    "peer_reshard": "peer_transfer",
    # Native-engine waits (fs plugin, io_uring reap/drain): time the
    # pipeline spent blocked on queued kernel I/O — submissions are
    # non-blocking, so these spans ARE the engine's storage wait.
    "native_write": "native_io",
    "native_read": "native_io",
    "collective_wait": "collective_wait",
}

#: Fused/container spans: name -> (residual category, covering
#: categories). A fused span interleaves two resources (PR 1/3
#: streaming: stage of sub-chunk N+1 under the write of N), so charging
#: its whole window to one category would call every streamed tmpfs
#: save "storage-bound". Instead, the window NOT covered by the inner
#: covering-category spans — the time the pipeline sat in the fused
#: span with no instrumented pipeline work running, i.e. waiting on the
#: residual resource — attributes to the residual category.
FUSED_SPANS: Dict[str, Tuple[str, Tuple[str, ...]]] = {
    "stream_write": ("storage_write", ("stage_copy", "hash", "native_io")),
    "stream_read": ("storage_read", ("decode", "peer_transfer", "native_io")),
    "stage": ("stage_copy", ("hash", "stage_copy")),
}

_CATEGORY_CLASS: Dict[str, str] = {
    "storage_write": "storage",
    "storage_read": "storage",
    "native_io": "storage",
    "collective_wait": "coordination",
}

#: Tuning hint per binding category — the "what do I turn" line the
#: explain CLI prints. {rate}/{ranks}/{defers} are filled at render time.
_HINTS: Dict[str, str] = {
    "storage_write": (
        "storage-write-bound at {rate} on rank(s) {ranks} — raise "
        "TORCHSNAPSHOT_TPU_IO_CONCURRENCY, keep streaming writes "
        "elected (TORCHSNAPSHOT_TPU_STREAM_WRITES), or move the tier "
        "(mirror to faster storage)"
    ),
    "storage_read": (
        "storage-read-bound at {rate} on rank(s) {ranks} — raise "
        "TORCHSNAPSHOT_TPU_IO_CONCURRENCY, keep streamed reads on "
        "(TORCHSNAPSHOT_TPU_STREAM_READS), or let cooperative restore "
        "fan out (TORCHSNAPSHOT_TPU_COOP_RESTORE)"
    ),
    "stage_copy": (
        "staging-bound (DtoH copy/serialization) on rank(s) {ranks} — "
        "pipeline-bound: the native pinned-staging fast path is the "
        "lever, not storage tuning"
    ),
    "hash": (
        "hash-bound on rank(s) {ranks} — skip the preverify pass "
        "(TORCHSNAPSHOT_TPU_PREVERIFY=never) or record device digests "
        "so unchanged payloads skip hashing"
    ),
    "decode": (
        "verify/decompress-bound on rank(s) {ranks} — lower the "
        "compression level or codec (TORCHSNAPSHOT_TPU_COMPRESSION); "
        "pipeline-bound"
    ),
    "peer_transfer": (
        "peer-transfer-bound on rank(s) {ranks} — the host network is "
        "the bottleneck; shrink the cooperative fan-out "
        "(TORCHSNAPSHOT_TPU_COOP_RESTORE=never) or widen the NIC"
    ),
    "native_io": (
        "native-engine-bound at {rate} on rank(s) {ranks} — the "
        "io_uring queue is the bottleneck: raise "
        "TORCHSNAPSHOT_TPU_NATIVE_QUEUE_DEPTH, or move the tier to "
        "faster storage (the Python pipeline is already off the path)"
    ),
    "collective_wait": (
        "coordination-bound — rank(s) {ranks} spent the critical path "
        "blocked in collectives; inspect the straggler with `watch` "
        "(live) or `blackbox` (post-abort)"
    ),
    "sched_idle": (
        "scheduler-idle-bound on rank(s) {ranks} — {defers} budget "
        "defer(s); raise TORCHSNAPSHOT_TPU_PER_RANK_MEMORY_BUDGET_BYTES "
        "or reduce concurrent per-host ranks"
    ),
}


#: A resource "binds" the op only when it gated the majority of the
#: critical path; below this share the verdict stays pipeline-bound.
_BOUND_SHARE = 0.5


def classify_category(category: Optional[str]) -> str:
    """``storage`` / ``coordination`` / ``pipeline`` for a category."""
    if category is None:
        return "pipeline"
    return _CATEGORY_CLASS.get(category, "pipeline")


# ---------------------------------------------------------- interval math


def _union_seconds(
    intervals: List[Tuple[float, float]],
    lo: Optional[float] = None,
    hi: Optional[float] = None,
) -> float:
    """Total length of the union of ``intervals``, optionally clipped to
    ``[lo, hi]`` — the anti-double-count primitive: sixteen concurrent
    sub-chunk writes are one wall-clock lane, not sixteen."""
    clipped = []
    for a, b in intervals:
        if lo is not None:
            a = max(a, lo)
        if hi is not None:
            b = min(b, hi)
        if b > a:
            clipped.append((a, b))
    if not clipped:
        return 0.0
    clipped.sort()
    total = 0.0
    cur_a, cur_b = clipped[0]
    for a, b in clipped[1:]:
        if a > cur_b:
            total += cur_b - cur_a
            cur_a, cur_b = a, b
        else:
            cur_b = max(cur_b, b)
    total += cur_b - cur_a
    return total


def _merge_intervals(
    intervals: List[Tuple[float, float]]
) -> List[Tuple[float, float]]:
    merged: List[Tuple[float, float]] = []
    for a, b in sorted(i for i in intervals if i[1] > i[0]):
        if merged and a <= merged[-1][1]:
            merged[-1] = (merged[-1][0], max(merged[-1][1], b))
        else:
            merged.append((a, b))
    return merged


def _subtract_intervals(
    intervals: List[Tuple[float, float]],
    cover: List[Tuple[float, float]],
) -> List[Tuple[float, float]]:
    """``intervals`` minus ``cover`` — the residual-attribution primitive
    for fused spans."""
    out: List[Tuple[float, float]] = []
    cover = _merge_intervals(cover)
    for a, b in _merge_intervals(intervals):
        cur = a
        for ca, cb in cover:
            if cb <= cur:
                continue
            if ca >= b:
                break
            if ca > cur:
                out.append((cur, min(ca, b)))
            cur = max(cur, cb)
            if cur >= b:
                break
        if cur < b:
            out.append((cur, b))
    return out


# ------------------------------------------------------ per-rank records


def _span_intervals(
    events: List[Dict[str, Any]]
) -> Dict[str, List[Tuple[float, float]]]:
    per_cat: Dict[str, List[Tuple[float, float]]] = {}
    fused: Dict[str, List[Tuple[float, float]]] = {}
    for ev in events:
        if ev.get("ph") != "span":
            continue
        name = ev.get("name", "")
        ts = ev.get("ts")
        dur = ev.get("dur")
        if ts is None or dur is None or dur < 0:
            continue
        cat = SPAN_CATEGORIES.get(name)
        if cat is not None:
            per_cat.setdefault(cat, []).append((ts, ts + dur))
        elif name in FUSED_SPANS:
            fused.setdefault(name, []).append((ts, ts + dur))
    # Fused spans: attribute the window their covering categories did
    # not occupy to the residual resource (see FUSED_SPANS). Sorted so
    # "stage" folds its residual into stage_copy BEFORE stream_write
    # computes its cover from it — deterministic, and staging time
    # inside a fused write never leaks into the storage residual.
    for name in sorted(fused):
        intervals = fused[name]
        residual_cat, cover_cats = FUSED_SPANS[name]
        cover: List[Tuple[float, float]] = []
        for c in cover_cats:
            cover.extend(per_cat.get(c, []))
        per_cat.setdefault(residual_cat, []).extend(
            _subtract_intervals(intervals, cover)
        )
    return per_cat


def build_attribution(
    events: List[Dict[str, Any]],
    wall_s: Optional[float] = None,
    rank: int = 0,
) -> Dict[str, Any]:
    """One rank's attribution record from its op-scoped bus events.

    ``categories`` maps each taxonomy category to its busy seconds (span
    union); ``sched_idle`` is the wall no category covered. ``segments``
    cuts the op at collective boundaries (``collective_wait`` spans,
    keyed by the shared ``ns#cseq``) with a per-segment breakdown —
    compact by construction: a take has a handful of collectives, never
    one per sub-chunk."""
    spans = [
        ev
        for ev in events
        if ev.get("ph") == "span"
        and ev.get("ts") is not None
        and ev.get("dur") is not None
    ]
    per_cat = _span_intervals(spans)
    if spans:
        t_begin = min(ev["ts"] for ev in spans)
        t_end = max(ev["ts"] + ev["dur"] for ev in spans)
    else:
        t_begin = t_end = 0.0
    wall = wall_s if wall_s is not None else (t_end - t_begin)
    categories: Dict[str, float] = {}
    all_intervals: List[Tuple[float, float]] = []
    for cat, intervals in per_cat.items():
        busy = _union_seconds(intervals)
        if busy > 0:
            categories[cat] = round(busy, 6)
        all_intervals.extend(intervals)
    covered = _union_seconds(all_intervals)
    idle = max(0.0, (wall or 0.0) - covered)
    if idle > 0:
        categories["sched_idle"] = round(idle, 6)

    colls = sorted(
        (ev for ev in spans if ev.get("name") == "collective_wait"),
        key=lambda ev: ev["ts"],
    )
    segments: List[Dict[str, Any]] = []
    prev = t_begin
    for coll in colls:
        args = coll.get("args") or {}
        key = f"{args.get('ns')}#{args.get('cseq')}"
        seg = _segment(per_cat, prev, coll["ts"])
        seg.update(
            key=key,
            kind=args.get("kind"),
            wait_s=round(coll["dur"], 6),
        )
        segments.append(seg)
        prev = coll["ts"] + coll["dur"]
    if spans:
        tail = _segment(per_cat, prev, t_end)
        tail.update(key="tail", kind=None, wait_s=0.0)
        segments.append(tail)
    return {
        "rank": rank,
        "wall_s": round(wall or 0.0, 6),
        "categories": categories,
        "segments": segments,
    }


def _segment(
    per_cat: Dict[str, List[Tuple[float, float]]], lo: float, hi: float
) -> Dict[str, Any]:
    cats: Dict[str, float] = {}
    all_iv: List[Tuple[float, float]] = []
    for cat, intervals in per_cat.items():
        if cat == "collective_wait":
            continue  # the segment's own wait is reported separately
        busy = _union_seconds(intervals, lo, hi)
        if busy > 0:
            cats[cat] = round(busy, 6)
        all_iv.extend(intervals)
    busy_all = _union_seconds(all_iv, lo, hi)
    dur = max(0.0, hi - lo)
    idle = max(0.0, dur - busy_all)
    if idle > 0:
        cats["sched_idle"] = round(idle, 6)
    return {"dur_s": round(dur, 6), "categories": cats}


# --------------------------------------------------------- fleet stitching


def merge_attributions(
    rank_attrs: List[Optional[Dict[str, Any]]],
    aggregate: Optional[Dict[str, Any]] = None,
) -> Optional[Dict[str, Any]]:
    """Stitch per-rank attributions into the fleet's critical path.

    Segments are aligned by collective key (identical on every rank of
    one collective); within each, the gating rank is the one with the
    longest segment, and its categories — not the waiters'
    ``collective_wait`` — enter the fleet attribution. Ranks whose
    telemetry was off contribute None; with no shared segments (single
    rank, skew) the slowest rank's whole-op attribution stands in.
    ``aggregate`` (the fleet counter sums) turns the binding window into
    an achieved rate for the storage/staging categories."""
    present = [
        (i, a) for i, a in enumerate(rank_attrs) if isinstance(a, dict)
    ]
    if not present:
        return None
    walls = [(a.get("wall_s", 0.0), i) for i, a in present]
    wall_max, slowest = max(walls)
    wall_min, fastest = min(walls)

    seg_by_rank: Dict[int, Dict[str, Dict[str, Any]]] = {}
    for i, a in present:
        table: Dict[str, Dict[str, Any]] = {}
        for seg in a.get("segments") or []:
            table.setdefault(seg.get("key", "?"), seg)
        seg_by_rank[i] = table
    ordered_keys = [
        seg.get("key", "?") for seg in (present[0][1].get("segments") or [])
    ]
    shared = [
        k
        for k in ordered_keys
        if all(k in seg_by_rank[i] for i, _ in present)
    ]

    fleet_cats: Dict[str, float] = {}
    critical_path: List[Dict[str, Any]] = []
    if len(present) > 1 and shared:
        crit_wall = 0.0
        for key in shared:
            dur, owner = max(
                (seg_by_rank[i][key].get("dur_s", 0.0), i)
                for i, _ in present
            )
            seg = seg_by_rank[owner][key]
            crit_wall += dur
            top = None
            for cat, busy in (seg.get("categories") or {}).items():
                fleet_cats[cat] = round(fleet_cats.get(cat, 0.0) + busy, 6)
                if top is None or busy > seg["categories"][top]:
                    top = cat
            critical_path.append(
                {
                    "key": key,
                    "kind": seg.get("kind"),
                    "rank": owner,
                    "dur_s": round(dur, 6),
                    "top": top,
                }
            )
    else:
        slowest_attr = dict(present[0][1])
        for i, a in present:
            if i == slowest:
                slowest_attr = a
        fleet_cats = dict(slowest_attr.get("categories") or {})
        crit_wall = slowest_attr.get("wall_s", wall_max)

    binding_cat = (
        max(fleet_cats.items(), key=lambda kv: kv[1])[0]
        if fleet_cats
        else "sched_idle"
    )
    binding_ranks = sorted(
        i
        for i, a in present
        if (a.get("categories") or {})
        and max(a["categories"].items(), key=lambda kv: kv[1])[0]
        == binding_cat
    )
    busy = fleet_cats.get(binding_cat, 0.0)
    binding: Dict[str, Any] = {
        "category": binding_cat,
        "class": classify_category(binding_cat),
        "busy_s": round(busy, 6),
        "share": round(busy / crit_wall, 4) if crit_wall > 0 else None,
        "ranks": binding_ranks,
    }
    bytes_moved = _binding_bytes(binding_cat, aggregate)
    if bytes_moved and busy > 0:
        binding["gbps"] = round(bytes_moved / busy / 1e9, 4)
    # The verdict: "X-bound" is a stronger claim than "X was the largest
    # category" — it means X gated the MAJORITY of the critical path. A
    # fast local save whose pwrite is its biggest instrumented slice at
    # 20% of the wall is still pipeline-bound (the other 80% is pipeline
    # machinery); calling it storage-bound would tell the operator to
    # buy faster disks that would not help.
    share = binding.get("share") or 0.0
    cls = binding.get("class")
    if cls == "storage" and share > _BOUND_SHARE:
        verdict = "storage-bound"
    elif cls == "coordination" and share > _BOUND_SHARE:
        verdict = "coordination-bound"
    else:
        verdict = "pipeline-bound"
    return {
        "verdict": verdict,
        "reporting": len(present),
        "wall_s_max": round(wall_max, 6),
        "critical_wall_s": round(crit_wall, 6),
        "slowest_rank": slowest,
        "fastest_rank": fastest,
        "straggler_delta_s": round(wall_max - wall_min, 6),
        "categories": fleet_cats,
        "critical_path": critical_path,
        "binding": binding,
    }


def _binding_bytes(
    category: str, aggregate: Optional[Dict[str, Any]]
) -> Optional[float]:
    if not aggregate:
        return None
    return {
        "storage_write": aggregate.get("bytes_written"),
        "storage_read": aggregate.get("bytes_read"),
        "stage_copy": aggregate.get("bytes_staged"),
        "peer_transfer": aggregate.get("bytes_to_peers"),
        # The native engine moves whichever direction the op ran; saves
        # dominate in practice and a restore-bound native path reports
        # bytes_read through storage_read's row anyway.
        "native_io": aggregate.get("bytes_written") or aggregate.get("bytes_read"),
    }.get(category)


def live_binding(events: List[Dict[str, Any]]) -> Optional[str]:
    """Cheap in-flight binding hint from a recent window of bus events
    (the heartbeat's ``binding`` field): the category with the largest
    summed span time. Summed, not unioned — a 1 Hz hint does not earn
    the union sweep."""
    busy: Dict[str, float] = {}
    for ev in events:
        if ev.get("ph") != "span":
            continue
        cat = SPAN_CATEGORIES.get(ev.get("name", ""))
        if cat is not None and ev.get("dur"):
            busy[cat] = busy.get(cat, 0.0) + ev["dur"]
    if not busy:
        return None
    return max(busy.items(), key=lambda kv: kv[1])[0]


# ------------------------------------------------------------ persistence


def build_attribution_document(
    op: str,
    world_size: int,
    fleet: Optional[Dict[str, Any]],
    rates: Optional[Dict[str, Any]] = None,
    governor: Optional[List[Dict[str, Any]]] = None,
) -> Dict[str, Any]:
    """The compact ``.snapshot_critpath`` record (per-rank attributions
    stay inside the telemetry document's rank summaries)."""
    return {
        "version": 1,
        "op": op,
        "world_size": world_size,
        "fleet": fleet,
        "rates": rates,
        "governor": governor,
    }


def derive_document_from_telemetry(
    telemetry_doc: Dict[str, Any]
) -> Optional[Dict[str, Any]]:
    """Re-derive an attribution document from a persisted telemetry
    summary document (rank summaries carry ``attribution`` blobs) — the
    ``explain`` fallback for snapshots that predate ``.snapshot_critpath``
    or whose rank 0 failed to persist it."""
    ranks = telemetry_doc.get("ranks") or []
    attrs = [
        (r or {}).get("attribution") if isinstance(r, dict) else None
        for r in ranks
    ]
    aggregate = (telemetry_doc.get("fleet") or {}).get("aggregate")
    fleet = merge_attributions(attrs, aggregate=aggregate)
    if fleet is None:
        return None
    rank0 = next((r for r in ranks if isinstance(r, dict)), {}) or {}
    return build_attribution_document(
        telemetry_doc.get("op") or "unknown",
        telemetry_doc.get("world_size") or len(ranks),
        fleet,
        rates=rank0.get("rates"),
        governor=rank0.get("governor"),
    )


# -------------------------------------------------------------- rendering


def _fmt_rate(gbps: Optional[float]) -> str:
    return f"{gbps:.2f} GB/s" if gbps is not None else "unmeasured"


def render_attribution(doc: Dict[str, Any], verbose: bool = False) -> str:
    """The ``explain`` CLI rendering: critical path, binding resource
    with its measured rate (cross-checked against the governor's
    measured rates recorded at decision time), straggler delta, and the
    tuning hint."""
    fleet = doc.get("fleet") or {}
    binding = fleet.get("binding") or {}
    lines: List[str] = []
    lines.append(f"op:          {doc.get('op')}")
    lines.append(f"world_size:  {doc.get('world_size')}")
    lines.append(
        f"wall:        {fleet.get('wall_s_max', 0):.3f}s (slowest rank "
        f"{fleet.get('slowest_rank')}, straggler "
        f"+{fleet.get('straggler_delta_s', 0):.3f}s over fastest)"
    )
    path = fleet.get("critical_path") or []
    if path:
        lines.append(
            f"critical path ({len(path)} segment(s), "
            f"{fleet.get('critical_wall_s', 0):.3f}s):"
        )
        for n, seg in enumerate(path, 1):
            kind = f" -> {seg['kind']}" if seg.get("kind") else ""
            lines.append(
                f"  [{n}] rank {seg.get('rank')}  "
                f"{seg.get('dur_s', 0):>8.3f}s  "
                f"top {seg.get('top') or 'none'}{kind}"
            )
    cats = fleet.get("categories") or {}
    if cats:
        lines.append("attribution (critical-path busy seconds):")
        total = sum(cats.values()) or 1.0
        for cat, busy in sorted(cats.items(), key=lambda kv: -kv[1]):
            lines.append(
                f"  {cat:<16} {busy:>9.3f}s  ({busy / total:>5.1%})"
            )
    cat = binding.get("category")
    if cat:
        share = binding.get("share")
        lines.append(
            f"binding:     {cat} [{binding.get('class')}] — "
            f"{binding.get('busy_s', 0):.3f}s busy"
            + (f", {share:.0%} of the critical path" if share else "")
        )
        if fleet.get("verdict"):
            lines.append(f"verdict:     {fleet['verdict']}")
        if binding.get("gbps") is not None:
            lines.append(
                f"rate:        {_fmt_rate(binding.get('gbps'))} achieved "
                "over the binding window"
            )
        rates = doc.get("rates") or {}
        table = {
            "storage_write": rates.get("write_bps"),
            "storage_read": rates.get("read_bps"),
            "hash": {"hash": rates.get("hash_bps")},
        }.get(cat)
        if isinstance(table, dict) and any(
            v for v in table.values() if v is not None
        ):
            measured = ", ".join(
                f"{k or 'all'}={v / 1e9:.2f} GB/s"
                for k, v in table.items()
                if isinstance(v, (int, float))
            )
            lines.append(f"governor:    measured {measured} at decision time")
        hint = _HINTS.get(cat)
        if hint:
            ranks = binding.get("ranks") or []
            lines.append(
                "hint:        "
                + hint.format(
                    rate=_fmt_rate(binding.get("gbps")),
                    ranks=",".join(map(str, ranks)) if ranks else "all",
                    defers="some",
                )
            )
    if verbose and doc.get("governor"):
        lines.append("elections:")
        for row in doc["governor"]:
            args = ", ".join(
                f"{k}={v}" for k, v in row.items() if k != "site"
            )
            lines.append(f"  {row.get('site', '?')}: {args}")
    return "\n".join(lines)


def binding_exit_code(doc: Dict[str, Any]) -> int:
    """``explain``'s verdict as an exit code: 1 when the take was
    storage-bound (the storage class gated the majority of the critical
    path), 0 otherwise (pipeline- or coordination-bound) — so a bench
    can assert the ROADMAP claim with one subprocess call."""
    return 1 if (doc.get("fleet") or {}).get("verdict") == "storage-bound" else 0
