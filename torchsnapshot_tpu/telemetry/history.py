"""Checkpoint history: a crash-safe per-root journal of committed takes,
with p50 regression detection and an OpenMetrics export.

Every committed take appends one compact JSON line to
``<root>/.telemetry_history.jsonl`` (``<root>`` = the directory holding
the snapshot, i.e. the CheckpointManager root for managed saves):
duration, fleet GB/s, bytes moved (storage vs peers), retries,
failovers, overlap — the numbers an operator needs to answer "did last
week's change make saves slower?" without re-running a benchmark.
``python -m torchsnapshot_tpu stats <root> --trend`` renders the
trajectory and exits non-zero when the recent p50 regressed past a
threshold, so the check drops into CI; ``--openmetrics`` emits the same
counters in OpenMetrics text format for a scrape pipeline.

Crash safety of the append: the record is ONE ``os.write`` on an
``O_APPEND`` descriptor (atomic for sane record sizes on POSIX), fenced
by an exclusive ``flock`` so two managers sharing a root interleave
whole lines. A torn line from a mid-write SIGKILL is skipped by the
reader — the journal is advisory history, never restore-critical state.

Wall-clock note: records carry ``time.time()`` (calendar time — this is
history ACROSS processes, where the in-process monotonic clock means
nothing). Durations still come from the telemetry bus clock upstream.
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)

HISTORY_FNAME = ".telemetry_history.jsonl"
TREND_THRESHOLD_ENV_VAR = "TORCHSNAPSHOT_TPU_TREND_THRESHOLD"
_DEFAULT_THRESHOLD = 0.25  # recent p50 >25% slower than baseline p50

#: Counters copied from the fleet aggregate into each history record.
_RECORD_COUNTERS = (
    "bytes_written",
    "bytes_read",
    "bytes_to_peers",
    "bytes_deduped",
    "retry_attempts",
    "store_failovers",
    "lease_renewals",
    "fanout_fallbacks",
    "mirror_failovers",
    "journal_appends",
    "journal_bytes",
    "journal_replays",
    "journal_truncations",
    "bytes_from_seeders",
    "seed_cache_hits",
    "epoch_push_bytes",
    "pages_faulted",
    "pages_prefetched",
    "pagein_bytes",
    "profile_skips",
    "georep_bases_shipped",
    "georep_epochs_shipped",
    "georep_bytes_shipped",
    "georep_ship_errors",
    "georep_frames_rejected",
    "georep_splice_refusals",
    "georep_steps_dropped",
)


def trend_threshold() -> float:
    raw = os.environ.get(TREND_THRESHOLD_ENV_VAR, "").strip()
    try:
        return float(raw) if raw else _DEFAULT_THRESHOLD
    except ValueError:
        return _DEFAULT_THRESHOLD


def history_path(root: str) -> str:
    return os.path.join(root, HISTORY_FNAME)


def build_record(
    op: str,
    path: str,
    wall_s: float,
    world_size: int,
    fleet: Optional[Dict[str, Any]],
    rank_summary: Optional[Dict[str, Any]] = None,
    step: Optional[int] = None,
    attribution: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """One compact history line from whatever the take measured.

    Works with the telemetry bus OFF: wall time and identity always
    record; counters/rates appear when the bus contributed a fleet view."""
    rec: Dict[str, Any] = {
        "ts": round(time.time(), 3),
        "op": op,
        "snapshot": os.path.basename(path.rstrip("/")),
        "world_size": world_size,
        "wall_s": round(wall_s, 6),
    }
    if step is not None:
        rec["step"] = step
    agg = (fleet or {}).get("aggregate") or {}
    for key in _RECORD_COUNTERS:
        val = agg.get(key)
        if val:
            rec[key] = val
    for key in ("write_gbps", "read_gbps"):
        if agg.get(key):
            rec[key] = round(agg[key], 4)
    # The remote tier's RPO exposure at commit time. A gauge, not a
    # summed counter: the shipper is rank-0-only, so the local gauge IS
    # the fleet value — recorded so ``stats --trend`` can gate RPO.
    from . import core

    lag = (core.gauges() or {}).get("replication_lag_s")
    if lag is not None:
        rec["replication_lag_s"] = round(float(lag), 3)
    if fleet:
        rec["skew_s"] = fleet.get("skew_s")
        rec["slowest_rank"] = fleet.get("slowest_rank")
    # Critical-path verdict (critpath.merge_attributions): the binding
    # category per take, so the trend view can answer "when did saves
    # become storage-bound?" without re-opening every snapshot.
    binding = (attribution or {}).get("binding") or {}
    if binding.get("category"):
        rec["binding"] = binding["category"]
        if binding.get("gbps") is not None:
            rec["binding_gbps"] = binding["gbps"]
    # Overlap ratio: time the pipeline spent inside storage I/O spans
    # over the op wall — >1 means I/O genuinely overlapped with staging/
    # verify (the PR 1/3 streaming design working), <<1 means the op was
    # bound elsewhere. From the local (rank-0) summary; absent with the
    # bus off.
    spans = (rank_summary or {}).get("spans") or {}
    io_s = sum(
        (spans.get(name) or {}).get("total_s", 0.0)
        for name in ("storage_write", "stream_write", "storage_read", "read_stream")
    )
    if io_s and wall_s > 0:
        rec["overlap_ratio"] = round(io_s / wall_s, 3)
    return rec


def append_record(root: str, record: Dict[str, Any]) -> bool:
    """Fenced, crash-safe append of one record; returns False (never
    raises) when the root is not an appendable local directory."""
    try:
        if not os.path.isdir(root):
            return False
        line = (json.dumps(record, default=repr) + "\n").encode("utf-8")
        fd = os.open(
            history_path(root), os.O_APPEND | os.O_CREAT | os.O_WRONLY, 0o644
        )
        try:
            try:
                import fcntl

                fcntl.flock(fd, fcntl.LOCK_EX)
            except (ImportError, OSError):  # non-POSIX / NFS without locks
                pass
            os.write(fd, line)  # one write: whole-line atomicity
        finally:
            os.close(fd)
        return True
    except OSError:
        logger.debug("history append skipped", exc_info=True)
        return False


def load_history(path_or_root: str) -> List[Dict[str, Any]]:
    """Parse a history journal (given the journal file or its root
    directory). Torn/malformed lines are skipped."""
    path = path_or_root
    if os.path.isdir(path):
        path = history_path(path)
    records: List[Dict[str, Any]] = []
    if not os.path.isfile(path):
        return records
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # torn append from a killed writer
            if isinstance(rec, dict) and "wall_s" in rec:
                records.append(rec)
    return records


def load_profiles(path_or_root: str) -> List[Dict[str, Any]]:
    """Parse the journal's learned-profile records (``type="profile"``,
    appended by the IOGovernor's closed loop — scheduler.observe_verdict
    via autotune.AutoTuner.profile_record), newest last.

    Profile records deliberately carry no ``wall_s``, so they are
    invisible to :func:`load_history` and the trend math; this is their
    reader. Records with no binding category are skipped here too — the
    same bus-off-take rule the learner applies (a ``None`` category must
    not poison a profile key)."""
    path = path_or_root
    if os.path.isdir(path):
        path = history_path(path)
    records: List[Dict[str, Any]] = []
    if not os.path.isfile(path):
        return records
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # torn append from a killed writer
            if (
                isinstance(rec, dict)
                and rec.get("type") == "profile"
                and isinstance(rec.get("binding"), str)
                and rec.get("binding")
            ):
                records.append(rec)
    return records


def render_profiles(records: List[Dict[str, Any]]) -> str:
    """The ``explain --profiles`` rendering: per profile key, the
    converged settings, the smoothed verdict score, and the recent
    perturbation trail (dim, from -> to, kept/reverted/neutral) — the
    governor's full decision story for a root."""
    latest: Dict[str, Dict[str, Any]] = {}
    for rec in records:
        key = (
            f"{rec.get('plugin', '?')}|w{rec.get('world_size', '?')}|"
            f"{rec.get('binding', '?')}"
        )
        latest[key] = rec  # newest last wins
    lines = [
        f"learned profiles: {len(latest)} key(s) "
        f"({len(records)} journal record(s))"
    ]
    for key in sorted(latest):
        rec = latest[key]
        when = time.strftime(
            "%Y-%m-%d %H:%M:%S", time.localtime(rec.get("ts", 0))
        )
        score = rec.get("score_gbps")
        lines.append(
            f"\n{key}  [{rec.get('op', '?')}]  "
            f"score {score:.2f} GB/s" if isinstance(score, (int, float))
            else f"\n{key}  [{rec.get('op', '?')}]  score ?"
        )
        lines.append(
            f"  takes {rec.get('takes', 0)}, last updated {when}"
        )
        settings = rec.get("settings") or {}
        if settings:
            for dim in sorted(settings):
                val = settings[dim]
                if dim.startswith("sub_chunk") and isinstance(val, int):
                    shown = f"{val >> 20} MB"
                else:
                    shown = str(val)
                lines.append(f"  {dim:<22} {shown}")
        else:
            lines.append("  (no converged settings yet — heuristics hold)")
        trials = rec.get("trials") or []
        for t in trials[-MAX_RENDERED_TRIALS:]:
            if not isinstance(t, dict):
                continue
            lines.append(
                f"  trial {t.get('dim', '?'):<18} "
                f"{t.get('from', '?')} -> {t.get('to', '?')}  "
                f"{t.get('verdict', '?'):<8} "
                f"({t.get('gbps', '?')} vs incumbent "
                f"{t.get('incumbent_gbps', '?')} GB/s)"
            )
    return "\n".join(lines)


MAX_RENDERED_TRIALS = 8


def _p50(values: List[float]) -> float:
    ordered = sorted(values)
    n = len(ordered)
    mid = n // 2
    if n % 2:
        return ordered[mid]
    return (ordered[mid - 1] + ordered[mid]) / 2.0


def detect_regression(
    records: List[Dict[str, Any]],
    metric: str = "wall_s",
    threshold: Optional[float] = None,
    recent_n: int = 5,
) -> Dict[str, Any]:
    """Compare the recent window's p50 against the baseline p50.

    ``metric``: ``wall_s`` (higher is worse) or a throughput metric
    ending in ``_gbps`` (lower is worse). The last ``recent_n`` records
    form the recent window; everything before is baseline. Needs at
    least 3 baseline and 2 recent points — fewer returns
    ``{"regressed": False, "reason": "insufficient history"}`` (a young
    deployment must not fail CI on noise)."""
    if threshold is None:
        threshold = trend_threshold()
    vals = [
        (r.get(metric), r) for r in records if isinstance(r.get(metric), (int, float))
    ]
    series = [float(v) for v, _ in vals]
    recent_n = max(1, min(recent_n, len(series) // 2))
    baseline, recent = series[:-recent_n], series[-recent_n:]
    if len(baseline) < 3 or len(recent) < 2:
        return {
            "metric": metric,
            "regressed": False,
            "reason": "insufficient history",
            "n": len(series),
        }
    base_p50, recent_p50 = _p50(baseline), _p50(recent)
    higher_is_worse = not metric.endswith("_gbps")
    if higher_is_worse:
        ratio = recent_p50 / base_p50 if base_p50 > 0 else 1.0
        regressed = ratio > 1.0 + threshold
    else:
        ratio = recent_p50 / base_p50 if base_p50 > 0 else 1.0
        regressed = ratio < 1.0 - threshold
    return {
        "metric": metric,
        "baseline_p50": round(base_p50, 6),
        "recent_p50": round(recent_p50, 6),
        "ratio": round(ratio, 4),
        "threshold": threshold,
        "baseline_n": len(baseline),
        "recent_n": len(recent),
        "regressed": regressed,
    }


_SPARK_CHARS = "▁▂▃▄▅▆▇█"


def _sparkline(values: List[float]) -> str:
    if not values:
        return ""
    lo, hi = min(values), max(values)
    if hi <= lo:
        return _SPARK_CHARS[0] * len(values)
    return "".join(
        _SPARK_CHARS[
            min(
                len(_SPARK_CHARS) - 1,
                int((v - lo) / (hi - lo) * (len(_SPARK_CHARS) - 1)),
            )
        ]
        for v in values
    )


def render_trend(
    records: List[Dict[str, Any]], verdicts: List[Dict[str, Any]]
) -> str:
    """The ``stats --trend`` rendering: per-metric trajectory sparklines,
    the last few takes in detail, and each regression verdict."""
    from .export import fmt_bytes

    lines = [f"history: {len(records)} committed take(s)"]
    for metric, label in (
        ("wall_s", "wall"),
        ("write_gbps", "write GB/s"),
        ("replication_lag_s", "repl lag"),
    ):
        series = [
            float(r[metric])
            for r in records
            if isinstance(r.get(metric), (int, float))
        ]
        if series:
            lines.append(
                f"  {label:<11} {_sparkline(series[-60:])}  "
                f"last={series[-1]:.3f} min={min(series):.3f} "
                f"max={max(series):.3f}"
            )
    lines.append("")
    for rec in records[-8:]:
        when = time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(rec.get("ts", 0)))
        extras = []
        if rec.get("write_gbps"):
            extras.append(f"{rec['write_gbps']:.2f} GB/s")
        if rec.get("bytes_written"):
            extras.append(fmt_bytes(rec["bytes_written"]))
        if rec.get("retry_attempts"):
            extras.append(f"{rec['retry_attempts']:.0f} retries")
        if rec.get("store_failovers"):
            extras.append(f"{rec['store_failovers']:.0f} store failover(s)")
        if rec.get("fanout_fallbacks"):
            extras.append(f"{rec['fanout_fallbacks']:.0f} fanout fallback(s)")
        if rec.get("bytes_from_seeders"):
            extras.append(f"{fmt_bytes(rec['bytes_from_seeders'])} from seeders")
        if rec.get("epoch_push_bytes"):
            extras.append(f"{fmt_bytes(rec['epoch_push_bytes'])} pushed")
        if rec.get("mirror_failovers"):
            extras.append(f"{rec['mirror_failovers']:.0f} mirror failover(s)")
        if rec.get("journal_replays"):
            extras.append(f"{rec['journal_replays']:.0f} journal replay(s)")
        if rec.get("journal_truncations"):
            extras.append(
                f"{rec['journal_truncations']:.0f} torn journal tail(s)"
            )
        if rec.get("binding"):
            extras.append(f"bound: {rec['binding']}")
        lines.append(
            f"  {when}  {rec.get('snapshot', '?'):<16} "
            f"{rec.get('op', '?'):<5} {rec.get('wall_s', 0):>9.3f}s"
            + ("  " + ", ".join(extras) if extras else "")
        )
    lines.append("")
    for v in verdicts:
        if v.get("reason"):
            lines.append(f"trend[{v['metric']}]: {v['reason']} (n={v.get('n', 0)})")
            continue
        word = "REGRESSED" if v["regressed"] else "ok"
        lines.append(
            f"trend[{v['metric']}]: {word} — recent p50 {v['recent_p50']:.3f} "
            f"vs baseline p50 {v['baseline_p50']:.3f} "
            f"(ratio {v['ratio']:.2f}, threshold ±{v['threshold']:.0%}, "
            f"{v['baseline_n']}+{v['recent_n']} takes)"
        )
    return "\n".join(lines)
