"""The flight-recorder event taxonomy: every recordable event, by name.

The flight recorder (flightrec.py) is always on, so its event stream is
an OPERATOR INTERFACE, not debug logging: the ``blackbox`` CLI merges
rank dumps by matching these names, post-mortem runbooks grep for them,
and tests assert on them. A name invented ad hoc at a call site would be
invisible to all three — so the taxonomy is pinned here, and
``scripts/check_event_taxonomy.py`` (tier-1, the same lint culture as
``check_fault_sites.py``) verifies every ``flightrec.record(...)`` call
in the package uses a registered string literal, and that every
registered name is actually wired somewhere.

Unlike fault-injection sites, one event name MAY have several call sites
(``collective.enter`` fires from every collective verb); what must be
unique is the meaning, which the registry row documents.

Causal keys: events carry whatever coordination identity the layer has —
``ns``/``cseq`` (the PGWrapper namespace + collective sequence, shared
by all ranks of one collective), ``epoch`` (store leadership), ``gen``
(the commit-fence generation) — so the cross-rank merge can align
timelines without comparable clocks.
"""

from __future__ import annotations

from typing import Dict

EVENTS: Dict[str, str] = {
    # operation lifecycle (snapshot.py)
    "op.begin": "a take/restore began on this rank (op, rank, path)",
    "op.abort": "a take/restore raised (op, error, kind) — triggers a dump",
    "phase": "op phase transition (_PhaseTimer.mark: name, op, dur_s)",
    "progress": "periodic pipeline progress sample (scheduler reporter)",
    # collectives (pg_wrapper.py)
    "collective.enter": "entered a KV-store collective (kind, ns, cseq, deadline_s)",
    "collective.exit": "left a collective (kind, ns, cseq, ok[, error])",
    # coordination store (dist_store.py)
    "store.failover": "client adopted a new store leader (epoch, leader, cause)",
    "store.epoch": "a standby assumed leadership / a leader was deposed (epoch, role)",
    "store.lease": "leader lease renewal round (epoch, replicas)",
    # storage degradation (storage_plugins/)
    "retry.attempt": "transient storage error scheduled for retry (kind, op, attempt)",
    "retry.exhausted": "retry budget exhausted; error propagates (kind, op, attempts)",
    "mirror.failover": "primary-tier read failed over to the mirror (path, kind)",
    # cooperative restore (fanout.py)
    "fanout.fallback": "peer-fed unit degraded to a direct storage read (key, owner)",
    # planned reshard (reshard.py)
    "reshard.plan": "one entry's minimal-movement reshard plan computed "
    "(shards, planned, owned, recv)",
    # commit protocol (snapshot.py)
    "fence.plant": "rank 0 planted the commit fence (gen)",
    "commit.decision": "fenced commit decision (gen, found, ok) — StaleCommitError when not ok",
    # adaptive tuning (scheduler.IOGovernor consumers)
    "governor.elect": "an IOGovernor election was made (site, decision fields, "
    "measured rates at decision time) — recorded wherever the governor "
    "picks streaming on/off, sub-chunk size, I/O concurrency, the "
    "preverify gate, or cooperative restore",
    "governor.learn": "the autotuner scored a committed op's critical-"
    "path verdict against the incumbent profile (key, trial dim, "
    "kept/reverted/neutral verdict, GB/s) — or skipped an unattributed "
    "op (skipped=True, counted as profile_skips)",
    # native I/O engine (native_io.py / io_preparers/array.py)
    "native.degrade": "the native I/O tier degraded (site, cause) — the "
    "capability probe failed at startup or the staging pool fell back to "
    "Python slabs mid-run",
    # cross-cutting
    "fault.trip": "a fault-injection rule fired (site, hit, action)",
    "preempt.signal": "a termination signal was observed (signum)",
    "flight.dump": "ring dump header (rank, reason, events, dropped)",
    # stall forensics (forensics.py)
    "forensic.dump": "the hang watchdog dumped thread stacks (rank, "
    "trigger, reason) — self-triggered or remote-requested",
    # delta journal (journal.py)
    "journal.open": "rank 0 planted a journal epoch fence (gen, epoch)",
    "journal.commit": "a journal epoch committed — metadata published, "
    "fence cleared (gen, epoch, records)",
    "journal.replay": "committed journal epochs replayed onto a restored "
    "base (gen, epochs, records, truncated)",
    # fleet distribution tier (distrib.py)
    "distrib.register": "a chunk this replica now holds was registered "
    "in the seed catalog (digest, nbytes, depth, holder)",
    "distrib.fetch": "a chunk arrived from a seeding peer and verified "
    "its content address (digest, nbytes, parent, depth)",
    "distrib.push": "one committed journal epoch was pushed to a live "
    "replica and acked (gen, epoch, nbytes, target, dup)",
    # tenancy (tenancy/)
    "tenant.admit": "a tenant-scoped op registered in the admission "
    "table and got its bandwidth share (tenant, op, priority, share)",
    "tenant.evict": "quota retention reclaimed a tenant's oldest "
    "step(s) (tenant, evicted, used, quota)",
    # lazy page-in restore (pagein.py)
    "pagein.begin": "a lazy restore returned with its hot set resident "
    "and handed the tail to the page-in engine (units, bytes, ttfi_s)",
    "pagein.fault": "a demand fault jumped the prefetch queue for a "
    "deferred leaf (path, state, direct)",
    "pagein.complete": "every deferred leaf landed — the lazy restore "
    "reached eager-equivalent residency (units, faulted, wall_s)",
    # cross-region geo-replication (georep.py)
    "georep.ship": "a base snapshot or epoch blob left the shipper for "
    "the remote tier (kind, step, nbytes, tier, dur_s)",
    "georep.apply": "a shipped epoch was verified and folded onto the "
    "remote tier — or refused (epoch, gen, nbytes, tier, ok)",
    "georep.lag": "the shipper fell behind — a ship cycle failed and the "
    "backlog is aging (tier, backlog_epochs, lag_s, error)",
}

FLIGHT_EVENTS = frozenset(EVENTS)

# ------------------------------------------------------------- histograms
#
# The latency-histogram instrument (core.histogram_observe) is the same
# kind of operator interface the flight-recorder events are: fleet merges
# sum bucket-wise by NAME, the stats/explain renderings and the live
# /metrics exporter expose families by NAME, and dashboards alert on
# them. So the names are pinned here, and check_event_taxonomy.py
# enforces that every ``histogram_observe(...)`` call in the package uses
# a registered literal and that every registered name is wired somewhere.
# The optional ``key`` argument (storage-plugin class, collective verb)
# becomes a label and is free-form; the FAMILY name is not.

HISTOGRAM_NAMES: Dict[str, str] = {
    "write.sub_chunk_s": "per-sub-chunk stage+write handoff latency on a "
    "streamed write (scheduler; key = storage plugin)",
    "read.sub_chunk_s": "per-sub-chunk delivery latency on a streamed or "
    "peer-fed read (scheduler; key = storage plugin or 'peer')",
    "write.entry_s": "buffered per-entry storage write latency "
    "(scheduler; key = storage plugin)",
    "read.entry_s": "buffered per-entry storage read latency "
    "(scheduler; key = storage plugin)",
    "storage.op_s": "per-storage-operation latency in the cloud retry "
    "tier (retry/_retrying; key = '<Plugin>.<op>')",
    "collective.wait_s": "wall time inside one KV-store collective "
    "(pg_wrapper; key = collective verb)",
}

HISTOGRAMS = frozenset(HISTOGRAM_NAMES)
