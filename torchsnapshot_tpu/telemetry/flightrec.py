"""Always-on flight recorder: a bounded ring of structured events, dumped
per rank on abort and merged across ranks by the ``blackbox`` CLI.

The telemetry bus (core.py) answers "why was this take slow?" — but only
when ``TORCHSNAPSHOT_TPU_TELEMETRY=1`` was set before the incident, which
on a real fleet it never was. The flight recorder is the complement:
**on by default**, bounded, and cheap enough to stay on, recording only
the low-frequency events that matter for a post-mortem (phase
transitions, collective enter/exit, store failovers, retries, fence
decisions — the taxonomy in taxonomy.py), never per-sub-chunk samples.
When a rank aborts, its ring is written to
``<snapshot>/.flight/rank_<r>.jsonl``; ``python -m torchsnapshot_tpu
blackbox <snapshot>`` merges the rank dumps into one causal timeline, so
"who deserted whom at which barrier" is one command instead of an
N-way log grep.

Design rules (the telemetry/faultinject lineage):

1. **Lock-cheap when enabled, one flag check when disabled.** The ring
   is a ``collections.deque(maxlen=N)`` — append is atomic under the
   GIL, so the hot path takes no lock; the sequence counter is an
   ``itertools.count`` (also GIL-atomic). Disable with
   ``TORCHSNAPSHOT_TPU_FLIGHTREC=0``; size the ring with
   ``TORCHSNAPSHOT_TPU_FLIGHTREC_RING`` (default 4096 events).
2. **Strictly stdlib.** Imported by ``dist_store``/``pg_wrapper`` (the
   coordination plane, which must never import jax).
3. **The blessed clock.** Timestamps come from ``core.monotonic`` — the
   timing lint covers this file (scripts/check_timing_lint.py), unlike
   the rest of the telemetry package, because flightrec is a *consumer*
   of the clock, not its owner.
4. **Dumps never raise.** A dump happens while an operation is already
   unwinding; masking the original error with a telemetry IOError would
   be the one unforgivable failure mode.

Cross-rank causality: monotonic clocks are not comparable across hosts,
so events carry coordination identity instead — the PGWrapper
``(ns, cseq)`` collective key (identical on every rank of one
collective), the store leadership ``epoch``, and the commit-fence
``gen``. ``merge_timeline`` aligns rank clocks on a shared collective
anchor and derives findings (desertions, failovers, stale commits) from
the keys, not the clocks.
"""

from __future__ import annotations

import collections
import itertools
import json
import logging
import os
import threading
from typing import Any, Dict, List, Optional, Tuple

from .core import monotonic
from .taxonomy import FLIGHT_EVENTS

logger = logging.getLogger(__name__)

FLIGHTREC_ENV_VAR = "TORCHSNAPSHOT_TPU_FLIGHTREC"
RING_ENV_VAR = "TORCHSNAPSHOT_TPU_FLIGHTREC_RING"
DUMP_DIR_ENV_VAR = "TORCHSNAPSHOT_TPU_FLIGHTREC_DIR"
_DEFAULT_RING = 4096

#: Dump directory inside a snapshot path (sibling of .telemetry/).
FLIGHT_DIR = ".flight"


def _env_enabled() -> bool:
    # Always-on is the point: anything but an explicit off-value enables.
    raw = os.environ.get(FLIGHTREC_ENV_VAR, "").strip().lower()
    return raw not in ("0", "off", "false", "no", "never")


def _read_ring_size() -> int:
    raw = os.environ.get(RING_ENV_VAR, "").strip()
    try:
        return max(16, int(raw)) if raw else _DEFAULT_RING
    except ValueError:
        return _DEFAULT_RING


_enabled: bool = _env_enabled()
_ring: "collections.deque" = collections.deque(maxlen=_read_ring_size())
_seq = itertools.count(1)
# Dumps are serialized (two layers of one unwinding abort may both ask);
# the RECORD path never touches this lock.
_dump_lock = threading.Lock()


def enabled() -> bool:
    return _enabled


def set_enabled(value: bool) -> None:
    """Programmatic override of the env gate (tests, bench trials)."""
    global _enabled
    _enabled = bool(value)


def refresh_from_env() -> bool:
    """Re-read the enable flag and ring size (subprocess workers that
    mutate os.environ after import call this, telemetry-style)."""
    global _enabled, _ring
    _enabled = _env_enabled()
    size = _read_ring_size()
    if size != _ring.maxlen:
        _ring = collections.deque(_ring, maxlen=size)
    return _enabled


def ring_size() -> int:
    return _ring.maxlen or _DEFAULT_RING


def record(event: str, **args: Any) -> None:
    """Record one event. ``event`` must be a registered literal from
    events.FLIGHT_EVENTS (scripts/check_event_taxonomy.py enforces it);
    ``args`` may use any keys EXCEPT the record envelope's own
    (``seq``/``t``/``ev``/``rank``/``rel_t``).

    Hot path: one module-global flag check when disabled; one atomic
    deque append when enabled — no lock, no I/O, no string formatting."""
    if not _enabled:
        return
    _ring.append((next(_seq), monotonic(), event, args or None))


def snapshot_ring() -> List[Tuple[int, float, str, Optional[Dict[str, Any]]]]:
    """A stable copy of the current ring contents (oldest first)."""
    return list(_ring)


def recorded_total() -> int:
    """Highest sequence recorded so far (>= len(ring) once the ring has
    wrapped and begun dropping oldest-first)."""
    return _ring[-1][0] if _ring else 0


def reset() -> None:
    """Drop the ring (tests; between unrelated ops in one process)."""
    global _seq
    _ring.clear()
    _seq = itertools.count(1)


# ------------------------------------------------------------------- dumps


def dump_path_for_rank(rank: int) -> str:
    return f"{FLIGHT_DIR}/rank_{rank}.jsonl"


def _resolve_dump_dir(path: Optional[str]) -> Optional[str]:
    """The local directory to dump under: the snapshot path when it is a
    local filesystem target, else the DUMP_DIR env override, else None
    (dump skipped — a remote-only abort still has the rank's log)."""
    if path is not None:
        from ..storage_plugin import local_fs_root

        local = local_fs_root(path)
        if local is not None:
            return local
    env_dir = os.environ.get(DUMP_DIR_ENV_VAR, "").strip()
    return env_dir or None


def dump(path: Optional[str], rank: int, reason: str) -> Optional[str]:
    """Write the ring to ``<path>/.flight/rank_<rank>.jsonl``.

    Called on the abort path (unhandled exception, StaleCommitError,
    barrier timeout, SIGTERM) — NEVER raises, returns the file written
    or None. Local filesystem targets only; for remote snapshot paths
    set ``TORCHSNAPSHOT_TPU_FLIGHTREC_DIR`` to a local spool directory.
    Repeated dumps of one incident overwrite (the last writer holds the
    superset of events)."""
    if not _enabled:
        return None
    try:
        base = _resolve_dump_dir(path)
        if base is None:
            return None
        events = snapshot_ring()
        out = os.path.join(base, FLIGHT_DIR, f"rank_{rank}.jsonl")
        with _dump_lock:
            os.makedirs(os.path.dirname(out), exist_ok=True)
            total = events[-1][0] if events else 0
            header = {
                "seq": 0,
                "t": round(monotonic(), 6),
                "ev": "flight.dump",
                "rank": rank,
                "reason": reason,
                "events": len(events),
                "dropped": max(0, total - len(events)),
                "ring": ring_size(),
            }
            with open(out, "w") as f:
                f.write(json.dumps(header, default=repr) + "\n")
                for seq, ts, name, args in events:
                    rec = {"seq": seq, "t": round(ts, 6), "ev": name}
                    if args:
                        rec.update(args)
                    f.write(json.dumps(rec, default=repr) + "\n")
        logger.warning(
            "flight recorder: dumped %d event(s) to %s (%s)",
            len(events),
            out,
            reason,
        )
        # Every path that dumps the flight ring also dumps stacks: the
        # hook lives HERE (not at each abort site) so any future dump
        # path inherits the pairing. Lazy import breaks the cycle —
        # forensics imports this module for the spool-dir resolution.
        try:
            from . import forensics

            forensics.dump_stacks(path, rank, reason, trigger="abort")
        except Exception:  # noqa: BLE001 - same rule as the ring dump
            logger.debug("abort stack dump failed (continuing)", exc_info=True)
        return out
    except Exception:  # noqa: BLE001 - a dump must never mask the abort
        logger.exception("flight-recorder dump failed (continuing)")
        return None


# --------------------------------------------------- cross-rank timeline


def load_dumps(path: str) -> Dict[int, List[Dict[str, Any]]]:
    """Parse ``<path>/.flight/rank_*.jsonl`` into ``{rank: [records]}``.

    Torn trailing lines (a writer died mid-dump) are skipped, not fatal
    — the blackbox must work on exactly the wrecks it exists for."""
    flight_dir = os.path.join(path, FLIGHT_DIR)
    out: Dict[int, List[Dict[str, Any]]] = {}
    if not os.path.isdir(flight_dir):
        return out
    for fname in sorted(os.listdir(flight_dir)):
        if not (fname.startswith("rank_") and fname.endswith(".jsonl")):
            continue
        try:
            rank = int(fname[len("rank_"):-len(".jsonl")])
        except ValueError:
            continue
        records: List[Dict[str, Any]] = []
        with open(os.path.join(flight_dir, fname)) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue  # torn line: the dumping writer died here
                if isinstance(rec, dict) and "ev" in rec:
                    records.append(rec)
        out[rank] = records
    return out


def _collective_key(rec: Dict[str, Any]) -> Optional[Tuple[str, int, str]]:
    if rec.get("ev") not in ("collective.enter", "collective.exit"):
        return None
    ns, cseq = rec.get("ns"), rec.get("cseq")
    if ns is None or cseq is None:
        return None
    return (str(ns), int(cseq), str(rec.get("kind", "?")))


def merge_timeline(dumps: Dict[int, List[Dict[str, Any]]]) -> Dict[str, Any]:
    """Merge per-rank dumps into one causal view.

    Clock alignment: per-rank monotonic clocks are incomparable, so each
    rank's timeline is rebased on the earliest collective ``(ns, cseq)``
    key that EVERY dumped rank entered (all ranks of one collective enter
    it within the coordination round trip — microseconds to milliseconds
    of true skew, good enough to read a timeline). With no shared anchor
    (single rank, or totally divergent rings) ranks render on their own
    zero-based axes, flagged ``aligned: False``.

    Findings are derived from the causal keys, never the clocks:

    - ``desertion`` — a collective some ranks entered and either never
      left or left with an error, while other ranks never arrived (or
      also never left): names the collective, who waited, who never came.
    - ``store-failover`` — every adopted leader change, with the epoch.
    - ``stale-commit`` — a fenced commit decision that refused (gen vs
      found).
    - ``abort`` — each rank's recorded op.abort, with the error.
    - ``fault-trip`` — injected faults that fired (drills name their
      own causes).
    """
    ranks = sorted(dumps)
    by_key_enter: Dict[Tuple, Dict[int, Dict[str, Any]]] = {}
    by_key_exit: Dict[Tuple, Dict[int, Dict[str, Any]]] = {}
    for rank in ranks:
        for rec in dumps[rank]:
            key = _collective_key(rec)
            if key is None:
                continue
            table = by_key_enter if rec["ev"] == "collective.enter" else by_key_exit
            table.setdefault(key, {})[rank] = rec

    # -- clock alignment on the earliest fully-shared enter key
    offsets: Dict[int, float] = {r: 0.0 for r in ranks}
    aligned = False
    shared = [
        k for k, entries in by_key_enter.items() if set(entries) == set(ranks)
    ]
    if shared and len(ranks) > 1:
        anchor = min(
            shared, key=lambda k: by_key_enter[k][ranks[0]].get("t", 0.0)
        )
        t0 = by_key_enter[anchor][ranks[0]].get("t", 0.0)
        for r in ranks:
            offsets[r] = by_key_enter[anchor][r].get("t", 0.0) - t0
        aligned = True
    elif ranks:
        # Zero-base each rank on its own first event.
        for r in ranks:
            ts = [rec.get("t", 0.0) for rec in dumps[r] if rec.get("seq", 0) > 0]
            offsets[r] = min(ts) if ts else 0.0
        aligned = len(ranks) == 1

    merged: List[Dict[str, Any]] = []
    for r in ranks:
        for rec in dumps[r]:
            if rec.get("seq", 0) <= 0:  # the dump header
                continue
            out = dict(rec)
            out["rank"] = r
            out["rel_t"] = rec.get("t", 0.0) - offsets[r]
            merged.append(out)
    merged.sort(key=lambda e: (e["rel_t"], e["rank"], e.get("seq", 0)))
    # Rebase the whole timeline to its earliest event: the offsets above
    # only RECONCILE rank clocks (aligned case: onto rank 0's raw
    # monotonic axis, which is seconds-since-boot) — without this the
    # typical aligned timeline would print absolute +90000s stamps.
    if merged:
        base = merged[0]["rel_t"]
        for out in merged:
            out["rel_t"] = round(out["rel_t"] - base, 6)

    findings: List[Dict[str, Any]] = []
    for key in sorted(by_key_enter, key=lambda k: (k[0], k[1])):
        entered = by_key_enter.get(key, {})
        exited = by_key_exit.get(key, {})
        errored = {
            r for r, rec in exited.items() if rec.get("ok") is False
        }
        stuck = set(entered) - set(exited)
        missing = set(ranks) - set(entered)
        if not (errored or stuck) and not missing:
            continue
        if not entered:
            continue
        if missing or errored or stuck:
            ns, cseq, kind = key
            findings.append(
                {
                    "class": "desertion" if (missing or stuck) else "collective-error",
                    "kind": kind,
                    "ns": ns,
                    "cseq": cseq,
                    "entered": sorted(entered),
                    "never_arrived": sorted(missing),
                    "stuck": sorted(stuck),
                    "errored": sorted(errored),
                    "errors": {
                        r: exited[r].get("error") for r in sorted(errored)
                    },
                }
            )
    for rank in ranks:
        for rec in dumps[rank]:
            ev = rec.get("ev")
            if ev == "store.failover":
                findings.append(
                    {
                        "class": "store-failover",
                        "rank": rank,
                        "epoch": rec.get("epoch"),
                        "leader": rec.get("leader"),
                        "cause": rec.get("cause"),
                    }
                )
            elif ev == "commit.decision" and rec.get("ok") is False:
                findings.append(
                    {
                        "class": "stale-commit",
                        "rank": rank,
                        "gen": rec.get("gen"),
                        "found": rec.get("found"),
                    }
                )
            elif ev == "op.abort":
                findings.append(
                    {
                        "class": "abort",
                        "rank": rank,
                        "op": rec.get("op"),
                        "error": rec.get("error"),
                        "gen": rec.get("gen"),
                    }
                )
            elif ev == "fault.trip":
                findings.append(
                    {
                        "class": "fault-trip",
                        "rank": rank,
                        "site": rec.get("site"),
                        "hit": rec.get("hit"),
                        "action": rec.get("action"),
                    }
                )
    # Replication lag collapses to the LATEST sample per (rank, tier):
    # the shipper records georep.lag every failed cycle, and a hundred
    # copies of the same aging backlog is one finding, not a hundred.
    lagging: Dict[Any, Dict[str, Any]] = {}
    for rank in ranks:
        for rec in dumps[rank]:
            if rec.get("ev") != "georep.lag":
                continue
            lagging[(rank, rec.get("tier"))] = {
                "class": "replication-lag",
                "rank": rank,
                "tier": rec.get("tier"),
                "backlog_epochs": rec.get("backlog_epochs"),
                "lag_s": rec.get("lag_s"),
                "error": rec.get("error"),
            }
    findings.extend(lagging[k] for k in sorted(lagging, key=str))
    return {
        "ranks": ranks,
        "aligned": aligned,
        "events": merged,
        "findings": findings,
    }


def render_timeline(merged: Dict[str, Any], verbose: bool = False) -> str:
    """Human-readable blackbox report: findings first (the diagnosis),
    then the merged timeline (the evidence)."""
    lines: List[str] = []
    ranks = merged.get("ranks") or []
    events = merged.get("events") or []
    lines.append(
        f"flight dumps: {len(ranks)} rank(s) ({', '.join(map(str, ranks))}), "
        f"{len(events)} event(s)"
        + ("" if merged.get("aligned") else " [clocks not aligned: no shared anchor]")
    )
    stack_ranks = merged.get("stack_ranks") or []
    if stack_ranks:
        n_dumps = sum((merged.get("stack_dumps") or {}).values())
        lines.append(
            f"stack dumps: {len(stack_ranks)} rank(s) "
            f"({', '.join(map(str, stack_ranks))}), {n_dumps} dump(s)"
        )
    findings = merged.get("findings") or []
    if findings:
        lines.append("")
        lines.append("findings:")
    for f in findings:
        cls = f.get("class")
        if cls in ("desertion", "collective-error"):
            what = []
            if f["never_arrived"]:
                what.append(
                    "rank(s) "
                    + ", ".join(map(str, f["never_arrived"]))
                    + " never arrived"
                )
            if f["stuck"]:
                what.append(
                    "rank(s) " + ", ".join(map(str, f["stuck"])) + " still waiting"
                )
            for r in f.get("errored", []):
                what.append(f"rank {r} raised ({f['errors'].get(r)})")
            line = (
                f"  DESERTION      collective {f['kind']} #{f['cseq']} "
                f"[{f['ns']}]: " + "; ".join(what)
            )
            # Stack-dump annotation (telemetry/forensics.py): WHERE each
            # waiter actually sat when it last dumped — the difference
            # between "rank 1 still waiting" and "rank 1 still waiting,
            # wedged under storage_write @ fs.py:write".
            frames = f.get("frames") or {}
            if frames:
                line += "; executing: " + ", ".join(
                    f"r{r} {frames[r]}" for r in sorted(frames)
                )
            lines.append(line)
        elif cls == "store-failover":
            lines.append(
                f"  STORE-FAILOVER rank {f['rank']} adopted leader "
                f"{f.get('leader')} at epoch {f.get('epoch')} "
                f"(cause: {f.get('cause')})"
            )
        elif cls == "stale-commit":
            lines.append(
                f"  STALE-COMMIT   rank {f['rank']} refused to commit: fence "
                f"held {f.get('found')!r}, expected generation {f.get('gen')!r}"
            )
        elif cls == "abort":
            gen = f" [generation {f['gen']}]" if f.get("gen") else ""
            lines.append(
                f"  ABORT          rank {f['rank']} {f.get('op')}{gen}: "
                f"{f.get('error')}"
            )
        elif cls == "fault-trip":
            lines.append(
                f"  FAULT-TRIP     rank {f['rank']} site {f.get('site')} "
                f"hit #{f.get('hit')} -> {f.get('action')}"
            )
        elif cls == "wedge":
            lines.append(
                f"  WEDGE          rank {f['rank']} wedged in "
                f"{f.get('category')} at {f.get('frame')} "
                f"({f.get('dumps')} consecutive dump(s), "
                f"thread {f.get('thread')})"
            )
        elif cls == "replication-lag":
            lines.append(
                f"  REPLICATION-LAG rank {f['rank']} tier {f.get('tier')} "
                f"is {f.get('backlog_epochs')} epoch(s) behind, oldest "
                f"unshipped state {f.get('lag_s')}s old "
                f"(last error: {f.get('error')})"
            )
    lines.append("")
    lines.append("timeline (relative seconds):")
    shown = events if verbose else events[-200:]
    if len(shown) < len(events):
        lines.append(f"  ... {len(events) - len(shown)} earlier event(s) elided "
                     "(-v shows all)")
    for ev in shown:
        extras = {
            k: v
            for k, v in ev.items()
            if k not in ("rank", "rel_t", "seq", "t", "ev")
        }
        detail = " ".join(f"{k}={v}" for k, v in extras.items())
        lines.append(
            f"  [{ev['rel_t']:+10.3f}s] r{ev['rank']} {ev['ev']}"
            + (f"  {detail}" if detail else "")
        )
    return "\n".join(lines)


# Registered-name self-check (import-time, cheap): a record() call with a
# typo'd name would silently vanish from every runbook grep; the AST lint
# catches package call sites, this catches dynamic callers in tests.
def check_name(name: str) -> bool:
    return name in FLIGHT_EVENTS
