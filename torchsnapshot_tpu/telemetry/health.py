"""Live fleet health plane: per-rank heartbeats over the coordination
store, rendered in flight by the ``watch`` CLI.

The flight recorder (flightrec.py) explains an abort AFTER it happened;
this module is the view BEFORE — a rank stalling toward the barrier
timeout shows up here minutes before ``TORCHSNAPSHOT_TPU_BARRIER_TIMEOUT``
turns it into a fleet abort. Each rank of an in-flight take/restore
publishes a small progress record to the existing replicated KV store
(the same plane every collective already rides — no new ports, and the
leased-leader failover tier makes the heartbeats themselves survive a
store-host death) on a low cadence; ``python -m torchsnapshot_tpu watch
<store-addr>`` polls the keys and renders the fleet: per-rank phase,
bytes staged/written, queue depths, ETA, and — the point — which ranks
have stopped moving.

Mechanics:

- **Publisher.** ``maybe_start`` arms a daemon thread per operation
  (world > 1, store present, cadence > 0). The thread owns a CLONED
  store connection: the primary connection blocks for whole collectives
  under the client lock, and a heartbeat that queues behind a 1800 s
  barrier wait would defeat its purpose. Publishing is ``store.set`` on
  ``tsnap/health/<rank>`` — failover-transparent like every client op;
  a failed tick is skipped, never raised (the op outranks its
  telemetry).
- **Progress state.** Pipeline layers push fields into a module-level
  dict (``update(phase=..., written_bytes=...)``) — the scheduler's
  progress reporter and the snapshot phase timer both feed it; the
  publisher snapshots it each tick. Writers never touch the store.
- **Staleness is watcher-side.** Rank clocks are incomparable, so a
  heartbeat carries a monotone ``seq`` and the WATCHER flags a rank
  stalled when its seq stops advancing for ``--stall`` seconds of
  watcher time — no clock agreement needed, and a mid-poll store
  failover (one poll erroring) degrades to a "store unreachable" line,
  never a crash.

Cadence: ``TORCHSNAPSHOT_TPU_HEARTBEAT_S`` (seconds, default 1.0;
``0`` disables publishing). One small set per rank per cadence is noise
against the store's collective traffic.
"""

from __future__ import annotations

import json
import logging
import os
import threading
from typing import Any, Dict, Optional

from .core import monotonic

logger = logging.getLogger(__name__)

HEARTBEAT_ENV_VAR = "TORCHSNAPSHOT_TPU_HEARTBEAT_S"
_DEFAULT_CADENCE_S = 1.0

#: Store key namespace. Fixed (not per-op-namespace) so a watcher needs
#: no handshake — it reads whatever the fleet currently publishes.
HEARTBEAT_PREFIX = "tsnap/health/"


def heartbeat_cadence_s() -> float:
    raw = os.environ.get(HEARTBEAT_ENV_VAR, "").strip()
    try:
        return float(raw) if raw else _DEFAULT_CADENCE_S
    except ValueError:
        return _DEFAULT_CADENCE_S


# ------------------------------------------------------- progress state

_state_lock = threading.Lock()
_state: Dict[str, Any] = {}


def update(**fields: Any) -> None:
    """Merge progress fields for the NEXT heartbeat tick (phase, bytes,
    queue depths...). Called by the scheduler reporter and the snapshot
    phase timer; cheap (one small dict update under a lock, no I/O)."""
    with _state_lock:
        _state.update(fields)


def clear() -> None:
    with _state_lock:
        _state.clear()


def current_state() -> Dict[str, Any]:
    with _state_lock:
        return dict(_state)


# ------------------------------------------------------------ publisher


class HeartbeatPublisher:
    """Publishes this rank's progress record on a cadence until stopped.

    Owns a cloned store connection so heartbeats never queue behind the
    primary connection's blocking collective waits."""

    def __init__(self, store: Any, rank: int, op: str, path: str,
                 cadence_s: Optional[float] = None) -> None:
        self.rank = rank
        self.op = op
        self.path = path
        # Tenant-scoped key prefix, resolved NOW on the calling thread
        # (the publisher thread would not see the caller's activation).
        from ..tenancy import current_tenant, scope_key

        tenant = current_tenant()
        self.prefix = (
            scope_key(HEARTBEAT_PREFIX, tenant.id)
            if tenant is not None
            else HEARTBEAT_PREFIX
        )
        self.cadence_s = (
            cadence_s if cadence_s is not None else heartbeat_cadence_s()
        )
        self._store = store.clone()
        self._stop = threading.Event()
        self._delete_on_stop = True
        self._seq = 0
        self._t0 = monotonic()
        self._thread = threading.Thread(
            target=self._loop, name="tsnap-heartbeat", daemon=True
        )

    def start(self) -> "HeartbeatPublisher":
        self._publish()  # first beat immediately: the watcher sees the
        self._thread.start()  # op the moment it begins, not a tick later
        return self

    def _payload(self) -> bytes:
        self._seq += 1
        rec = {
            "rank": self.rank,
            "op": self.op,
            "path": self.path,
            "seq": self._seq,
            "wall_s": round(monotonic() - self._t0, 3),
        }
        rec.update(current_state())
        # ETA from the monotone byte counters when both sides are known.
        done = rec.get("written_bytes") or rec.get("read_bytes") or 0
        total = rec.get("total_bytes") or 0
        wall = rec["wall_s"]
        if done and total and wall > 0 and total >= done:
            rate = done / wall
            if rate > 0:
                rec["eta_s"] = round((total - done) / rate, 1)
        return json.dumps(rec, default=repr).encode("utf-8")

    def _publish(self) -> None:
        try:
            self._store.set(f"{self.prefix}{self.rank}", self._payload())
        except Exception:  # noqa: BLE001 - heartbeats must never fail the op
            logger.debug("heartbeat publish skipped", exc_info=True)

    def _loop(self) -> None:
        while not self._stop.wait(self.cadence_s):
            self._publish()
        # Retraction + close happen ON THIS THREAD, strictly after the
        # last publish: if stop()'s bounded join gave up on a publish
        # blocked in a slow store.set, a caller-side delete could land
        # BEFORE that set completes server-side — resurrecting the key
        # as a permanent ghost rank that `watch` flags STALLED forever.
        if self._delete_on_stop:
            try:
                self._store.delete(f"{self.prefix}{self.rank}")
            except Exception:  # noqa: BLE001
                pass
        try:
            self._store.close()
        except Exception:  # noqa: BLE001
            pass

    def stop(self, delete: bool = True) -> None:
        """Stop the cadence; ``delete`` retracts the key so a finished
        rank doesn't linger as a false stall on the watch display. The
        retraction runs on the publisher thread (ordered after its final
        publish); the join is bounded, so a thread wedged in a dead
        store's set doesn't block the op's exit — it retracts whenever
        it unblocks."""
        self._delete_on_stop = delete
        self._stop.set()
        try:
            self._thread.join(timeout=self.cadence_s + 5.0)
        except Exception:  # noqa: BLE001
            pass


def maybe_start(pg_wrapper: Any, op: str, path: str) -> Optional[HeartbeatPublisher]:
    """Arm a publisher for this operation, or None when there is nothing
    to publish to (single process / no store) or the cadence is 0.
    Resets the shared progress state so a new op never inherits the
    previous one's bytes."""
    cadence = heartbeat_cadence_s()
    if cadence <= 0:
        return None
    pg = getattr(pg_wrapper, "pg", None)
    store = getattr(pg, "store", None)
    if store is None or pg_wrapper.get_world_size() <= 1:
        return None
    # ``step`` is annotated by the layer ABOVE the op (CheckpointManager,
    # before Snapshot.take starts this publisher) — it survives the
    # per-op reset the way telemetry.annotate_next_op survives begin_op.
    sticky = {k: v for k, v in current_state().items() if k == "step"}
    clear()
    update(phase="begin", **sticky)
    try:
        return HeartbeatPublisher(
            store, pg_wrapper.get_rank(), op, path, cadence_s=cadence
        ).start()
    except Exception:  # noqa: BLE001 - observability never fails the op
        logger.debug("heartbeat publisher failed to start", exc_info=True)
        return None


# -------------------------------------------------------------- watcher


def read_fleet(
    store: Any, prefix: Optional[str] = None
) -> Dict[int, Dict[str, Any]]:
    """One non-blocking snapshot of every published heartbeat.

    Uses the store's ``collect`` with count=0 — an immediate
    prefix scan, no waiting. Raises whatever the store client raises on
    a dead tier (the CLI degrades, this function does not). ``prefix``
    defaults to the active/ambient tenant's scoped keyspace (watching a
    tenant's fleet needs TORCHSNAPSHOT_TPU_TENANT set to match)."""
    if prefix is None:
        from ..tenancy import current_tenant, scope_key

        tenant = current_tenant()
        prefix = (
            scope_key(HEARTBEAT_PREFIX, tenant.id)
            if tenant is not None
            else HEARTBEAT_PREFIX
        )
    _, items = store.collect(prefix, 0, timeout=5.0)
    fleet: Dict[int, Dict[str, Any]] = {}
    for key, raw in items.items():
        try:
            rank = int(key[len(prefix):])
            rec = json.loads(bytes(raw).decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            continue
        if isinstance(rec, dict):
            fleet[rank] = rec
    return fleet


#: Heartbeat fields whose change means the rank is actually MOVING.
#: ``seq``/``wall_s`` advance on every beat even when the pipeline is
#: wedged, so staleness keys on the progress fingerprint instead — a
#: rank heartbeating dutifully while its bytes stand still is exactly
#: the straggler the watcher exists to flag.
_PROGRESS_FIELDS = (
    "op", "phase", "staged_bytes", "written_bytes", "read_bytes",
    "seed_bytes", "done_entries", "resident_frac",
)


def _progress_fingerprint(rec: Dict[str, Any]) -> tuple:
    return tuple(rec.get(k) for k in _PROGRESS_FIELDS)


class FleetTracker:
    """Watcher-side staleness bookkeeping across polls: a rank is STALLED
    when its progress fingerprint (phase/bytes/entries — NOT the
    heartbeat seq) has not changed for ``stall_s`` seconds of the
    watcher's own clock. No cross-host clock agreement is needed, and a
    rank whose heartbeats stop entirely goes stale the same way."""

    def __init__(self, stall_s: float = 5.0) -> None:
        self.stall_s = stall_s
        self._last_fp: Dict[int, tuple] = {}
        self._last_change: Dict[int, float] = {}

    def observe(self, fleet: Dict[int, Dict[str, Any]]) -> Dict[int, float]:
        """Update from one poll; returns {rank: seconds_since_progress}."""
        now = monotonic()
        ages: Dict[int, float] = {}
        for rank, rec in fleet.items():
            fp = _progress_fingerprint(rec)
            if self._last_fp.get(rank) != fp or rank not in self._last_change:
                self._last_fp[rank] = fp
                self._last_change[rank] = now
            ages[rank] = now - self._last_change[rank]
        # Ranks that vanished (finished, key deleted) drop out of the view.
        for rank in list(self._last_fp):
            if rank not in fleet:
                self._last_fp.pop(rank, None)
                self._last_change.pop(rank, None)
        return ages

    def stalled(self, ages: Dict[int, float]) -> Dict[int, bool]:
        return {r: age >= self.stall_s for r, age in ages.items()}


def render_fleet(
    fleet: Dict[int, Dict[str, Any]],
    ages: Dict[int, float],
    stall_s: float,
    wedged: Optional[Dict[int, str]] = None,
) -> str:
    """One watch frame: a per-rank table plus skew/straggler summary."""
    from .export import fmt_bytes

    if not fleet:
        return "no in-flight operation (no heartbeat keys published)"
    lines = []
    # The ``seed`` column is the seed-vs-storage byte mix of a fleet
    # restore (distrib.py): ``read`` counts what came from storage,
    # ``seed`` what arrived from seeding peers — a healthy seeded fleet
    # shows one replica with a big ``read`` and the rest mostly ``seed``.
    # The ``resid`` column is a lazy restore's resident fraction
    # (pagein.py): a replica serving before fully restored climbs from
    # its hot-set fraction to 100% as the tail pages in; eager ops show
    # ``-``.
    # The ``profile`` column is the autotuner's active profile key
    # (scheduler.begin_io_op -> autotune.profile_key); a trailing ``*``
    # marks a rank currently running a perturbation trial on that op.
    # The ``repl`` column is the geo-replication lag (georep.py,
    # rank-0-only): the age of the oldest committed-but-unshipped state
    # — the remote tier's live RPO exposure; ranks without a shipper
    # show ``-``. None of these fields is in _PROGRESS_FIELDS — a
    # background tier toggling must never mask (or fake) byte-level
    # progress in the stall fingerprint.
    lines.append(
        f"{'rank':>4}  {'op':<8} {'phase':<14} {'staged':>10} {'written':>10} "
        f"{'read':>10} {'seed':>10} {'total':>10} {'resid':>6} {'io':>3} "
        f"{'eta':>7} {'wall':>8}  {'bound on':<15} {'profile':<28} "
        f"{'repl':>7} status"
    )
    walls = []
    for rank in sorted(fleet):
        rec = fleet[rank]
        age = ages.get(rank, 0.0)
        stalled = age >= stall_s
        status = f"STALLED {age:.0f}s" if stalled else "ok"
        # The forensic wedge frame (watch --dump, telemetry/forensics.py)
        # rides inline on the row: a STALLED rank that also says
        # "wedged storage_write @ fs.py:write:99" needs no second tool.
        if wedged and rank in wedged:
            status += f"  wedged {wedged[rank]}"
        eta = rec.get("eta_s")
        walls.append((rec.get("wall_s") or 0.0, rank))
        # The binding-resource hint (scheduler reporter -> critpath
        # live estimate): a STALLED row that also says "storage_write"
        # tells the on-call WHAT the straggler is stuck on.
        binding = rec.get("binding") or "-"
        resid = rec.get("resident_frac")
        resid_txt = f"{resid * 100:.0f}%" if resid is not None else "-"
        profile = str(rec.get("profile") or "-")
        if rec.get("trial"):
            profile += "*"
        repl_lag = rec.get("georep_lag_s")
        repl_txt = f"{repl_lag:.1f}s" if repl_lag is not None else "-"
        lines.append(
            f"{rank:>4}  {str(rec.get('op', '?')):<8} "
            f"{str(rec.get('phase', '?')):<14} "
            f"{fmt_bytes(rec.get('staged_bytes')):>10} "
            f"{fmt_bytes(rec.get('written_bytes')):>10} "
            f"{fmt_bytes(rec.get('read_bytes')):>10} "
            f"{fmt_bytes(rec.get('seed_bytes')):>10} "
            f"{fmt_bytes(rec.get('total_bytes')):>10} "
            f"{resid_txt:>6} "
            f"{rec.get('inflight_io', 0):>3} "
            f"{(str(eta) + 's') if eta is not None else '?':>7} "
            f"{rec.get('wall_s', 0):>7.1f}s  {str(binding):<15} "
            f"{profile:<28} {repl_txt:>7} {status}"
        )
    if len(walls) > 1:
        wall_max, slowest = max(walls)
        wall_min, _fastest = min(walls)
        lines.append(
            f"skew: {wall_max - wall_min:.1f}s (slowest rank {slowest})"
        )
    stalled_ranks = [r for r in sorted(fleet) if ages.get(r, 0.0) >= stall_s]
    if stalled_ranks:
        lines.append(
            "stalled rank(s): "
            + ", ".join(map(str, stalled_ranks))
            + f" (no heartbeat progress for >= {stall_s:.1f}s)"
        )
    return "\n".join(lines)
