"""Live OpenMetrics exporter: a stdlib-only HTTP endpoint Prometheus can
scrape while a take/restore is IN FLIGHT.

``stats --openmetrics`` exposes a finished take's persisted summary;
this module is the live complement — the same exposition format served
from the process's CURRENT telemetry state: counters, gauges, the
latency histograms, and the health-plane heartbeat fields (phase, bytes,
binding resource), so a dashboard shows a fleet mid-save instead of only
post-hoc summaries.

Off by default. ``TORCHSNAPSHOT_TPU_METRICS_PORT=<port>`` arms it: the
first operation to begin (Snapshot.take/async_take/restore call
:func:`maybe_start`) binds the port and serves ``GET /metrics`` from a
daemon thread for the life of the process. Port ``0`` binds an ephemeral
port (tests; :attr:`MetricsExporter.port` reports the real one).

Design rules:

- **Stdlib only** (``http.server``): the exporter must not add a
  dependency, and must import cleanly in hermetic containers.
- **Read-only and lock-light.** A scrape snapshots the bus under its
  existing lock (the same ``counters()``/``gauges()``/``histograms()``
  surface every consumer uses) — it can never block the pipeline beyond
  one dict copy.
- **One formatter.** Histogram families render through
  ``export.om_histogram_lines`` — the exact code path ``stats
  --openmetrics`` uses — so the live and post-hoc expositions cannot
  drift apart.
- **Never fails the op.** ``maybe_start`` swallows bind errors with a
  log line: a port collision must not take down a training job.
"""

from __future__ import annotations

import logging
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional

from . import core, health
from .export import (
    _om_label_str,
    om_family_name,
    om_histogram_lines,
)

logger = logging.getLogger(__name__)

METRICS_PORT_ENV_VAR = "TORCHSNAPSHOT_TPU_METRICS_PORT"

CONTENT_TYPE = "application/openmetrics-text; version=1.0.0; charset=utf-8"

#: Heartbeat fields exported as numeric gauges (the rest — op, phase,
#: binding — are strings and ride the info-style sample's labels).
_HEARTBEAT_NUMERIC = (
    "step",
    "total_entries",
    "done_entries",
    "inflight_io",
    "staged_bytes",
    "written_bytes",
    "read_bytes",
    "total_bytes",
    "georep_lag_s",
    "georep_backlog",
)


def render_live(rank: Optional[int] = None) -> str:
    """The current process's telemetry state as one OpenMetrics
    exposition: counter/gauge/histogram families from the bus plus the
    health-plane heartbeat state. Valid (ends in ``# EOF``) even with
    the bus disabled and empty — a scrape between ops is normal."""
    labels: Dict[str, Any] = {}
    if rank is not None:
        labels["rank"] = rank
    lines = []
    for name, value in sorted(core.counters().items()):
        family = om_family_name(name)
        lines.append(f"# TYPE {family} counter")
        lines.append(f"{family}_total{_om_label_str(labels)} {value:g}")
    for name, value in sorted(core.gauges().items()):
        family = om_family_name(name)
        lines.append(f"# TYPE {family} gauge")
        lines.append(f"{family}{_om_label_str(labels)} {value:g}")
    for name, by_key in sorted(core.histograms().items()):
        lines.extend(om_histogram_lines(name, by_key, extra_labels=labels))
    state = health.current_state()
    if state:
        info_labels = dict(labels)
        for key in ("op", "phase", "binding"):
            if state.get(key) is not None:
                info_labels[key] = state[key]
        family = om_family_name("heartbeat")
        lines.append(f"# TYPE {family} gauge")
        lines.append(f"{family}{_om_label_str(info_labels)} 1")
        for key in _HEARTBEAT_NUMERIC:
            value = state.get(key)
            if isinstance(value, (int, float)):
                family = om_family_name(f"heartbeat_{key}")
                lines.append(f"# TYPE {family} gauge")
                lines.append(f"{family}{_om_label_str(labels)} {value:g}")
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


class _Handler(BaseHTTPRequestHandler):
    server_version = "torchsnapshot-tpu-metrics"

    def do_GET(self) -> None:  # noqa: N802 - http.server API
        if self.path.split("?", 1)[0] not in ("/metrics", "/"):
            self.send_error(404)
            return
        try:
            body = render_live(rank=self.server._tsnap_rank).encode("utf-8")
        except Exception:  # noqa: BLE001 - a scrape must never crash
            logger.exception("metrics render failed")
            self.send_error(500)
            return
        self.send_response(200)
        self.send_header("Content-Type", CONTENT_TYPE)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt: str, *args: Any) -> None:
        logger.debug("metrics scrape: " + fmt, *args)


class MetricsExporter:
    """A running /metrics endpoint. Created via :func:`start_exporter`
    (or :func:`maybe_start` from the env gate); ``port`` is the bound
    port (meaningful with an ephemeral port request), ``stop()`` shuts
    the server down (tests — production exporters live as long as the
    process)."""

    def __init__(self, port: int, rank: Optional[int] = None) -> None:
        self._server = ThreadingHTTPServer(("", port), _Handler)
        self._server.daemon_threads = True
        self._server._tsnap_rank = rank
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="tsnap-metrics",
            daemon=True,
        )
        self._thread.start()

    def set_rank(self, rank: Optional[int]) -> None:
        self._server._tsnap_rank = rank

    def stop(self) -> None:
        try:
            self._server.shutdown()
            self._server.server_close()
        except Exception:  # noqa: BLE001
            pass
        self._thread.join(timeout=5.0)


_exporter: Optional[MetricsExporter] = None
_exporter_lock = threading.Lock()


def start_exporter(port: int, rank: Optional[int] = None) -> MetricsExporter:
    """Start (or return the already-running) exporter. Raises OSError on
    a bind failure — callers that must not fail go through
    :func:`maybe_start`."""
    global _exporter
    with _exporter_lock:
        if _exporter is None:
            _exporter = MetricsExporter(port, rank=rank)
            logger.info(
                "live metrics exporter serving on :%d/metrics", _exporter.port
            )
        elif rank is not None:
            _exporter.set_rank(rank)
        return _exporter


def active_exporter() -> Optional[MetricsExporter]:
    return _exporter


def stop_exporter() -> None:
    """Tear the exporter down (tests)."""
    global _exporter
    with _exporter_lock:
        if _exporter is not None:
            _exporter.stop()
            _exporter = None


def maybe_start(rank: Optional[int] = None) -> Optional[MetricsExporter]:
    """Env-gated idempotent start, called at op begin: no env var (the
    default) means no listener, no thread, no port; a malformed value or
    bind failure logs and returns None — observability never fails the
    operation."""
    raw = os.environ.get(METRICS_PORT_ENV_VAR, "").strip()
    if not raw:
        return None
    try:
        port = int(raw)
    except ValueError:
        logger.warning("ignoring non-integer %s=%r", METRICS_PORT_ENV_VAR, raw)
        return None
    if port < 0:
        return None
    try:
        return start_exporter(port, rank=rank)
    except OSError:
        logger.exception(
            "live metrics exporter failed to bind port %d (continuing)", port
        )
        return None
