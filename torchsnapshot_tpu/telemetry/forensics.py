"""Stall forensics: an always-on hang watchdog that answers the one
question the health plane cannot — *what is that rank executing right
now?*

The flight recorder (flightrec.py) explains an abort after it happened;
the health plane (health.py) flags a rank whose progress fingerprint
froze. Neither can see INSIDE the wedge: a rank blocked in a collective,
a storage op stuck behind a throttled device, a lock ordering bug — on a
real fleet these stall everything until the 1800 s barrier deadline
turns a hang into an abort, and the post-mortem holds event *names* but
no stacks. This module closes that gap with a per-op watchdog thread
(armed alongside the heartbeat publisher, default on like the flight
recorder; ``TORCHSNAPSHOT_TPU_FORENSICS=0`` disables) that samples
``sys._current_frames()`` on a low cadence, folds the samples into a
collapsed-stack (flame-format) profile, and maps each thread's innermost
package frame onto the pinned critpath taxonomy
(:data:`..telemetry.critpath.CATEGORIES`) — so a dump says "wedged in
``collective_wait`` at ``pg_wrapper.py:wait``", not just a raw
traceback.

Three trigger classes:

1. **Self-triggered.** A collective past a fraction of its bounded
   deadline (``TORCHSNAPSHOT_TPU_FORENSICS_DEADLINE_FRAC``, default
   0.5 — the hook is ``collective_begin``/``collective_end`` from
   ``PGWrapper._recorded``), a storage op exceeding ``k×`` its own
   recent p99 (the watchdog keeps its own duration ring per op kind —
   the telemetry histograms are off by default, so it cannot lean on
   them), or a frozen local progress fingerprint (the health plane's
   staleness rule, applied to this rank's own ``health.current_state``;
   ``TORCHSNAPSHOT_TPU_FORENSICS_STALL_S``, default 30). A trigger
   records a ``forensic.dump`` flight event and appends one stack dump
   to ``<snapshot>/.flight/rank_<r>.stacks.jsonl`` (same spool-dir
   resolution as the flight ring for remote snapshot paths).
2. **Remote-requested.** ``watch --dump <rank>`` sets
   ``tsnap/forensic/<rank>`` through the replicated store; the watchdog
   polls the key on its CLONED store connection (the primary blocks for
   whole collectives — exactly the thing being diagnosed), dumps, and
   publishes a compact summary under ``tsnap/forensic_out/<rank>`` that
   ``watch`` renders inline on the rank's row.
3. **On-abort.** Every path that dumps the flight ring also dumps
   stacks (the hook lives in ``flightrec.dump``), so a blackbox wreck
   always carries both the event timeline and the final stacks.

``blackbox`` merges the stack dumps into its causal timeline: DESERTION
findings name who never arrived *and* what the waiters were executing,
and a WEDGE finding fires when >= 2 consecutive dumps from one rank
share an identical non-idle leaf frame — the signature of a true hang
rather than slow progress.

Design rules (the flightrec lineage): strictly stdlib, never raises
into the op, one flag check when disabled, and all measurement on the
blessed ``core.monotonic`` clock (the timing lint covers this file).
"""

from __future__ import annotations

import collections
import itertools
import json
import logging
import os
import sys
import threading
from contextlib import contextmanager
from typing import Any, Dict, List, Optional, Tuple

from . import flightrec
from .core import monotonic

logger = logging.getLogger(__name__)

FORENSICS_ENV_VAR = "TORCHSNAPSHOT_TPU_FORENSICS"
SAMPLE_ENV_VAR = "TORCHSNAPSHOT_TPU_FORENSICS_SAMPLE_S"
DEADLINE_FRAC_ENV_VAR = "TORCHSNAPSHOT_TPU_FORENSICS_DEADLINE_FRAC"
STALL_ENV_VAR = "TORCHSNAPSHOT_TPU_FORENSICS_STALL_S"

_DEFAULT_SAMPLE_S = 0.5
_DEFAULT_DEADLINE_FRAC = 0.5
_DEFAULT_STALL_S = 30.0

#: Storage-op trigger: in-flight duration must exceed k x the op kind's
#: own recent p99 (with an absolute floor) before the watchdog calls it
#: wedged. Fixed, not an env knob: the p99 baseline already adapts to
#: the deployment's real latency distribution.
P99_MULTIPLIER = 4.0
P99_FLOOR_S = 1.0
#: Before the duration ring holds enough history for a meaningful p99,
#: only a grossly-overdue op (past this many seconds) triggers.
NO_HISTORY_FLOOR_S = 30.0
_MIN_P99_SAMPLES = 16
_DURATION_RING = 256

#: Remote-request store keys. Fixed namespace, like the heartbeat
#: prefix: the watcher needs no handshake.
FORENSIC_REQ_PREFIX = "tsnap/forensic/"
FORENSIC_OUT_PREFIX = "tsnap/forensic_out/"

#: Per-watchdog bound on self-triggered dumps: a rank wedged for an hour
#: must not grow an unbounded stacks file (remote requests and abort
#: dumps are operator-paced and do not count against it).
MAX_SELF_DUMPS = 32
#: Per-thread stack depth kept in a dump record.
MAX_FRAMES = 40
#: Distinct folded stacks kept in the collapsed profile.
MAX_PROFILE_STACKS = 512


def _env_enabled() -> bool:
    # Always-on is the point: anything but an explicit off-value enables.
    raw = os.environ.get(FORENSICS_ENV_VAR, "").strip().lower()
    return raw not in ("0", "off", "false", "no", "never")


def _env_float(var: str, default: float, minimum: float = 0.0) -> float:
    raw = os.environ.get(var, "").strip()
    try:
        return max(minimum, float(raw)) if raw else default
    except ValueError:
        return default


def sample_cadence_s() -> float:
    return _env_float(SAMPLE_ENV_VAR, _DEFAULT_SAMPLE_S, minimum=0.05)


def deadline_fraction() -> float:
    return _env_float(DEADLINE_FRAC_ENV_VAR, _DEFAULT_DEADLINE_FRAC,
                      minimum=0.05)


def stall_window_s() -> float:
    return _env_float(STALL_ENV_VAR, _DEFAULT_STALL_S, minimum=0.1)


_enabled: bool = _env_enabled()


def enabled() -> bool:
    return _enabled


def set_enabled(value: bool) -> None:
    """Programmatic override of the env gate (tests, bench trials)."""
    global _enabled
    _enabled = bool(value)


def refresh_from_env() -> bool:
    """Re-read the enable flag (subprocess workers that mutate
    os.environ after import call this, flightrec-style)."""
    global _enabled
    _enabled = _env_enabled()
    return _enabled


# ------------------------------------------------------ stack sampling

_PKG_FRAGMENT = os.sep + "torchsnapshot_tpu" + os.sep

#: Package modules that OBSERVE the pipeline rather than being it: a
#: thread whose only package frames are here is idle plumbing, and a
#: wedged thread's innermost attribution frame must never land on them.
#: faultinject.py is listed because an injected delay executes inside
#: the injector while SIMULATING a slow call at the wired site — the
#: site's frame (one above the injector) is the honest attribution.
_OBSERVER_FRAGMENTS = (
    os.path.join("telemetry", ""),
    "faultinject.py",
    "test_utils.py",
)

#: Module -> critpath category, matched on the package-relative path.
#: Targets the PINNED taxonomy (critpath.CATEGORIES) so forensics,
#: `explain`, and the fleet merges all speak the same nine words.
_CATEGORY_TABLE: Tuple[Tuple[str, str], ...] = (
    ("pg_wrapper.py", "collective_wait"),
    ("dist_store.py", "collective_wait"),
    ("native_io.py", "native_io"),
    ("fanout.py", "peer_transfer"),
    ("reshard.py", "peer_transfer"),
    ("serialization.py", "stage_copy"),
    ("memoryview_stream.py", "stage_copy"),
    (os.path.join("io_preparers", ""), "stage_copy"),
    ("integrity.py", "hash"),
    ("device_digest.py", "hash"),
    ("compression.py", "decode"),
    ("partial_reader.py", "storage_read"),
)

#: Function-name hints that split storage_plugins/* frames into the
#: read vs write lanes of the taxonomy.
_READ_HINTS = ("read", "get", "download", "recv")


def _rel_frame(filename: str) -> Optional[str]:
    """Package-relative path for a package frame, else None."""
    idx = filename.rfind(_PKG_FRAGMENT)
    if idx < 0:
        return None
    return filename[idx + len(_PKG_FRAGMENT):]


def format_frame(filename: str, func: str, lineno: int) -> str:
    rel = _rel_frame(filename)
    return f"{rel or os.path.basename(filename)}:{func}:{lineno}"


def classify_frames(
    frames: List[Tuple[str, str, int]],
) -> Tuple[Optional[str], Optional[str]]:
    """Map one thread's stack (root -> leaf ``(filename, func, lineno)``
    triples) onto the critpath taxonomy.

    Returns ``(category, frame)`` where ``frame`` is the innermost
    NON-OBSERVER package frame formatted ``relpath:func:lineno`` and
    ``category`` is its critpath lane — or ``(None, None)`` for an idle
    thread (no package frame outside the observer modules)."""
    for filename, func, lineno in reversed(frames):
        rel = _rel_frame(filename)
        if rel is None:
            continue
        if any(frag in rel for frag in _OBSERVER_FRAGMENTS):
            continue
        fmt = f"{rel}:{func}:{lineno}"
        if rel.startswith("storage_plugins" + os.sep) or rel == "storage_plugin.py":
            lowered = func.lower()
            if any(h in lowered for h in _READ_HINTS):
                return "storage_read", fmt
            return "storage_write", fmt
        for fragment, category in _CATEGORY_TABLE:
            if rel.startswith(fragment) or rel == fragment:
                return category, fmt
        # A package frame with no mapping: real work the taxonomy does
        # not itemize — attribute like critpath does (uncovered wall).
        return "sched_idle", fmt
    return None, None


def sample_stacks() -> List[Dict[str, Any]]:
    """One sample of every thread's stack: name, daemon flag, frames
    (root -> leaf), the categorized innermost package frame, and the
    idle verdict. The sampler's own thread is included but classifies
    idle (its package frames are all observer modules), so it can never
    be blamed as the wedge."""
    frames_by_ident = sys._current_frames()
    meta = {t.ident: t for t in threading.enumerate()}
    out: List[Dict[str, Any]] = []
    for ident, frame in frames_by_ident.items():
        raw: List[Tuple[str, str, int]] = []
        f = frame
        while f is not None:
            code = f.f_code
            raw.append((code.co_filename, code.co_name, f.f_lineno))
            f = f.f_back
        raw.reverse()
        category, leaf = classify_frames(raw)
        thread = meta.get(ident)
        out.append({
            "name": thread.name if thread is not None else f"ident-{ident}",
            "daemon": bool(thread.daemon) if thread is not None else True,
            "idle": category is None,
            "category": category,
            "leaf": leaf,
            "frames": [format_frame(*t) for t in raw[-MAX_FRAMES:]],
        })
    out.sort(key=lambda t: (t["idle"], t["name"]))
    return out


def fold_into(profile: Dict[str, int], threads: List[Dict[str, Any]]) -> None:
    """Fold one sample into a collapsed-stack (flame-format) profile:
    ``thread;frame;frame;...`` (root -> leaf) -> sample count. Bounded:
    past :data:`MAX_PROFILE_STACKS` distinct stacks the rarest are
    evicted (the wedge, by definition, is the commonest stack)."""
    for t in threads:
        key = ";".join([t["name"], *t["frames"]])
        profile[key] = profile.get(key, 0) + 1
    if len(profile) > MAX_PROFILE_STACKS:
        keep = sorted(profile.items(), key=lambda kv: -kv[1])
        profile.clear()
        profile.update(keep[:MAX_PROFILE_STACKS // 2])


def pick_wedge(
    threads: List[Dict[str, Any]], prefer: Optional[str] = None
) -> Optional[Dict[str, Any]]:
    """The thread a dump blames: prefer the trigger's category, then any
    non-idle thread with a real (non-sched_idle) lane, then any non-idle
    thread at all."""
    candidates = [t for t in threads if not t["idle"]]
    if not candidates:
        return None
    if prefer is not None:
        for t in candidates:
            if t["category"] == prefer or (
                prefer == "storage" and str(t["category"]).startswith("storage")
            ):
                return t
    for t in candidates:
        if t["category"] != "sched_idle":
            return t
    return candidates[0]


# --------------------------------------------------- trigger registries
#
# Shared module state, flightrec-style: the pipeline layers notify cheap
# facts (a collective began, a storage op finished in N seconds) and the
# watchdog evaluates them on its own thread. All writers take one short
# lock; the disabled path is a single flag check.

_reg_lock = threading.Lock()
_collectives: Dict[Tuple[Any, Any], Dict[str, Any]] = {}
_storage_inflight: Dict[int, Dict[str, Any]] = {}
_storage_durations: Dict[str, "collections.deque"] = {}
_storage_token = itertools.count(1)


def collective_begin(
    kind: str, ns: Any, cseq: Any, deadline_s: Optional[float]
) -> None:
    """A collective entered on this rank (PGWrapper._recorded). The
    deadline is the EFFECTIVE one — the collective's own bound or the
    store's barrier timeout — so the watchdog's fraction rule always has
    a denominator."""
    if not _enabled:
        return
    with _reg_lock:
        _collectives[(ns, cseq)] = {
            "kind": kind, "t0": monotonic(), "deadline_s": deadline_s,
        }


def collective_end(ns: Any, cseq: Any) -> None:
    if not _enabled:
        return
    with _reg_lock:
        _collectives.pop((ns, cseq), None)


@contextmanager
def storage_op(kind: str, path: Optional[str] = None):
    """Always-on guard around one storage operation (scheduler write /
    read sites): registers the op in flight and, on exit, feeds its
    duration into the per-kind ring the p99 trigger baselines on. One
    dict insert + remove; no I/O."""
    if not _enabled:
        yield
        return
    token = next(_storage_token)
    t0 = monotonic()
    with _reg_lock:
        _storage_inflight[token] = {"kind": kind, "t0": t0, "path": path}
    try:
        yield
    finally:
        dur = monotonic() - t0
        with _reg_lock:
            _storage_inflight.pop(token, None)
            ring = _storage_durations.get(kind)
            if ring is None:
                ring = _storage_durations[kind] = collections.deque(
                    maxlen=_DURATION_RING
                )
            ring.append(dur)


def _p99(kind: str) -> Optional[float]:
    ring = _storage_durations.get(kind)
    if ring is None or len(ring) < _MIN_P99_SAMPLES:
        return None
    ordered = sorted(ring)
    return ordered[int(0.99 * (len(ordered) - 1))]


def collectives_overdue(now: float, fraction: float) -> List[Dict[str, Any]]:
    """Collectives past ``fraction`` of their effective deadline."""
    out = []
    with _reg_lock:
        items = list(_collectives.items())
    for (ns, cseq), rec in items:
        deadline = rec.get("deadline_s")
        if not deadline or deadline <= 0:
            continue
        waited = now - rec["t0"]
        if waited >= fraction * deadline:
            out.append({
                "kind": rec["kind"], "ns": ns, "cseq": cseq,
                "waited_s": round(waited, 3), "deadline_s": deadline,
            })
    return out


def storage_overdue(now: float) -> List[Dict[str, Any]]:
    """In-flight storage ops past ``max(k x own p99, floor)`` — or past
    the no-history floor when the ring is still warming up."""
    out = []
    with _reg_lock:
        items = list(_storage_inflight.values())
    for rec in items:
        p99 = _p99(rec["kind"])
        threshold = (
            max(P99_MULTIPLIER * p99, P99_FLOOR_S)
            if p99 is not None else NO_HISTORY_FLOOR_S
        )
        waited = now - rec["t0"]
        if waited >= threshold:
            out.append({
                "kind": rec["kind"], "path": rec.get("path"),
                "waited_s": round(waited, 3),
                "threshold_s": round(threshold, 3),
            })
    return out


def _reset_registries_for_tests() -> None:
    with _reg_lock:
        _collectives.clear()
        _storage_inflight.clear()
        _storage_durations.clear()


# ---------------------------------------------------------------- dumps

STACKS_SUFFIX = ".stacks.jsonl"

_dump_lock = threading.Lock()
_dump_seq = itertools.count(1)


def stacks_path_for_rank(rank: int) -> str:
    return f"{flightrec.FLIGHT_DIR}/rank_{rank}{STACKS_SUFFIX}"


def build_dump_record(
    rank: int,
    reason: str,
    trigger: str,
    threads: Optional[List[Dict[str, Any]]] = None,
    profile: Optional[Dict[str, int]] = None,
    prefer: Optional[str] = None,
) -> Dict[str, Any]:
    """One stack-dump record: the sampled threads, the collapsed profile
    accumulated so far, and the blamed wedge frame."""
    if threads is None:
        threads = sample_stacks()
    wedge = pick_wedge(threads, prefer=prefer)
    rec: Dict[str, Any] = {
        "seq": next(_dump_seq),
        "t": round(monotonic(), 6),
        "rank": rank,
        "reason": reason,
        "trigger": trigger,
        "threads": threads,
    }
    if profile:
        top = sorted(profile.items(), key=lambda kv: -kv[1])[:40]
        rec["profile"] = dict(top)
    if wedge is not None:
        rec["wedge"] = {
            "thread": wedge["name"],
            "frame": wedge["leaf"],
            "category": wedge["category"],
        }
    return rec


def dump_stacks(
    path: Optional[str],
    rank: int,
    reason: str,
    trigger: str = "abort",
    threads: Optional[List[Dict[str, Any]]] = None,
    profile: Optional[Dict[str, int]] = None,
    prefer: Optional[str] = None,
) -> Optional[str]:
    """Append one stack dump to ``<path>/.flight/rank_<rank>.stacks.jsonl``.

    NEVER raises (abort paths call this mid-unwind); returns the file
    written or None. Appending (unlike the flight ring's overwrite) is
    the point: the WEDGE finding needs CONSECUTIVE dumps to compare."""
    if not _enabled:
        return None
    try:
        base = flightrec._resolve_dump_dir(path)
        if base is None:
            return None
        rec = build_dump_record(
            rank, reason, trigger, threads=threads, profile=profile,
            prefer=prefer,
        )
        out = os.path.join(
            base, flightrec.FLIGHT_DIR, f"rank_{rank}{STACKS_SUFFIX}"
        )
        with _dump_lock:
            os.makedirs(os.path.dirname(out), exist_ok=True)
            with open(out, "a") as f:
                f.write(json.dumps(rec, default=repr) + "\n")
        logger.warning(
            "stall forensics: dumped %d thread stack(s) to %s (%s: %s)",
            len(rec["threads"]), out, trigger, reason,
        )
        return out
    except Exception:  # noqa: BLE001 - a dump must never mask the abort
        logger.exception("forensic stack dump failed (continuing)")
        return None


# ------------------------------------------------------------- watchdog


class Watchdog:
    """Per-op watchdog: samples stacks on a cadence, folds the collapsed
    profile, evaluates the self-triggers, and answers remote dump
    requests on a cloned store connection. Armed by :func:`arm`
    alongside the heartbeat publisher; stopped in the op's finally."""

    def __init__(
        self,
        rank: int,
        op: str,
        path: Optional[str],
        store: Any = None,
        cadence_s: Optional[float] = None,
    ) -> None:
        self.rank = rank
        self.op = op
        self.path = path
        self.cadence_s = cadence_s if cadence_s is not None else sample_cadence_s()
        self._fraction = deadline_fraction()
        self._stall_s = stall_window_s()
        self._store = None
        if store is not None:
            try:
                # A cloned connection, like the heartbeat publisher: the
                # primary blocks under the client lock for whole
                # collectives — the very hang being diagnosed.
                self._store = store.clone()
            except Exception:  # noqa: BLE001 - store is optional
                self._store = None
        self._stop = threading.Event()
        self._profile: Dict[str, int] = {}
        self._fp: Optional[tuple] = None
        self._fp_changed_t = monotonic()
        self._last_dump_t: Optional[float] = None
        self._self_dumps = 0
        self._published = False
        self._thread = threading.Thread(
            target=self._loop, name="tsnap-forensics", daemon=True
        )

    def start(self) -> "Watchdog":
        self._thread.start()
        return self

    def stop(self) -> None:
        """Bounded, like the heartbeat's: a watchdog wedged in a dead
        store's RPC must not block the op's exit."""
        self._stop.set()
        try:
            self._thread.join(timeout=self.cadence_s + 5.0)
        except Exception:  # noqa: BLE001
            pass

    # -- the sampling loop -------------------------------------------

    def _loop(self) -> None:
        while not self._stop.wait(self.cadence_s):
            try:
                self._tick()
            except Exception:  # noqa: BLE001 - observability never raises
                logger.debug("forensics tick failed", exc_info=True)
        # Retraction on the watchdog's own thread, strictly after its
        # last publish (the heartbeat's ghost-key rule).
        if self._store is not None:
            if self._published:
                try:
                    self._store.delete(f"{FORENSIC_OUT_PREFIX}{self.rank}")
                except Exception:  # noqa: BLE001
                    pass
            try:
                self._store.close()
            except Exception:  # noqa: BLE001
                pass

    def _tick(self) -> None:
        now = monotonic()
        threads = sample_stacks()
        fold_into(self._profile, threads)
        self._poll_remote(threads)
        trigger = self._evaluate(now)
        if trigger is None:
            return
        name, reason, prefer = trigger
        if self._self_dumps >= MAX_SELF_DUMPS:
            return
        # Cooldown: keep dumping while the condition persists (WEDGE
        # needs consecutive dumps) but never more than ~1/cooldown Hz.
        cooldown = max(2.0 * self.cadence_s, 1.0)
        if self._last_dump_t is not None and now - self._last_dump_t < cooldown:
            return
        self._last_dump_t = now
        self._self_dumps += 1
        flightrec.record(
            "forensic.dump", rank=self.rank, trigger=name, reason=reason
        )
        dumped = dump_stacks(
            self.path, self.rank, reason, trigger=name, threads=threads,
            profile=self._profile, prefer=prefer,
        )
        if dumped is not None:
            self._publish(threads, name, reason, prefer)

    # -- triggers ----------------------------------------------------

    def _evaluate(self, now: float) -> Optional[Tuple[str, str, Optional[str]]]:
        overdue = collectives_overdue(now, self._fraction)
        if overdue:
            c = max(overdue, key=lambda r: r["waited_s"])
            return (
                "collective-deadline",
                f"{c['kind']} #{c['cseq']} [{c['ns']}] waited "
                f"{c['waited_s']:.1f}s of a {c['deadline_s']:.0f}s deadline",
                "collective_wait",
            )
        slow = storage_overdue(now)
        if slow:
            s = max(slow, key=lambda r: r["waited_s"])
            return (
                "storage-p99",
                f"{s['kind']} in flight {s['waited_s']:.1f}s "
                f"(threshold {s['threshold_s']:.1f}s"
                + (f", path {s['path']}" if s.get("path") else "")
                + ")",
                "storage",
            )
        # Frozen progress fingerprint: the health plane's staleness rule
        # applied to this rank's OWN state — no watcher needed.
        from . import health

        state = health.current_state()
        fp = health._progress_fingerprint(state) if state else None
        if fp != self._fp:
            self._fp = fp
            self._fp_changed_t = now
            return None
        if fp is not None and now - self._fp_changed_t >= self._stall_s:
            frozen_for = now - self._fp_changed_t
            return (
                "frozen-progress",
                f"progress fingerprint frozen {frozen_for:.1f}s "
                f"(phase {state.get('phase')!r})",
                None,
            )
        return None

    # -- remote requests ---------------------------------------------

    def _poll_remote(self, threads: List[Dict[str, Any]]) -> None:
        if self._store is None:
            return
        req_key = f"{FORENSIC_REQ_PREFIX}{self.rank}"
        try:
            if not self._store.check(req_key):
                return
            self._store.delete(req_key)
        except Exception:  # noqa: BLE001 - the op outranks its telemetry
            logger.debug("forensic request poll skipped", exc_info=True)
            return
        reason = "remote dump request"
        flightrec.record(
            "forensic.dump", rank=self.rank, trigger="remote", reason=reason
        )
        dump_stacks(
            self.path, self.rank, reason, trigger="remote", threads=threads,
            profile=self._profile,
        )
        self._publish(threads, "remote", reason, None)

    def _publish(
        self,
        threads: List[Dict[str, Any]],
        trigger: str,
        reason: str,
        prefer: Optional[str],
    ) -> None:
        """Publish a compact summary under ``tsnap/forensic_out/<rank>``
        so ``watch`` can render the wedged frame inline."""
        if self._store is None:
            return
        wedge = pick_wedge(threads, prefer=prefer)
        payload = {
            "rank": self.rank,
            "op": self.op,
            "trigger": trigger,
            "reason": reason,
            "threads": len(threads),
        }
        if wedge is not None:
            payload["wedge"] = f"{wedge['category']} @ {wedge['leaf']}"
            payload["thread"] = wedge["name"]
        try:
            self._store.set(
                f"{FORENSIC_OUT_PREFIX}{self.rank}",
                json.dumps(payload, default=repr).encode("utf-8"),
            )
            self._published = True
        except Exception:  # noqa: BLE001
            logger.debug("forensic publish skipped", exc_info=True)


def arm(pg_wrapper: Any, op: str, path: Optional[str]) -> Optional[Watchdog]:
    """Arm the watchdog for one operation (called next to
    ``health.maybe_start``), or None when forensics is disabled. Unlike
    the heartbeat, single-process ops still arm — the self-triggers and
    abort dumps are rank-local; only the remote-request channel needs
    the store."""
    if not _enabled:
        return None
    try:
        rank = pg_wrapper.get_rank()
        store = None
        if pg_wrapper.get_world_size() > 1:
            pg = getattr(pg_wrapper, "pg", None)
            store = getattr(pg, "store", None)
        return Watchdog(rank, op, path, store=store).start()
    except Exception:  # noqa: BLE001 - observability never fails the op
        logger.debug("forensic watchdog failed to arm", exc_info=True)
        return None


# ------------------------------------------------ blackbox integration


def load_stack_dumps(path: str) -> Dict[int, List[Dict[str, Any]]]:
    """Parse ``<path>/.flight/rank_*.stacks.jsonl`` into
    ``{rank: [records]}``, oldest dump first. Torn trailing lines are
    skipped, exactly like the flight-ring loader."""
    flight_dir = os.path.join(path, flightrec.FLIGHT_DIR)
    out: Dict[int, List[Dict[str, Any]]] = {}
    if not os.path.isdir(flight_dir):
        return out
    for fname in sorted(os.listdir(flight_dir)):
        if not (fname.startswith("rank_") and fname.endswith(STACKS_SUFFIX)):
            continue
        try:
            rank = int(fname[len("rank_"):-len(STACKS_SUFFIX)])
        except ValueError:
            continue
        records: List[Dict[str, Any]] = []
        with open(os.path.join(flight_dir, fname)) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict) and "threads" in rec:
                    records.append(rec)
        if records:
            out[rank] = records
    return out


def _nonidle_leaves(rec: Dict[str, Any]) -> Dict[str, Tuple[str, str]]:
    """{thread name: (leaf frame, category)} for one dump's non-idle
    threads."""
    out: Dict[str, Tuple[str, str]] = {}
    for t in rec.get("threads") or []:
        if t.get("idle") or not t.get("leaf"):
            continue
        out[str(t.get("name"))] = (str(t["leaf"]), str(t.get("category")))
    return out


def derive_wedge_findings(
    stacks: Dict[int, List[Dict[str, Any]]],
) -> List[Dict[str, Any]]:
    """WEDGE: >= 2 CONSECUTIVE dumps from one rank share an identical
    non-idle leaf frame — slow progress moves its leaf between dumps; a
    true hang does not. One finding per (rank, thread, frame) streak,
    counting the dumps that agreed."""
    findings: List[Dict[str, Any]] = []
    for rank in sorted(stacks):
        records = stacks[rank]
        prev: Dict[str, Tuple[str, str]] = {}
        run: Dict[str, int] = {}
        best: Dict[Tuple[str, str], Tuple[int, str]] = {}
        for rec in records:
            leaves = _nonidle_leaves(rec)
            new_run: Dict[str, int] = {}
            for name, (leaf, category) in leaves.items():
                same = prev.get(name, (None, None))[0] == leaf
                new_run[name] = run.get(name, 1) + 1 if same else 1
                if new_run[name] >= 2:
                    key = (name, leaf)
                    cur = best.get(key)
                    if cur is None or new_run[name] > cur[0]:
                        best[key] = (new_run[name], category)
            prev, run = leaves, new_run
        for (name, leaf), (count, category) in sorted(best.items()):
            findings.append({
                "class": "wedge",
                "rank": rank,
                "thread": name,
                "frame": leaf,
                "category": category,
                "dumps": count,
            })
    return findings


def latest_wedge(stacks: Dict[int, List[Dict[str, Any]]], rank: int) -> Optional[str]:
    """``category @ frame`` from the rank's most recent dump, if any."""
    records = stacks.get(rank) or []
    for rec in reversed(records):
        wedge = rec.get("wedge")
        if isinstance(wedge, dict) and wedge.get("frame"):
            return f"{wedge.get('category')} @ {wedge['frame']}"
        leaves = _nonidle_leaves(rec)
        if leaves:
            name = sorted(leaves)[0]
            leaf, category = leaves[name]
            return f"{category} @ {leaf}"
    return None


def merge_stack_findings(
    merged: Dict[str, Any], stacks: Dict[int, List[Dict[str, Any]]]
) -> Dict[str, Any]:
    """Fold stack dumps into a ``merge_timeline`` result: append WEDGE
    findings and annotate DESERTION findings with what the waiting /
    stuck ranks were executing (``frames``: {rank: "category @ frame"}).
    Mutates and returns ``merged``; a no-op without stack dumps."""
    if not stacks:
        return merged
    merged["stack_ranks"] = sorted(stacks)
    merged["stack_dumps"] = {r: len(v) for r, v in stacks.items()}
    findings = merged.setdefault("findings", [])
    for f in findings:
        if f.get("class") not in ("desertion", "collective-error"):
            continue
        frames: Dict[int, str] = {}
        for rank in itertools.chain(f.get("stuck") or [], f.get("entered") or []):
            if rank in frames:
                continue
            wedge = latest_wedge(stacks, rank)
            if wedge is not None:
                frames[rank] = wedge
        if frames:
            f["frames"] = frames
    findings.extend(derive_wedge_findings(stacks))
    return merged
