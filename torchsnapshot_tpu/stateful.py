"""The Stateful protocol: what can be checkpointed (reference: stateful.py:13-23).

Anything exposing ``state_dict()``/``load_state_dict()`` participates in an
app state. For JAX the canonical unit of state is a pytree; ``StateDict``
adapts a raw pytree into a Stateful.
"""

from __future__ import annotations

from typing import Any, Dict, Protocol, runtime_checkable


@runtime_checkable
class Stateful(Protocol):
    def state_dict(self) -> Dict[str, Any]:
        ...

    def load_state_dict(self, state_dict: Dict[str, Any]) -> None:
        ...


AppState = Dict[str, Stateful]
