"""Serving hot-reload: follow a training run's checkpoints, moving only
the bytes that changed.

A serving/eval process keeps model state resident on device and
periodically picks up the trainer's newest snapshot. With incremental
snapshots + device digests the reload cost scales with what CHANGED,
not with model size, on both ends:

- the trainer saves step N+1 incrementally against step N — unchanged
  payloads skip the DtoH transfer and the storage write entirely
  (fingerprinted on device, device_digest.py);
- the server restores step N+1 with ``device_digests=True`` — its
  resident arrays are fingerprinted on device against the snapshot's
  manifest, and only changed payloads are read and transferred HtoD.

Here the "trainer" freezes the backbone and trains a small adapter (the
LoRA pattern): each reload moves only the adapter's bytes while the
backbone — most of the model — never crosses the wire in either
direction after step 0.

Run: JAX_PLATFORMS=cpu python examples/serving_reload.py
"""

from __future__ import annotations

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The final act reshards across a 4-device mesh; give the CPU backend
# virtual devices BEFORE jax initializes (a plain JAX_PLATFORMS=cpu run
# has one device and would silently skip the demo's point).
if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=4"
    ).strip()

import jax

from _example_utils import force_cpu_if_requested

force_cpu_if_requested()

import jax.numpy as jnp
import numpy as np

from torchsnapshot_tpu import CheckpointManager, Snapshot, StateDict

BACKBONE = (512, 512)
ADAPTER = (512, 8)


def main() -> None:
    tmp = tempfile.mkdtemp(prefix="serving_reload_")
    root = os.path.join(tmp, "ckpt")

    # ---- trainer side -------------------------------------------------
    key = jax.random.PRNGKey(0)
    backbone = jax.random.normal(key, BACKBONE, jnp.bfloat16)  # frozen
    adapter = jnp.zeros(ADAPTER, jnp.float32)

    trainer = CheckpointManager(root, incremental=True, device_digests=True)

    def train_and_save(step: int, adapter):
        adapter = adapter + 0.01 * (step + 1)  # "training"
        trainer.save(
            step,
            {"model": StateDict(backbone=backbone, adapter=adapter)},
            force=True,
        )
        return adapter

    adapter = train_and_save(0, adapter)

    # ---- server side --------------------------------------------------
    # Resident state: restored once in full, then hot-reloaded.
    served = {
        "model": StateDict(
            backbone=jnp.zeros(BACKBONE, jnp.bfloat16),
            adapter=jnp.zeros(ADAPTER, jnp.float32),
        )
    }
    step = trainer.latest_step()
    Snapshot(trainer.path_for(step)).restore(served)
    print(f"server: cold restore of step {step} (full read)")

    # Count payload consumes to show exactly what later reloads move.
    from torchsnapshot_tpu.io_preparers.array import ArrayBufferConsumer

    reads = []
    orig = ArrayBufferConsumer._consume_sync

    def counting(self, buf):
        reads.append(self.entry.location)
        return orig(self, buf)

    ArrayBufferConsumer._consume_sync = counting
    try:
        for step in (1, 2, 3):
            adapter = train_and_save(step, adapter)
            reads.clear()
            Snapshot(trainer.path_for(step)).restore(served, device_digests=True)
            assert all("adapter" in loc for loc in reads), reads
            print(
                f"server: hot-reloaded step {step} — {len(reads)} payload(s) "
                f"moved ({', '.join(sorted(reads))}); backbone untouched"
            )
    finally:
        ArrayBufferConsumer._consume_sync = orig

    np.testing.assert_array_equal(
        np.asarray(served["model"]["adapter"]), np.asarray(adapter)
    )
    np.testing.assert_array_equal(
        np.asarray(served["model"]["backbone"]), np.asarray(backbone)
    )
    print("served state bit-exact with the trainer's latest. done.")

    # ---- serving mesh != training mesh --------------------------------
    # The skip survives a LAYOUT change: the server shards the model for
    # inference differently than the trainer saved it. Saved pieces are
    # fingerprinted against (re)assembled slices of the destination —
    # global slices on a fully-addressable host, stitched local shards in
    # multi-process pods (io_preparers/sharded.py:_dst_already_matches) —
    # so only the changed adapter moves even though every box differs.
    if len(jax.devices()) >= 4:
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        devs = np.array(jax.devices()[:4])
        train_mesh = Mesh(devs.reshape(2, 2), ("data", "model"))
        serve_mesh = Mesh(devs.reshape(4), ("model",))

        backbone_t = jax.device_put(
            backbone, NamedSharding(train_mesh, P("data", "model"))
        )
        adapter_t = jax.device_put(
            adapter, NamedSharding(train_mesh, P("model", None))
        )
        trainer.save(
            4,
            {"model": StateDict(backbone=backbone_t, adapter=adapter_t)},
            force=True,
        )

        served_sharded = {
            "model": StateDict(
                backbone=jax.device_put(
                    np.asarray(served["model"]["backbone"]),
                    NamedSharding(serve_mesh, P("model", None)),
                ),
                adapter=jax.device_put(
                    np.asarray(served["model"]["adapter"]) * 0,  # stale
                    NamedSharding(serve_mesh, P(None, "model")),
                ),
            )
        }
        from torchsnapshot_tpu.io_preparers.sharded import _ShardScatterConsumer

        sharded_reads = []
        orig_s = _ShardScatterConsumer._consume_sync

        def counting_s(self, buf):
            sharded_reads.append(self.shard.array.location)
            return orig_s(self, buf)

        _ShardScatterConsumer._consume_sync = counting_s
        try:
            Snapshot(trainer.path_for(4)).restore(
                served_sharded, device_digests=True
            )
        finally:
            _ShardScatterConsumer._consume_sync = orig_s
        assert all("adapter" in loc for loc in sharded_reads), sharded_reads
        np.testing.assert_array_equal(
            np.asarray(served_sharded["model"]["backbone"]), np.asarray(backbone)
        )
        np.testing.assert_array_equal(
            np.asarray(served_sharded["model"]["adapter"]), np.asarray(adapter)
        )
        print(
            "server (different mesh): reloaded step 4 — "
            f"{len(sharded_reads)} shard read(s), all adapter; backbone "
            "verified across the layout change without a byte moved"
        )


if __name__ == "__main__":
    main()
