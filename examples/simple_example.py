"""Minimal train/checkpoint/resume loop (reference: examples/simple_example.py).

Trains a tiny MLP with optax, snapshots every few steps (progress counter
in a StateDict), then simulates a restart: rebuilds fresh state, restores,
and continues from the saved step with a bit-exact parameter match.

Run: python examples/simple_example.py [--work-dir /tmp/snapshots]
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

from _example_utils import force_cpu_if_requested

force_cpu_if_requested()
import jax.numpy as jnp
import numpy as np
import optax

from torchsnapshot_tpu import RNGState, Snapshot, StateDict


def init_params(key):
    k1, k2 = jax.random.split(key)
    return {
        "w1": jax.random.normal(k1, (8, 16)) * 0.1,
        "w2": jax.random.normal(k2, (16, 1)) * 0.1,
    }


@jax.jit
def loss_fn(params, x, y):
    h = jnp.tanh(x @ params["w1"])
    pred = h @ params["w2"]
    return jnp.mean((pred - y) ** 2)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--work-dir", default=None)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--snapshot-every", type=int, default=5)
    args = ap.parse_args()
    work_dir = args.work_dir or tempfile.mkdtemp(prefix="simple_example_")

    tx = optax.adam(1e-2)
    params = init_params(jax.random.PRNGKey(0))
    opt_state = tx.init(params)
    progress = StateDict(step=0)
    grad_fn = jax.jit(jax.grad(loss_fn))

    x = jnp.asarray(np.random.default_rng(0).standard_normal((32, 8)))
    y = jnp.sum(x, axis=1, keepdims=True)

    app_state = {
        "model": StateDict(params=params),
        "optim": StateDict(state=opt_state),
        "progress": progress,
        "rng": RNGState(),
    }

    last_snapshot = None
    while progress["step"] < args.steps:
        grads = grad_fn(app_state["model"]["params"], x, y)
        updates, new_opt = tx.update(
            grads, app_state["optim"]["state"], app_state["model"]["params"]
        )
        app_state["model"]["params"] = optax.apply_updates(
            app_state["model"]["params"], updates
        )
        app_state["optim"]["state"] = new_opt
        progress["step"] += 1

        if progress["step"] % args.snapshot_every == 0:
            path = f"{work_dir}/step_{progress['step']}"
            # async_take returns once staging is done; training can resume
            # immediately while storage I/O completes in the background.
            pending = Snapshot.async_take(path, app_state)
            last_snapshot = (path, pending)
            print(f"step {progress['step']}: snapshot -> {path}")

    if last_snapshot is None or last_snapshot[0] != f"{work_dir}/step_{args.steps}":
        # Final step didn't land on the cadence — snapshot it synchronously
        # so the restart below always resumes from step == args.steps.
        # Drain the superseded async snapshot first: dropping its handle
        # would orphan in-flight I/O and swallow its errors.
        if last_snapshot is not None and last_snapshot[1] is not None:
            last_snapshot[1].wait()
        path = f"{work_dir}/step_{args.steps}"
        Snapshot.take(path, app_state)
        last_snapshot = (path, None)
        print(f"step {progress['step']}: final snapshot -> {path}")

    path, pending = last_snapshot
    if pending is not None:
        pending.wait()

    # ----- simulated restart: fresh state, restore, verify
    params_before = app_state["model"]["params"]
    restored = {
        "model": StateDict(params=init_params(jax.random.PRNGKey(42))),
        "optim": StateDict(state=tx.init(init_params(jax.random.PRNGKey(42)))),
        "progress": StateDict(step=0),
        "rng": RNGState(),
    }
    Snapshot(path).restore(restored)
    assert restored["progress"]["step"] == args.steps
    for a, b in zip(
        jax.tree.leaves(restored["model"]["params"]), jax.tree.leaves(params_before)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    print(f"resumed from step {restored['progress']['step']}: params bit-exact")


if __name__ == "__main__":
    main()
