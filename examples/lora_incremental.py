"""Frozen-backbone (LoRA-style) fine-tuning with incremental snapshots.

The dominant fine-tuning pattern: a large frozen backbone plus a small
trainable adapter. Incremental snapshots make checkpointing cost scale
with the TRAINABLE fraction — the backbone's bytes are written once, and
every later snapshot references them instead of rewriting them
(torchsnapshot_tpu/dedup.py). The chain is then consolidated into a
self-contained snapshot so the old checkpoints can be deleted, and a
restart restores from it bit-exactly.

Run: JAX_PLATFORMS=cpu python examples/lora_incremental.py
"""

from __future__ import annotations

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

from _example_utils import force_cpu_if_requested

force_cpu_if_requested()
import jax.numpy as jnp
import numpy as np
import optax

from torchsnapshot_tpu import Snapshot, StateDict


D_IN, D_HID, RANK = 64, 256, 4


def init_state(key):
    kb1, kb2, ka = jax.random.split(key, 3)
    backbone = {
        "w1": jax.random.normal(kb1, (D_IN, D_HID)) * 0.05,
        "w2": jax.random.normal(kb2, (D_HID, 1)) * 0.05,
    }
    adapter = {  # low-rank update to w1, LoRA-style
        "a": jax.random.normal(ka, (D_IN, RANK)) * 0.05,
        "b": jnp.zeros((RANK, D_HID)),
    }
    return backbone, adapter


@jax.jit
def loss_fn(backbone, adapter, x, y):
    w1 = backbone["w1"] + adapter["a"] @ adapter["b"]
    pred = jnp.tanh(x @ w1) @ backbone["w2"]
    return jnp.mean((pred - y) ** 2)


from functools import partial


@partial(jax.jit, static_argnames="tx_update")
def train_step(backbone, adapter, opt_state, x, y, tx_update):
    grads = jax.grad(loss_fn, argnums=1)(backbone, adapter, x, y)
    updates, opt_state = tx_update(grads, opt_state, adapter)
    return optax.apply_updates(adapter, updates), opt_state


def snap_bytes(path):
    return sum(
        os.path.getsize(os.path.join(r, f))
        for r, _, fs in os.walk(path)
        for f in fs
    )


def main() -> None:
    work = tempfile.mkdtemp(prefix="lora_snap_")
    key = jax.random.PRNGKey(0)
    backbone, adapter = init_state(key)
    tx = optax.adam(1e-2)
    opt_state = tx.init(adapter)
    x = jax.random.normal(jax.random.PRNGKey(1), (128, D_IN))
    y = jnp.sum(x[:, :4], axis=1, keepdims=True)

    def app_state(step):
        return {
            "backbone": StateDict(**backbone),  # frozen: identical each save
            "adapter": StateDict(**adapter),
            "opt": StateDict(state=opt_state),
            "progress": StateDict(step=step),
        }

    ckpts = []
    for step in range(30):
        adapter, opt_state = train_step(backbone, adapter, opt_state, x, y, tx.update)
        if (step + 1) % 10 == 0:
            path = os.path.join(work, f"step_{step + 1}")
            base = ckpts[-1] if ckpts else None
            # device_digests: the frozen backbone is detected unchanged ON
            # DEVICE, so on TPU it never even crosses to the host — the
            # dominant save cost for this workload (see device_digest.py).
            Snapshot.take(
                path,
                app_state(step + 1),
                incremental_base=base,
                record_digests=True,
                device_digests=True,
            )
            ckpts.append(path)
            kind = f"incremental on {os.path.basename(base)}" if base else "full"
            print(
                f"step {step + 1}: saved {os.path.basename(path)} "
                f"({kind}, {snap_bytes(path) / 1e3:.0f} KB on disk)"
            )

    # Retire the chain: one self-contained snapshot, old checkpoints deletable.
    from torchsnapshot_tpu.dedup import consolidate

    final = os.path.join(work, "final")
    consolidate(ckpts[-1], final)
    print(f"consolidated -> final ({snap_bytes(final) / 1e3:.0f} KB, no bases needed)")

    # Simulated restart: fresh state, restore, verify.
    backbone2, adapter2 = init_state(jax.random.PRNGKey(9))
    opt_state2 = tx.init(adapter2)
    progress = StateDict(step=0)
    dst = {
        "backbone": StateDict(**backbone2),
        "adapter": StateDict(**adapter2),
        "opt": StateDict(state=opt_state2),
        "progress": progress,
    }
    Snapshot(final).restore(dst)
    np.testing.assert_array_equal(
        np.asarray(dst["adapter"]["a"]), np.asarray(adapter["a"])
    )
    np.testing.assert_array_equal(
        np.asarray(dst["backbone"]["w1"]), np.asarray(backbone["w1"])
    )
    print(f"restored at step {progress['step']}; parameters bit-exact. done.")


if __name__ == "__main__":
    main()
