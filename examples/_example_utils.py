"""Shared example plumbing."""

from __future__ import annotations

import os


def force_cpu_if_requested() -> None:
    """Honor JAX_PLATFORMS=cpu even when the interpreter pre-imported
    jax aimed at an experimental TPU platform (the env var alone can be
    too late; jax.config takes effect at first backend init). Call
    after ``import jax`` and before any device use."""
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")
