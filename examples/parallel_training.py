"""End-to-end parallel training + checkpointing demo.

Runs on an 8-device virtual CPU mesh (no TPU pod needed):

1. Train a MoE transformer with dp x cp x tp x ep sharding — ring attention
   over the 'seq' axis, tensor-parallel weights over 'model', top-2 MoE
   experts sharded over 'model'.
2. Mid-training, take a non-blocking snapshot (``async_take``) and keep
   training through the storage I/O.
3. "Elastic resume": rebuild the model on a DIFFERENT mesh layout and
   restore the same snapshot into it — overlap resharding handles the
   layout change.
4. Production checkpoint config: async + incremental + mirrored saves
   composed (an unchanged re-save writes zero payloads).
5. Bonus: run a GPipe pipeline-parallel train step on a ('data','pipe')
   mesh (see parallel/pipeline.py).

Usage: python examples/parallel_training.py
"""

from __future__ import annotations

import os
import sys
import tempfile


def main() -> None:
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    )
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from torchsnapshot_tpu import Snapshot, StateDict
    from torchsnapshot_tpu.models import transformer as T
    from torchsnapshot_tpu.parallel import make_mesh

    # ---- 1. dp x cp x tp x ep training -----------------------------------
    mesh = make_mesh({"data": 2, "seq": 2, "model": 2})
    cfg = T.TransformerConfig(
        vocab_size=256, d_model=32, n_heads=2, n_layers=2, d_ff=64,
        max_seq_len=64, attn_impl="ring", n_experts=2,
    )
    tx = T.make_optimizer()
    state = T.init_state(jax.random.PRNGKey(0), cfg, tx, mesh=mesh)
    step = jax.jit(T.make_train_step(cfg, tx, mesh=mesh))

    rng = np.random.default_rng(0)
    def batch():
        toks = rng.integers(0, 256, (4, 64), dtype=np.int32)
        b = {"tokens": jnp.asarray(toks), "targets": jnp.asarray(np.roll(toks, -1, 1))}
        return jax.device_put(b, NamedSharding(mesh, P("data", "seq")))

    for i in range(3):
        state, loss = step(state, batch())
        print(f"step {int(state['step'])}: loss {float(loss):.4f}")

    # ---- 2. async snapshot mid-training ----------------------------------
    tmp = tempfile.mkdtemp(prefix="tsnap_demo_")
    pending = Snapshot.async_take(f"{tmp}/ckpt", {"train": StateDict(state=state)})
    for i in range(2):  # training continues during storage I/O
        state, loss = step(state, batch())
        print(f"step {int(state['step'])} (snapshot in flight): loss {float(loss):.4f}")
    snapshot = pending.wait()
    print(f"snapshot committed at {snapshot.path}")

    # ---- 3. elastic resume on a different mesh ---------------------------
    mesh2 = make_mesh({"data": 4, "seq": 1, "model": 2})
    cfg2 = T.TransformerConfig(
        vocab_size=256, d_model=32, n_heads=2, n_layers=2, d_ff=64,
        max_seq_len=64, attn_impl="dense", n_experts=2,
    )
    state2 = T.init_state(jax.random.PRNGKey(1), cfg2, tx, mesh=mesh2)
    dst = {"train": StateDict(state=state2)}
    snapshot.restore(dst)
    resumed = dst["train"]["state"]
    # the resumed step counter picks up where the snapshot was taken
    print(f"resumed on mesh {dict(mesh2.shape)} at step {int(resumed['step'])}")
    step2 = jax.jit(T.make_train_step(cfg2, tx, mesh=mesh2))
    b = jax.device_put(
        {
            "tokens": jnp.zeros((4, 64), jnp.int32),
            "targets": jnp.zeros((4, 64), jnp.int32),
        },
        NamedSharding(mesh2, P("data", None)),
    )
    resumed, loss = step2(resumed, b)
    print(f"post-resume step {int(resumed['step'])}: loss {float(loss):.4f}")

    # ---- 4. production checkpoint config ---------------------------------
    # Periodic saves compose: async (no training stall past staging) +
    # incremental (unchanged payloads referenced, not rewritten) + a
    # durable mirror tier (fast local primary, background replica).
    prod_opts = {"mirror_url": f"{tmp}/durable_0"}
    Snapshot.take(
        f"{tmp}/prod_0", {"train": StateDict(state=resumed)},
        storage_options=prod_opts, record_digests=True,
    )
    # A re-save against the base writes only what changed — nothing has
    # trained since prod_0, so ZERO payloads hit storage here (a full
    # optimizer step touches every tensor; examples/lora_incremental.py
    # shows the frozen-backbone case where the win persists through
    # training).
    pending = Snapshot.async_take(
        f"{tmp}/prod_1", {"train": StateDict(state=resumed)},
        storage_options={"mirror_url": f"{tmp}/durable_1"},
        incremental_base=f"{tmp}/prod_0",
    )
    resumed, loss = step2(resumed, b)  # keeps training during I/O
    pending.wait()
    def payload_count(root):
        return sum(
            1 for _, _, files in os.walk(root)
            for f in files if f != ".snapshot_metadata"
        )

    print(
        f"incremental+mirrored snapshot committed: "
        f"{payload_count(f'{tmp}/prod_1')} of {payload_count(f'{tmp}/prod_0')} "
        "payloads rewritten (unchanged ones reference prod_0)"
    )
    # Disaster recovery: deduplicated payloads record each base's MIRROR
    # in the metadata, so the durable tier alone restores the whole chain
    # even after every fast/primary tier is gone.
    import shutil

    shutil.rmtree(f"{tmp}/prod_0")
    shutil.rmtree(f"{tmp}/prod_1")
    dst2 = {"train": StateDict(state=T.init_state(jax.random.PRNGKey(3), cfg2, tx, mesh=mesh2))}
    Snapshot(f"{tmp}/durable_1").restore(dst2)
    print(
        "primaries wiped; durable tier restores the chain at step "
        f"{int(dst2['train']['state']['step'])} "
        "(deduped payloads read from durable_0 via origin_mirrors)"
    )
    # To retire a chain into one self-contained artifact:
    from torchsnapshot_tpu.dedup import consolidate

    consolidate(f"{tmp}/durable_1", f"{tmp}/durable_standalone")
    print("consolidated standalone replica written (no bases required)")

    # ---- 5. pipeline parallelism -----------------------------------------
    from torchsnapshot_tpu.parallel import pipeline_param_sharding, pipelined_apply

    pmesh = make_mesh({"data": 2, "pipe": 4})
    L, D = 8, 16

    def layer_fn(layer, h):
        return jnp.tanh(h @ layer["w"])

    params = {"w": jax.random.normal(jax.random.PRNGKey(2), (L, D, D)) * (D**-0.5)}
    params = jax.device_put(params, pipeline_param_sharding(params, pmesh))
    x = jax.device_put(jnp.ones((8, D)), NamedSharding(pmesh, P("data")))
    out = jax.jit(
        lambda p, x: pipelined_apply(p, x, pmesh, layer_fn=layer_fn, n_micro=4)
    )(params, x)
    print(f"pipeline output: shape {out.shape}, finite {bool(jnp.isfinite(out).all())}")


if __name__ == "__main__":
    main()
