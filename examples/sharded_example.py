"""Sharded training-state checkpointing on a device mesh.

Shows the GSPMD path: the flagship transformer's params/optimizer state
sharded over a ('data','model') mesh, saved once (shard-deduped), then
restored onto a DIFFERENT mesh layout — the resharding that makes
checkpoints world-size- and layout-independent.

Runs on any device count; use virtual CPU devices to try multi-chip:
  python examples/sharded_example.py --cpu-devices 8
"""

from __future__ import annotations

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if "--cpu-devices" in sys.argv:
    _n = int(sys.argv[sys.argv.index("--cpu-devices") + 1])
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={_n}"
    )
    import jax as _jax

    _jax.config.update("jax_platforms", "cpu")

import jax

from _example_utils import force_cpu_if_requested

force_cpu_if_requested()

import numpy as np

from torchsnapshot_tpu import Snapshot, StateDict
from torchsnapshot_tpu.models import transformer as T
from torchsnapshot_tpu.parallel import make_mesh


def main() -> None:
    n = len(jax.devices())
    work_dir = tempfile.mkdtemp(prefix="sharded_example_")

    cfg = T.TransformerConfig(
        vocab_size=1024, d_model=64, n_heads=4, n_layers=2, d_ff=128, max_seq_len=64
    )
    tx = T.make_optimizer()

    mesh_a = make_mesh(devices=jax.devices())
    state = T.init_state(jax.random.PRNGKey(0), cfg, tx, mesh=mesh_a)
    print(f"mesh A: {dict(mesh_a.shape)}")

    path = f"{work_dir}/snap"
    Snapshot.take(path, {"train": StateDict(**state)})
    print(f"saved sharded state -> {path}")

    # Restore onto a different layout: swap the axis sizes if possible.
    if n >= 2:
        mesh_b = make_mesh({"data": 1, "model": n}, devices=jax.devices())
    else:
        mesh_b = mesh_a
    fresh = T.init_state(jax.random.PRNGKey(7), cfg, tx, mesh=mesh_b)
    dst = {"train": StateDict(**fresh)}
    Snapshot(path).restore(dst)
    print(f"restored onto mesh B: {dict(mesh_b.shape)}")

    a = np.asarray(jax.device_get(state["params"]["embed"]))
    b = np.asarray(jax.device_get(dst["train"]["params"]["embed"]))
    assert a.tobytes() == b.tobytes()
    emb = dst["train"]["params"]["embed"]
    print(f"bit-exact across resharding; restored sharding: {emb.sharding}")


if __name__ == "__main__":
    main()
