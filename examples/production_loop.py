"""The production checkpointing recipe, end to end.

Everything a real training loop wants from the framework, composed the
way a job would actually run it:

1. `CheckpointManager` owns cadence, naming, retention, and resume.
2. `warmup()` pre-faults staging buffers so even the FIRST async save
   blocks only for steady-state staging time.
3. Async saves block the loop only for staging; storage I/O overlaps
   the next steps.
4. A mirror root gives two-tier durability (fast primary + replica per
   step) without slowing the loop.
5. The process "crashes"; a fresh manager discovers the latest
   committed step and resumes — and re-running the restored step does
   NOT overwrite its committed snapshot.
6. A preemption (SIGTERM, as cloud spot/maintenance eviction sends)
   triggers a collectively consistent off-cadence emergency save; the
   loop exits cleanly, and a third run resumes from the exact
   preempted step.

Run: JAX_PLATFORMS=cpu python examples/production_loop.py
"""

from __future__ import annotations

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

from _example_utils import force_cpu_if_requested

force_cpu_if_requested()
import jax.numpy as jnp
import numpy as np
import optax

from torchsnapshot_tpu import (
    CheckpointManager,
    PreemptionWatcher,
    RNGState,
    StateDict,
    simulate_preemption_now,
)

D = 256


def init_state(key):
    params = {
        "w1": jax.random.normal(key, (D, D)) * 0.05,
        "w2": jnp.zeros((D, 1)),
    }
    tx = optax.adamw(1e-3)
    return params, tx, tx.init(params)


@jax.jit
def loss_fn(params, x, y):
    return jnp.mean((jnp.tanh(x @ params["w1"]) @ params["w2"] - y) ** 2)


def train(
    root: str,
    mirror: str,
    n_steps: int,
    crash_at: int | None,
    preempt_at: int | None = None,
) -> float:
    key = jax.random.PRNGKey(0)
    params, tx, opt_state = init_state(key)

    watcher = PreemptionWatcher()   # SIGTERM -> flag; handler chained
    mgr = CheckpointManager(
        root,
        save_interval_steps=5,      # checkpoint every 5 steps
        keep_last=2,                # retention: newest 2 survive
        async_save=True,            # block only for staging
        storage_options={"mirror_url": mirror},
        preemption=watcher,         # emergency save on eviction
    )
    app_state = {
        "model": StateDict(params=params),
        "optim": StateDict(state=opt_state),
        "progress": StateDict(step=0),
        "rng": RNGState(),
    }

    start = 0
    latest = mgr.latest_step()
    if latest is not None:
        start = mgr.restore(app_state) + 1
        params = app_state["model"]["params"]
        opt_state = app_state["optim"]["state"]
        print(f"resumed from step {latest}; continuing at {start}")
    else:
        # Pre-fault staging buffers off the critical path: the first
        # async save now blocks like a warm one.
        warmed = mgr.warmup(app_state)
        print(f"warmup pre-faulted {warmed / 1e6:.0f} MB of staging buffers")

    grad_fn = jax.jit(jax.grad(loss_fn))
    rng = np.random.default_rng(7)
    loss = float("nan")
    for step in range(start, n_steps):
        x = jnp.asarray(rng.standard_normal((64, D), np.float32))
        y = jnp.asarray(rng.standard_normal((64, 1), np.float32))
        grads = grad_fn(params, x, y)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)

        app_state["model"] = StateDict(params=params)
        app_state["optim"] = StateDict(state=opt_state)
        app_state["progress"] = StateDict(step=step)
        if preempt_at is not None and step == preempt_at:
            # What the cloud does to a spot slice, self-inflicted:
            simulate_preemption_now()
        mgr.save(step, app_state)   # no-op unless due; drains previous async
        if watcher.consumed:
            # Emergency snapshot committed ON EVERY RANK (consumed is the
            # collective signal; `preempted` is rank-local). Exit inside
            # the grace window.
            print(f"preempted: emergency snapshot committed at step {step}")
            watcher.close()
            return float("nan")

        if crash_at is not None and step == crash_at:
            mgr.wait()
            watcher.close()  # a real crash wouldn't, but an in-process
            # "crash" must not leak its SIGTERM handler into later runs
            print(f"simulating a crash after step {step}")
            return float("nan")

        loss = float(loss_fn(params, x, y))
    mgr.wait()
    watcher.close()
    return loss


def main() -> None:
    tmp = tempfile.mkdtemp(prefix="production_loop_")
    root = os.path.join(tmp, "ckpt")
    mirror = f"fs://{tmp}/mirror"

    train(root, mirror, n_steps=20, crash_at=11)   # run 1: dies at step 11
    train(root, mirror, n_steps=20, crash_at=None, preempt_at=17)  # run 2: evicted
    loss = train(root, mirror, n_steps=20, crash_at=None)  # run 3: resumes

    steps = sorted(os.listdir(root))
    print(f"committed snapshots after retention: {steps}")
    # Step 17 is the off-cadence emergency snapshot from the eviction.
    assert steps == ["step_0000000015", "step_0000000017"], steps
    # Retention governs the PRIMARY tier; the durable mirror keeps every
    # step as archival history (prune it with `torchsnapshot-tpu prune`
    # when that history should be bounded too).
    mirrors = sorted(os.listdir(os.path.join(tmp, "mirror")))
    print(f"mirror replicas (archival, unpruned): {mirrors}")
    print(f"final loss {loss:.5f} — resume + retention + mirror + preemption all verified")


if __name__ == "__main__":
    main()
