"""Fleet-scale seeded restore vs N direct reads, emulated world-64.

PR 9's cooperative restore measured the COLLECTIVE case (BENCH_r09:
ranks restoring together partition the reads — 1.0x amplification, 2.65x
speedup at world 4). This measures the FLEET case the distribution tier
(distrib.py) targets: 64 independent replica restores — separate
process groups, no collective — picking up the same snapshot from
throttled storage. Directly, that is 64x storage-read amplification by
construction; seeded, every replica that has a chunk serves it to the
replicas that still need it, so the fleet reads each byte ~once.

Legs (one JSON line each, plus a summary):

- ``direct``: N sample replicas restore with the tier off; per-replica
  wall on the throttled pipe calibrates the 64x baseline.
- ``seeded``: 64 replicas restore with ``SEED_RESTORE=always``, each
  with its OWN persistent SeedSession (the process-global is parked
  between restores, so every emulated replica keeps seeding the rest of
  the rollout, exactly like a real fleet). Asserts fleet
  storage_read_amplification <= 1.2 — the r13 acceptance criterion.
- ``fanout``: a concurrent chunk wave (staggered rollout arrivals,
  threads per wave) through raw SeedSessions, recording the measured
  seeding-tree depth under the busy bound.
- ``update``: journal-delta rolling update — one manager pushes its
  committed epochs to 8 registered live replicas; asserts pushed bytes
  per replica <= 1.5x the committed epoch bytes on disk (r13) and the
  replica states converge bit-exact.

Replicas restore CONCURRENTLY in a real fleet, so aggregate GB/s is
modeled as world x payload / mean per-replica wall (the serial emulation
measures each replica's wall without contention); the same model prices
the direct baseline, and amplification — the criterion — is a pure byte
count, model-free.

Usage: JAX_PLATFORMS=cpu python benchmarks/fleet_restore.py [mb_total]
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

THROTTLE_BPS = 40e6  # ~40 MB/s: shared-filer / modest object-store regime
FLEET = 64
DIRECT_SAMPLES = 4
UPDATE_REPLICAS = 8


def _state(mb_total: float):
    import numpy as np

    n_arrays = 8
    elems = int(mb_total * 1e6 / n_arrays / 4)
    rng = np.random.default_rng(42)
    return {
        f"w{i}": rng.standard_normal(elems).astype(np.float32)
        for i in range(n_arrays)
    }


def _throttle_and_count():
    """The BENCH_r09 throttle: one per-process rate lock models a shared
    per-host storage pipe at THROTTLE_BPS; counts payload bytes served
    (replicated/ and sharded/ only), so a silent fallback to direct
    reads cannot masquerade as seeding."""
    import asyncio

    from torchsnapshot_tpu.io_types import ReadStream
    from torchsnapshot_tpu.storage_plugins.fs import FSStoragePlugin

    counts = {"payload": 0}
    # Unlike the subprocess benches, every emulated replica restores in
    # THIS process with its own event loop — the rate lock is per loop
    # (restores are serial, so the shared-pipe model is preserved).
    rate_locks: dict = {}

    def _is_payload(path: str) -> bool:
        return "replicated/" in path or "sharded/" in path

    async def _pay(n: int) -> None:
        counts["payload"] += n
        loop = asyncio.get_running_loop()
        lock = rate_locks.get(id(loop))
        if lock is None:
            lock = rate_locks[id(loop)] = asyncio.Lock()
        async with lock:
            await asyncio.sleep(n / THROTTLE_BPS)

    orig_read = FSStoragePlugin.read

    async def slow_read(self, read_io, _orig=orig_read):
        await _orig(self, read_io)
        if _is_payload(read_io.path):
            await _pay(memoryview(read_io.buf).nbytes)

    orig_stream = FSStoragePlugin.read_stream

    async def slow_stream(self, read_io, sub_chunk, _orig=orig_stream):
        inner = await _orig(self, read_io, sub_chunk)
        path = read_io.path

        async def chunks():
            async for c in inner.chunks:
                if _is_payload(path):
                    await _pay(memoryview(c).nbytes)
                yield c

        return ReadStream(path=inner.path, nbytes=inner.nbytes, chunks=chunks())

    FSStoragePlugin.read = slow_read
    FSStoragePlugin.read_stream = slow_stream
    return counts


def _restore_once(root, state):
    import numpy as np

    from torchsnapshot_tpu import Snapshot, StateDict

    dst = {"model": StateDict(**{k: np.zeros_like(v) for k, v in state.items()})}
    t0 = time.perf_counter()
    Snapshot(root).restore(dst)
    wall = time.perf_counter() - t0
    for k, v in state.items():
        assert dst["model"][k].tobytes() == v.tobytes(), f"{k} not bit-exact"
    return wall


def _restore_legs(tmp, client, mb_total):
    import numpy as np  # noqa: F401 - jax/np import order

    from torchsnapshot_tpu import Snapshot, StateDict, distrib

    state = _state(mb_total)
    payload = sum(v.nbytes for v in state.values())
    root = os.path.join(tmp, "base")
    # The take is untimed and unthrottled; only restores pay the pipe.
    Snapshot.take(root, {"model": StateDict(**state)}, replicated=["model/**"])
    counts = _throttle_and_count()

    os.environ["TORCHSNAPSHOT_TPU_SEED_RESTORE"] = "never"
    direct_walls = [_restore_once(root, state) for _ in range(DIRECT_SAMPLES)]
    direct_wall = sum(direct_walls) / len(direct_walls)
    direct_read = counts["payload"]
    direct = {
        "benchmark": "fleet_restore/direct",
        "replicas_sampled": DIRECT_SAMPLES,
        "payload_mb": round(payload / 1e6, 1),
        "mean_replica_wall_s": round(direct_wall, 3),
        # Every direct replica reads every payload byte: the fleet-64
        # baseline is 64x by construction, measured here per replica.
        "per_replica_amplification": round(
            direct_read / payload / DIRECT_SAMPLES, 3
        ),
        "fleet_amplification": round(
            FLEET * direct_read / payload / DIRECT_SAMPLES, 1
        ),
        "modeled_aggregate_gbps": round(FLEET * payload / 1e9 / direct_wall, 3),
    }
    print(json.dumps(direct), flush=True)

    counts["payload"] = 0
    os.environ["TORCHSNAPSHOT_TPU_SEED_RESTORE"] = "always"
    distrib.configure_registry(client)
    sessions = []
    walls = []
    try:
        t0 = time.perf_counter()
        for _ in range(FLEET):
            walls.append(_restore_once(root, state))
            # Park this replica's session (it keeps serving) and let the
            # next restore build its own — one persistent mesh member per
            # emulated replica.
            sess = distrib._session
            with distrib._session_lock:
                distrib._session = None
            if sess is not None:
                sessions.append(sess)
        total_wall = time.perf_counter() - t0
        fleet_read = counts["payload"]
        seeded = {
            "benchmark": "fleet_restore/seeded",
            "replicas": FLEET,
            "payload_mb": round(payload / 1e6, 1),
            "mean_replica_wall_s": round(sum(walls) / len(walls), 3),
            "rollout_wall_s": round(total_wall, 3),
            "storage_read_amplification": round(fleet_read / payload, 3),
            "modeled_aggregate_gbps": round(
                FLEET * payload / 1e9 / (sum(walls) / len(walls)), 3
            ),
            "mesh_sessions": len(sessions),
            "max_restore_depth": max(
                (s.max_registered_depth for s in sessions), default=0
            ),
        }
        print(json.dumps(seeded), flush=True)
    finally:
        for s in sessions:
            s.close()
        distrib.reset_session()
        distrib.configure_registry(None)
    return direct, seeded, payload


def _fanout_leg(client):
    """Staggered rollout waves fetching ONE chunk concurrently through
    raw sessions: the busy bound (SEED_FANOUT serves per holder) pushes
    late arrivals to deeper parents, so the measured max depth is the
    seeding tree materializing. Fallbacks (every candidate busy at once)
    model as direct reads: publish at depth 0, count."""
    import numpy as np

    from torchsnapshot_tpu import distrib
    from torchsnapshot_tpu.fanout import content_address

    chunk = np.random.default_rng(7).bytes(8 << 20)
    uid = "sha256:" + "f" * 64  # a synthetic catalog key
    seed = distrib.SeedSession(client(), holder_id="fleet-seed")
    sessions = [seed]
    fallbacks = [0]
    lock = threading.Lock()
    digest = seed.publish(uid, chunk, depth=0)
    assert digest == content_address(chunk)

    def fetch_one(idx: int, barrier: threading.Barrier) -> None:
        s = distrib.SeedSession(client(), holder_id=f"fleet-{idx}")
        with lock:
            sessions.append(s)
        barrier.wait()
        try:
            buf = s.fetch(uid, digest, len(chunk))
            assert content_address(buf) == digest
        except distrib.SeedUnavailable:
            with lock:
                fallbacks[0] += 1
            s.publish(uid, chunk, depth=0)

    idx = 0
    try:
        for wave in (3, 9, 27):
            barrier = threading.Barrier(wave)
            threads = [
                threading.Thread(target=fetch_one, args=(idx + i, barrier))
                for i in range(wave)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120.0)
            idx += wave
        max_depth = max(s.max_registered_depth for s in sessions)
        leg = {
            "benchmark": "fleet_restore/fanout",
            "replicas": idx + 1,
            "chunk_mb": round(len(chunk) / 1e6, 1),
            "seed_fanout": distrib.seed_fanout(),
            "max_tree_depth": max_depth,
            "storage_fallbacks": fallbacks[0],
        }
        print(json.dumps(leg), flush=True)
        # The tree must have engaged at all; the depth itself is recorded,
        # not asserted (it depends on arrival overlap).
        assert max_depth >= 1, "no replica ever registered below the root"
        return leg
    finally:
        for s in sessions:
            s.close()


def _update_leg(tmp, client):
    """Rolling update: one manager journals two epochs over a mostly-
    frozen state and pushes the committed deltas to 8 registered live
    replicas. Bytes shipped per replica must stay <= 1.5x the committed
    epoch bytes on disk (the journal regions move verbatim — no
    re-encode amplification), and every replica must converge bit-exact."""
    import numpy as np

    from torchsnapshot_tpu import CheckpointManager, StateDict, distrib, journal
    from torchsnapshot_tpu.storage_plugin import local_fs_root

    os.environ["TORCHSNAPSHOT_TPU_JOURNAL"] = "1"
    distrib.configure_registry(client)
    root = os.path.join(tmp, "update")

    def make_state():
        rng = np.random.default_rng(3)
        return {
            "model": StateDict(
                frozen=rng.standard_normal(500_000).astype(np.float32),
                hot=np.zeros(20_000, np.float32),
                step=np.array([0], dtype=np.int64),
            )
        }

    live = make_state()
    mgr = CheckpointManager(root, save_interval_steps=100)
    mgr.save(0, live)
    mgr.wait()
    replicas = [make_state() for _ in range(UPDATE_REPLICAS)]
    receivers = [
        distrib.UpdateReceiver(client(), r, base_step=0) for r in replicas
    ]
    try:
        t0 = time.perf_counter()
        for step in (1, 2):
            live["model"]["hot"] = live["model"]["hot"] + float(step)
            live["model"]["step"] = np.array([step], dtype=np.int64)
            assert mgr.journal_step(step, live)
        out = mgr.push_update()
        push_wall = time.perf_counter() - t0
        jdir = os.path.join(
            local_fs_root(mgr.path_for(0)), journal.JOURNAL_DIRNAME
        )
        committed = journal.committed_epochs(journal.read_epoch_metas(jdir))
        epoch_bytes = sum(committed[-1]["offsets"].values())
        per_replica = out["bytes"] / max(out["replicas"], 1)
        for rep in replicas:
            assert (
                rep["model"]["hot"].tobytes() == live["model"]["hot"].tobytes()
            ), "replica did not converge"
            assert int(np.asarray(rep["model"]["step"])[0]) == 2
        leg = {
            "benchmark": "fleet_restore/update",
            "replicas": out["replicas"],
            "epochs": out["epochs"],
            "nacks": out["nacks"],
            "committed_epoch_bytes": epoch_bytes,
            "pushed_bytes_per_replica": int(per_replica),
            "push_amplification": round(per_replica / epoch_bytes, 3),
            "push_wall_s": round(push_wall, 3),
        }
        print(json.dumps(leg), flush=True)
        assert out["replicas"] == UPDATE_REPLICAS and out["nacks"] == 0, out
        assert per_replica <= 1.5 * epoch_bytes, (
            f"rolling update shipped {per_replica} B/replica for "
            f"{epoch_bytes} B of committed epochs (> 1.5x)"
        )
        return leg
    finally:
        for rx in receivers:
            rx.close()
        distrib.configure_registry(None)


def main() -> int:
    mb_total = float(sys.argv[1]) if len(sys.argv) > 1 else 16.0

    from torchsnapshot_tpu.dist_store import TCPStore

    server = TCPStore("127.0.0.1", is_server=True, timeout=30.0)
    port = server.port

    def client() -> TCPStore:
        return TCPStore("127.0.0.1", port, is_server=False, timeout=30.0)

    tmp = tempfile.mkdtemp(prefix="fleet_restore_")
    try:
        direct, seeded, payload = _restore_legs(tmp, client, mb_total)
        fanout = _fanout_leg(client)
        update = _update_leg(tmp, client)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
        server.close()

    r09_w4_coop_gbps = None
    r09_path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "BENCH_r09.json",
    )
    try:
        with open(r09_path) as f:
            r09_w4_coop_gbps = json.load(f)["worlds"]["4"]["coop_gbps"]
    except (OSError, KeyError, ValueError):
        pass

    summary = {
        "benchmark": "fleet_restore/summary",
        "fleet": FLEET,
        "payload_mb": round(payload / 1e6, 1),
        "throttle_mbps": THROTTLE_BPS / 1e6,
        "direct_fleet_amplification": direct["fleet_amplification"],
        "seeded_amplification": seeded["storage_read_amplification"],
        "direct_gbps": direct["modeled_aggregate_gbps"],
        "seeded_gbps": seeded["modeled_aggregate_gbps"],
        "speedup": round(
            seeded["modeled_aggregate_gbps"]
            / max(direct["modeled_aggregate_gbps"], 1e-9),
            2,
        ),
        "max_tree_depth": fanout["max_tree_depth"],
        "r09_w4_coop_gbps": r09_w4_coop_gbps,
        "push_amplification": update["push_amplification"],
    }
    print(json.dumps(summary), flush=True)

    # The r13 acceptance criteria.
    assert summary["seeded_amplification"] <= 1.2, (
        f"fleet-64 seeded amplification {summary['seeded_amplification']}x "
        "> 1.2x"
    )
    assert summary["direct_fleet_amplification"] >= 0.8 * FLEET, (
        "the direct baseline is not N independent reads: "
        f"{summary['direct_fleet_amplification']}x"
    )
    if r09_w4_coop_gbps:
        assert summary["seeded_gbps"] > r09_w4_coop_gbps, (
            f"fleet-64 seeding ({summary['seeded_gbps']} GB/s) does not "
            f"scale past the w4 cooperative restore ({r09_w4_coop_gbps} GB/s)"
        )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
