"""Lazy page-in restore leg (ISSUE 18): time-to-first-inference vs the
eager restore wall, on throttled storage.

The TTFI model (docs/source/serving.rst): a serving replica does not
need the whole checkpoint to answer its first request — it needs the
metadata and the hot set (embedding tables, the head, whatever the
first forward pass touches). Eager restore pays the full payload at
storage bandwidth before the process can serve; lazy restore returns
once the hot set is resident and pages the tail in behind the first
requests. On a ``B``-bytes/s pipe the floor is ``hot_bytes / B`` vs
``total_bytes / B`` — the ratio this leg measures and gates (>= 5x
floor; the ISSUE 18 target is 10x at a <=10% hot set).

Storage reads are throttled to THROTTLE_BPS with the same
single-rate-lock-per-event-loop model as coop_restore.py /
journal_rpo.py (the shared-filer regime lazy restore exists for — on
tmpfs a "read" is a memcpy and eager is already instant). Payload
bytes are COUNTED inside the fs plugin, so the leg also gates total
bytes moved: lazy must stay <= 1.1x eager (demand faults that fall
back to direct reads re-read at leaf granularity; the bound proves the
engine doesn't read the snapshot twice).

Three legs on the same snapshot (~96 leaves x 2 MiB, hot set 4 leaves
≈ 4% of payload):

- eager: LAZY_RESTORE unset — first inference possible only after the
  last byte; wall IS the eager TTFI, bytes counted.
- lazy: LAZY_RESTORE=always with a 4-rule hot set — wall of restore()
  IS the lazy TTFI (hot leaves verified bit-exact at return, not
  timed); then drain via session.wait() and verify EVERY leaf
  bit-exact, bytes counted.
- demand-only (informational): prefetch disabled, every tail leaf
  demand-faulted — the pure fault-path wall, no gate.

Emits one JSON line per leg plus a ``lazy_restore/summary`` line
(bench.py's ``_lazy_leg`` persists that to BENCH_r15.json).

Usage: JAX_PLATFORMS=cpu python benchmarks/lazy_restore.py
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench_utils import report  # noqa: E402

# Simulated per-host storage read bandwidth. In family with the other
# throttled legs (coop_restore 40 MB/s, journal_rpo 50 MB/s): the
# shared-filer / object-store regime where restore walls are
# bandwidth-bound and serving before the last byte is the win.
THROTTLE_BPS = 60e6

N_LEAVES = 96
LEAF_ELEMS = (2 << 20) // 4  # 2 MiB float32 per leaf
HOT_LEAVES = 4  # ~4% of payload: embeddings + head

SPEEDUP_FLOOR = 5.0  # hard gate; the ISSUE 18 target is 10x
BYTES_CEILING = 1.1  # lazy total reads <= 1.1x eager


def _throttle_and_count():
    """Charge THROTTLE_BPS transfer time for every payload byte read
    from storage, through one rate lock per event loop (the restore
    loop, the page-in engine's loop, and any direct-read fallback loop
    each rebuild it — a Lock is bound to the loop that created it), and
    count the bytes. Concurrent reads on one loop SHARE the simulated
    pipe; independent sleeps would let I/O concurrency multiply the
    'bandwidth' away."""
    import asyncio

    from torchsnapshot_tpu.storage_plugins.fs import FSStoragePlugin

    counts = {"payload": 0}
    rate_lock: list = [None, None]

    def _is_payload(path: str) -> bool:
        return not os.path.basename(path).startswith(".")

    async def _pay(n: int) -> None:
        counts["payload"] += n
        loop = asyncio.get_running_loop()
        if rate_lock[1] is not loop:
            rate_lock[0] = asyncio.Lock()
            rate_lock[1] = loop
        async with rate_lock[0]:
            await asyncio.sleep(n / THROTTLE_BPS)

    orig_read = FSStoragePlugin.read

    async def slow_read(self, read_io, _orig=orig_read):
        await _orig(self, read_io)
        if _is_payload(read_io.path):
            await _pay(memoryview(read_io.buf).nbytes)

    FSStoragePlugin.read = slow_read

    orig_stream = FSStoragePlugin.read_stream

    async def slow_stream(self, read_io, sub_chunk, _orig=orig_stream):
        inner = await _orig(self, read_io, sub_chunk)
        path = read_io.path

        async def chunks():
            async for c in inner.chunks:
                if _is_payload(path):
                    await _pay(memoryview(c).nbytes)
                yield c

        inner.chunks = chunks()
        return inner

    FSStoragePlugin.read_stream = slow_stream
    return counts


def _build_state(np):
    from torchsnapshot_tpu import StateDict

    rng = np.random.default_rng(7)
    leaves = {}
    for i in range(HOT_LEAVES):
        leaves[f"hot_{i:02d}"] = rng.standard_normal(LEAF_ELEMS).astype(
            np.float32
        )
    for i in range(N_LEAVES - HOT_LEAVES):
        leaves[f"tail_{i:02d}"] = rng.standard_normal(LEAF_ELEMS).astype(
            np.float32
        )
    state = StateDict(**leaves)
    hot_bytes = sum(
        v.nbytes for k, v in leaves.items() if k.startswith("hot_")
    )
    total_bytes = sum(v.nbytes for v in leaves.values())
    return {"model": state}, total_bytes, hot_bytes


def _zeros(np, src):
    from torchsnapshot_tpu import StateDict

    return {
        "model": StateDict(
            **{k: np.zeros_like(np.asarray(v)) for k, v in src["model"].items()}
        )
    }


HOT_RULES = [r"model/hot_"]


def main() -> None:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    # The throttle patches the Python fs read paths; the io_uring engine
    # would bypass them.
    os.environ["TORCHSNAPSHOT_TPU_NATIVE_IO"] = "never"
    import numpy as np

    from torchsnapshot_tpu import Snapshot
    from torchsnapshot_tpu.pagein import LeafFuture

    app_state, total_bytes, hot_bytes = _build_state(np)

    root = tempfile.mkdtemp(prefix="lazy_restore_")
    snap = os.path.join(root, "snap")
    try:
        Snapshot.take(snap, app_state)  # unthrottled: the leg prices reads
        counts = _throttle_and_count()

        # ---- eager leg: TTFI == the full restore wall -----------------
        os.environ.pop("TORCHSNAPSHOT_TPU_LAZY_RESTORE", None)
        dst = _zeros(np, app_state)
        counts["payload"] = 0
        t0 = time.perf_counter()
        sess = Snapshot(snap).restore(dst)
        ttfi_eager = time.perf_counter() - t0
        assert sess is None
        bytes_eager = counts["payload"]
        for k, v in app_state["model"].items():
            np.testing.assert_array_equal(dst["model"][k], v)
        report(
            "lazy_restore/eager",
            {
                "state_mib": round(total_bytes / (1 << 20), 1),
                "throttle_mb_s": THROTTLE_BPS / 1e6,
                "wall_s": round(ttfi_eager, 4),
                "payload_bytes_read": bytes_eager,
            },
            data_bytes=total_bytes,
        )

        # ---- lazy leg: TTFI == restore() wall, then drain -------------
        os.environ["TORCHSNAPSHOT_TPU_LAZY_RESTORE"] = "always"
        dst = _zeros(np, app_state)
        counts["payload"] = 0
        t0 = time.perf_counter()
        sess = Snapshot(snap).restore(dst, hot=HOT_RULES)
        ttfi_lazy = time.perf_counter() - t0
        assert sess is not None
        # First inference is servable NOW: hot leaves bit-exact at
        # return (verified outside the timed region).
        for i in range(HOT_LEAVES):
            k = f"hot_{i:02d}"
            assert not isinstance(dst["model"][k], LeafFuture)
            np.testing.assert_array_equal(dst["model"][k], app_state["model"][k])
        resident_at_return = sess.resident_fraction()
        t0 = time.perf_counter()
        sess.wait(timeout=600)
        drain_s = time.perf_counter() - t0
        bytes_lazy = counts["payload"]
        bitexact = True
        for k, v in app_state["model"].items():
            got = dst["model"][k]
            if isinstance(got, LeafFuture):
                got = got.result(timeout=60)
            np.testing.assert_array_equal(np.asarray(got), v)
        report(
            "lazy_restore/lazy",
            {
                "hot_mib": round(hot_bytes / (1 << 20), 1),
                "ttfi_s": round(ttfi_lazy, 4),
                "resident_at_return": round(resident_at_return, 4),
                "drain_s": round(drain_s, 4),
                "payload_bytes_read": bytes_lazy,
                "bitexact": bitexact,
            },
            data_bytes=hot_bytes,
        )

        # ---- demand-only leg (informational): pure fault path ---------
        os.environ["TORCHSNAPSHOT_TPU_PAGEIN_PREFETCH"] = "0"
        dst = _zeros(np, app_state)
        counts["payload"] = 0
        sess = Snapshot(snap).restore(dst, hot=HOT_RULES)
        assert sess is not None
        t0 = time.perf_counter()
        for path in sess.pending_paths():
            sess.fault(path, timeout=600)
        sess.wait(timeout=600)
        fault_drain_s = time.perf_counter() - t0
        report(
            "lazy_restore/demand_only",
            {
                "faults": N_LEAVES - HOT_LEAVES,
                "drain_s": round(fault_drain_s, 4),
                "payload_bytes_read": counts["payload"],
            },
            data_bytes=total_bytes - hot_bytes,
        )
        os.environ.pop("TORCHSNAPSHOT_TPU_PAGEIN_PREFETCH", None)

        speedup = ttfi_eager / ttfi_lazy
        bytes_x = bytes_lazy / max(bytes_eager, 1)
        summary = {
            "benchmark": "lazy_restore/summary",
            "state_mib": round(total_bytes / (1 << 20), 1),
            "hot_mib": round(hot_bytes / (1 << 20), 1),
            "hot_fraction": round(hot_bytes / total_bytes, 4),
            "throttle_mb_s": THROTTLE_BPS / 1e6,
            "ttfi_eager_s": round(ttfi_eager, 4),
            "ttfi_lazy_s": round(ttfi_lazy, 4),
            "ttfi_speedup_x": round(speedup, 1),
            "lazy_drain_s": round(drain_s, 4),
            "bytes_eager": bytes_eager,
            "bytes_lazy": bytes_lazy,
            "bytes_amplification_x": round(bytes_x, 3),
            "bitexact": bitexact,
        }
        print(json.dumps(summary), flush=True)
        assert speedup >= SPEEDUP_FLOOR, (
            f"TTFI speedup {speedup:.1f}x < {SPEEDUP_FLOOR}x "
            f"(eager {ttfi_eager:.3f}s vs lazy {ttfi_lazy:.3f}s)"
        )
        assert bytes_x <= BYTES_CEILING, (
            f"lazy read {bytes_x:.3f}x the eager payload bytes "
            f"(> {BYTES_CEILING}x): the engine is re-reading the snapshot"
        )
    finally:
        shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    main()
