"""Replicated-parameter save benchmark (reference: benchmarks/ddp/main.py:38-70).

Workload: N params of ~100 MB each, fully replicated on device (the DDP
analogue on TPU: a fully-replicated NamedSharding). Compares:
  - snapshot: Snapshot.take through the budgeted async scheduler
  - naive:    jax.device_get + np.save per param (the torch.save analogue)

Usage:
  python benchmarks/replicated_save.py [--gb 1.0] [--params 10] [--cpu]
"""

from __future__ import annotations

import argparse
import os
import shutil
import tempfile

import numpy as np


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--gb", type=float, default=0.5, help="total model size, decimal GB")
    ap.add_argument("--params", type=int, default=5)
    ap.add_argument("--cpu", action="store_true", help="force CPU backend")
    args = ap.parse_args()

    from bench_utils import force_cpu_devices, payload_bytes, report, timed_rss

    if args.cpu:
        force_cpu_devices(1)
    import jax
    import jax.numpy as jnp

    from torchsnapshot_tpu import Snapshot, StateDict

    per_param = int(args.gb * 1e9) // args.params
    side = int((per_param // 4) ** 0.5)
    key = jax.random.PRNGKey(0)
    params = {}
    for i in range(args.params):
        key, sub = jax.random.split(key)
        params[f"param_{i}"] = jax.random.normal(sub, (side, side), jnp.float32)
    jax.block_until_ready(params)
    nbytes = sum(v.nbytes for v in params.values())

    base = "/dev/shm" if os.path.isdir("/dev/shm") else None
    tmp = tempfile.mkdtemp(dir=base, prefix="bench_replicated_")
    try:
        # naive baseline: serial DtoH + np.save per param
        res: dict = {}
        with timed_rss(res):
            for name, v in params.items():
                np.save(f"{tmp}/naive_{name}.npy", np.asarray(jax.device_get(v)))
        report("replicated_save/naive_npsave", res, nbytes)

        res = {}
        with timed_rss(res):
            Snapshot.take(f"{tmp}/snap", {"model": StateDict(**params)})
        report("replicated_save/snapshot", res, nbytes)

        # restore
        dst = StateDict(**{k: jnp.zeros_like(v) for k, v in params.items()})
        res = {}
        with timed_rss(res):
            Snapshot(f"{tmp}/snap").restore({"model": dst})
        report("replicated_save/snapshot_restore", res, nbytes)
        a = np.asarray(jax.device_get(params["param_0"]))
        b = np.asarray(jax.device_get(dst["param_0"]))
        assert a.tobytes() == b.tobytes(), "restore not bit-exact"

        # reduced-precision storage: fp32 state stored bf16 (half the
        # staged/written bytes), restored back into fp32 params
        res = {}
        with timed_rss(res):
            Snapshot.take(
                f"{tmp}/snap_bf16",
                {"model": StateDict(**params)},
                save_dtype={"model/**": "bfloat16"},
            )
        res["written_mb"] = round(payload_bytes(f"{tmp}/snap_bf16") / 1e6, 1)
        report("replicated_save/snapshot_bf16", res, nbytes)

        dst16 = StateDict(**{k: jnp.zeros_like(v) for k, v in params.items()})
        res = {}
        with timed_rss(res):
            Snapshot(f"{tmp}/snap_bf16").restore({"model": dst16})
        report("replicated_save/snapshot_bf16_restore", res, nbytes)
        want = np.asarray(jax.device_get(params["param_0"])).astype(
            "bfloat16"
        ).astype("float32")
        got = np.asarray(jax.device_get(dst16["param_0"]))
        assert got.dtype == np.float32 and got.tobytes() == want.tobytes(), (
            "bf16 round-trip mismatch"
        )
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    import os
    import sys

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    main()
