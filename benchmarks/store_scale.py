"""Coordination-plane stress at pod-scale world sizes.

The snapshot commit path rides one TCP KV server (dist_store._StoreServer)
for ALL metadata traffic: per-key lockstep barriers, the replication
negotiation gathers, and — the heavyweight — the full-manifest all-gather
at commit (snapshot.py). The north star is a v5p-128 pod: 128 ranks, a
manifest with tens of thousands of shard entries. This benchmark stands up
one real server and drives it with `world` thread-ranks, each over its own
TCP connection, measuring:

1. ``barrier``    — p50/p99 wall per full-world PGWrapper.barrier round.
2. ``gather``     — the commit-path shape: every rank contributes a
                    manifest shard (``entries_per_rank`` ArrayEntry-shaped
                    dicts) and receives all world shards. Reports wall,
                    server-side payload traffic, and per-rank RTT counts.
3. ``lockstep``   — K sequential broadcast+barrier cycles (the per-key
                    lockstep pattern in Snapshot's restore/save loops).

Thread-ranks on one host measure the SERVER's scalability (requests ride
real sockets); client-side GIL contention makes absolute walls pessimistic
vs a real pod where each rank is its own host. No O(world²) blowup must
appear: gather wall should grow ~linearly in world (payload volume), not
quadratically (round trips).

Usage: python benchmarks/store_scale.py [--worlds 32,64,128]
                                        [--entries-per-rank 400]
Emits one JSON line per (leg, world).
"""

from __future__ import annotations

import os
import statistics
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench_utils import report  # noqa: E402

from torchsnapshot_tpu.dist_store import TCPStore  # noqa: E402
from torchsnapshot_tpu.pg_wrapper import PGWrapper, ProcessGroup  # noqa: E402


def _manifest_shard(rank: int, n_entries: int) -> dict:
    """ArrayEntry-shaped payload: what one rank contributes to the commit
    gather for a sharded model (realistic key paths, shapes, checksums)."""
    return {
        f"0/model/layer_{i // 4}/param_{i % 4}": {
            "type": "sharded",
            "location": f"sharded/model.layer_{i // 4}.param_{i % 4}_{rank}_{i}",
            "serializer": "buffer_protocol",
            "dtype": "bfloat16",
            "shape": [8192, 1024],
            "byte_range": [0, 16777216],
            "checksum": f"crc32c:{(rank * 1000003 + i) & 0xFFFFFFFF:08x}",
            "replicated": False,
        }
        for i in range(n_entries)
    }


def _run_ranks(world: int, fn) -> list:
    """Run fn(rank, pg_wrapper_factory) in `world` threads; returns results."""
    server = TCPStore("127.0.0.1", None, is_server=True)
    results = [None] * world
    errors = []

    def runner(rank: int) -> None:
        store = server.clone() if rank else server
        pg = ProcessGroup(store, rank, world)
        try:
            results[rank] = fn(rank, pg)
        except BaseException as e:  # noqa: B036
            errors.append((rank, e))
        finally:
            if rank:
                store.close()

    threads = [
        threading.Thread(target=runner, args=(r,), daemon=True)
        for r in range(world)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=600)
    server.close()
    if errors:
        raise errors[0][1]
    return results


def bench_barrier(world: int, rounds: int = 20) -> None:
    def rank_fn(rank: int, pg: ProcessGroup):
        w = PGWrapper(pg, namespace=f"stress/barrier/{world}")
        walls = []
        for _ in range(rounds):
            t0 = time.perf_counter()
            w.barrier()
            walls.append(time.perf_counter() - t0)
        return walls

    t0 = time.perf_counter()
    per_rank = _run_ranks(world, rank_fn)
    total = time.perf_counter() - t0
    # A round's wall is the slowest rank's (barrier releases together);
    # aggregate across rounds for the distribution.
    rounds_wall = [
        max(per_rank[r][i] for r in range(world)) for i in range(rounds)
    ]
    report(
        "store_scale/barrier",
        {
            "world": world,
            "rounds": rounds,
            "p50_ms": round(statistics.median(rounds_wall) * 1e3, 2),
            "p99_ms": round(
                sorted(rounds_wall)[max(0, int(len(rounds_wall) * 0.99) - 1)] * 1e3,
                2,
            ),
            "total_s": round(total, 3),
        },
    )


def bench_gather(world: int, entries_per_rank: int) -> None:
    shard_template = _manifest_shard(0, entries_per_rank)

    def rank_fn(rank: int, pg: ProcessGroup):
        w = PGWrapper(pg, namespace=f"stress/gather/{world}")
        shard = _manifest_shard(rank, entries_per_rank)
        t0 = time.perf_counter()
        gathered = w.all_gather_object(shard)
        wall = time.perf_counter() - t0
        assert len(gathered) == world
        total_entries = sum(len(g) for g in gathered)
        return wall, total_entries

    import pickle

    from torchsnapshot_tpu.pg_wrapper import _dumps, _loads

    shard_bytes = len(pickle.dumps(shard_template))
    # Client-side decode cost of the leader-assembled blob, measured once:
    # with `world` thread-ranks sharing THIS host's GIL, total wall is
    # dominated by world × this (serialized); on a real pod each rank
    # decodes on its own host, in parallel.
    assembled = [_manifest_shard(r, entries_per_rank) for r in range(world)]
    blob = _dumps(assembled)
    t0 = time.perf_counter()
    _loads(blob)
    decode_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    results = _run_ranks(world, rank_fn)
    total = time.perf_counter() - t0
    walls = [r[0] for r in results]
    assert all(r[1] == world * entries_per_rank for r in results)
    report(
        "store_scale/gather",
        {
            "world": world,
            "entries_per_rank": entries_per_rank,
            "total_entries": world * entries_per_rank,
            "shard_pickle_kb": round(shard_bytes / 1e3, 1),
            "logical_traffic_mb": round(world * world * shard_bytes / 1e6, 1),
            "assembled_blob_mb": round(len(blob) / 1e6, 2),
            "per_rank_decode_s": round(decode_s, 3),
            "server_side_s_est": round(max(0.0, total - world * decode_s), 3),
            "p50_rank_wall_s": round(statistics.median(walls), 3),
            "max_rank_wall_s": round(max(walls), 3),
            "total_s": round(total, 3),
        },
    )


def bench_lockstep(world: int, n_keys: int = 10) -> None:
    def rank_fn(rank: int, pg: ProcessGroup):
        w = PGWrapper(pg, namespace=f"stress/lockstep/{world}")
        t0 = time.perf_counter()
        for i in range(n_keys):
            w.broadcast_object({"key": i, "plan": rank} if rank == 0 else None)
            w.barrier()
        return time.perf_counter() - t0

    t0 = time.perf_counter()
    results = _run_ranks(world, rank_fn)
    total = time.perf_counter() - t0
    report(
        "store_scale/lockstep",
        {
            "world": world,
            "n_keys": n_keys,
            "per_key_ms": round(max(results) / n_keys * 1e3, 2),
            "total_s": round(total, 3),
        },
    )


def bench_death_detection(world: int) -> None:
    """Latency from a rank's connection dropping to every blocked peer
    raising: world-1 waiters block in a collective-style wait on a key
    that will never arrive (racing the death channel); one liveness-
    registered connection closes abruptly."""
    from torchsnapshot_tpu.dist_store import DEATH_KEY

    server = TCPStore("127.0.0.1", None, is_server=True)
    dier = server.clone()
    dier.register_liveness(DEATH_KEY, b"rank-d-died")
    latencies = [None] * (world - 1)
    ready = threading.Barrier(world)

    def waiter(i: int) -> None:
        store = server.clone()
        ready.wait()
        key, _ = store.wait_any(["never/arrives", DEATH_KEY], timeout=60.0)
        assert key == DEATH_KEY
        latencies[i] = time.perf_counter()  # wake timestamp
        store.close()

    threads = [
        threading.Thread(target=waiter, args=(i,), daemon=True)
        for i in range(world - 1)
    ]
    for t in threads:
        t.start()
    ready.wait()  # all waiters blocked (modulo the final recv window)
    time.sleep(0.2)
    t_drop = time.perf_counter()
    dier.close()  # the "crash"
    for t in threads:
        t.join(timeout=60)
    server.close()
    # Latency from the DROP to each waiter's wake.
    walls = [v - t_drop for v in latencies if v is not None]
    report(
        "store_scale/death_detection",
        {
            "world": world,
            "p50_ms": round(statistics.median(walls) * 1e3, 2),
            "p99_ms": round(
                sorted(walls)[max(0, int(len(walls) * 0.99) - 1)] * 1e3, 2
            ),
        },
    )


def main() -> int:
    worlds = [32, 64, 128]
    entries = 400
    for a in sys.argv[1:]:
        if a.startswith("--worlds="):
            worlds = [int(x) for x in a.split("=", 1)[1].split(",")]
        elif a.startswith("--entries-per-rank="):
            entries = int(a.split("=", 1)[1])
    for world in worlds:
        bench_barrier(world)
        bench_gather(world, entries)
        bench_lockstep(world)
        bench_death_detection(world)
    return 0


if __name__ == "__main__":
    sys.exit(main())
