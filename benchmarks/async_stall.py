"""Async-take training stall benchmark — the north-star metric.

Runs a jitted transformer train step in a loop, fires
``Snapshot.async_take`` mid-run, and reports:

- ``blocked_s``: how long the ``async_take`` call itself blocked training
  (the staging / consistency-point interval);
- ``stall_pct``: step-time inflation while snapshot storage I/O overlaps
  training, relative to the undisturbed baseline step time;
- ``total_overhead_s``: blocked_s plus the summed per-step inflation —
  the total training time the snapshot cost.

Reference analogue: benchmarks/torchrec/main.py:136-151 measures the
blocked interval of its async path separately from total save time.
Target: stall_pct < 5.

Usage: python benchmarks/async_stall.py [model_mb] (default 256)
Emits one JSON line via bench_utils.report.
"""

from __future__ import annotations

import os
import statistics
import sys
import time


def main() -> None:
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from bench_utils import report

    import jax

    # The ambient environment may have pre-imported jax pointed at an
    # experimental TPU platform; the env var alone is too late by then —
    # re-apply it through jax.config (takes effect at backend init).
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import jax.numpy as jnp

    from torchsnapshot_tpu import Snapshot, StateDict
    from torchsnapshot_tpu.models import transformer as T

    model_mb = int(sys.argv[1]) if len(sys.argv) > 1 else 256
    # d_model sized so params+opt state ~ model_mb (params are fp32; adamw
    # doubles them with mu/nu).
    d_model = max(128, int((model_mb * 1e6 / (3 * 4 * 12 * 4)) ** 0.5) // 64 * 64)
    cfg = T.TransformerConfig(
        vocab_size=4096,
        d_model=d_model,
        n_heads=8,
        n_layers=4,
        d_ff=4 * d_model,
        max_seq_len=128,
    )
    tx = T.make_optimizer()
    state = T.init_state(jax.random.PRNGKey(0), cfg, tx)
    step = jax.jit(T.make_train_step(cfg, tx))
    batch = {
        "tokens": jnp.zeros((8, 128), jnp.int32),
        "targets": jnp.zeros((8, 128), jnp.int32),
    }

    nbytes = sum(
        x.nbytes for x in jax.tree_util.tree_leaves(state) if hasattr(x, "nbytes")
    )

    def run_step(state):
        state, loss = step(state, batch)
        jax.block_until_ready(loss)
        return state

    # Warm-up (compile) + baseline.
    state = run_step(state)
    baseline_times = []
    for _ in range(10):
        t0 = time.perf_counter()
        state = run_step(state)
        baseline_times.append(time.perf_counter() - t0)
    baseline = statistics.median(baseline_times)

    import shutil
    import tempfile

    base = "/dev/shm" if os.path.isdir("/dev/shm") else None
    tmp = tempfile.mkdtemp(prefix="tsnap_stall_", dir=base)
    try:
        app_state = {"train": StateDict(dict(state))}

        t0 = time.perf_counter()
        pending = Snapshot.async_take(f"{tmp}/snap", app_state)
        blocked_s = time.perf_counter() - t0

        # Train through the overlapping storage I/O.
        overlap_times = []
        while not pending.done():
            t0 = time.perf_counter()
            state = run_step(state)
            overlap_times.append(time.perf_counter() - t0)
        overlapped_steps = len(overlap_times)
        # A few steps after completion (should match baseline again).
        for _ in range(3):
            state = run_step(state)
        pending.wait()

        overlap_mean = (
            statistics.mean(overlap_times) if overlap_times else baseline
        )
        stall_pct = max(0.0, (overlap_mean - baseline) / baseline * 100.0)
        total_overhead_s = blocked_s + max(
            0.0, sum(overlap_times) - baseline * overlapped_steps
        )
    finally:
        shutil.rmtree(tmp, ignore_errors=True)

    report(
        "async_stall",
        {
            "model_bytes": nbytes,
            "baseline_step_s": round(baseline, 4),
            "blocked_s": round(blocked_s, 3),
            "overlapped_steps": overlapped_steps,
            "overlap_step_s": round(overlap_mean, 4),
            "stall_pct": round(stall_pct, 1),
            "total_overhead_s": round(total_overhead_s, 3),
        },
    )


if __name__ == "__main__":
    main()
