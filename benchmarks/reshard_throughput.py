"""Planned reshard vs N direct reads on a pure layout change.

The acceptance geometry from ISSUE 12: a checkpoint saved at world 2
under tp2 row-parallel (``P("x", None)``) restored at world 4 under
column-parallel (``P(None, "x")``) — every saved shard overlaps every
destination rank, so a direct restore reads each shard 4x fleet-wide
while the planned path reads each shard ONCE (its owner) and moves
minimal region bundles over the peer channel.

On THROTTLED storage (the shared-filer regime where the reshard
election's byte-amplification gate matters; same rate-lock model as
coop_restore.py) this measures, for RESHARD=never vs =always:

- aggregate restore throughput: world x payload / slowest-rank wall,
- storage-read amplification: fleet payload bytes served by storage /
  payload bytes (counted inside the fs plugin),
- peer bundle traffic (telemetry: bytes_resharded_from_peers),

asserting planned amplification <= 1.3x vs ~4x direct, a >= 1.5x
aggregate speedup, zero fallbacks, and bit-exact values on every rank.

Usage: JAX_PLATFORMS=cpu python benchmarks/reshard_throughput.py [mb_total]
Emits one JSON line per mode leg plus a final summary line.
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import coop_restore  # noqa: E402
from coop_restore import _throttle_and_count  # noqa: E402

# Slower than coop_restore's 40 MB/s: the peer-channel cost (CRC +
# loopback + scatter) scales with the payload, so the planned path's
# advantage only dominates once the simulated pipe is clearly the
# bottleneck — 20 MB/s puts the measured speedup near its geometric 2x
# instead of hovering at the assertion line.
THROTTLE_BPS = 20e6

COLS = 1024


def _shape(mb_total: float):
    # Rows divisible by 2 (save shards) and 4 (restore strips need the
    # COLUMN divisible by 4; rows only by 2) — round to a multiple of 4.
    rows = max(4, int(mb_total * 1e6 / (COLS * 4)) // 4 * 4)
    return rows, COLS


def _vals(mb_total: float):
    import numpy as np

    rows, cols = _shape(mb_total)
    return np.arange(rows * cols, dtype=np.float32).reshape(rows, cols)


def _init_jax_dist(rank, world_size, port):
    import re

    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = re.sub(
        r"--xla_force_host_platform_device_count=\d+",
        "",
        os.environ.get("XLA_FLAGS", ""),
    )
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(
        coordinator_address=f"localhost:{port}",
        num_processes=world_size,
        process_id=rank,
    )
    return jax


def _make(jax, values, spec):
    import numpy as np
    from jax.sharding import Mesh, NamedSharding

    mesh = Mesh(np.array(jax.devices()), ("x",))
    return jax.make_array_from_callback(
        values.shape, NamedSharding(mesh, spec), lambda idx: values[idx]
    )


def _save_worker(rank, world_size, root, port, mb_total):
    jax = _init_jax_dist(rank, world_size, port)
    from jax.sharding import PartitionSpec as P

    from torchsnapshot_tpu import Snapshot, StateDict
    from torchsnapshot_tpu.layout import LayoutSpec, Rule

    arr = _make(jax, _vals(mb_total), P("x", None))
    layout = LayoutSpec(
        [("x", world_size)], [Rule.of(r"model/w$", ["x", None])]
    )
    Snapshot.take(root, {"model": StateDict(w=arr)}, layout=layout)
    return "ok"


def _restore_worker(rank, world_size, root, port, mb_total, mode):
    import numpy as np

    os.environ["TORCHSNAPSHOT_TPU_RESHARD"] = mode
    os.environ["TORCHSNAPSHOT_TPU_TELEMETRY"] = "1"
    os.environ["TORCHSNAPSHOT_TPU_COOP_RESTORE"] = "never"
    os.environ["TORCHSNAPSHOT_TPU_COOP_TIMEOUT"] = "120"
    jax = _init_jax_dist(rank, world_size, port)
    from jax.sharding import PartitionSpec as P

    from torchsnapshot_tpu import Snapshot, StateDict, telemetry

    telemetry.refresh_from_env()  # the launcher imported us before the env
    coop_restore.THROTTLE_BPS = THROTTLE_BPS  # _pay reads the module global
    counts = _throttle_and_count()
    values = _vals(mb_total)
    dst = {
        "model": StateDict(
            w=_make(jax, np.zeros(values.shape, np.float32), P(None, "x"))
        )
    }
    t0 = time.perf_counter()
    Snapshot(root).restore(dst)
    wall = time.perf_counter() - t0
    for shard in dst["model"]["w"].addressable_shards:
        np.testing.assert_array_equal(
            np.asarray(shard.data), values[shard.index]
        )
    c = telemetry.counters()
    return {
        "wall_s": wall,
        "payload_read": counts["payload"],
        "from_peers": int(c.get("bytes_resharded_from_peers", 0)),
        "fallbacks": int(c.get("fanout_fallbacks", 0)),
    }


def main() -> int:
    # Sized so the throttled read time dominates the ~0.3 s fixed
    # restore overhead (direct legs spend ~3 s in the simulated pipe).
    mb_total = float(sys.argv[1]) if len(sys.argv) > 1 else 64.0

    from torchsnapshot_tpu.test_utils import _find_free_port, run_with_subprocesses

    payload = _vals(mb_total).nbytes
    root = os.path.join(tempfile.mkdtemp(prefix="reshard_tput_"), "snap")
    legs = {}
    try:
        ranks = run_with_subprocesses(
            _save_worker, 2, root, _find_free_port(), mb_total, timeout=300.0
        )
        assert all(v == "ok" for v in ranks.values())
        for mode, name in (("never", "direct"), ("always", "planned")):
            ranks = run_with_subprocesses(
                _restore_worker, 4, root, _find_free_port(), mb_total, mode,
                timeout=600.0,
            )
            wall = max(r["wall_s"] for r in ranks.values())
            fleet_read = sum(r["payload_read"] for r in ranks.values())
            leg = {
                "benchmark": f"reshard_throughput/{name}",
                "mode": name,
                "save_world": 2,
                "restore_world": 4,
                "payload_mb": round(payload / 1e6, 1),
                "slowest_rank_wall_s": round(wall, 3),
                "aggregate_gbps": round(4 * payload / 1e9 / wall, 3),
                "storage_read_amplification": round(fleet_read / payload, 3),
                "peer_mb": round(
                    sum(r["from_peers"] for r in ranks.values()) / 1e6, 1
                ),
                "fallbacks": sum(r["fallbacks"] for r in ranks.values()),
            }
            legs[name] = leg
            print(json.dumps(leg), flush=True)
    finally:
        shutil.rmtree(os.path.dirname(root), ignore_errors=True)

    direct, planned = legs["direct"], legs["planned"]
    summary = {
        "benchmark": "reshard_throughput/summary",
        "payload_mb": round(payload / 1e6, 1),
        "throttle_mbps": THROTTLE_BPS / 1e6,
        "direct_gbps": direct["aggregate_gbps"],
        "planned_gbps": planned["aggregate_gbps"],
        "speedup": round(
            planned["aggregate_gbps"] / max(direct["aggregate_gbps"], 1e-9), 2
        ),
        "direct_amplification": direct["storage_read_amplification"],
        "planned_amplification": planned["storage_read_amplification"],
        "peer_mb": planned["peer_mb"],
    }
    print(json.dumps(summary), flush=True)

    # The ISSUE 12 acceptance criteria, asserted here so a planner
    # regression fails the benchmark instead of shipping a bad number.
    assert summary["direct_amplification"] >= 3.5, (
        f"direct amplification {summary['direct_amplification']}x — the "
        "baseline being measured is not 4 direct reads"
    )
    assert summary["planned_amplification"] <= 1.3, (
        f"planned amplification {summary['planned_amplification']}x > 1.3x"
    )
    assert summary["speedup"] >= 1.5, (
        f"planned speedup {summary['speedup']}x < 1.5x on throttled storage"
    )
    assert planned["peer_mb"] > 0, "no bytes moved over the peer channel"
    assert planned["fallbacks"] == 0, "planned path fell back to storage"
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
