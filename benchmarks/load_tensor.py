"""Memory-budgeted single-tensor load (reference: benchmarks/load_tensor/main.py:24-92).

Saves one large array, then loads it via read_object with and without a
memory budget. The budgeted load must show bounded peak RSS (byte-range
chunked reads) at comparable throughput.

Usage:
  python benchmarks/load_tensor.py [--gb 1.0] [--budget-mb 100] [--cpu]
"""

from __future__ import annotations

import argparse
import os
import shutil
import tempfile

import numpy as np


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--gb", type=float, default=0.5)
    ap.add_argument("--budget-mb", type=int, default=100)
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()

    from bench_utils import force_cpu_devices, report, timed_rss

    if args.cpu:
        force_cpu_devices(1)

    from torchsnapshot_tpu import Snapshot, StateDict

    side = int((args.gb * 1e9 / 4) ** 0.5)
    arr = np.random.default_rng(0).standard_normal((side, side)).astype(np.float32)
    nbytes = arr.nbytes

    base = "/dev/shm" if os.path.isdir("/dev/shm") else None
    tmp = tempfile.mkdtemp(dir=base, prefix="bench_load_tensor_")
    try:
        Snapshot.take(f"{tmp}/snap", {"t": StateDict(x=arr)})
        snap = Snapshot(f"{tmp}/snap")

        res: dict = {}
        with timed_rss(res):
            out = snap.read_object("0/t/x")
        assert out.tobytes() == arr.tobytes()
        del out
        report("load_tensor/unbudgeted", res, nbytes)

        budget = args.budget_mb * 1024 * 1024
        dst = np.zeros_like(arr)
        res = {"budget_mb": args.budget_mb}
        with timed_rss(res):
            snap.read_object("0/t/x", obj_out=dst, memory_budget_bytes=budget)
        assert dst.tobytes() == arr.tobytes()
        report("load_tensor/budgeted", res, nbytes)

        # naive baseline
        np.save(f"{tmp}/naive.npy", arr)
        res = {}
        with timed_rss(res):
            loaded = np.load(f"{tmp}/naive.npy")
        assert loaded.shape == arr.shape
        del loaded
        report("load_tensor/naive_npload", res, nbytes)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    import os
    import sys

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    main()
