"""Geo-replication RPO leg (ISSUE 20): remote-tier recovery point vs
journal cadence on WAN-throttled storage, plus the foreground-overhead
gate.

The DR model (docs/source/fault_tolerance.rst, "Cross-region disaster
recovery"): the remote tier's recovery point is the primary's
durability cadence PLUS the replication lag — the time an epoch takes
to cross the WAN and fold onto the remote tier. This leg measures both
halves on a throttled remote tier:

* ``base_ship_s`` — shipping the full base snapshot (what a remote RPO
  would cost per cadence point WITHOUT journal-epoch shipping: every
  durability point re-pays the whole state over the WAN).
* ``epoch_ship_s`` — shipping one committed journal epoch carrying only
  the hot set. Remote RPO then tracks the JOURNAL cadence
  (``cadence + epoch_ship_s``) instead of the full-save cadence, and
  the leg gates the ratio (>= 3x here; ~16x ideal for this shape).
* the foreground gate — ``journal_step`` wall with the shipper armed
  and actively pushing over the throttled WAN must stay within 5% (with
  a 50 ms floor) of the unarmed wall: replication is an enqueue on the
  foreground path, never a blocking write.

Only the REMOTE tier is throttled (``_RemoteTier.write`` pays
WAN_BPS transfer time under one rate lock); primary-side saves run at
local speed — the asymmetry is the point, a WAN is slower than the
local filer and the shipper must absorb that without the training loop
noticing.

Emits one JSON line per leg plus a ``georep_rpo/summary`` line
(bench.py's ``_georep_leg`` persists that to BENCH_r17.json).

Usage: JAX_PLATFORMS=cpu python benchmarks/georep_rpo.py
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench_utils import report  # noqa: E402

# Simulated cross-region WAN bandwidth. Deliberately below the
# throttled-filer rates the other legs use (coop_restore 40 MB/s,
# journal_rpo 50 MB/s): inter-region links are the slowest pipe in the
# system, the regime async shipping exists for.
WAN_BPS = 20e6

# Journal cadences (seconds) the summary expresses remote RPO at.
CADENCES_S = (1, 5, 30)

FOREGROUND_TRIALS = 3
SHIP_TRIALS = 3


def _throttle_wan():
    """Charge WAN_BPS transfer time for every byte written to the
    remote tier, through one rate lock (the shipper is single-threaded
    today, but the lock keeps the model honest if that changes). The
    primary tier stays unthrottled. Returns a byte counter."""
    from torchsnapshot_tpu import georep as georep_mod

    lock = threading.Lock()
    shipped = {"bytes": 0}
    orig_write = georep_mod._RemoteTier.write

    def slow_write(self, rel, buf, _orig=orig_write):
        _orig(self, rel, buf)
        shipped["bytes"] += len(buf)
        with lock:
            time.sleep(len(buf) / WAN_BPS)

    georep_mod._RemoteTier.write = slow_write
    orig_append = georep_mod._RemoteTier.append

    def slow_append(self, rel, existing, region, _orig=orig_append):
        _orig(self, rel, existing, region)
        shipped["bytes"] += len(region)
        with lock:
            time.sleep(len(region) / WAN_BPS)

    georep_mod._RemoteTier.append = slow_append
    return shipped


def _build_state(np):
    """~32 MiB frozen bulk + a ~2 MiB hot set (one head array and 32
    small embedding rows) — base ship pays the bulk once, epoch ships
    pay only the hot set."""
    from torchsnapshot_tpu import StateDict

    frozen = {
        f"frozen_{i}": np.random.default_rng(i)
        .standard_normal((8 << 20) // 4)
        .astype(np.float32)
        for i in range(4)
    }
    hot = {"head": np.zeros((2 << 20) // 4, dtype=np.float32)}
    for i in range(32):
        hot[f"emb_{i}"] = np.zeros(1024, dtype=np.float32)
    state = StateDict(**frozen, **hot, step=0)
    hot_bytes = sum(v.nbytes for v in hot.values())
    total_bytes = hot_bytes + sum(v.nbytes for v in frozen.values())
    return {"model": state}, total_bytes, hot_bytes


def _mutate_hot(app_state, np, step: int) -> None:
    st = app_state["model"]
    st["head"] = np.full_like(st["head"], float(step))
    for i in range(32):
        st[f"emb_{i}"] = np.full_like(st[f"emb_{i}"], float(step + i))
    st["step"] = step


def main() -> None:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ["TORCHSNAPSHOT_TPU_JOURNAL"] = "1"
    import numpy as np

    from torchsnapshot_tpu import CheckpointManager
    from torchsnapshot_tpu import georep, journal

    app_state, total_bytes, hot_bytes = _build_state(np)
    shipped = _throttle_wan()

    root = tempfile.mkdtemp(prefix="georep_rpo_p_")
    remote = tempfile.mkdtemp(prefix="georep_rpo_r_")
    rep = None
    hook = None
    try:
        mgr = CheckpointManager(root, save_interval_steps=1)
        base_step = 100
        mgr.save(base_step, app_state)  # primary tier: unthrottled

        # Foreground baseline: epoch commits with NO shipper armed.
        off_walls = []
        for t in range(FOREGROUND_TRIALS):
            _mutate_hot(app_state, np, 200 + t)
            t0 = time.perf_counter()
            assert mgr.journal_step(200 + t, app_state)
            off_walls.append(time.perf_counter() - t0)
        t_off = min(off_walls)

        # Arm the shipper the way the manager does: a journal commit
        # hook that enqueues, nothing else on the foreground path.
        rep = georep.GeoReplicator(remote, interval=0.05)

        def hook(base_dir, bstep, _epoch, _rep=rep):
            _rep.enqueue(base_dir, bstep)

        journal.register_commit_hook(hook)

        # Base ship: full state + the baseline epochs cross the WAN.
        shipped["bytes"] = 0
        t0 = time.perf_counter()
        rep.enqueue(mgr.path_for(base_step), base_step)
        assert rep.drain(timeout=120.0), rep.last_error
        t_base = time.perf_counter() - t0
        base_bytes = shipped["bytes"]
        report(
            "georep_rpo/base_ship",
            {
                "state_mib": round(total_bytes / (1 << 20), 1),
                "wan_mb_s": WAN_BPS / 1e6,
                "shipped_mib": round(base_bytes / (1 << 20), 1),
                "wall_s": round(t_base, 4),
            },
            data_bytes=base_bytes,
        )

        # Epoch ships: one committed epoch (hot set only) per trial,
        # wall measured commit -> remote-applied. Also the foreground
        # gate: journal_step wall with the shipper armed and pushing.
        on_walls, ship_walls, epoch_bytes = [], [], []
        for t in range(SHIP_TRIALS):
            step = 300 + t
            _mutate_hot(app_state, np, step)
            t0 = time.perf_counter()
            assert mgr.journal_step(step, app_state)
            on_walls.append(time.perf_counter() - t0)
            shipped["bytes"] = 0
            t0 = time.perf_counter()
            assert rep.drain(timeout=60.0), rep.last_error
            ship_walls.append(time.perf_counter() - t0)
            epoch_bytes.append(shipped["bytes"])
        t_on = min(on_walls)
        t_epoch = min(ship_walls)
        report(
            "georep_rpo/epoch_ship",
            {
                "hot_mib": round(hot_bytes / (1 << 20), 2),
                "trials_s": [round(w, 4) for w in ship_walls],
                "shipped_mib": round(min(epoch_bytes) / (1 << 20), 2),
                "wall_s": round(t_epoch, 4),
            },
            data_bytes=min(epoch_bytes),
        )
        report(
            "georep_rpo/foreground",
            {
                "journal_step_off_s": round(t_off, 4),
                "journal_step_on_s": round(t_on, 4),
                "off_trials_s": [round(w, 4) for w in off_walls],
                "on_trials_s": [round(w, 4) for w in on_walls],
            },
            data_bytes=hot_bytes,
        )

        # Sanity: the remote tier is a real snapshot — the drill proper
        # (bit-exact restore) lives in tests/test_georep.py; here just
        # check the cursor reached the last committed epoch.
        st = georep.status(root, remote_root=remote)
        assert st["backlog_epochs"] == 0, st
        assert st["applied_epoch"] == st["local_epochs"], st

        ship_ratio = t_base / t_epoch
        summary = {
            "benchmark": "georep_rpo/summary",
            "state_mib": round(total_bytes / (1 << 20), 1),
            "hot_mib": round(hot_bytes / (1 << 20), 2),
            "wan_mb_s": WAN_BPS / 1e6,
            "base_ship_s": round(t_base, 4),
            "epoch_ship_s": round(t_epoch, 4),
            "ship_reduction_x": round(ship_ratio, 1),
            "journal_step_off_s": round(t_off, 4),
            "journal_step_on_s": round(t_on, 4),
            "foreground_overhead_pct": round(
                (t_on - t_off) / t_off * 100.0, 2
            ),
            # Remote RPO at each journal cadence: the durability
            # interval plus the measured WAN fold time. The base-ship
            # row is what every cadence point would cost without
            # epoch shipping.
            "rpo_remote_by_cadence_s": {
                str(c): round(c + t_epoch, 2) for c in CADENCES_S
            },
            "rpo_remote_base_only_by_cadence_s": {
                str(c): round(c + t_base, 2) for c in CADENCES_S
            },
        }
        print(json.dumps(summary), flush=True)
        assert ship_ratio >= 3.0, (
            f"epoch ship {t_epoch:.3f}s not meaningfully cheaper than "
            f"base ship {t_base:.3f}s ({ship_ratio:.1f}x < 3x)"
        )
        assert t_on <= max(t_off * 1.05, t_off + 0.05), (
            f"armed journal_step {t_on:.4f}s exceeds foreground gate "
            f"(unarmed {t_off:.4f}s): shipping is leaking into the "
            f"foreground path"
        )
    finally:
        if hook is not None:
            journal.unregister_commit_hook(hook)
        if rep is not None:
            rep.close(drain_timeout=0.1)
        shutil.rmtree(root, ignore_errors=True)
        shutil.rmtree(remote, ignore_errors=True)


if __name__ == "__main__":
    main()
